"""Engine graph nodes and their executors.

This is the TPU-engine's operator vocabulary — the capability contract the
reference exposes as the ~55-method `Graph` trait
(/root/reference/src/engine/graph.rs:643-992). Build-time `Node` descriptors
are created by the Table API; at run time each node instantiates a `NodeExec`
that consumes/emits columnar `DiffBatch`es per logical tick.

Incremental strategy: stateless ops are vectorized streaming maps; stateful
ops (join/groupby/sort/...) keep keyed state and restate only *touched* keys
per tick — the microbatch analog of differential dataflow's arrangements
(reference: src/engine/dataflow.rs join_tables:2740, group_by_table:3404).
"""

from __future__ import annotations

import itertools
import os
import sys
from typing import Any, Callable, Iterable, Sequence

import numpy as np
import pandas as pd  # factorize powers the columnar groupby/join paths

from pathway_tpu.engine.arrangement import (
    Arrangement,
    Rows,
    concat_columns,
    consolidate_mixed,
    merge_rows_sorted,
    merge_sorted,
    mix_keys,
    sorted_member,
)
from pathway_tpu.engine.batch import (
    END_OF_TIME,
    DiffBatch,
    MultisetState,
    make_column,
)
from pathway_tpu.engine.expression_eval import (
    EvalContext,
    InternalColRef,
    eval_expr,
)
from pathway_tpu.engine.reducers import ReducerSpec
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.api import (
    ERROR,
    Pointer,
    match_keys,
    ptr_column,
    ref_scalar,
    ref_scalars_columns,
)
from pathway_tpu.internals.errors import record_error
from pathway_tpu.internals.json import Json

_node_counter = itertools.count()


ALL_NODES: list["Node"] = []  # every node built since the last G.clear()
# (run_all executes the WHOLE declared graph, outputs or not — reference:
# GraphRunner.run_all vs run_outputs, internals/graph_runner/__init__.py)


# package root used to find the user frame that declared a node (the
# first stack frame outside pathway_tpu itself)
# trailing separator: a SIBLING path that merely shares the directory
# name as a prefix (".../pathway_tpu_demo.py") is user code, not ours
_PKG_ROOT = (
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep
)


def _declaration_frame() -> tuple[str, int, str] | None:
    """(filename, lineno, function) of the user code declaring a node —
    the provenance the Graph Doctor attaches to diagnostics (a cheap
    frame walk, no traceback materialization)."""
    try:
        f = sys._getframe(1)
    except ValueError:  # pragma: no cover
        return None
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_ROOT):
            return (fn, f.f_lineno, f.f_code.co_name)
        f = f.f_back
    return None


class Node:
    """Build-time descriptor."""

    # --- static-analysis metadata (pathway_tpu/analysis) ---------------
    # Whether the exec keeps keyed state across ticks — drives the Graph
    # Doctor's unbounded-state and graph-stats rules.
    is_stateful = False

    def __init__(self, inputs: Sequence["Node"], column_names: Sequence[str]):
        self.id = next(_node_counter)
        self.inputs = list(inputs)
        self.column_names = list(column_names)
        self.name = type(self).__name__
        # declaration-site provenance for diagnostics
        self.trace = _declaration_frame()
        # error-log scope captured at build time (pw.local_error_log)
        from pathway_tpu.internals.errors import current_build_scope

        self._error_scope = current_build_scope()
        ALL_NODES.append(self)

    def key_columns(self) -> tuple[str, ...]:
        """Input columns that determine keyed-state routing (grouping
        keys, join keys, dedup instances, ...) — () for stateless or
        row-key-routed nodes."""
        return ()

    def make_exec(self) -> "NodeExec":
        raise NotImplementedError

    def __repr__(self):
        return f"<{self.name}#{self.id}>"


class NodeExec:
    def __init__(self, node: Node):
        self.node = node

    def process(self, t: int, inputs: list[list[DiffBatch]]) -> list[DiffBatch]:
        raise NotImplementedError

    def on_end(self) -> list[DiffBatch]:
        return []

    # --- operator-state snapshots (reference: chunked operator snapshots,
    # src/persistence/operator_snapshot.rs:21-31 + MaybePersist wrappers,
    # src/engine/dataflow/persist.rs) -----------------------------------
    # Default: every attribute except the build-time node descriptor IS the
    # incremental state (the exec pattern keeps all state in plain dicts).
    # Execs holding unpicklables (device arrays, meshes) override.

    def state_dict(self) -> dict | None:
        """Picklable snapshot of this exec's incremental state, or None
        when the exec is stateless.  "_m_"-prefixed attributes are
        metrics-registry handles (hold locks, process-global) and are
        never part of operator state."""
        state = {
            k: v
            for k, v in self.__dict__.items()
            if k != "node" and not k.startswith("_m_")
        }
        return state or None

    def load_state(self, state: dict) -> None:
        self.__dict__.update(state)

    # --- incremental (arrangement-backed) snapshots ---------------------
    # Execs whose state lives in Arrangements (engine/arrangement.py)
    # expose it so the persistence glue can write sealed segments
    # incrementally (content-addressed by segment id, bytes ∝ churn) and
    # recover by mmap-loading them instead of unpickling a monolith.

    def arranged_state(self) -> tuple[dict, dict[str, Any]] | None:
        """(residual_state, {name: Arrangement}) when this exec's state
        should snapshot incrementally, or None to snapshot monolithically
        via state_dict().  The residual must be small (indices, flags) —
        everything that grows with state belongs in the arrangements."""
        return None

    def load_arranged_state(
        self, residual: dict, arrangements: dict[str, Any]
    ) -> None:
        """Default restore: residual attrs + each arrangement under its
        part name (parts named after plain attributes).  Execs that nest
        arrangements inside helper objects override this."""
        self.load_state(residual)
        for name, arr in arrangements.items():
            setattr(self, name, arr)

    # --- memory ledger (observability/tickscope.py) ---------------------

    def memory_ledger(self, deep: bool = False) -> dict[str, int]:
        """Resident bytes per state part.  Default: every Arrangement
        attribute reports its segment/staged bytes; ``deep`` adds the
        monolith-pickle size for execs still snapshotting via
        state_dict() (the exact number the ROADMAP's "kill the last
        monolith" item needs measured, but costs a pickle — never on
        by default).  Execs with doubled state (GroupByExec's live dict
        + pickled ledger) override to name both sides."""
        from pathway_tpu.engine.arrangement import Arrangement

        parts: dict[str, int] = {}
        for k, v in self.__dict__.items():
            if isinstance(v, Arrangement):
                parts[f"arrangement:{k}"] = v.resident_bytes()
        if deep and self.arranged_state() is None:
            try:
                state = self.state_dict()
                if state:
                    import pickle

                    parts["monolith_pickle"] = len(
                        pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
                    )
            except Exception:
                pass
        return parts


def _concat_inputs(batches: list[DiffBatch], names: Sequence[str]) -> DiffBatch:
    batches = [b for b in batches if len(b)]
    if not batches:
        return DiffBatch.empty(names)
    return DiffBatch.concat(batches)


# ---------------------------------------------------------------------------
# Input


class InputNode(Node):
    """Source-fed table (reference: Graph::connector_table,
    src/engine/dataflow.rs:3672)."""

    def __init__(self, source: Any, column_names: Sequence[str]):
        super().__init__([], column_names)
        self.source = source

    def make_exec(self):
        return InputExec(self)


class InputExec(NodeExec):
    def __init__(self, node: InputNode):
        super().__init__(node)
        self.pending: list[DiffBatch] = []
        # Tick Forge typed ingest: resolved once per exec (the flag is
        # per-run like the compiled plan itself)
        self._tighten: bool | None = None

    def inject(self, batch: DiffBatch) -> None:
        if self._tighten is None:
            from pathway_tpu.engine.compile import compiled_tick_enabled

            self._tighten = compiled_tick_enabled()
        if self._tighten:
            from pathway_tpu.engine.expression_eval import tighten_batch

            batch = tighten_batch(batch)
        self.pending.append(batch)

    def process(self, t, inputs):
        out = self.pending
        self.pending = []
        return out


# ---------------------------------------------------------------------------
# Rowwise (select / with_columns) — stateless fast path


class RowwiseNode(Node):
    """Compute output columns from expressions over aligned inputs
    (reference: expression_table, src/engine/dataflow.rs:1735)."""

    def __init__(
        self,
        inputs: Sequence[Node],
        exprs: dict[str, expr_mod.ColumnExpression],
        deterministic: bool = True,
    ):
        super().__init__(inputs, list(exprs.keys()))
        self.exprs = exprs
        self.deterministic = deterministic

    @property
    def is_stateful(self) -> bool:  # type: ignore[override]
        # AlignedRowwiseExec keeps per-input multiset state; the
        # single-input deterministic fast path is a pure streaming map
        return len(self.inputs) > 1 or not self.deterministic

    def make_exec(self):
        if len(self.inputs) == 1 and self.deterministic:
            return StreamMapExec(self)
        return AlignedRowwiseExec(self)


class StreamMapExec(NodeExec):
    def process(self, t, inputs):
        batch = _concat_inputs(inputs[0], self.node.inputs[0].column_names)
        if not len(batch):
            return []
        ctx = EvalContext(batch.keys, [batch.columns])
        out_cols = {
            name: eval_expr(e, ctx) for name, e in self.node.exprs.items()
        }
        return [DiffBatch(batch.keys, batch.diffs, out_cols)]


class AlignedRowwiseExec(NodeExec):
    """Multi-input select: inputs share the universe of input 0; output row for
    key k combines the states of all inputs at k. Also used for
    non-deterministic expressions (cached replay on retraction)."""

    def __init__(self, node: RowwiseNode):
        super().__init__(node)
        self.states = [MultisetState(inp.column_names) for inp in node.inputs]
        self.emitted: dict[int, tuple] = {}

    def process(self, t, inputs):
        touched: dict[int, None] = {}
        for i, (inp_batches, state) in enumerate(zip(inputs, self.states)):
            for b in inp_batches:
                for k, d, vals in b.iter_rows():
                    touched[k] = None
                    state.apply_row(k, d, vals)
        if not touched:
            return []
        keys = list(touched.keys())
        primary = self.states[0]
        new_keys = [k for k in keys if primary.get(k) is not None]
        # build aligned context for recomputation
        out_rows: list[tuple[int, int, tuple]] = []
        if new_keys:
            karr = np.asarray(new_keys, dtype=np.uint64)
            col_sets = []
            for state in self.states:
                cols = {}
                for ci, cname in enumerate(state.column_names):
                    col = np.empty(len(new_keys), dtype=object)
                    for i, k in enumerate(new_keys):
                        row = state.get(k)
                        col[i] = row[ci] if row is not None else None
                    cols[cname] = col
                col_sets.append(cols)
            ctx = EvalContext(karr, col_sets)
            out_cols = [eval_expr(e, ctx) for e in self.node.exprs.values()]
            new_vals = {
                k: tuple(c[i] for c in out_cols) for i, k in enumerate(new_keys)
            }
        else:
            new_vals = {}
        from pathway_tpu.engine.batch import _values_eq

        for k in keys:
            old = self.emitted.get(k)
            new = new_vals.get(k)
            if old is not None and new is not None and _values_eq(old, new):
                continue
            if old is not None:
                out_rows.append((k, -1, old))
                del self.emitted[k]
            if new is not None:
                out_rows.append((k, 1, new))
                self.emitted[k] = new
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Filter


class FilterNode(Node):
    def __init__(self, input: Node, predicate: expr_mod.ColumnExpression):
        super().__init__([input], input.column_names)
        self.predicate = predicate

    def make_exec(self):
        return FilterExec(self)


class FilterExec(NodeExec):
    def process(self, t, inputs):
        batch = _concat_inputs(inputs[0], self.node.inputs[0].column_names)
        if not len(batch):
            return []
        ctx = EvalContext(batch.keys, [batch.columns])
        pred = eval_expr(self.node.predicate, ctx)
        if pred.dtype == object:
            from pathway_tpu.internals.api import Error

            mask = np.empty(len(pred), dtype=bool)
            for i, p in enumerate(pred):
                if isinstance(p, Error):
                    mask[i] = False
                    record_error(
                        "Error value encountered in filter condition, "
                        "skipping the row",
                        str(self.node),
                    )
                else:
                    mask[i] = bool(p)
        else:
            mask = pred.astype(bool)
        out = batch.mask(mask)
        return [out] if len(out) else []


# ---------------------------------------------------------------------------
# Reindex (with_id / with_id_from)


class ReindexNode(Node):
    """Change row keys (reference: Graph::reindex / with_id_from)."""

    def __init__(self, input: Node, key_expr: expr_mod.ColumnExpression):
        super().__init__([input], input.column_names)
        self.key_expr = key_expr

    def make_exec(self):
        return ReindexExec(self)


class ReindexExec(NodeExec):
    def process(self, t, inputs):
        batch = _concat_inputs(inputs[0], self.node.inputs[0].column_names)
        if not len(batch):
            return []
        ctx = EvalContext(batch.keys, [batch.columns])
        new_keys = eval_expr(self.node.key_expr, ctx)
        karr = np.empty(len(batch), dtype=np.uint64)
        for i, k in enumerate(new_keys):
            karr[i] = int(k)
        return [DiffBatch(karr, batch.diffs, batch.columns)]


# ---------------------------------------------------------------------------
# Groupby / reduce


class GroupByNode(Node):
    """(reference: group_by_table, src/engine/dataflow.rs:3404)"""

    is_stateful = True

    def __init__(
        self,
        input: Node,
        grouping_cols: Sequence[str],
        reducer_specs: dict[str, ReducerSpec],
        instance_col: str | None = None,
        set_id: bool = False,
        sort_by: str | None = None,
    ):
        out_cols = list(grouping_cols) + list(reducer_specs.keys())
        super().__init__([input], out_cols)
        self.grouping_cols = list(grouping_cols)
        self.reducer_specs = reducer_specs
        self.instance_col = instance_col
        self.set_id = set_id
        self.sort_by = sort_by

    def key_columns(self) -> tuple[str, ...]:
        out = tuple(self.grouping_cols)
        if self.instance_col:
            out += (self.instance_col,)
        return out

    def _make_local_exec(self):
        from pathway_tpu.parallel.mesh import get_engine_mesh

        em = get_engine_mesh()
        if em is not None:
            from pathway_tpu.engine.sharded import ShardedGroupByExec

            return ShardedGroupByExec(self, em[0], em[1])
        return GroupByExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnGroupByExec

            return DcnGroupByExec(self)
        return self._make_local_exec()


class _GroupState:
    __slots__ = ("gvals", "count", "accs", "emitted")

    def __init__(self, gvals: tuple, specs: Iterable[ReducerSpec]):
        self.gvals = gvals
        self.count = 0
        self.accs = [spec.make() for spec in specs]
        self.emitted: tuple | None = None


class GroupByExec(NodeExec):
    def __init__(self, node: GroupByNode):
        super().__init__(node)
        self.groups: dict[int, _GroupState] = {}
        in_cols = node.inputs[0].column_names
        self.g_idx = [in_cols.index(c) for c in node.grouping_cols]
        self.inst_idx = (
            in_cols.index(node.instance_col) if node.instance_col else None
        )
        self.sort_idx = (
            in_cols.index(node.sort_by) if node.sort_by else None
        )
        self.specs = list(node.reducer_specs.values())
        self.arg_idx = [
            tuple(in_cols.index(c) for c in spec.arg_cols) for spec in self.specs
        ]
        # persistence ledger: a side arrangement mirroring per-group state
        # as immutable pickled blobs, appended only for groups a tick
        # touches — so operator snapshots write O(churn) segment bytes
        # instead of re-pickling the whole groups dict. The COMPUTE path
        # is untouched (groupby stays on the dict accumulators); the
        # glue enables this only when persistence is attached.
        self.ledger = Arrangement(1)
        self._ledgered: set[int] = set()
        self._ledger_enabled = False
        # Tick Forge: the semigroup partial-aggregation pass
        # (dcounts/sums) can run as one jitted segment_sum program —
        # opt-in/auto per backend (compile.compiled_groupby_enabled);
        # None = not yet resolved, False after any device failure
        self._compiled_semigroup: bool | None = None

    def enable_state_ledger(self) -> None:
        self._ledger_enabled = True

    def _ledger_append(self, touched) -> None:
        if not self._ledger_enabled or not touched:
            return
        try:
            import pickle as _pickle

            jks: list[int] = []
            diffs: list[int] = []
            blobs: list = []
            for gk in touched:
                gs = self.groups.get(gk)
                if gk in self._ledgered:
                    jks.append(gk)
                    diffs.append(-1)
                    blobs.append(None)  # cancels by (jk, key); value unused
                    if gs is None:
                        self._ledgered.discard(gk)
                if gs is not None:
                    jks.append(gk)
                    diffs.append(1)
                    blobs.append(
                        _pickle.dumps(gs, protocol=_pickle.HIGHEST_PROTOCOL)
                    )
                    self._ledgered.add(gk)
            if jks:
                jka = np.asarray(jks, dtype=np.uint64)
                col = np.empty(len(blobs), dtype=object)
                col[:] = blobs
                self.ledger.append(
                    jka, jka, np.asarray(diffs, dtype=np.int64), [col]
                )
        except Exception:
            # unpicklable accumulator (e.g. a closure-bound stateful
            # reducer): drop to the monolithic snapshot path permanently —
            # same degraded contract the whole-state pickler already has
            import logging

            logging.getLogger("pathway_tpu").warning(
                "groupby state ledger disabled (unpicklable group state) "
                "for node %s; snapshots fall back to the monolithic path",
                self.node,
                exc_info=True,
            )
            self._ledger_enabled = False
            self.ledger = Arrangement(1)
            self._ledgered = set()

    def arranged_state(self):
        if not self._ledger_enabled:
            return None
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("node", "groups", "ledger", "_ledgered")
            and not k.startswith("_m_")
        }
        return residual, {"ledger": self.ledger}

    def load_arranged_state(self, residual, arrangements) -> None:
        import pickle as _pickle

        self.__dict__.update(residual)
        self.ledger = arrangements["ledger"]
        rows = self.ledger.entries()
        self.groups = {
            int(jk): _pickle.loads(blob)
            for jk, blob in zip(rows.jk.tolist(), rows.cols[0].tolist())
        }
        self._ledgered = set(self.groups)

    def load_state(self, state: dict) -> None:
        enabled = self._ledger_enabled  # set by the persistence glue
        super().load_state(state)
        if enabled and not self._ledger_enabled:
            # the snapshot was taken by a run without the ledger (legacy
            # or PATHWAY_PERSIST_MONOLITH): re-enable for THIS run
            self._ledger_enabled = True
        if self._ledger_enabled and self.groups and not self._ledgered:
            # seed the ledger with every restored group — otherwise the
            # next incremental snapshot would persist only groups touched
            # since the restore and silently drop the rest
            self._ledger_append(list(self.groups))

    def memory_ledger(self, deep: bool = False) -> dict[str, int]:
        """Groupby's residency is DOUBLED when the state ledger is on:
        the live ``groups`` dict (compute path) plus the pickled-blob
        mirror in ``self.ledger`` (persistence path).  Name both sides
        so Tick Scope's top-owners list can show the doubling the
        ROADMAP's columnar-memory refactor wants to collapse.  The dict
        side is estimated per group via sys.getsizeof on the state's
        __dict__ values (cheap; exact would re-pickle every group)."""
        import sys

        parts = {"ledger_blobs": self.ledger.resident_bytes()}
        dict_bytes = sys.getsizeof(self.groups)
        for gs in self.groups.values():
            dict_bytes += sys.getsizeof(gs)
            d = getattr(gs, "__dict__", None)
            if d:
                dict_bytes += sum(
                    sys.getsizeof(v)
                    + (v.nbytes if isinstance(v, np.ndarray) else 0)
                    for v in d.values()
                )
        parts["groups_dict"] = dict_bytes
        if deep and not self._ledger_enabled:
            base = super().memory_ledger(deep=True)
            if "monolith_pickle" in base:
                parts["monolith_pickle"] = base["monolith_pickle"]
        return parts

    def _group_key(self, vals: tuple) -> int:
        gvals = tuple(vals[i] for i in self.g_idx)
        if self.node.set_id and len(gvals) == 1 and isinstance(gvals[0], Pointer):
            # grouping by an id column: reuse it (reference groupby id behavior)
            base = gvals[0]
        else:
            base = ref_scalar(*gvals)
        if self.inst_idx is not None:
            base = base.with_shard_of(ref_scalar(vals[self.inst_idx]))
        return int(base)

    def _group_keys_batch(self, b) -> "Any":
        """Vectorized group keys for a whole batch via the native batch
        hasher (falls back to per-row ref_scalar)."""
        from pathway_tpu.internals.api import ref_scalars_columns

        cols = list(b.columns.values())
        gcols = [cols[i] for i in self.g_idx]
        return ref_scalars_columns(gcols, len(b))

    _BULK_SEMIGROUP = ("count", "sum", "avg")
    _BULK_MULTISET = ("min", "max", "argmin", "argmax", "unique")

    # pandas hashes some value pairs equal that ref_scalar distinguishes
    # (True==1==1.0; None merges with NaN in float columns), so the
    # factorize fast path only fires when each grouping column's value
    # types make those collisions impossible; anything else falls back to
    # the exact per-row hash.
    _SAFE_TYPESETS = (
        {str},
        {str, type(None)},
        {int},
        {int, type(None)},
        {float},
        {bool},
        {type(None)},
    )

    def _bulk_codes(self, b):
        """Factorize the grouping columns: (codes [n] int64 dense 0..nu-1 in
        first-appearance order, nu, first_idx [nu]) or None when any column
        is factorize-unsafe. Replaces hashing every row: group keys are
        derived (via the exact C hasher) for the nu distinct groups only —
        the O(n) work drops from ~1 us/row blake2b to a pandas hash."""
        cols = list(b.columns.values())
        parts: list[tuple[np.ndarray, int]] = []
        for j in self.g_idx:
            arr = cols[j]
            if arr.dtype == object:
                ts = set(map(type, arr.tolist()))
                if ts not in self._SAFE_TYPESETS:
                    return None
            elif arr.dtype.kind not in "biufUS" or arr.ndim != 1:
                return None
            try:
                codes_j, uniq_j = pd.factorize(arr, use_na_sentinel=False)
            except TypeError:
                return None
            parts.append((codes_j.astype(np.int64), max(1, len(uniq_j))))
        codes, nu = parts[0]
        if len(parts) > 1:
            # mixed-radix combination must fit int64 or wrapped codes could
            # collide and silently merge distinct groups — fall back to the
            # exact per-row hash beyond that
            radix = nu
            for _cj, nj in parts[1:]:
                radix *= nj
                if radix > (1 << 62):
                    return None
            for cj, nj in parts[1:]:
                codes = codes * nj + cj
            codes, uniq_c = pd.factorize(codes, use_na_sentinel=False)
            codes = codes.astype(np.int64)
            nu = len(uniq_c)
        n = len(codes)
        # smallest row index per group: reversed fancy assignment makes the
        # earliest row the last (winning) write for each code
        first_idx = np.empty(nu, dtype=np.int64)
        first_idx[codes[::-1]] = np.arange(n - 1, -1, -1, dtype=np.int64)
        return codes, nu, first_idx

    def _semigroup_partials(self, codes, diffs, arg_arrays, nu):
        """Per-group (diff counts, weighted sums) for the semigroup
        reducers.  Host scatter (np.add.at) by default; the jitted
        segment_sum twin rides the compiled-tick cache when the backend
        makes device scatter a win (PATHWAY_COMPILED_GROUPBY — see
        engine/compile.py for the measured CPU numbers)."""
        if self._compiled_semigroup is None:
            from pathway_tpu.engine.compile import (
                compiled_groupby_enabled,
                compiled_tick_enabled,
            )

            self._compiled_semigroup = (
                compiled_tick_enabled() and compiled_groupby_enabled()
            )
        if self._compiled_semigroup:
            from pathway_tpu.engine.compile import (
                NotCompilable,
                semigroup_partials,
            )

            sem_args = [
                a if (s.kind in ("sum", "avg")) else None
                for s, a in zip(self.specs, arg_arrays)
            ]
            try:
                return semigroup_partials(codes, diffs, sem_args, nu)
            except NotCompilable:
                pass  # unsupported dtype this batch: host path below
            except Exception:
                import logging

                logging.getLogger("pathway_tpu").warning(
                    "compiled groupby partials failed for %s; using the "
                    "host scatter path from now on",
                    self.node,
                    exc_info=True,
                )
                self._compiled_semigroup = False
        dcounts = np.zeros(nu, dtype=np.int64)
        np.add.at(dcounts, codes, diffs)
        partials: list[np.ndarray | None] = []
        for spec, arr in zip(self.specs, arg_arrays):
            if arr is None:
                partials.append(None)
            else:
                part = np.zeros(
                    nu, dtype=arr.dtype if arr.dtype.kind == "i" else np.float64
                )
                np.add.at(part, codes, arr * diffs)
                partials.append(part)
        return dcounts, partials

    def _try_bulk(self, b, touched, t) -> bool:
        """Columnar groupby path (the microbatch analog of differential's
        batched reduce, reference src/engine/reduce.rs:40): factorize the
        grouping columns, hash only the distinct groups, accumulate
        semigroup reducers (count/sum/avg) with bincount-style partial sums
        and multiset reducers (min/max/argmin/argmax/unique/any) with one
        tight per-group bulk update — no per-row Python tuples."""
        if self.sort_idx is not None or len(b) < 256:
            return False
        if not self.g_idx:
            # global reduce (no grouping columns): _bulk_codes has no
            # column to factorize — use the per-row path
            return False
        for s in self.specs:
            if s.kind in self._BULK_SEMIGROUP:
                # count(col) must see its argument column (ERROR poison,
                # skip_nones) — only argument-less count is a pure semigroup
                if s.skip_nones or (s.kind == "count" and s.arg_cols):
                    return False
            elif s.kind not in self._BULK_MULTISET:
                return False
        cols = list(b.columns.values())
        diffs = b.diffs
        # pre-validate semigroup argument columns as dense numerics
        arg_arrays: list[np.ndarray | None] = []
        for spec, idx in zip(self.specs, self.arg_idx):
            if spec.kind not in self._BULK_SEMIGROUP or spec.kind == "count":
                arg_arrays.append(None)
                continue
            arr = cols[idx[0]]
            if arr.dtype == object:
                try:
                    arr = np.array(arr.tolist())
                except (TypeError, ValueError):
                    return False
            if arr.dtype.kind not in "if" or arr.ndim != 1:
                return False  # ndarray-valued sums use the per-row path
            arg_arrays.append(arr)
        fact = self._bulk_codes(b)
        if fact is None:
            return False
        codes, nu, first_idx = fact
        # exact group keys for the distinct groups only (same C hasher and
        # column layout as _group_keys_batch, so keys are byte-identical
        # across the bulk and per-row paths)
        from pathway_tpu.internals.api import ref_scalars_columns

        gks_u = ref_scalars_columns(
            [cols[j][first_idx] for j in self.g_idx], nu
        )
        dcounts, partials = self._semigroup_partials(
            codes, diffs, arg_arrays, nu
        )
        # group the batch's row positions by code for multiset bulk updates
        any_multiset = any(s.kind in self._BULK_MULTISET for s in self.specs)
        if any_multiset:
            order = np.argsort(codes, kind="stable")
            bounds = np.searchsorted(codes[order], np.arange(nu + 1))
            diffs_l = diffs.tolist()
        for gi in range(nu):
            gk = int(gks_u[gi])
            gs = self.groups.get(gk)
            if gs is None:
                i0 = int(first_idx[gi])
                gs = _GroupState(
                    tuple(cols[j][i0] for j in self.g_idx), self.specs
                )
                self.groups[gk] = gs
            d = int(dcounts[gi])
            gs.count += d
            if any_multiset:
                g_rows = order[bounds[gi] : bounds[gi + 1]]
            for acc, spec, part, idx in zip(
                gs.accs, self.specs, partials, self.arg_idx
            ):
                if spec.kind == "count":
                    acc.c += d
                elif spec.kind == "sum":
                    p = part[gi]
                    acc.s = acc.s + (
                        int(p) if part.dtype.kind == "i" else float(p)
                    )
                    acc.n += d
                elif spec.kind == "avg":
                    acc.s += float(part[gi])
                    acc.c += d
                else:  # multiset bulk
                    try:
                        acc.update_bulk(
                            [cols[j][g_rows].tolist() for j in idx],
                            [diffs_l[r] for r in g_rows],
                        )
                    except Exception as exc:
                        # same degraded-but-running contract as the per-row
                        # path (e.g. unhashable ndarray args)
                        record_error(exc, str(self.node))
            touched[gk] = None
        return True

    def process(self, t, inputs):
        batches = inputs[0]
        touched: dict[int, None] = {}
        simple_keys = not self.node.set_id and self.inst_idx is None
        for b in batches:
            if simple_keys and len(b) and self._try_bulk(b, touched, t):
                continue
            gks = self._group_keys_batch(b) if simple_keys and len(b) else None
            cols = list(b.columns.values())
            keys_a, diffs_a = b.keys, b.diffs
            for i in range(len(b)):
                vals = tuple(c[i] for c in cols)
                k = int(keys_a[i])
                d = int(diffs_a[i])
                if any(vals[j] is ERROR for j in self.g_idx) or (
                    self.inst_idx is not None
                    and vals[self.inst_idx] is ERROR
                ):
                    record_error(
                        "Error value encountered in grouping columns, "
                        "skipping the row",
                        str(self.node),
                    )
                    continue
                gk = int(gks[i]) if gks is not None else self._group_key(vals)
                gs = self.groups.get(gk)
                if gs is None:
                    gs = _GroupState(
                        tuple(vals[j] for j in self.g_idx), self.specs
                    )
                    self.groups[gk] = gs
                gs.count += d
                # ordered reducers (tuple/ndarray/earliest) sort by this token
                order = (vals[self.sort_idx], k) if self.sort_idx is not None else k
                for acc, idx in zip(gs.accs, self.arg_idx):
                    args = tuple(vals[j] for j in idx)
                    if any(a is ERROR for a in args):
                        # skip_errors (the groupby default) drops ERROR
                        # args; otherwise they poison the aggregate while
                        # present and a retraction un-poisons (reference:
                        # Value::Error propagation, src/engine/error.rs).
                        # Stateful reducers are append-only and cannot
                        # retract: their poison is permanent (reference:
                        # stateful reducers do not recover from errors)
                        if not acc.spec.skip_errors:
                            acc.poisoned_count += (
                                abs(d) if acc.spec.kind == "stateful" else d
                            )
                        continue
                    try:
                        acc.update(args, d, order, t)
                    except Exception as exc:
                        # a failing STATEFUL combine poisons its aggregate
                        # permanently (append-only state cannot retract);
                        # other reducers just log, matching the bulk path
                        record_error(exc, str(self.node), user=True)
                        if acc.spec.kind == "stateful":
                            acc.poisoned_count += abs(d)
                touched[gk] = None
        out_rows: list[tuple[int, int, tuple]] = []
        from pathway_tpu.engine.batch import _values_eq

        for gk, gs in [(gk, self.groups[gk]) for gk in touched]:
            if gs.count > 0:
                try:
                    new = gs.gvals + tuple(
                        ERROR if acc.poisoned_count > 0 else acc.value()
                        for acc in gs.accs
                    )
                except Exception as exc:
                    record_error(exc, str(self.node))
                    new = gs.gvals + tuple(ERROR for _ in gs.accs)
            else:
                new = None
            old = gs.emitted
            if old is not None and new is not None and _values_eq(old, new):
                continue
            if old is not None:
                out_rows.append((gk, -1, old))
            if new is not None:
                out_rows.append((gk, 1, new))
            gs.emitted = new
            if new is None and gs.count == 0:
                del self.groups[gk]
        self._ledger_append(touched)
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Join


class JoinNode(Node):
    """Binary equijoin (reference: join_tables, src/engine/dataflow.rs:2740).

    Output columns: left columns as 'l.<name>', right as 'r.<name>', plus
    '_left_id'/'_right_id' pointers (None on the unmatched side)."""

    is_stateful = True

    def __init__(
        self,
        left: Node,
        right: Node,
        left_on: Sequence[str],
        right_on: Sequence[str],
        mode: str,  # inner | left | right | outer
        id_from: str | None = None,  # None | 'left' | 'right'
        exact_match: bool = False,
    ):
        cols = (
            ["l." + c for c in left.column_names]
            + ["r." + c for c in right.column_names]
            + ["_left_id", "_right_id"]
        )
        super().__init__([left, right], cols)
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.mode = mode
        self.id_from = id_from

    def key_columns(self) -> tuple[str, ...]:
        return tuple(self.left_on) + tuple(self.right_on)

    def _make_local_exec(self):
        from pathway_tpu.parallel.mesh import get_engine_mesh

        em = get_engine_mesh()
        if em is not None:
            from pathway_tpu.engine.sharded import ShardedJoinExec

            return ShardedJoinExec(self, em[0], em[1])
        return JoinExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnJoinExec

            return DcnJoinExec(self)
        return self._make_local_exec()


class _SideState:
    """Rowwise dict state — jk -> {rowkey: [vals, count]}.  Only the
    oracle/fallback representation: the engine's steady-state join keeps
    its state in columnar Arrangements (engine/arrangement.py); this dict
    form survives for the differential-testing oracle
    (PATHWAY_JOIN_ROWWISE=1) and as the degraded-but-running escape hatch
    when the vectorized path hits something unexpected."""

    __slots__ = ("by_jk",)

    def __init__(self):
        self.by_jk: dict[int, dict[int, list]] = {}

    def apply(self, jk: int, k: int, d: int, vals: tuple):
        rows = self.by_jk.setdefault(jk, {})
        e = rows.get(k)
        if e is None:
            if d != 0:
                rows[k] = [vals, d]
        else:
            e[1] += d
            if d > 0:
                e[0] = vals
            if e[1] == 0:
                del rows[k]
        if not rows:
            del self.by_jk[jk]

    def rows(self, jk: int) -> dict[int, list]:
        return self.by_jk.get(jk, {})


def _none_col(n: int) -> np.ndarray:
    return np.full(n, None, dtype=object)


def _eq_scalar(x, y) -> bool:
    """Python `==` with the engine's value conventions (ndarray values
    compare elementwise, None equals only None, un-comparable objects
    fall back to identity) — the scalar twin of batch._values_eq."""
    if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
        return (
            isinstance(x, np.ndarray)
            and isinstance(y, np.ndarray)
            and x.shape == y.shape
            and bool(np.all(x == y))
        )
    try:
        return bool(x == y) or (x is None and y is None)
    except (ValueError, TypeError):
        return x is y


_eq_elem = np.frompyfunc(_eq_scalar, 2, 1)


def _column_eq(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise value equality of two aligned columns (bool array);
    typed columns compare at C speed, object columns row by row with
    _eq_scalar semantics."""
    if a.dtype != object and b.dtype != object:
        try:
            return np.asarray(a == b, dtype=bool)
        except (TypeError, ValueError):
            pass
    return _eq_elem(a, b).astype(bool)


def _state_rowwise_env() -> bool:
    """The shared rowwise-oracle knob for every arrangement-backed
    stateful exec (dedupe / temporal joins / session assignment)."""
    return os.environ.get("PATHWAY_STATE_ROWWISE", "") not in ("", "0")


def _fallback_counter():
    """One counter for every arrangement-backed exec's degradation to the
    rowwise path — a single definition so the metric cannot fork."""
    from pathway_tpu.observability import REGISTRY

    return REGISTRY.counter(
        "pathway_engine_state_fallbacks_total",
        "arrangement-backed stateful execs degraded to the rowwise "
        "path, by node class and reason",
        ("node", "reason"),
    )


# vectorized Pointer boxing for the _left_id/_right_id output columns
_box_pointers = np.frompyfunc(Pointer, 1, 1)


class _TickDelta:
    """One side's delta for one tick, pre-sorted and fingerprinted once —
    shared by the overlay, the changed-row seeds, and the arrangement
    append (which reuses the sort instead of redoing it)."""

    __slots__ = ("n", "jks", "keys", "diffs", "cols", "order",
                 "mix", "mix_sorted", "clean")

    def __init__(self, jks: np.ndarray, batch: DiffBatch):
        self.n = len(jks)
        self.jks = jks
        self.keys = batch.keys
        self.diffs = batch.diffs
        self.cols = list(batch.columns.values())
        if self.n:
            self.order = np.argsort(jks, kind="stable")
            self.mix = mix_keys(jks, batch.keys)
            self.mix_sorted = np.sort(self.mix)
            self.clean = bool((batch.diffs > 0).all()) and not bool(
                (self.mix_sorted[1:] == self.mix_sorted[:-1]).any()
            )
        else:
            self.order = np.empty(0, dtype=np.int64)
            self.mix = np.empty(0, dtype=np.uint64)
            self.mix_sorted = np.empty(0, dtype=np.uint64)
            self.clean = True


class JoinExec(NodeExec):
    """Incremental equijoin over columnar arranged state.

    Every tick applies the delta-join rule (ΔL ⋈ R ∪ L′ ⋈ ΔR): both
    sides' state lives in Arrangements (engine/arrangement.py), a tick
    probes them for the touched join keys only, overlays the delta, and
    builds the output diff with vectorized pair expansion
    (api.match_keys / searchsorted), diff-weighted retractions,
    per-jk match-count tracking for left/right/outer unmatched padding,
    and batch-hashed output keys — the general path, not a bulk special
    case.  The rowwise dict path survives solely as the differential-
    testing oracle (PATHWAY_JOIN_ROWWISE=1) and as a runtime escape hatch
    (counted in pathway_engine_join_fallbacks, labeled by reason)."""

    def __init__(self, node: JoinNode):
        super().__init__(node)
        lcols = node.inputs[0].column_names
        rcols = node.inputs[1].column_names
        self.l_on_idx = [lcols.index(c) for c in node.left_on]
        self.r_on_idx = [rcols.index(c) for c in node.right_on]
        self.n_l = len(lcols)
        self.n_r = len(rcols)
        self.arr_l = Arrangement(self.n_l)
        self.arr_r = Arrangement(self.n_r)
        # rowwise fallback state (materialized from the arrangements only
        # if the fallback ever fires)
        self.left: _SideState | None = None
        self.right: _SideState | None = None
        self._rowwise = False
        self._fallback_reason: str | None = None
        # Flight Recorder counters ("_m_" attrs are excluded from operator
        # snapshots — registry children hold locks)
        from pathway_tpu.observability import REGISTRY

        self._m_hits = REGISTRY.counter(
            "pathway_engine_join_bulk_hits_total",
            "join ticks fully served by the columnar arrangement "
            "(delta-join) path",
        )
        self._m_fallbacks = REGISTRY.counter(
            "pathway_engine_join_fallbacks_total",
            "join ticks served by the rowwise fallback path, by reason",
            ("reason",),
        )
        if os.environ.get("PATHWAY_JOIN_ROWWISE", "") not in ("", "0"):
            self._to_rowwise("env")

    # --- operator snapshots ---------------------------------------------
    # state_dict (base) already skips registry handles; arranged_state
    # additionally routes the two side arrangements through the
    # incremental segment-snapshot path when the columnar path is live.

    def arranged_state(self):
        if self._rowwise or self.left is not None:
            return None  # dict fallback state: monolith snapshot
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("node", "arr_l", "arr_r")
            and not k.startswith("_m_")
        }
        return residual, {"arr_l": self.arr_l, "arr_r": self.arr_r}

    def load_arranged_state(self, residual, arrangements) -> None:
        super().load_arranged_state(residual, arrangements)
        # the env oracle knob outlives the snapshot that was taken on the
        # columnar path — re-apply it so a restart honors the escape hatch
        if os.environ.get("PATHWAY_JOIN_ROWWISE", "") not in ("", "0"):
            self._to_rowwise("env")

    # --- fallback management --------------------------------------------

    def _to_rowwise(self, reason: str) -> None:
        """Materialize dict state from the arrangements and stay rowwise
        from here on (degraded-but-running contract)."""
        self._rowwise = True
        self._fallback_reason = reason
        if self.left is None:
            self.left = self._materialize_side(self.arr_l)
            self.right = self._materialize_side(self.arr_r)
            self.arr_l = Arrangement(self.n_l)
            self.arr_r = Arrangement(self.n_r)

    @staticmethod
    def _materialize_side(arr: Arrangement) -> _SideState:
        side = _SideState()
        rows = arr.entries()
        cols = [c.tolist() for c in rows.cols]
        vals: Any = zip(*cols) if cols else iter([()] * len(rows))
        by = side.by_jk
        for jk, k, c, v in zip(
            rows.jk.tolist(), rows.key.tolist(), rows.count.tolist(), vals
        ):
            by.setdefault(jk, {})[k] = [v, c]
        return side

    def _jk(self, vals: tuple, idx: list[int]) -> int:
        return int(ref_scalar(*(vals[i] for i in idx)))

    def _outputs_for_jk(self, jk: int) -> dict[int, tuple]:
        """Full current output rows for one join key."""
        node = self.node
        lrows = self.left.rows(jk)
        rrows = self.right.rows(jk)
        out: dict[int, tuple] = {}

        def emit(okey: int, vals: tuple):
            if okey in out:
                # duplicate output id (id_from with non-unique matches) —
                # reference raises a duplicate-id error; we poison + log
                record_error(
                    KeyError(
                        "duplicate row id in join output (id= used with "
                        "non-unique matches)"
                    ),
                    str(node),
                )
                return
            out[okey] = vals

        if lrows and rrows:
            for lk, (lvals, lc) in lrows.items():
                for rk, (rvals, rc) in rrows.items():
                    n = lc * rc
                    if n <= 0:
                        continue
                    if node.id_from == "left":
                        okey = lk
                    elif node.id_from == "right":
                        okey = rk
                    else:
                        okey = int(ref_scalar(Pointer(lk), Pointer(rk)))
                    emit(
                        okey,
                        lvals + rvals + (Pointer(lk), Pointer(rk)),
                    )
        if node.mode in ("left", "outer") and not rrows:
            for lk, (lvals, lc) in lrows.items():
                if lc <= 0:
                    continue
                okey = lk if node.id_from == "left" else int(
                    ref_scalar(Pointer(lk), None)
                )
                emit(okey, lvals + (None,) * self.n_r + (Pointer(lk), None))
        if node.mode in ("right", "outer") and not lrows:
            for rk, (rvals, rc) in rrows.items():
                if rc <= 0:
                    continue
                okey = rk if node.id_from == "right" else int(
                    ref_scalar(None, Pointer(rk))
                )
                emit(okey, (None,) * self.n_l + rvals + (None, Pointer(rk)))
        return out

    def _batch_jks(self, b, on_idx, side_tag: str = "") -> np.ndarray:
        """Join keys for a whole batch via the C batch hasher (byte-
        identical to per-row ref_scalar, same contract as the groupby
        path's _group_keys_batch). A row with None in ANY on-column gets a
        PRIVATE key (side + row id): null keys never match the other side
        but still pad as unmatched in outer modes (reference: chained
        outer joins do not equate padded Nones)."""
        from pathway_tpu.internals.api import ref_scalar, ref_scalars_columns

        cols = list(b.columns.values())
        jks = ref_scalars_columns([cols[i] for i in on_idx], len(b))
        null_rows = None
        for i in on_idx:
            col = cols[i]
            if col.dtype == object:
                # per-element identity test: `col == None` would dispatch
                # elementwise __eq__, which ndarray values hijack into
                # arrays ("truth value ... is ambiguous")
                m = np.fromiter(
                    (v is None for v in col), dtype=bool, count=len(col)
                )
                if not m.any():
                    continue
                null_rows = m if null_rows is None else (null_rows | m)
        if null_rows is not None and null_rows.any():
            # batch the private-key derivation through the C columns
            # hasher: constant ("__pw_null", side) columns + the row-key
            # buffer, byte-identical to the old per-row ref_scalar loop
            idx = np.nonzero(null_rows)[0]
            n_null = len(idx)
            priv = ref_scalars_columns(
                [
                    np.full(n_null, "__pw_null", dtype=object),
                    np.full(n_null, side_tag, dtype=object),
                    ptr_column(b.keys[idx]),
                ],
                n_null,
            )
            jks = np.array(jks, copy=True)
            jks[idx] = priv
        return jks

    # --- columnar delta join --------------------------------------------

    @staticmethod
    def _overlay(
        before: Rows,
        d: "_TickDelta",
        age_base: int,
        before_seed: np.ndarray,
        before_mix: np.ndarray,
    ) -> Rows:
        """State after this tick's delta.  A clean delta (insert-only, no
        duplicate pairs) touching no existing entry merges in with two
        searchsorteds; anything else re-consolidates the before-rows with
        the delta entries appended at strictly later ages."""
        if not d.n:
            return before
        if d.clean and not before_seed.any():
            ages = (age_base + d.order).astype(np.int64)
            delta_rows = Rows(
                d.jks[d.order],
                d.keys[d.order],
                d.diffs[d.order],
                ages,
                [np.asarray(c)[d.order] for c in d.cols],
            )
            return merge_rows_sorted(before, delta_rows)
        ages = np.arange(age_base, age_base + d.n, dtype=np.int64)
        cols = [np.asarray(c) for c in d.cols]
        if not len(before):
            return consolidate_mixed(
                d.jks, d.keys, d.diffs, ages, cols, d.mix
            )
        return consolidate_mixed(
            np.concatenate([before.jk, d.jks]),
            np.concatenate([before.key, d.keys]),
            np.concatenate([before.count, d.diffs]),
            np.concatenate([before.age, ages]),
            [
                concat_columns([bc, dc])
                for bc, dc in zip(before.cols, cols)
            ],
            np.concatenate([before_mix, d.mix]),
        )

    @staticmethod
    def _jk_positions(
        rows: Rows, touched: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """(per-row index into ``touched``, entries per touched jk) — the
        per-jk match-count tracking behind unmatched-padding deltas."""
        jpos = np.searchsorted(touched, rows.jk)
        return jpos, np.bincount(jpos, minlength=len(touched))

    def _drop_duplicate_ids(self, L, R, li, ri):
        """id_from output keys with non-unique matches: per jk, the first
        pair (in emission order) wins; later collisions poison + log —
        same contract as the rowwise path's per-jk emit() check."""
        node = self.node
        okeys = L.key[li] if node.id_from == "left" else R.key[ri]
        jk = L.jk[li]
        n = len(li)
        order = np.lexsort((np.arange(n), okeys, jk))
        jk_o = jk[order]
        ok_o = okeys[order]
        dup = np.zeros(n, dtype=bool)
        dup[1:] = (jk_o[1:] == jk_o[:-1]) & (ok_o[1:] == ok_o[:-1])
        if dup.any():
            for _ in range(int(dup.sum())):
                record_error(
                    KeyError(
                        "duplicate row id in join output (id= used with "
                        "non-unique matches)"
                    ),
                    str(self.node),
                )
            keep = np.ones(n, dtype=bool)
            keep[order[dup]] = False
            li, ri = li[keep], ri[keep]
        return li, ri

    def _state_output(
        self,
        L: Rows,
        R: Rows,
        seed_l,
        seed_r,
        flip_l,
        flip_r,
        jpos_l,
        jpos_r,
        l_cnt,
        r_cnt,
        full: bool,
    ) -> list[tuple]:
        """Output rows of ONE state (before or after) restricted to rows
        that can differ across the tick: pairs with at least one delta-
        touched endpoint (all pairs when ``full``) plus unmatched-padding
        rows whose row changed or whose other-side presence flipped.
        Returns chunks (kind, L, li, R, ri)."""
        node = self.node
        parts: list[tuple] = []
        if len(L) and len(R):
            if full:
                li, ri = match_keys(L.jk, R.jk, right_sorted=True)
            else:
                l_seed_idx = np.nonzero(seed_l)[0]
                a1, b1 = match_keys(
                    L.jk[l_seed_idx], R.jk, right_sorted=True
                )
                l_rest_idx = np.nonzero(~seed_l)[0]
                r_seed_idx = np.nonzero(seed_r)[0]
                a2, b2 = match_keys(
                    L.jk[l_rest_idx], R.jk[r_seed_idx], right_sorted=True
                )
                li = np.concatenate([l_seed_idx[a1], l_rest_idx[a2]])
                ri = np.concatenate([b1, r_seed_idx[b2]])
            if len(li):
                # a pair is in the output iff the product of its net
                # weights is positive (matching the dict path's lc*rc>0)
                m = (L.count[li] * R.count[ri]) > 0
                li, ri = li[m], ri[m]
            if len(li) and node.id_from is not None:
                li, ri = self._drop_duplicate_ids(L, R, li, ri)
            if len(li):
                parts.append(("pair", L, li, R, ri))
        if node.mode in ("left", "outer") and len(L):
            elig = (r_cnt[jpos_l] == 0) & (L.count > 0)
            if not full:
                elig &= seed_l | flip_r[jpos_l]
            idx = np.nonzero(elig)[0]
            if len(idx):
                parts.append(("lpad", L, idx, None, None))
        if node.mode in ("right", "outer") and len(R):
            elig = (l_cnt[jpos_r] == 0) & (R.count > 0)
            if not full:
                elig &= seed_r | flip_l[jpos_r]
            idx = np.nonzero(elig)[0]
            if len(idx):
                parts.append(("rpad", None, None, R, idx))
        return parts

    _PAIR_C1 = np.uint64(0x9E3779B97F4A7C15)
    _PAIR_C2 = np.uint64(0xC2B2AE3D27D4EB4F)
    _PAIR_C3 = np.uint64(0x165667B19E3779F9)
    _PAIR_C4 = np.uint64(0x27D4EB2F165667C5)

    @classmethod
    def _chunk_pair_ids(cls, kind: str, L, li, R, ri) -> np.ndarray:
        """64-bit identity of each output row's (pair, kind) — used to
        detect whether the before and after emit-sets can overlap at all
        (only then can retraction-vs-insert rows cancel and the value-hash
        consolidation pay for itself)."""
        if kind == "pair":
            return (L.key[li] * cls._PAIR_C1) ^ (R.key[ri] * cls._PAIR_C2)
        if kind == "lpad":
            return L.key[li] * cls._PAIR_C3
        return R.key[ri] * cls._PAIR_C4

    def _chunk_okeys(self, kind: str, L, li, R, ri) -> np.ndarray:
        """Output keys for one chunk, derived through the batch hasher
        (byte-identical to the rowwise path's per-row ref_scalar)."""
        node = self.node
        if kind == "pair":
            if node.id_from == "left":
                return L.key[li]
            if node.id_from == "right":
                return R.key[ri]
            return ref_scalars_columns(
                [ptr_column(L.key[li]), ptr_column(R.key[ri])], len(li)
            )
        if kind == "lpad":
            lk = L.key[li]
            if node.id_from == "left":
                return lk
            return ref_scalars_columns(
                [ptr_column(lk), _none_col(len(li))], len(li)
            )
        rk = R.key[ri]
        if node.id_from == "right":
            return rk
        return ref_scalars_columns(
            [_none_col(len(ri)), ptr_column(rk)], len(ri)
        )

    def _chunk_columns(self, kind: str, L, li, R, ri, n: int) -> list:
        """Output value columns for one chunk: gathered side columns,
        None-padding for the unmatched side, and the _left_id/_right_id
        pointer columns (boxed only when the liveness pass says a
        downstream expression reads them)."""
        live = getattr(self.node, "_live_cols", None)
        cols: list[np.ndarray] = []
        if L is not None:
            cols.extend(c[li] for c in L.cols)
        else:
            cols.extend(_none_col(n) for _ in range(self.n_l))
        if R is not None:
            cols.extend(c[ri] for c in R.cols)
        else:
            cols.extend(_none_col(n) for _ in range(self.n_r))
        if L is not None and (live is None or "_left_id" in live):
            cols.append(_box_pointers(L.key[li]))
        else:
            cols.append(_none_col(n))
        if R is not None and (live is None or "_right_id" in live):
            cols.append(_box_pointers(R.key[ri]))
        else:
            cols.append(_none_col(n))
        return cols

    def _bulk_first_tick(self, dl: "_TickDelta", dr: "_TickDelta") -> list[DiffBatch]:
        """Insert-only inner join into empty state (the batch-analytics
        bulk load): no before-set exists, so matches emit straight from
        the C probe over the raw delta key arrays."""
        out: list[DiffBatch] = []
        li, ri = match_keys(dl.jks, dr.jks)
        total = len(li)
        if total:
            okeys = ref_scalars_columns(
                [ptr_column(dl.keys[li]), ptr_column(dr.keys[ri])], total
            )
            live = getattr(self.node, "_live_cols", None)
            names = self.node.column_names
            columns = {}
            ncol = 0
            for c in dl.cols:
                columns[names[ncol]] = c[li]
                ncol += 1
            for c in dr.cols:
                columns[names[ncol]] = c[ri]
                ncol += 1
            columns[names[ncol]] = (
                _box_pointers(dl.keys[li])
                if live is None or "_left_id" in live
                else _none_col(total)
            )
            columns[names[ncol + 1]] = (
                _box_pointers(dr.keys[ri])
                if live is None or "_right_id" in live
                else _none_col(total)
            )
            out.append(
                DiffBatch(okeys, np.ones(total, dtype=np.int64), columns)
            )
        self._commit_deltas(dl, dr)
        return out

    def _commit_deltas(self, dl: "_TickDelta", dr: "_TickDelta") -> None:
        """Apply the tick's deltas to BOTH arrangements atomically: stage
        (all allocations, may raise) before committing either side, so
        the exception fallback can never see one side's delta applied
        without the other's."""
        staged_l = self.arr_l.stage(
            dl.jks, dl.keys, dl.diffs, dl.cols,
            jk_order=dl.order, mix_sorted=dl.mix_sorted, clean=dl.clean,
        )
        staged_r = self.arr_r.stage(
            dr.jks, dr.keys, dr.diffs, dr.cols,
            jk_order=dr.order, mix_sorted=dr.mix_sorted, clean=dr.clean,
        )
        self.arr_l.commit(staged_l)
        self.arr_r.commit(staged_r)

    def _delta_tick(self, lb, rb, jks_l, jks_r) -> list[DiffBatch]:
        """One tick on the columnar path: probe arranged state for the
        touched jks, overlay the delta, emit the (before ⊖ after) diff."""
        node = self.node
        dl = _TickDelta(jks_l, lb)
        dr = _TickDelta(jks_r, rb)
        inner_simple = node.mode == "inner" and node.id_from is None
        if (
            inner_simple
            and dl.clean
            and dr.clean
            and not len(self.arr_l)
            and not len(self.arr_r)
        ):
            # first-tick bulk load into empty state: no probe, no
            # overlay, no before-set — emit the matches directly (the
            # batch-analytics fast path, on the same machinery)
            return self._bulk_first_tick(dl, dr)
        # touched jks from the per-side sorted deltas (no extra sort)
        if dl.n and dr.n:
            tj = merge_sorted(jks_l[dl.order], jks_r[dr.order])
        elif dl.n:
            tj = jks_l[dl.order]
        else:
            tj = jks_r[dr.order]
        if len(tj) > 1:
            keep = np.empty(len(tj), dtype=bool)
            keep[0] = True
            keep[1:] = tj[1:] != tj[:-1]
            touched = tj[keep]
        else:
            touched = tj
        # inner joins with a one-sided, collision-free delta never read
        # the quiet side's existing rows: pairs with two unchanged
        # endpoints cancel, there is no padding, and the overlay adds
        # only brand-new entries — skip that probe entirely
        skip_l = (
            inner_simple
            and dr.n == 0
            and dl.clean
            and not self.arr_l.overlaps(dl.mix)
        )
        skip_r = (
            inner_simple
            and dl.n == 0
            and dr.clean
            and not self.arr_r.overlaps(dr.mix)
        )
        before_l = (
            Rows.empty(self.n_l) if skip_l else self.arr_l.probe(touched)
        )
        before_r = (
            Rows.empty(self.n_r) if skip_r else self.arr_r.probe(touched)
        )
        # changed-row seeds: state rows whose (jk, key) the delta touches
        mix_bl = mix_keys(before_l.jk, before_l.key)
        mix_br = mix_keys(before_r.jk, before_r.key)
        sl_b = sorted_member(mix_bl, dl.mix_sorted)
        sr_b = sorted_member(mix_br, dr.mix_sorted)
        after_l = self._overlay(
            before_l, dl, self.arr_l.next_age(), sl_b, mix_bl
        )
        after_r = self._overlay(
            before_r, dr, self.arr_r.next_age(), sr_b, mix_br
        )
        # empty before-state: every after-row came from this delta
        sl_a = (
            np.ones(len(after_l), dtype=bool)
            if not len(before_l)
            else sorted_member(
                mix_keys(after_l.jk, after_l.key), dl.mix_sorted
            )
        )
        sr_a = (
            np.ones(len(after_r), dtype=bool)
            if not len(before_r)
            else sorted_member(
                mix_keys(after_r.jk, after_r.key), dr.mix_sorted
            )
        )
        # id_from can alias output keys across state versions, so those
        # joins recompute the touched jks fully; otherwise only pairs with
        # a delta-touched endpoint can change — everything else cancels
        full = node.id_from is not None
        if node.mode == "inner" and not full:
            # no padding, no full recompute: the per-jk group counts and
            # presence flips are never read
            jp_lb = jp_rb = jp_la = jp_ra = None
            lc_b = rc_b = lc_a = rc_a = None
            flip_l = flip_r = None
        else:
            jp_lb, lc_b = self._jk_positions(before_l, touched)
            jp_rb, rc_b = self._jk_positions(before_r, touched)
            jp_la, lc_a = self._jk_positions(after_l, touched)
            jp_ra, rc_a = self._jk_positions(after_r, touched)
            flip_l = (lc_b > 0) != (lc_a > 0)
            flip_r = (rc_b > 0) != (rc_a > 0)
        bef_parts = self._state_output(
            before_l, before_r, sl_b, sr_b, flip_l, flip_r,
            jp_lb, jp_rb, lc_b, rc_b, full,
        )
        aft_parts = self._state_output(
            after_l, after_r, sl_a, sr_a, flip_l, flip_r,
            jp_la, jp_ra, lc_a, rc_a, full,
        )
        out: list[DiffBatch] = []
        if bef_parts or aft_parts:
            okeys_l: list[np.ndarray] = []
            diffs_l: list[np.ndarray] = []
            col_parts: list[list[np.ndarray]] = [
                [] for _ in node.column_names
            ]
            for sign, chunks in ((-1, bef_parts), (1, aft_parts)):
                for kind, L, li, R, ri in chunks:
                    n = len(li) if li is not None else len(ri)
                    okeys_l.append(self._chunk_okeys(kind, L, li, R, ri))
                    diffs_l.append(np.full(n, sign, dtype=np.int64))
                    for ci, col in enumerate(
                        self._chunk_columns(kind, L, li, R, ri, n)
                    ):
                        col_parts[ci].append(col)
            batch = DiffBatch(
                np.concatenate(okeys_l).astype(np.uint64, copy=False),
                np.concatenate(diffs_l),
                {
                    name: concat_columns(col_parts[ci])
                    for ci, name in enumerate(node.column_names)
                },
            )
            if bef_parts and aft_parts:
                # unchanged re-emissions cancel retraction-vs-insert in
                # consolidate() — but value-hashing every emitted row is
                # the dominant cost of retraction ticks, so only pay it
                # when the two emit-sets actually share a pair (disjoint
                # sets — pure insert+retract churn — cannot cancel)
                ids_b = np.sort(
                    np.concatenate(
                        [self._chunk_pair_ids(*c) for c in bef_parts]
                    )
                )
                ids_a = np.concatenate(
                    [self._chunk_pair_ids(*c) for c in aft_parts]
                )
                if sorted_member(ids_a, ids_b).any():
                    batch = batch.consolidate()
            if len(batch):
                out.append(batch)
        # commit the delta into arranged state only after the pure
        # computation succeeded (the exception fallback must see pre-tick
        # state); the append reuses this tick's sort + fingerprints
        self._commit_deltas(dl, dr)
        return out

    def _drop_error_keys(
        self, b: DiffBatch, on_idx: list[int]
    ) -> tuple[DiffBatch, DiffBatch | None]:
        """Rows whose join-key columns hold ERROR are skipped and logged
        (reference: join condition error handling, dataflow.rs join
        arrangement Error filtering)."""
        from pathway_tpu.internals.api import Error

        cols = list(b.columns.values())
        bad = None
        for i in on_idx:
            col = cols[i]
            if col.dtype == object:
                m = np.fromiter(
                    (isinstance(v, Error) for v in col), bool, count=len(b)
                )
                bad = m if bad is None else (bad | m)
        if bad is None or not bad.any():
            return b, None
        for _ in range(int(bad.sum())):
            record_error(
                "Error value encountered in join condition, "
                "skipping the row",
                str(self.node),
            )
        return b.mask(~bad), b.mask(bad)

    def _outer_rows_for_dropped(
        self, dropped: DiffBatch, side: str
    ) -> list[tuple[int, int, tuple]]:
        """Error-keyed rows never match, but outer joins still surface
        them as unmatched rows of their side (reference: left join keeps
        the Error row with nulls on the other side)."""
        node = self.node
        out = []
        for k, d, vals in dropped.iter_rows():
            if side == "left":
                okey = k if node.id_from == "left" else int(
                    ref_scalar(Pointer(k), None)
                )
                out.append(
                    (okey, d, vals + (None,) * self.n_r + (Pointer(k), None))
                )
            else:
                okey = k if node.id_from == "right" else int(
                    ref_scalar(None, Pointer(k))
                )
                out.append(
                    (okey, d, (None,) * self.n_l + vals + (None, Pointer(k)))
                )
        return out

    def process(self, t, inputs):
        lb = _concat_inputs(inputs[0], self.node.inputs[0].column_names)
        rb = _concat_inputs(inputs[1], self.node.inputs[1].column_names)
        outer_rows: list[tuple[int, int, tuple]] = []
        if len(lb):
            lb, dropped = self._drop_error_keys(lb, self.l_on_idx)
            if dropped is not None and self.node.mode in ("left", "outer"):
                outer_rows.extend(self._outer_rows_for_dropped(dropped, "left"))
        if len(rb):
            rb, dropped = self._drop_error_keys(rb, self.r_on_idx)
            if dropped is not None and self.node.mode in ("right", "outer"):
                outer_rows.extend(
                    self._outer_rows_for_dropped(dropped, "right")
                )
        extra = (
            [DiffBatch.from_rows(outer_rows, self.node.column_names)]
            if outer_rows
            else []
        )
        if not len(lb) and not len(rb):
            return extra
        jks_l = (
            self._batch_jks(lb, self.l_on_idx, "l")
            if len(lb)
            else np.empty(0, np.uint64)
        )
        jks_r = (
            self._batch_jks(rb, self.r_on_idx, "r")
            if len(rb)
            else np.empty(0, np.uint64)
        )
        if not self._rowwise:
            try:
                out = self._delta_tick(lb, rb, jks_l, jks_r)
            except Exception as exc:
                # degraded-but-running: log, materialize dict state from
                # the (un-mutated) arrangements, finish the tick rowwise
                record_error(exc, str(self.node))
                self._to_rowwise("exception")
            else:
                self._m_hits.inc()
                return extra + out
        self._m_fallbacks.labels(self._fallback_reason or "unknown").inc()
        return extra + self._process_rowwise(lb, rb, jks_l, jks_r)

    def _process_rowwise(self, lb, rb, jks_l, jks_r) -> list[DiffBatch]:
        """Touched-jk dict recompute — the differential-testing oracle."""
        touched: dict[int, None] = {}
        jl = jks_l.tolist()
        l_updates = []
        for i, (k, d, vals) in enumerate(lb.iter_rows()):
            jk = jl[i]
            touched[jk] = None
            l_updates.append((jk, k, d, vals))
        jr = jks_r.tolist()
        r_updates = []
        for i, (k, d, vals) in enumerate(rb.iter_rows()):
            jk = jr[i]
            touched[jk] = None
            r_updates.append((jk, k, d, vals))
        before = {jk: self._outputs_for_jk(jk) for jk in touched}
        for jk, k, d, vals in l_updates:
            self.left.apply(jk, k, d, vals)
        for jk, k, d, vals in r_updates:
            self.right.apply(jk, k, d, vals)
        from pathway_tpu.engine.batch import _values_eq

        out_rows: list[tuple[int, int, tuple]] = []
        for jk in touched:
            after = self._outputs_for_jk(jk)
            bef = before[jk]
            for okey, vals in bef.items():
                new = after.get(okey)
                if new is None or not _values_eq(vals, new):
                    out_rows.append((okey, -1, vals))
            for okey, vals in after.items():
                old = bef.get(okey)
                if old is None or not _values_eq(old, vals):
                    out_rows.append((okey, 1, vals))
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Concat / union


class ConcatNode(Node):
    def __init__(self, inputs: Sequence[Node]):
        super().__init__(inputs, inputs[0].column_names)

    def make_exec(self):
        return ConcatExec(self)


class ConcatExec(NodeExec):
    def process(self, t, inputs):
        out = []
        for inp_node, batches in zip(self.node.inputs, inputs):
            for b in batches:
                if len(b):
                    out.append(b.select_columns(self.node.column_names))
        return out


# ---------------------------------------------------------------------------
# Update rows / cells (reference: Table.update_rows / update_cells)


class UpdateRowsNode(Node):
    is_stateful = True

    def __init__(self, left: Node, right: Node):
        super().__init__([left, right], left.column_names)

    def _make_local_exec(self):
        return UpdateRowsExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnUpdateRowsExec

            return DcnUpdateRowsExec(self)
        return self._make_local_exec()


class UpdateRowsExec(NodeExec):
    """Right rows override left rows on key collision; union of key sets."""

    def __init__(self, node):
        super().__init__(node)
        self.states = [
            MultisetState(node.inputs[0].column_names),
            MultisetState(node.inputs[1].column_names),
        ]
        self.emitted: dict[int, tuple] = {}
        rcols = node.inputs[1].column_names
        self.r_order = [rcols.index(c) for c in node.column_names]

    def process(self, t, inputs):
        touched: dict[int, None] = {}
        for state, batches in zip(self.states, inputs):
            for b in batches:
                for k, d, vals in b.iter_rows():
                    touched[k] = None
                    state.apply_row(k, d, vals)
        from pathway_tpu.engine.batch import _values_eq

        out_rows = []
        for k in touched:
            rrow = self.states[1].get(k)
            if rrow is not None:
                new = tuple(rrow[i] for i in self.r_order)
            else:
                new = self.states[0].get(k)
            old = self.emitted.get(k)
            if old is not None and new is not None and _values_eq(old, new):
                continue
            if old is not None:
                out_rows.append((k, -1, old))
                del self.emitted[k]
            if new is not None:
                out_rows.append((k, 1, new))
                self.emitted[k] = new
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Flatten


class RemoveRetractionsNode(Node):
    """Append-only view: deletions are dropped (reference:
    Table._remove_retractions, internals/table.py)."""

    def __init__(self, input: Node):
        super().__init__([input], input.column_names)

    def make_exec(self):
        return RemoveRetractionsExec(self)


class RemoveRetractionsExec(NodeExec):
    def process(self, t, inputs):
        out = []
        for b in inputs[0]:
            m = b.diffs > 0
            if m.all():
                out.append(b)
            elif m.any():
                out.append(b.mask(m))
        return out


class FlattenNode(Node):
    """(reference: Graph::flatten_table; Table.flatten internals/table.py:2089)"""

    def __init__(
        self, input: Node, flatten_col: str, origin_id: str | None = None
    ):
        cols = list(input.column_names)
        if origin_id is not None:
            cols.append(origin_id)
        super().__init__([input], cols)
        self.flatten_col = flatten_col
        self.origin_id = origin_id

    def make_exec(self):
        return FlattenExec(self)


class FlattenExec(NodeExec):
    """Columnar flatten: expand the container column per row, then build
    all output columns by np.repeat/fancy-indexing and derive the output
    keys with ONE batch hash over (parent pointer, item index) — the
    per-output-row blake2b of the rowwise version dominated flatten-heavy
    pipelines (e.g. the fuzzy join's token-edge expansion)."""

    def process(self, t, inputs):
        node = self.node
        in_cols = node.inputs[0].column_names
        fidx = in_cols.index(node.flatten_col)
        out = []
        from pathway_tpu.engine.batch import _obj_column
        from pathway_tpu.internals.api import ref_scalars_columns

        for b in inputs[0]:
            n = len(b)
            if not n:
                continue
            cols = list(b.columns.values())
            items_all: list = []
            counts = np.zeros(n, dtype=np.int64)
            for i, container in enumerate(cols[fidx].tolist()):
                if container is None:
                    continue
                if isinstance(container, Json):
                    # only JSON arrays flatten (reference test_json.py
                    # test_json_flatten_wrong_values)
                    if not isinstance(container.value, list):
                        record_error(
                            ValueError(
                                f"Pathway can't flatten this Json: {container}"
                            ),
                            str(node),
                        )
                        continue
                    items = [Json(x) for x in container.value]
                else:
                    try:
                        items = list(container)
                    except TypeError:
                        record_error(
                            TypeError(f"cannot flatten {container!r}"),
                            str(node),
                        )
                        continue
                counts[i] = len(items)
                items_all.extend(items)
            total = int(counts.sum())
            if not total:
                continue
            rep = np.repeat(np.arange(n), counts)
            idx_within = np.arange(total) - np.repeat(
                np.cumsum(counts) - counts, counts
            )
            from pathway_tpu.internals.api import ptr_column

            nkeys = ref_scalars_columns(
                [ptr_column(b.keys[rep]), idx_within], total
            )
            new_cols = {}
            for ci, name in enumerate(in_cols):
                if ci == fidx:
                    new_cols[name] = _obj_column(items_all)
                else:
                    new_cols[name] = cols[ci][rep]
            if node.origin_id is not None:
                new_cols[node.origin_id] = _obj_column(
                    list(map(Pointer, b.keys[rep].tolist()))
                )
            out.append(DiffBatch(nkeys, b.diffs[rep], new_cols))
        return out


# ---------------------------------------------------------------------------
# Sort (prev/next pointers)


class SortNode(Node):
    """Incremental prev/next pointers over a sorted order
    (reference: src/engine/dataflow/operators/prev_next.rs)."""

    is_stateful = True

    def __init__(self, input: Node, key_col: str, instance_col: str | None):
        super().__init__([input], ["prev", "next"])
        self.key_col = key_col
        self.instance_col = instance_col

    def key_columns(self) -> tuple[str, ...]:
        out = (self.key_col,)
        if self.instance_col:
            out += (self.instance_col,)
        return out

    def _make_local_exec(self):
        from pathway_tpu.parallel.mesh import get_engine_mesh

        em = get_engine_mesh()
        # instance-less sort is one global order: sharding would route
        # every row to shard 0 and pay exchange overhead for nothing
        if em is not None and self.instance_col is not None:
            from pathway_tpu.engine.sharded import ShardedSortExec

            return ShardedSortExec(self, em[0], em[1])
        return SortExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnSortExec

            return DcnSortExec(self)
        return self._make_local_exec()


class SortExec(NodeExec):
    """Incremental prev/next maintenance: a sorted (sortval, rowkey) list
    per instance, updated by bisect so a tick touching c rows costs
    O(c log n) comparisons and emits only the changed pointer pairs — the
    microbatch analog of the reference's pointer-maintaining prev_next
    operator (src/engine/dataflow/operators/prev_next.rs:1-891). Ticks that
    change a large fraction of an instance fall back to one full sort."""

    def __init__(self, node: SortNode):
        super().__init__(node)
        in_cols = node.inputs[0].column_names
        self.k_idx = in_cols.index(node.key_col)
        self.i_idx = (
            in_cols.index(node.instance_col) if node.instance_col else None
        )
        # instance -> {rowkey: sortval}
        self.instances: dict[Any, dict[int, Any]] = {}
        # instance -> maintained sorted list[(sortval, rowkey)]
        self.orders: dict[Any, list] = {}
        # instance -> {rowkey: (prev, next)} previously emitted
        self.emitted: dict[Any, dict[int, tuple]] = {}
        # instances that ever saw a NaN sort key: bisect cannot locate NaN
        # tuples, so those instances stay on the full-rebuild path
        self.nan_insts: set = set()

    def _emit_diff(self, out_rows, emitted, k, new):
        old = emitted.get(k)
        if old == new:
            return
        if old is not None:
            out_rows.append((k, -1, old))
        if new is not None:
            out_rows.append((k, 1, new))
            emitted[k] = new
        else:
            emitted.pop(k, None)

    def _rebuild(self, out_rows, rows, order, emitted):
        order[:] = sorted((v, k) for k, v in rows.items())
        new_vals: dict[int, tuple] = {}
        n = len(order)
        for i, (_, k) in enumerate(order):
            prev_k = Pointer(order[i - 1][1]) if i > 0 else None
            next_k = Pointer(order[i + 1][1]) if i < n - 1 else None
            new_vals[k] = (prev_k, next_k)
        for k in set(emitted) | set(new_vals):
            self._emit_diff(out_rows, emitted, k, new_vals.get(k))

    def _drop_entry(self, order, affected, v, k, bisect_left) -> None:
        idx = bisect_left(order, (v, k))
        if idx < len(order) and order[idx] == (v, k):
            order.pop(idx)
            # the two rows that now become neighbors
            if idx > 0:
                affected.add(order[idx - 1][1])
            if idx < len(order):
                affected.add(order[idx][1])

    def _incremental(self, out_rows, rows, order, emitted, chs, bisect_left):
        affected: set[int] = set()
        deleted: set[int] = set()
        for k, d, v in chs:
            if d > 0:
                if k in rows:
                    # upsert / repeated insert: drop the stale order entry
                    # first or it would linger as a ghost (the rows dict is
                    # last-write-wins, matching the full-rebuild path)
                    self._drop_entry(order, affected, rows[k], k, bisect_left)
                rows[k] = v
                idx = bisect_left(order, (v, k))
                # the two rows that will now point at k
                if idx > 0:
                    affected.add(order[idx - 1][1])
                if idx < len(order):
                    affected.add(order[idx][1])
                order.insert(idx, (v, k))
                affected.add(k)
                deleted.discard(k)
            else:
                if k not in rows:
                    continue
                v_old = rows.pop(k)
                self._drop_entry(order, affected, v_old, k, bisect_left)
                deleted.add(k)
                affected.discard(k)
        for k in deleted:
            self._emit_diff(out_rows, emitted, k, None)
        n = len(order)
        for k in affected:
            v = rows.get(k)
            if v is None and k not in rows:
                continue  # re-deleted within this tick
            idx = bisect_left(order, (v, k))
            prev_k = Pointer(order[idx - 1][1]) if idx > 0 else None
            next_k = Pointer(order[idx + 1][1]) if idx < n - 1 else None
            self._emit_diff(out_rows, emitted, k, (prev_k, next_k))

    def process(self, t, inputs):
        from bisect import bisect_left

        changes: dict[Any, list] = {}
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                inst = vals[self.i_idx] if self.i_idx is not None else None
                changes.setdefault(inst, []).append((k, d, vals[self.k_idx]))
        out_rows: list[tuple[int, int, tuple]] = []
        for inst, chs in changes.items():
            rows = self.instances.setdefault(inst, {})
            order = self.orders.setdefault(inst, [])
            emitted = self.emitted.setdefault(inst, {})
            if inst not in self.nan_insts and any(
                isinstance(v, float) and v != v for _k, _d, v in chs
            ):
                self.nan_insts.add(inst)
            if inst in self.nan_insts or len(chs) * 8 >= len(order) + 1:
                for k, d, v in chs:
                    if d > 0:
                        rows[k] = v
                    else:
                        rows.pop(k, None)
                self._rebuild(out_rows, rows, order, emitted)
            else:
                self._incremental(
                    out_rows, rows, order, emitted, chs, bisect_left
                )
            if not rows:
                self.instances.pop(inst, None)
                self.orders.pop(inst, None)
                self.emitted.pop(inst, None)
                self.nan_insts.discard(inst)
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Gradual broadcast


class GradualBroadcastNode(Node):
    """Roll out a changing scalar (model version, threshold, ...) to all
    rows without mass retraction (reference:
    src/engine/dataflow/operators/gradual_broadcast.rs:1-490, API at
    python/pathway/internals/table.py:631). The threshold table supplies a
    (lower, value, upper) triplet; each data row gets apx_value = upper if
    its key hash falls below the (value-lower)/(upper-lower) fraction of
    the key space, else lower — so as `value` sweeps lower->upper, rows
    flip individually instead of all at once."""

    is_stateful = True

    def __init__(self, data: Node, thr: Node):
        super().__init__([data, thr], ["apx_value"])

    def _make_local_exec(self):
        return GradualBroadcastExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnGradualBroadcastExec

            return DcnGradualBroadcastExec(self)
        return self._make_local_exec()


_KEY_SPACE = float(1 << 64)


class GradualBroadcastExec(NodeExec):
    def __init__(self, node: GradualBroadcastNode):
        super().__init__(node)
        self.counts: dict[int, int] = {}  # data rowkey -> multiplicity
        self.keys_sorted: list[int] = []
        self.thr_state: dict[int, list] = {}  # thr rowkey -> [vals, count]
        self.triplet: tuple | None = None
        self.emitted: dict[int, Any] = {}  # data rowkey -> apx value

    @staticmethod
    def _threshold(triplet) -> int:
        lower, value, upper = triplet
        if upper == lower:
            frac = 1.0
        else:
            frac = (value - lower) / (upper - lower)
        frac = min(max(frac, 0.0), 1.0)
        return int(frac * _KEY_SPACE)

    @staticmethod
    def _apx(k: int, triplet, thr: int):
        return triplet[2] if k < thr else triplet[0]

    def process(self, t, inputs):
        from bisect import bisect_left, insort

        out_rows: list[tuple[int, int, tuple]] = []
        # 1) data-side changes evaluated under the current triplet
        #    (reference: input1 batches apply with the pre-update triplet)
        thr_now = self._threshold(self.triplet) if self.triplet else None
        for b in inputs[0]:
            for k, d in zip(b.keys.tolist(), b.diffs.tolist()):
                c = self.counts.get(k, 0)
                nc = c + d
                if c <= 0 < nc:
                    insort(self.keys_sorted, k)
                    if self.triplet is not None:
                        v = self._apx(k, self.triplet, thr_now)
                        out_rows.append((k, 1, (v,)))
                        self.emitted[k] = v
                elif nc <= 0 < c:
                    idx = bisect_left(self.keys_sorted, k)
                    if idx < len(self.keys_sorted) and self.keys_sorted[idx] == k:
                        self.keys_sorted.pop(idx)
                    old = self.emitted.pop(k, None)
                    if old is not None:
                        out_rows.append((k, -1, (old,)))
                if nc == 0:
                    self.counts.pop(k, None)
                else:
                    self.counts[k] = nc
        # 2) threshold-side changes
        last_inserted = None
        thr_changed = False
        for b in inputs[1]:
            for k, d, vals in b.iter_rows():
                thr_changed = True
                e = self.thr_state.get(k)
                if e is None:
                    if d != 0:
                        self.thr_state[k] = [vals, d]
                else:
                    e[1] += d
                    if d > 0:
                        e[0] = vals
                    if e[1] == 0:
                        del self.thr_state[k]
                if d > 0:
                    last_inserted = vals
        if thr_changed:
            if last_inserted is not None:
                new_triplet = tuple(last_inserted[:3])
            elif self.thr_state:
                new_triplet = tuple(next(iter(self.thr_state.values()))[0][:3])
            else:
                new_triplet = self.triplet  # emptied: keep last (ref. keeps)
            if new_triplet is not None and new_triplet != self.triplet:
                old_triplet = self.triplet
                self.triplet = new_triplet
                thr_new = self._threshold(new_triplet)
                if old_triplet is None:
                    for k in self.keys_sorted:
                        v = self._apx(k, new_triplet, thr_new)
                        out_rows.append((k, 1, (v,)))
                        self.emitted[k] = v
                else:
                    # both apx functions are two-valued step functions with
                    # one breakpoint, so they differ on at most 3 contiguous
                    # key ranges — emit diffs only there (the "gradual"
                    # property: a value sweep touches only the swept range)
                    thr_old = self._threshold(old_triplet)
                    t1, t2 = min(thr_old, thr_new), max(thr_old, thr_new)
                    ks = self.keys_sorted
                    for seg_lo, seg_hi in ((0, t1), (t1, t2), (t2, 1 << 64)):
                        if seg_lo >= seg_hi:
                            continue
                        old_v = self._apx(seg_lo, old_triplet, thr_old)
                        new_v = self._apx(seg_lo, new_triplet, thr_new)
                        if old_v == new_v:
                            continue
                        lo_i = bisect_left(ks, seg_lo)
                        hi_i = bisect_left(ks, seg_hi)
                        for k in ks[lo_i:hi_i]:
                            out_rows.append((k, -1, (self.emitted[k],)))
                            out_rows.append((k, 1, (new_v,)))
                            self.emitted[k] = new_v
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Deduplicate


class DeduplicateNode(Node):
    """(reference: deduplicate, src/engine/dataflow.rs:3514)"""

    is_stateful = True

    def __init__(
        self,
        input: Node,
        instance_cols: Sequence[str],
        acceptor: Callable[[Any, Any], bool] | None,
        value_col: str | None,
    ):
        super().__init__([input], input.column_names)
        self.instance_cols = list(instance_cols)
        self.acceptor = acceptor
        self.value_col = value_col

    def key_columns(self) -> tuple[str, ...]:
        return tuple(self.instance_cols)

    def _make_local_exec(self):
        return DeduplicateExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnDeduplicateExec

            return DcnDeduplicateExec(self)
        return self._make_local_exec()


class DeduplicateExec(NodeExec):
    """Deduplicate over columnar arranged state.

    The accepted row per instance lives in an Arrangement (one net entry
    per instance hash, engine/arrangement.py): a tick derives instance
    keys with the C batch hasher, probes the touched instances with one
    searchsorted pass, decides acceptance vectorized (acceptor-None
    collapses to a compare-against-predecessor scan; a user acceptor
    folds per touched group), emits the NET per-instance change, and
    appends the retract/insert delta back into the arrangement — so
    bulk loads are columnar and snapshots are incremental segments.
    The per-row dict path survives as the differential-testing oracle
    (PATHWAY_STATE_ROWWISE=1) and as the exception escape hatch."""

    # persisted under its own identity even when inputs re-feed every run
    # (reference: deduplicate keeps state via its persistent id,
    # operators/stateful_reduce.rs non-retractable accumulators)
    persist_standalone = True

    def __init__(self, node: DeduplicateNode):
        super().__init__(node)
        in_cols = node.inputs[0].column_names
        self.inst_idx = [in_cols.index(c) for c in node.instance_cols]
        self.val_idx = (
            in_cols.index(node.value_col) if node.value_col else None
        )
        self.n_cols = len(in_cols)
        # instance key -> (accepted value, emitted row vals, out key) —
        # the rowwise oracle/fallback representation only
        self.state: dict[int, tuple] = {}
        self.arr = Arrangement(self.n_cols)
        self._rowwise = False
        self._fallback_reason: str | None = None
        self._m_fallbacks = _fallback_counter()
        if _state_rowwise_env():
            self._to_rowwise("env")

    # --- fallback / oracle management -----------------------------------

    def _to_rowwise(self, reason: str) -> None:
        """Materialize dict state from the arrangement and stay rowwise
        from here on (degraded-but-running contract)."""
        self._rowwise = True
        self._fallback_reason = reason
        self._m_fallbacks.labels(type(self).__name__, reason).inc()
        rows = self.arr.entries()
        if len(rows):
            cols = [c.tolist() for c in rows.cols]
            vals_it: Any = zip(*cols) if cols else iter([()] * len(rows))
            for jk, vals in zip(rows.jk.tolist(), vals_it):
                vals = tuple(vals)
                value = (
                    vals[self.val_idx] if self.val_idx is not None else vals
                )
                self.state[int(jk)] = (value, vals, int(jk))
        self.arr = Arrangement(self.n_cols)

    # --- operator snapshots ---------------------------------------------

    def arranged_state(self):
        if self._rowwise:
            return None
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("node", "arr", "state", "_restore_emit")
            and not k.startswith("_m_")
        }
        return residual, {"arr": self.arr}

    def load_arranged_state(self, residual, arrangements) -> None:
        self.__dict__.update(residual)
        self.arr = arrangements["arr"]
        self.state = {}
        if _state_rowwise_env():
            self._rowwise = False  # residual was snapshotted columnar
            self._to_rowwise("env")
        self._set_restore_emit()

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        if not self._rowwise and "arr" not in state and self.state:
            # legacy monolith snapshot (pre-arrangement dict state): seed
            # the arrangement so the columnar path continues with the
            # restored accepted rows instead of re-accepting duplicates
            entries = list(self.state.values())
            jks = np.asarray(
                [ik for (_v, _vals, ik) in entries], dtype=np.uint64
            )
            cols = []
            for ci in range(self.n_cols):
                col = np.empty(len(entries), dtype=object)
                col[:] = [vals[ci] for (_v, vals, _ik) in entries]
                cols.append(col)
            self.arr = Arrangement(self.n_cols)
            self.arr.append(
                jks, jks, np.ones(len(entries), dtype=np.int64), cols
            )
            self.state = {}
        self._set_restore_emit()

    def _set_restore_emit(self) -> None:
        # restored accumulator output re-emits on the first tick of the new
        # run so downstream consumers rebuild (reference: a restored
        # arrangement feeds its consolidated contents to consumers at the
        # initial time). The persistence glue clears this when the graph
        # restored downstream state too (inputs do not re-feed).
        if self.state:
            self._restore_emit = [
                (ik, 1, vals) for (_value, vals, ik) in self.state.values()
            ]
        else:
            rows = self.arr.entries()
            cols = [c.tolist() for c in rows.cols]
            vals_it: Any = zip(*cols) if cols else iter([()] * len(rows))
            self._restore_emit = [
                (int(jk), 1, tuple(vals))
                for jk, vals in zip(rows.jk.tolist(), vals_it)
            ]

    def state_dict(self) -> dict | None:
        state = super().state_dict()
        if state is not None:
            state.pop("_restore_emit", None)
        return state

    # --- columnar path ---------------------------------------------------

    def _accept_vectorized(self, cols, order, starts, prev, has_prev, prev_pos):
        """Acceptor-None acceptance: a row is accepted iff its value
        differs from its predecessor in the instance's sequence (the
        stored value for group firsts; no stored value accepts
        unconditionally).  Returns (selected original row per group,
        changed mask) — the last accepted row is the net new state."""
        n = len(order)
        cmp_idx = (
            [self.val_idx] if self.val_idx is not None else range(self.n_cols)
        )
        eq = np.ones(n, dtype=bool)
        for ci in cmp_idx:
            sc = cols[ci][order]
            e = np.empty(n, dtype=bool)
            e[0] = False
            if n > 1:
                e[1:] = _column_eq(sc[1:], sc[:-1])
            eq &= e
        first_eq = np.zeros(len(starts), dtype=bool)
        if len(prev) and has_prev.any():
            pi = prev_pos[has_prev]
            fe = np.ones(int(has_prev.sum()), dtype=bool)
            first_rows = order[starts[has_prev]]
            for ci in cmp_idx:
                fe &= _column_eq(cols[ci][first_rows], prev.cols[ci][pi])
            first_eq[has_prev] = fe
        eq[starts] = first_eq
        accept = ~eq
        posm = np.where(accept, np.arange(n, dtype=np.int64), np.int64(-1))
        last = np.maximum.reduceat(posm, starts)
        changed = last >= 0
        sel = order[np.where(changed, last, 0)]
        return sel, changed

    def _accept_acceptor(self, cols, order, starts, prev, has_prev, prev_pos):
        """User-acceptor acceptance: fold each touched instance's rows in
        arrival order.  An acceptor exception poisons ONLY that row —
        recorded, nothing emitted, stored state untouched — and the fold
        continues with the unchanged accepted value."""
        node = self.node
        n = len(order)
        g = len(starts)
        sel = np.zeros(g, dtype=np.int64)
        changed = np.zeros(g, dtype=bool)
        py_cols = [c.tolist() for c in cols]
        prev_py = [c.tolist() for c in prev.cols]
        val_idx = self.val_idx
        ends = np.empty(g, dtype=np.int64)
        ends[:-1] = starts[1:]
        ends[-1] = n
        for gi in range(g):
            have = bool(has_prev[gi])
            cur_value = None
            if have:
                pv = tuple(pc[prev_pos[gi]] for pc in prev_py)
                cur_value = pv[val_idx] if val_idx is not None else pv
            sel_i = -1
            for p in range(int(starts[gi]), int(ends[gi])):
                ri = int(order[p])
                vals = tuple(pc[ri] for pc in py_cols)
                value = vals[val_idx] if val_idx is not None else vals
                if have:
                    # the first value per instance is accepted without
                    # consulting the acceptor (reference: stateful_reduce
                    # passes None state only to the combine_fn, and the
                    # deduplicate acceptor never sees old_value=None)
                    try:
                        if not bool(node.acceptor(value, cur_value)):
                            continue
                    except Exception as exc:
                        record_error(exc, str(node))
                        continue
                have = True
                cur_value = value
                sel_i = ri
            if sel_i >= 0:
                changed[gi] = True
                sel[gi] = sel_i
        return sel, changed

    def _process_arranged(self, b: DiffBatch) -> list[DiffBatch]:
        if bool((b.diffs < 0).any()):
            b = b.mask(b.diffs >= 0)  # append-only semantics
            if not len(b):
                return []
        n = len(b)
        cols = list(b.columns.values())
        iks = ref_scalars_columns([cols[i] for i in self.inst_idx], n)
        order = np.argsort(iks, kind="stable")
        iks_s = iks[order]
        boundary = np.empty(n, dtype=bool)
        boundary[0] = True
        boundary[1:] = iks_s[1:] != iks_s[:-1]
        starts = np.nonzero(boundary)[0]
        touched = iks_s[starts]  # sorted unique instance keys
        g = len(starts)
        prev = self.arr.probe(touched)  # one net entry per stored instance
        has_prev = np.zeros(g, dtype=bool)
        prev_pos = np.zeros(g, dtype=np.int64)
        if len(prev):
            pos = np.searchsorted(touched, prev.jk)
            has_prev[pos] = True
            prev_pos[pos] = np.arange(len(prev), dtype=np.int64)
        if self.node.acceptor is None:
            sel, changed = self._accept_vectorized(
                cols, order, starts, prev, has_prev, prev_pos
            )
        else:
            sel, changed = self._accept_acceptor(
                cols, order, starts, prev, has_prev, prev_pos
            )
        if not changed.any():
            return []
        ch = np.nonzero(changed)[0]
        sel_rows = sel[changed]
        out_ik = touched[ch]
        ret_mask = has_prev[ch]
        nr = int(ret_mask.sum())
        ppos = prev_pos[ch][ret_mask]
        new_cols = [c[sel_rows] for c in cols]
        keys_parts = [out_ik[ret_mask], out_ik] if nr else [out_ik]
        diffs_parts = (
            [np.full(nr, -1, dtype=np.int64), np.ones(len(ch), np.int64)]
            if nr
            else [np.ones(len(ch), np.int64)]
        )
        col_parts = [
            ([prev.cols[i][ppos], new_cols[i]] if nr else [new_cols[i]])
            for i in range(self.n_cols)
        ]
        out = DiffBatch(
            np.concatenate(keys_parts),
            np.concatenate(diffs_parts),
            {
                name: concat_columns(col_parts[i])
                for i, name in enumerate(self.node.column_names)
            },
        )
        # commit the delta into arranged state LAST (pure computation
        # above may raise; the fallback must see pre-tick state): retract
        # entries first so consolidation picks the insert as the value
        d_jks = np.concatenate(keys_parts)
        self.arr.append(
            d_jks,
            d_jks,  # rowkey == jk: exactly one live entry per instance
            np.concatenate(diffs_parts),
            [concat_columns(col_parts[i]) for i in range(self.n_cols)],
        )
        return [out]

    # --- rowwise oracle / fallback ---------------------------------------

    def _process_rowwise(self, inputs) -> list[DiffBatch]:
        out_rows = []
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                if d < 0:
                    continue  # append-only semantics
                ivals = tuple(vals[i] for i in self.inst_idx)
                ik = int(ref_scalar(*ivals))
                value = vals[self.val_idx] if self.val_idx is not None else vals
                prev = self.state.get(ik)
                prev_value = prev[0] if prev else None
                accept = True
                if self.node.acceptor is not None and prev is not None:
                    try:
                        accept = bool(self.node.acceptor(value, prev_value))
                    except Exception as exc:
                        record_error(exc, str(self.node))
                        accept = False
                elif prev is not None and prev_value == value:
                    accept = False
                if not accept:
                    continue
                if prev is not None:
                    out_rows.append((prev[2], -1, prev[1]))
                self.state[ik] = (value, vals, ik)
                out_rows.append((ik, 1, vals))
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]

    def process(self, t, inputs):
        pre: list[DiffBatch] = []
        pending = getattr(self, "_restore_emit", None)
        if pending:
            pre = [DiffBatch.from_rows(pending, self.node.column_names)]
            self._restore_emit = None
        if self._rowwise:
            return pre + self._process_rowwise(inputs)
        b = _concat_inputs(inputs[0], self.node.inputs[0].column_names)
        if not len(b):
            return pre
        try:
            return pre + self._process_arranged(b)
        except Exception:
            import logging

            logging.getLogger("pathway_tpu").exception(
                "deduplicate columnar path failed; falling back to the "
                "rowwise path for node %s", self.node
            )
            self._to_rowwise("exception")
            return pre + self._process_rowwise(inputs)


# ---------------------------------------------------------------------------
# Ix (pointer lookup)


class IxNode(Node):
    """t2.ix(t1.ptr_col): fetch the row of `indexed` pointed to by a pointer
    column of `indexer`; result lives on the indexer's universe
    (reference: Graph::ix / Table.ix, internals/table.py:1164)."""

    is_stateful = True

    def __init__(
        self, indexer: Node, ptr_col: str, indexed: Node, optional: bool
    ):
        super().__init__([indexer, indexed], indexed.column_names)
        self.ptr_col = ptr_col
        self.optional = optional

    def _make_local_exec(self):
        return IxExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnIxExec

            return DcnIxExec(self)
        return self._make_local_exec()


class IxExec(NodeExec):
    def __init__(self, node: IxNode):
        super().__init__(node)
        self.indexer = MultisetState(node.inputs[0].column_names)
        self.indexed = MultisetState(node.inputs[1].column_names)
        self.reverse: dict[int, set[int]] = {}  # target key -> indexer keys
        self.emitted: dict[int, tuple] = {}
        self.ptr_idx = node.inputs[0].column_names.index(node.ptr_col)

    def process(self, t, inputs):
        touched: dict[int, None] = {}
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                old_row = self.indexer.get(k)
                if old_row is not None:
                    old_ptr = old_row[self.ptr_idx]
                    if old_ptr is not None:
                        s = self.reverse.get(int(old_ptr))
                        if s:
                            s.discard(k)
                self.indexer.apply_row(k, d, vals)
                new_row = self.indexer.get(k)
                if new_row is not None:
                    ptr = new_row[self.ptr_idx]
                    if ptr is not None:
                        self.reverse.setdefault(int(ptr), set()).add(k)
                touched[k] = None
        for b in inputs[1]:
            for k, d, vals in b.iter_rows():
                self.indexed.apply_row(k, d, vals)
                for ik in self.reverse.get(k, ()):
                    touched[ik] = None
        from pathway_tpu.engine.batch import _values_eq

        out_rows = []
        for k in touched:
            row = self.indexer.get(k)
            new = None
            if row is not None:
                ptr = row[self.ptr_idx]
                target = self.indexed.get(int(ptr)) if ptr is not None else None
                if target is not None:
                    new = target
                elif self.node.optional:
                    new = (None,) * len(self.node.column_names)
                else:
                    record_error(
                        KeyError(f"ix: no row with id {ptr!r}"), str(self.node)
                    )
                    new = tuple(ERROR for _ in self.node.column_names)
            old = self.emitted.get(k)
            if old is not None and new is not None and _values_eq(old, new):
                continue
            if old is not None:
                out_rows.append((k, -1, old))
                del self.emitted[k]
            if new is not None:
                out_rows.append((k, 1, new))
                self.emitted[k] = new
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Universe set ops


class UniverseSetOpNode(Node):
    """restrict / intersect / difference on key sets
    (reference: Graph::restrict_column / intersect_tables / subtract_table)."""

    is_stateful = True

    def __init__(self, left: Node, others: Sequence[Node], mode: str):
        super().__init__([left] + list(others), left.column_names)
        self.mode = mode  # 'intersect' | 'difference' | 'restrict'

    def _make_local_exec(self):
        return UniverseSetOpExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnUniverseSetOpExec

            return DcnUniverseSetOpExec(self)
        return self._make_local_exec()


class UniverseSetOpExec(NodeExec):
    def __init__(self, node: UniverseSetOpNode):
        super().__init__(node)
        self.left = MultisetState(node.inputs[0].column_names)
        self.other_counts: list[dict[int, int]] = [
            {} for _ in node.inputs[1:]
        ]
        self.emitted: dict[int, tuple] = {}

    def process(self, t, inputs):
        touched: dict[int, None] = {}
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                self.left.apply_row(k, d, vals)
                touched[k] = None
        for counts, batches in zip(self.other_counts, inputs[1:]):
            for b in batches:
                for k, d, _vals in b.iter_rows():
                    counts[k] = counts.get(k, 0) + d
                    if counts[k] == 0:
                        del counts[k]
                    touched[k] = None
        from pathway_tpu.engine.batch import _values_eq

        out_rows = []
        mode = self.node.mode
        for k in touched:
            row = self.left.get(k)
            present_in_others = [k in c for c in self.other_counts]
            if mode in ("intersect", "restrict"):
                ok = row is not None and all(present_in_others)
            else:  # difference
                ok = row is not None and not any(present_in_others)
            new = row if ok else None
            old = self.emitted.get(k)
            if old is not None and new is not None and _values_eq(old, new):
                continue
            if old is not None:
                out_rows.append((k, -1, old))
                del self.emitted[k]
            if new is not None:
                out_rows.append((k, 1, new))
                self.emitted[k] = new
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


# ---------------------------------------------------------------------------
# Output / subscribe


class OutputNode(Node):
    """(reference: output_table / subscribe_table,
    src/engine/dataflow.rs:3979,4080)"""

    def __init__(
        self,
        input: Node,
        on_batch: Callable[[int, DiffBatch], None],
        on_end: Callable[[], None] | None = None,
    ):
        super().__init__([input], input.column_names)
        self.on_batch = on_batch
        self.on_end_cb = on_end

    def make_exec(self):
        return OutputExec(self)


class OutputExec(NodeExec):
    def process(self, t, inputs):
        for b in inputs[0]:
            if len(b):
                self.node.on_batch(t, b)
        return []

    def on_end(self):
        if self.node.on_end_cb is not None:
            self.node.on_end_cb()
        return []


# ---------------------------------------------------------------------------
# Buffer / Forget / Freeze (temporal behaviors)


class BufferNode(Node):
    """Postpone rows until the time column passes a threshold
    (reference: postpone_core, src/engine/dataflow/operators/time_column.rs:248)."""

    is_stateful = True

    def __init__(
        self,
        input: Node,
        threshold_col: str,
        current_time_col: str,
        flush_on_end: bool = True,
    ):
        super().__init__([input], input.column_names)
        self.threshold_col = threshold_col
        self.current_time_col = current_time_col
        self.flush_on_end = flush_on_end

    def _make_local_exec(self):
        from pathway_tpu.parallel.mesh import get_engine_mesh

        em = get_engine_mesh()
        if em is not None:
            from pathway_tpu.engine.sharded import ShardedBufferExec

            return ShardedBufferExec(self, em[0], em[1])
        return BufferExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnWatermarkExec

            return DcnWatermarkExec(self)
        return self._make_local_exec()


def _watermark_ledger_append(arr: Arrangement, ops) -> None:
    """Append per-row state transitions to a watermark exec's
    persistence ledger.  ``ops`` are (flag, row_key, diff, vals): the
    flag is the arrangement's join key (0 = held/live, 1 = released), so
    the two lifecycle states of one row key consolidate independently;
    the row's values ride in a single object column."""
    if not ops:
        return
    n = len(ops)
    jks = np.fromiter((f for f, _k, _d, _v in ops), dtype=np.uint64, count=n)
    keys = np.fromiter(
        (k & 0xFFFFFFFFFFFFFFFF for _f, k, _d, _v in ops),
        dtype=np.uint64,
        count=n,
    )
    diffs = np.fromiter((d for _f, _k, d, _v in ops), dtype=np.int64, count=n)
    vcol = np.empty(n, dtype=object)
    vcol[:] = [v for _f, _k, _d, v in ops]
    arr.append(jks, keys, diffs, [vcol])


class BufferExec(NodeExec):
    """Dict compute state + an arrangement-backed persistence ledger
    (PR-7 State Ledger protocol): every held/released transition mirrors
    into ``self.ledger`` as an append-only delta, so snapshots write
    bytes ∝ churn instead of pickling the whole buffer.
    ``PATHWAY_STATE_ROWWISE=1`` disables the ledger — the monolithic
    pickle is the differential oracle."""

    def __init__(self, node: BufferNode):
        super().__init__(node)
        in_cols = node.inputs[0].column_names
        self.thr_idx = in_cols.index(node.threshold_col)
        self.cur_idx = in_cols.index(node.current_time_col)
        self.held: dict[int, list] = {}  # key -> [threshold, vals, count]
        self.released: set[int] = set()
        self.max_seen: Any = None
        self._ledger_on = not _state_rowwise_env()
        self.ledger = Arrangement(1)  # jk: 0 = held, 1 = released

    # --- persistence ledger ----------------------------------------------

    def arranged_state(self):
        if not self._ledger_on:
            return None
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("node", "held", "released", "ledger")
            and not k.startswith("_m_")
        }
        return residual, {"ledger": self.ledger}

    def load_arranged_state(self, residual, arrangements) -> None:
        self.__dict__.update(residual)
        self.ledger = arrangements["ledger"]
        self.held = {}
        self.released = set()
        rows = self.ledger.entries()
        if len(rows):
            vals_l = rows.cols[0].tolist()
            jks = rows.jk.tolist()
            keys = rows.key.tolist()
            counts = rows.count.tolist()
            for i in range(len(keys)):
                if counts[i] == 0:
                    continue
                if jks[i] == 0:
                    vals = vals_l[i]
                    self.held[keys[i]] = [
                        vals[self.thr_idx], vals, counts[i],
                    ]
                else:
                    self.released.add(keys[i])
        if _state_rowwise_env():
            self._ledger_on = False
            self.ledger = Arrangement(1)

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        # legacy (pre-ledger) monolith snapshot: seed the ledger so the
        # next incremental snapshot covers the restored state
        if (
            self._ledger_on
            and len(self.ledger) == 0
            and (self.held or self.released)
        ):
            ops = [
                (0, k, c, vals) for k, (_thr, vals, c) in self.held.items()
            ]
            ops += [(1, k, 1, ()) for k in self.released]
            _watermark_ledger_append(self.ledger, ops)

    def process(self, t, inputs):
        out_rows = []
        batch_max = None
        ops: list = []  # ledger mirror of every held/released transition
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                cur = vals[self.cur_idx]
                if cur is not None and (batch_max is None or cur > batch_max):
                    batch_max = cur
                if k in self.released:
                    out_rows.append((k, d, vals))
                    if d < 0:
                        self.released.discard(k)
                        ops.append((1, k, -1, vals))
                    continue
                if d > 0:
                    thr = vals[self.thr_idx]
                    prev = self.held.get(k)
                    if prev is not None:
                        ops.append((0, k, -prev[2], prev[1]))
                    self.held[k] = [thr, vals, d]
                    ops.append((0, k, d, vals))
                else:
                    if k in self.held:
                        prev = self.held.pop(k)
                        ops.append((0, k, -prev[2], prev[1]))
                    else:
                        out_rows.append((k, d, vals))
        # release is IMMEDIATE within a tick (a row whose threshold the
        # same batch's time column already passes flows straight through —
        # reference: postpone_core releases against `now` including the
        # current batch, and delay=0 must not hold rows a tick); contrast
        # ForgetExec/FreezeExec, whose watermarks genuinely lag
        if batch_max is not None and (
            self.max_seen is None or batch_max > self.max_seen
        ):
            self.max_seen = batch_max
        # release rows whose threshold <= watermark
        if self.max_seen is not None:
            ready = [
                k
                for k, (thr, _v, _c) in self.held.items()
                if thr is not None and thr <= self.max_seen
            ]
            for k in ready:
                thr, vals, c = self.held.pop(k)
                out_rows.append((k, c, vals))
                self.released.add(k)
                ops.append((0, k, -c, vals))
                ops.append((1, k, 1, vals))
        if self._ledger_on:
            _watermark_ledger_append(self.ledger, ops)
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]

    def on_end(self):
        if not self.node.flush_on_end:
            return []
        out_rows = []
        ops: list = []
        for k, (thr, vals, c) in self.held.items():
            out_rows.append((k, c, vals))
            self.released.add(k)
            ops.append((0, k, -c, vals))
            ops.append((1, k, 1, vals))
        self.held.clear()
        if self._ledger_on:
            _watermark_ledger_append(self.ledger, ops)
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


class ForgetNode(Node):
    """Retract rows older than threshold — bounds state
    (reference: TimeColumnForget, time_column.rs:426)."""

    is_stateful = True

    def __init__(
        self,
        input: Node,
        threshold_col: str,
        current_time_col: str,
        mark_forgetting_records: bool = False,
    ):
        super().__init__([input], input.column_names)
        self.threshold_col = threshold_col
        self.current_time_col = current_time_col
        if mark_forgetting_records:
            raise NotImplementedError(
                "mark_forgetting_records=True (tagging retractions caused "
                "by forgetting with an extra flag column, reference: "
                "TimeColumnForget) is not implemented yet"
            )
        self.mark_forgetting_records = mark_forgetting_records

    def _make_local_exec(self):
        return ForgetExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnWatermarkExec

            return DcnWatermarkExec(self)
        return self._make_local_exec()


class ForgetExec(NodeExec):
    """Same State-Ledger mirroring as BufferExec: the live-row dict is
    compute state, ``self.ledger`` is its append-only persistence mirror
    (single jk 0 — rows have one lifecycle state here)."""

    def __init__(self, node: ForgetNode):
        super().__init__(node)
        in_cols = node.inputs[0].column_names
        self.thr_idx = in_cols.index(node.threshold_col)
        self.cur_idx = in_cols.index(node.current_time_col)
        self.live: dict[int, list] = {}
        self.max_seen: Any = None
        self._scanned_at: Any = None  # watermark value at the last scan
        self._ledger_on = not _state_rowwise_env()
        self.ledger = Arrangement(1)

    def arranged_state(self):
        if not self._ledger_on:
            return None
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("node", "live", "ledger") and not k.startswith("_m_")
        }
        return residual, {"ledger": self.ledger}

    def load_arranged_state(self, residual, arrangements) -> None:
        self.__dict__.update(residual)
        self.ledger = arrangements["ledger"]
        self.live = {}
        rows = self.ledger.entries()
        if len(rows):
            vals_l = rows.cols[0].tolist()
            keys = rows.key.tolist()
            counts = rows.count.tolist()
            for i in range(len(keys)):
                if counts[i] > 0:
                    vals = vals_l[i]
                    self.live[keys[i]] = [vals[self.thr_idx], vals]
        if _state_rowwise_env():
            self._ledger_on = False
            self.ledger = Arrangement(1)

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        if self._ledger_on and len(self.ledger) == 0 and self.live:
            _watermark_ledger_append(
                self.ledger,
                [(0, k, 1, vals) for k, (_thr, vals) in self.live.items()],
            )

    def process(self, t, inputs):
        out_rows = []
        ops: list = []
        # Forgetting is DATA-driven, lagged one tick: rows stale against
        # the watermark of STRICTLY EARLIER ticks retract when new data
        # (or an externally advanced DCN watermark) arrives — never at the
        # end-of-stream flush tick, which carries no time advancement
        # (reference: TimeColumnForget reacts to input batches,
        # time_column.rs:426; batch mode forgets nothing).
        has_rows = any(len(b) for b in inputs[0])
        # _scanned_at is refreshed at the END of process, so this only
        # fires when max_seen moved OUTSIDE process() — the DCN watermark
        # wrapper advancing it from a peer's data
        externally_advanced = (
            self.max_seen is not None and self.max_seen != self._scanned_at
        )
        if (
            (has_rows or externally_advanced)
            and t < END_OF_TIME
            and self.max_seen is not None
        ):
            stale = [
                k
                for k, (thr, _v) in self.live.items()
                if thr is not None and thr <= self.max_seen
            ]
            for k in stale:
                thr, vals = self.live.pop(k)
                out_rows.append((k, -1, vals))
                ops.append((0, k, -1, vals))
        batch_max = None
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                cur = vals[self.cur_idx]
                if cur is not None and (batch_max is None or cur > batch_max):
                    batch_max = cur
                out_rows.append((k, d, vals))
                if d > 0:
                    prev = self.live.get(k)
                    if prev is not None:
                        ops.append((0, k, -1, prev[1]))
                    self.live[k] = [vals[self.thr_idx], vals]
                    ops.append((0, k, 1, vals))
                else:
                    prev = self.live.pop(k, None)
                    if prev is not None:
                        ops.append((0, k, -1, prev[1]))
        if batch_max is not None and (
            self.max_seen is None or batch_max > self.max_seen
        ):
            self.max_seen = batch_max
        self._scanned_at = self.max_seen
        if self._ledger_on:
            _watermark_ledger_append(self.ledger, ops)
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]


class FreezeNode(Node):
    """Drop late rows (reference: TimeColumnFreeze, time_column.rs:509)."""

    def __init__(self, input: Node, threshold_col: str, current_time_col: str):
        super().__init__([input], input.column_names)
        self.threshold_col = threshold_col
        self.current_time_col = current_time_col

    def _make_local_exec(self):
        return FreezeExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnWatermarkExec

            return DcnWatermarkExec(self)
        return self._make_local_exec()


class FreezeExec(NodeExec):
    def __init__(self, node: FreezeNode):
        super().__init__(node)
        in_cols = node.inputs[0].column_names
        self.thr_idx = in_cols.index(node.threshold_col)
        self.cur_idx = in_cols.index(node.current_time_col)
        self.max_seen: Any = None

    def process(self, t, inputs):
        out_rows = []
        batch_max = None
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                thr = vals[self.thr_idx]
                # lateness is judged against the watermark of STRICTLY
                # EARLIER ticks (reference: TimeColumnFreeze,
                # time_column.rs:509) — same-tick rows never freeze each
                # other out
                if (
                    self.max_seen is not None
                    and thr is not None
                    and thr <= self.max_seen
                ):
                    continue  # late — frozen out
                out_rows.append((k, d, vals))
                cur = vals[self.cur_idx]
                if cur is not None and (batch_max is None or cur > batch_max):
                    batch_max = cur
        if batch_max is not None and (
            self.max_seen is None or batch_max > self.max_seen
        ):
            self.max_seen = batch_max
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]
