"""Columnar arrangements — indexed operator state for delta joins.

The microbatch analog of differential dataflow's *arranged* collections
(reference: external/differential-dataflow arrangements; join_tables
arrange+join_core, src/engine/dataflow.rs:2740,2834): operator state is a
log-structured set of **sorted columnar segments** — join-key, row-key and
diff-weight ndarrays plus the value columns — instead of Python
dict-of-dicts.  Appending a tick's delta is O(sort of the delta); probing
gathers the full history of a set of join keys with one ``searchsorted``
per segment; entries collapse to current state (net weight, latest values)
with a single vectorized pass.

Lifecycle:

* ``append`` stages a delta batch (no work beyond bookkeeping).
* ``_seal`` sorts staged batches into segments.  Adjacent segments of
  similar size merge geometrically (entry-preserving scatter-merge of two
  sorted runs), so the segment count stays logarithmic and every entry is
  re-merged O(log n) times total — the lazy-merge schedule of an LSM tree
  / differential's merge batcher.
* ``compact`` rewrites the whole history into one consolidated segment
  (net weights, zero-weight groups dropped).  It runs when the fraction of
  retraction entries since the last compaction crosses
  ``PATHWAY_ARRANGE_COMPACT_RATIO`` (default 0.3) — retraction-heavy
  streams stay bounded, append-only streams never pay for it.

Each segment carries a sorted fingerprint of its (jk, rowkey) pairs and a
``clean`` flag (insert-only, no duplicate pairs).  Probes whose gathered
entries are provably clean skip consolidation — the append-only steady
state pays one stable argsort per probe instead of a 3-key lexsort plus
group reduction.

Consolidation semantics mirror the rowwise dict state exactly
(``nodes.py _SideState.apply``): net weight per (join key, row key) with
zero-weight entries dropped (negative weights are kept — a retraction may
precede its insert), values from the **last positive-weight** entry
(first entry when none ever was positive), and emission order by first
appearance.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np

_MIX = np.uint64(0x9E3779B97F4A7C15)


def mix_keys(jks: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """64-bit fingerprint of (jk, rowkey) pairs.  The fingerprint IS the
    pair's identity wherever it is used for grouping (consolidate_mixed)
    or cross-state cancelation — the same 64-bit hash-identity contract
    the engine already accepts for row keys and consolidate()'s value
    hashes.  Where it gates a fast path (cleanliness, overlap checks) a
    collision merely demotes to the slow path."""
    return (np.asarray(jks, dtype=np.uint64) * _MIX) ^ np.asarray(
        keys, dtype=np.uint64
    )


def _col_bytes(col: np.ndarray) -> int:
    """Resident bytes of one value column.  Object-dtype columns hold
    pointers; walk the elements so pickled blobs / nested arrays report
    their payload size (bytes -> len, ndarray -> nbytes, everything else
    sys.getsizeof)."""
    if col.dtype != object:
        return int(col.nbytes)
    import sys

    total = int(col.nbytes)  # the pointer array itself
    for v in col:
        if isinstance(v, (bytes, bytearray)):
            total += len(v)
        elif isinstance(v, np.ndarray):
            total += int(v.nbytes)
        elif v is not None:
            try:
                total += sys.getsizeof(v)
            except TypeError:
                pass
    return total


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# dtype-preserving column concat — canonical helper lives next to
# DiffBatch (state must never silently promote int64 to float64)
from pathway_tpu.engine.batch import concat_columns  # noqa: E402,F401


# vectorized range expansion — canonical helper lives in internals.api
# next to the match_keys probe that shares it
from pathway_tpu.internals.api import expand_ranges  # noqa: E402,F401


def sorted_member(values: np.ndarray, sorted_ref: np.ndarray) -> np.ndarray:
    """Membership mask of ``values`` in an already-sorted reference array
    — one searchsorted instead of np.isin's per-call re-sorts."""
    n = len(sorted_ref)
    if not n or not len(values):
        return np.zeros(len(values), dtype=bool)
    idx = np.searchsorted(sorted_ref, values)
    idx[idx == n] = n - 1
    return sorted_ref[idx] == values


def _merge_indices(
    a: np.ndarray, b: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Positions of two sorted runs' elements in their stable merge
    (b after equal a) — two searchsorteds instead of an argsort."""
    idx_a = np.arange(len(a), dtype=np.int64) + np.searchsorted(
        b, a, "left"
    )
    idx_b = np.arange(len(b), dtype=np.int64) + np.searchsorted(
        a, b, "right"
    )
    return idx_a, idx_b


def _scatter_merge(
    idx_a: np.ndarray, idx_b: np.ndarray, xa: np.ndarray, xb: np.ndarray
) -> np.ndarray:
    """Place two runs at precomputed merge positions, widening to object
    only when dtypes differ (values are never silently promoted)."""
    if xa.dtype == xb.dtype:
        out = np.empty(len(xa) + len(xb), dtype=xa.dtype)
    else:
        out = np.empty(len(xa) + len(xb), dtype=object)
        xa = xa.astype(object)
        xb = xb.astype(object)
    out[idx_a] = xa
    out[idx_b] = xb
    return out


def merge_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Stable merge of two sorted same-dtype arrays."""
    idx_a, idx_b = _merge_indices(a, b)
    return _scatter_merge(idx_a, idx_b, a, b)


class Rows:
    """A consolidated view of arrangement state: one entry per
    (join key, row key), sorted by (jk, age).  ``count`` is the net diff
    weight (never 0); ``age`` orders emission like dict insertion order;
    ``cols`` are the gathered value columns."""

    def __init__(self, jk, key, count, age, cols):
        self.jk = jk
        self.key = key
        self.count = count
        self.age = age
        self.cols = cols

    def __len__(self) -> int:
        return len(self.jk)

    @staticmethod
    def empty(n_cols: int) -> "Rows":
        return Rows(
            np.empty(0, np.uint64),
            np.empty(0, np.uint64),
            np.empty(0, np.int64),
            np.empty(0, np.int64),
            [np.empty(0, object) for _ in range(n_cols)],
        )

    def take(self, idx: np.ndarray) -> "Rows":
        return Rows(
            self.jk[idx],
            self.key[idx],
            self.count[idx],
            self.age[idx],
            [c[idx] for c in self.cols],
        )


def merge_rows_sorted(a: Rows, b: Rows) -> Rows:
    """Merge two Rows with disjoint (jk, key) sets into one (jk, age)-
    sorted Rows — valid only when every b age exceeds every a age (the
    delta-overlay fast path)."""
    if not len(a):
        return b
    if not len(b):
        return a
    idx_a, idx_b = _merge_indices(a.jk, b.jk)
    return Rows(
        _scatter_merge(idx_a, idx_b, a.jk, b.jk),
        _scatter_merge(idx_a, idx_b, a.key, b.key),
        _scatter_merge(idx_a, idx_b, a.count, b.count),
        _scatter_merge(idx_a, idx_b, a.age, b.age),
        [
            _scatter_merge(idx_a, idx_b, ca, cb)
            for ca, cb in zip(a.cols, b.cols)
        ],
    )


def consolidate_entries(
    jks: np.ndarray,
    keys: np.ndarray,
    diffs: np.ndarray,
    ages: np.ndarray,
    cols: Sequence[np.ndarray],
) -> Rows:
    """Collapse raw entries into current state per (jk, key) — the
    vectorized twin of replaying ``_SideState.apply`` row by row: net
    weight (zero-net groups dropped), values from the last positive-weight
    entry (first entry when none), age of first appearance.  Only valid
    over a key's FULL history (or a full-history prefix already collapsed
    to one entry + later entries): collapsing a middle slice could lose
    the last-positive value."""
    m = len(jks)
    if m == 0:
        return Rows.empty(len(cols))
    order = np.lexsort((ages, keys, jks))
    jk_s = jks[order]
    key_s = keys[order]
    d_s = diffs[order]
    age_s = ages[order]
    boundary = np.empty(m, dtype=bool)
    boundary[0] = True
    boundary[1:] = (jk_s[1:] != jk_s[:-1]) | (key_s[1:] != key_s[:-1])
    starts = np.nonzero(boundary)[0]
    net = np.add.reduceat(d_s, starts)
    # dict parity for re-created entries: when a group's running count
    # hits zero mid-history the dict DELETES the entry, and a later
    # entry re-creates it with fresh value memory and a fresh insertion
    # position — so value selection and the emission age are restricted
    # to the window after the group's last zero-crossing
    grp_id = np.cumsum(boundary) - 1
    cs = np.cumsum(d_s)
    offs = np.zeros(len(starts), dtype=np.int64)
    offs[1:] = cs[starts[1:] - 1]
    prefix = cs - offs[grp_id]
    idx = np.arange(m, dtype=np.int64)
    zpos = np.where(prefix == 0, idx, np.int64(-1))
    last_zero = np.maximum.reduceat(zpos, starts)
    wstart = np.where(last_zero >= 0, last_zero + 1, starts)
    # net==0 groups put wstart past their end; `keep` drops them anyway
    pos = np.where(
        (d_s > 0) & (idx >= wstart[grp_id]), idx, np.int64(-1)
    )
    last_pos = np.maximum.reduceat(pos, starts)
    sel = np.where(last_pos >= 0, last_pos, wstart)
    keep = net != 0
    kstarts = wstart[keep]
    src = order[sel[keep]]
    res = Rows(
        jk_s[kstarts],
        key_s[kstarts],
        net[keep],
        age_s[kstarts],
        [c[src] for c in cols],
    )
    if len(res) > 1:
        res = res.take(np.lexsort((res.age, res.jk)))
    return res


def consolidate_mixed(
    jks: np.ndarray,
    keys: np.ndarray,
    diffs: np.ndarray,
    ages: np.ndarray,
    cols: Sequence[np.ndarray],
    mix: np.ndarray,
) -> Rows:
    """consolidate_entries specialized for entry sets whose positions are
    age-ordered *within* each (jk, key) group (probe gathers and delta
    overlays are — segments and batches concatenate in age order): groups
    come from one sort of the 64-bit pair fingerprint and the
    last-positive/first selections become O(n) scatter reductions instead
    of a 3-key lexsort.  Inherits the engine-wide 64-bit hash-identity
    contract (row keys and consolidate()'s value hashes accept the same
    collision odds)."""
    m = len(jks)
    if m == 0:
        return Rows.empty(len(cols))
    _uniq, inverse = np.unique(mix, return_inverse=True)
    g = len(_uniq)
    net = np.zeros(g, dtype=np.int64)
    np.add.at(net, inverse, diffs)
    pos_mask = diffs > 0
    # zero-crossing resets (dict deletes + re-creates the entry) need the
    # per-group running count, which the sort-free path cannot see.  A
    # crossing requires >= 3 entries of mixed sign in one surviving
    # group — delegate exactly those inputs to the sorted path.
    if (~pos_mask).any():
        sizes = np.bincount(inverse, minlength=g)
        has_neg = np.zeros(g, dtype=bool)
        has_neg[inverse[~pos_mask]] = True
        has_pos = np.zeros(g, dtype=bool)
        has_pos[inverse[pos_mask]] = True
        if bool(
            ((sizes >= 3) & has_neg & has_pos & (net != 0)).any()
        ):
            return consolidate_entries(jks, keys, diffs, ages, cols)
    positions = np.arange(m, dtype=np.int64)
    first = np.full(g, m, dtype=np.int64)
    np.minimum.at(first, inverse, positions)
    last_pos = np.full(g, -1, dtype=np.int64)
    if pos_mask.any():
        np.maximum.at(last_pos, inverse[pos_mask], positions[pos_mask])
    sel = np.where(last_pos >= 0, last_pos, first)
    keep = net != 0
    fk = first[keep]
    sk = sel[keep]
    res = Rows(
        jks[fk], keys[fk], net[keep], ages[fk], [c[sk] for c in cols]
    )
    if len(res) > 1:
        res = res.take(np.lexsort((res.age, res.jk)))
    return res


class _Segment:
    """Immutable run sorted by jk (stable — equal-jk entries keep
    insertion order) with per-entry global ages, a sorted (jk, key)
    fingerprint for overlap/duplicate checks, and a ``clean`` flag
    (insert-only weights, no duplicate (jk, key) pairs).

    ``seg_id`` is a per-arrangement monotone identity assigned at creation
    (sealing, merging and compaction all mint fresh ids): a given id names
    one immutable byte-content forever, which is what lets the persistence
    layer content-address segment files and write only ids it has never
    seen (persistence/segments.py)."""

    __slots__ = (
        "jks", "keys", "diffs", "ages", "cols", "mix_sorted", "clean",
        "seg_id",
    )

    def __init__(
        self, jks, keys, diffs, ages, cols, mix_sorted, clean, seg_id=-1
    ):
        self.jks = jks
        self.keys = keys
        self.diffs = diffs
        self.ages = ages
        self.cols = cols
        self.mix_sorted = mix_sorted
        self.clean = clean
        self.seg_id = seg_id

    def __len__(self) -> int:
        return len(self.jks)

    def __getstate__(self):  # __slots__ classes need explicit pickling
        return {s: getattr(self, s) for s in self.__slots__}

    def __setstate__(self, state):
        self.seg_id = -1  # pre-seg_id pickles
        for k, v in state.items():
            setattr(self, k, v)


class Arrangement:
    """Log-structured columnar multiset of (jk, rowkey, weight, values)."""

    def __init__(
        self,
        n_cols: int,
        *,
        max_segments: int | None = None,
        compact_ratio: float | None = None,
    ):
        self.n_cols = n_cols
        self.segments: list[_Segment] = []
        self._staged: list[tuple] = []
        self._next_age = 0
        self._entries = 0  # raw entries across segments + staged
        self._neg_entries = 0  # retraction entries since last compaction
        self.compactions = 0
        self.merges = 0
        # persistence identity: epoch distinguishes this arrangement's
        # segment-id space from any earlier incarnation whose files may
        # still sit in a store (a fresh run after a structural-mismatch
        # restart would otherwise mint seg_id 0 again and collide with
        # the stale file of the same name); ids are monotone within one
        # epoch, including across save/restore
        self.epoch = os.urandom(6).hex()
        self._next_seg_id = 0
        self.max_segments = (
            max_segments
            if max_segments is not None
            else _env_int("PATHWAY_ARRANGE_MAX_SEGMENTS", 16)
        )
        self.compact_ratio = (
            compact_ratio
            if compact_ratio is not None
            else _env_float("PATHWAY_ARRANGE_COMPACT_RATIO", 0.3)
        )

    def __len__(self) -> int:
        return self._entries

    def resident_bytes(self) -> int:
        """Host-resident byte footprint of the log: sealed segments plus
        staged-but-uncommitted deltas. Numeric columns report ndarray
        nbytes; object columns report payload bytes per element (a
        pickled-blob column's 8-byte pointers would otherwise hide the
        actual residency the memory ledger exists to expose). Feeds
        Tick Scope's ``pathway_tickscope_resident_bytes`` families."""
        total = 0
        for seg in self.segments:
            total += (
                seg.jks.nbytes + seg.keys.nbytes + seg.diffs.nbytes
                + seg.ages.nbytes
            )
            if seg.mix_sorted is not None:
                total += seg.mix_sorted.nbytes
            for c in seg.cols:
                total += _col_bytes(c)
        for staged in self._staged:
            jks, keys, diffs, cols = staged[0], staged[1], staged[2], staged[3]
            total += jks.nbytes + keys.nbytes + diffs.nbytes
            for c in cols:
                total += _col_bytes(np.asarray(c))
        return total

    def __setstate__(self, state: dict) -> None:
        # monolith snapshots written before arrangements carried a
        # persistence identity unpickle without epoch/seg-id state; mint a
        # fresh epoch (stale same-name files cannot exist for it) and
        # re-id any legacy segments so manifest_of works after restore
        self.__dict__.update(state)
        if "epoch" not in state:
            self.epoch = os.urandom(6).hex()
        if "_next_seg_id" not in state:
            self._next_seg_id = 0
        for seg in self.segments:
            if getattr(seg, "seg_id", -1) < 0:
                seg.seg_id = self._alloc_seg_id()
            elif seg.seg_id >= self._next_seg_id:
                self._next_seg_id = seg.seg_id + 1

    def stage(
        self,
        jks: np.ndarray,
        keys: np.ndarray,
        diffs: np.ndarray,
        cols: Sequence[np.ndarray],
        *,
        jk_order: np.ndarray | None = None,
        mix_sorted: np.ndarray | None = None,
        clean: bool | None = None,
    ) -> tuple | None:
        """Build (but do not apply) a staged delta entry — everything
        that can allocate/raise happens here, so a caller updating TWO
        arrangements can stage both and then ``commit`` both without a
        failure window between the state mutations.
        ``jk_order``/``mix_sorted``/``clean`` let the caller donate work
        it already did this tick (the join exec sorts and fingerprints
        the delta anyway)."""
        if not len(jks):
            return None
        return (
            np.ascontiguousarray(jks, dtype=np.uint64),
            np.ascontiguousarray(keys, dtype=np.uint64),
            np.ascontiguousarray(diffs, dtype=np.int64),
            list(cols),
            jk_order,
            mix_sorted,
            clean,
            int((np.asarray(diffs) < 0).sum()),
        )

    def commit(self, staged: tuple | None) -> None:
        """Apply a ``stage``d entry: pure list/int bookkeeping."""
        if staged is None:
            return
        self._staged.append(staged[:7])
        self._entries += len(staged[0])
        self._neg_entries += staged[7]

    def append(
        self,
        jks: np.ndarray,
        keys: np.ndarray,
        diffs: np.ndarray,
        cols: Sequence[np.ndarray],
        *,
        jk_order: np.ndarray | None = None,
        mix_sorted: np.ndarray | None = None,
        clean: bool | None = None,
    ) -> None:
        """Stage + commit a delta batch in one step."""
        self.commit(
            self.stage(
                jks, keys, diffs, cols,
                jk_order=jk_order, mix_sorted=mix_sorted, clean=clean,
            )
        )

    def next_age(self) -> int:
        """First age any not-yet-appended entry would get — lets callers
        overlay a pending delta on probed state with consistent ordering."""
        return self._next_age + sum(len(s[0]) for s in self._staged)

    def _alloc_seg_id(self) -> int:
        sid = self._next_seg_id
        self._next_seg_id += 1
        return sid

    def seal(self) -> None:
        """Fold staged deltas into immutable segments now (probes do this
        lazily) — the persistence layer calls it so a snapshot manifest
        names only sealed, serializable segments."""
        self._seal()

    @classmethod
    def restore(
        cls,
        n_cols: int,
        segments: list[_Segment],
        *,
        epoch: str,
        next_age: int,
        next_seg_id: int,
        neg_entries: int = 0,
        max_segments: int | None = None,
        compact_ratio: float | None = None,
    ) -> "Arrangement":
        """Rebuild an arrangement from previously sealed segments (the
        mmap recovery path, persistence/segments.py). The epoch and the
        seg-id counter continue from the snapshot so future segment files
        never reuse a persisted name."""
        arr = cls(
            n_cols, max_segments=max_segments, compact_ratio=compact_ratio
        )
        arr.segments = list(segments)
        arr.epoch = epoch
        arr._next_age = int(next_age)
        arr._next_seg_id = int(next_seg_id)
        arr._entries = int(sum(len(s) for s in segments))
        arr._neg_entries = int(neg_entries)
        return arr

    def _seal(self) -> None:
        if self._staged:
            # pop as we go: if sealing batch k raises (allocation failure
            # mid-merge), batches 0..k-1 are committed to segments and
            # k.. remain staged — a retry (or the exception-fallback's
            # materialization) never seals the same entries twice
            while self._staged:
                jks, keys, diffs, cols, order, mix_sorted, clean = (
                    self._staged.pop(0)
                )
                n = len(jks)
                # ages reflect original (insertion) order
                ages = np.arange(
                    self._next_age, self._next_age + n, dtype=np.int64
                )
                self._next_age += n
                if order is None:
                    order = np.argsort(jks, kind="stable")
                if mix_sorted is None:
                    mix_sorted = np.sort(mix_keys(jks, keys))
                if clean is None:
                    clean = bool((diffs > 0).all()) and not bool(
                        (mix_sorted[1:] == mix_sorted[:-1]).any()
                    )
                self.segments.append(
                    _Segment(
                        jks[order],
                        keys[order],
                        diffs[order],
                        ages[order],
                        [np.asarray(c)[order] for c in cols],
                        mix_sorted,
                        clean,
                        self._alloc_seg_id(),
                    )
                )
                # geometric merge schedule: fold the newest segment into
                # its neighbor while they are within 4x in size — segment
                # count stays ~log4 of the arrangement (fewer probe
                # searchsorteds) and each entry is re-merged O(log n)
                # times over the arrangement's life
                while (
                    len(self.segments) >= 2
                    and len(self.segments[-2]) <= 4 * len(self.segments[-1])
                ):
                    self._merge_last_two()
            while len(self.segments) > self.max_segments:
                self._merge_last_two()
        if (
            self.segments
            and self._neg_entries
            and self._neg_entries >= self.compact_ratio * self._entries
        ):
            self.compact()

    def _merge_last_two(self) -> None:
        """Entry-preserving merge of the two newest (age-adjacent)
        segments: two sorted runs combine with searchsorted + scatter.
        No consolidation happens here: collapsing a partial history slice
        could lose last-positive values (see consolidate_entries)."""
        a, b = self.segments[-2], self.segments[-1]
        idx_a, idx_b = _merge_indices(a.jks, b.jks)
        mix_sorted = merge_sorted(a.mix_sorted, b.mix_sorted)
        clean = (
            a.clean
            and b.clean
            and not bool((mix_sorted[1:] == mix_sorted[:-1]).any())
        )
        merged = _Segment(
            _scatter_merge(idx_a, idx_b, a.jks, b.jks),
            _scatter_merge(idx_a, idx_b, a.keys, b.keys),
            _scatter_merge(idx_a, idx_b, a.diffs, b.diffs),
            _scatter_merge(idx_a, idx_b, a.ages, b.ages),
            [
                _scatter_merge(idx_a, idx_b, ca, cb)
                for ca, cb in zip(a.cols, b.cols)
            ],
            mix_sorted,
            clean,
            self._alloc_seg_id(),
        )
        self.segments[-2:] = [merged]
        self.merges += 1

    def compact(self) -> None:
        """Rewrite the full history as one consolidated segment."""
        rows = self._consolidate_all()
        m = len(rows)
        # rows are sorted by (jk, age); re-aging 0..m-1 preserves relative
        # emission order and keeps future ages strictly larger
        mix_sorted = np.sort(mix_keys(rows.jk, rows.key))
        seg = _Segment(
            rows.jk,
            rows.key,
            rows.count,
            np.arange(m, dtype=np.int64),
            rows.cols,
            mix_sorted,
            bool((rows.count > 0).all()),
            self._alloc_seg_id(),
        )
        self.segments = [seg] if m else []
        self._next_age = m
        self._entries = m
        self._neg_entries = 0
        self.compactions += 1

    def _consolidate_all(self) -> Rows:
        segs = self.segments
        if not segs:
            return Rows.empty(self.n_cols)
        return consolidate_entries(
            np.concatenate([s.jks for s in segs]),
            np.concatenate([s.keys for s in segs]),
            np.concatenate([s.diffs for s in segs]),
            np.concatenate([s.ages for s in segs]),
            [
                concat_columns([s.cols[i] for s in segs])
                for i in range(self.n_cols)
            ],
        )

    def probe(self, qjks: np.ndarray) -> Rows:
        """Current state for a set of join keys (sorted unique uint64):
        gathers every entry whose jk is in ``qjks`` across all segments
        (one searchsorted pair per segment) and consolidates — the
        delta-join's index lookup.  Gathers that are provably clean (one
        clean segment, or no duplicate pairs and insert-only weights
        across the gathered set) skip consolidation."""
        self._seal()
        if not len(qjks) or not self.segments:
            return Rows.empty(self.n_cols)
        hits: list[tuple[_Segment, np.ndarray]] = []
        for seg in self.segments:
            lo = np.searchsorted(seg.jks, qjks, "left")
            hi = np.searchsorted(seg.jks, qjks, "right")
            counts = hi - lo
            if counts.any():
                hits.append((seg, expand_ranges(lo, counts)))
        if not hits:
            return Rows.empty(self.n_cols)
        if len(hits) == 1:
            seg, si = hits[0]
            rows = Rows(
                seg.jks[si],
                seg.keys[si],
                seg.diffs[si],
                seg.ages[si],
                [c[si] for c in seg.cols],
            )
            if seg.clean:
                return rows  # ranges of a clean segment: already state
            return consolidate_entries(
                rows.jk, rows.key, rows.count, rows.age, rows.cols
            )
        jks_g = np.concatenate([s.jks[si] for s, si in hits])
        keys_g = np.concatenate([s.keys[si] for s, si in hits])
        diffs_g = np.concatenate([s.diffs[si] for s, si in hits])
        ages_g = np.concatenate([s.ages[si] for s, si in hits])
        cols_g = [
            concat_columns([s.cols[i][si] for s, si in hits])
            for i in range(self.n_cols)
        ]
        mix_g = mix_keys(jks_g, keys_g)
        if (
            len(np.unique(mix_g)) == len(mix_g)
            and bool((diffs_g > 0).all())
        ):
            # no duplicate (jk, key) pairs and insert-only: entries ARE
            # the state; one stable argsort restores (jk, age) order
            # (segment gathers concatenate in age order)
            order = np.argsort(jks_g, kind="stable")
            return Rows(
                jks_g[order],
                keys_g[order],
                diffs_g[order],
                ages_g[order],
                [c[order] for c in cols_g],
            )
        return consolidate_mixed(
            jks_g, keys_g, diffs_g, ages_g, cols_g, mix_g
        )

    def overlaps(self, mixes: np.ndarray) -> bool:
        """Whether any of the given (jk, key) fingerprints matches a
        stored entry — lets the join skip probing a side entirely when a
        delta can only create brand-new rows (no collision means no
        existing entry's state can change)."""
        self._seal()
        for seg in self.segments:
            if sorted_member(mixes, seg.mix_sorted).any():
                return True
        return False

    def entries(self) -> Rows:
        """Full consolidated state (rowwise-fallback materialization and
        introspection)."""
        self._seal()
        return self._consolidate_all()

    def segment_sizes(self) -> list[int]:
        return [len(s) for s in self.segments] + [
            len(s[0]) for s in self._staged
        ]
