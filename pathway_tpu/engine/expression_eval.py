"""Columnar expression evaluation.

TPU-native counterpart of the reference's row-at-a-time interpreter
(/root/reference/src/engine/expression.rs): expressions are evaluated over
whole column batches. Numeric columns run vectorized (numpy on host for small
ticks; large dense numeric work is dispatched through pathway_tpu.ops which
routes to jax/XLA); object columns (str/json/tuple) run elementwise.

`IfElse` evaluates branches only on the selected row subsets, matching the
reference's lazy per-row branch semantics. Runtime errors inside expressions
become `ERROR` poison values instead of crashing the graph
(reference: src/engine/error.rs Value::Error).
"""

from __future__ import annotations

import math
import operator
from typing import Any, Callable, Sequence

import numpy as np
import pandas as pd

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr
from pathway_tpu.internals.api import ERROR, Error, Pointer, ref_scalar
from pathway_tpu.internals.json import Json
from pathway_tpu.engine.batch import make_column


class InternalColRef(expr.ColumnExpression):
    """Resolved column reference: (input index, column name). 'id' = keys."""

    def __init__(self, input_index: int, name: str):
        self._input_index = input_index
        self._name = name

    def __repr__(self):
        return f"${self._input_index}.{self._name}"


class EvalContext:
    """Aligned row-batch over one or more same-universe inputs."""

    def __init__(self, keys: np.ndarray, column_sets: Sequence[dict[str, np.ndarray]]):
        self.keys = keys
        self.column_sets = list(column_sets)
        self._id_cache: np.ndarray | None = None

    @property
    def n(self) -> int:
        return len(self.keys)

    def id_column(self) -> np.ndarray:
        if self._id_cache is None:
            out = np.empty(len(self.keys), dtype=object)
            for i, k in enumerate(self.keys):
                out[i] = Pointer(int(k))
            self._id_cache = out
        return self._id_cache

    def fetch(self, ref: InternalColRef) -> np.ndarray:
        if ref._name == "id":
            return self.id_column()
        return self.column_sets[ref._input_index][ref._name]


def _is_numeric(a: np.ndarray) -> bool:
    return a.dtype.kind in "bifu"


def _full(n: int, value: Any) -> np.ndarray:
    if isinstance(value, bool):
        return np.full(n, value, dtype=bool)
    if isinstance(value, int) and not isinstance(value, Pointer):
        if -(2**63) <= value < 2**63:
            return np.full(n, value, dtype=np.int64)
    if isinstance(value, float):
        return np.full(n, value, dtype=np.float64)
    out = np.empty(n, dtype=object)
    out[:] = [value] * n
    return out


def _unbox_rows(arrays) -> list:
    """Per-row Python boundaries (UDFs, scalar method fns, elementwise
    operators) must see plain Python scalars: with typed ingest a column
    may be int64/float64/bool, and numpy SCALAR semantics differ from
    Python's exactly where the poison contract bites (np.int64 // 0
    warns and yields 0 instead of raising ZeroDivisionError;
    isinstance(v, int) is False for np.int64).  tolist() unboxes at C
    speed; object columns pass through untouched."""
    return [
        a.tolist()
        if isinstance(a, np.ndarray) and a.dtype != object
        else a
        for a in arrays
    ]


def _elementwise(fn: Callable, *arrays: np.ndarray) -> np.ndarray:
    n = len(arrays[0])
    arrays = _unbox_rows(arrays)
    out = np.empty(n, dtype=object)
    for i in range(n):
        args = [a[i] for a in arrays]
        if any(isinstance(a, Error) for a in args):
            out[i] = ERROR
            continue
        try:
            out[i] = fn(*args)
        except Exception as exc:
            from pathway_tpu.internals.errors import record_error

            record_error(exc)
            out[i] = ERROR
    return out


def _tighten(out: np.ndarray) -> np.ndarray:
    """Convert an object array to a typed one when ALL elements agree."""
    if out.dtype != object or len(out) == 0:
        return out
    all_bool = True
    all_int = True
    all_float = True
    for v in out:
        if not isinstance(v, (bool, np.bool_)):
            all_bool = False
        if (
            isinstance(v, (bool, np.bool_, Pointer))
            or not isinstance(v, (int, np.integer))
        ):
            all_int = False
        if (
            isinstance(v, (bool, np.bool_, Pointer))
            or not isinstance(v, (int, float, np.integer, np.floating))
        ):
            # Pointer subclasses int: letting it through would round-trip
            # row keys through float64 and corrupt them past 2**53
            all_float = False
        if not (all_bool or all_int or all_float):
            return out
    try:
        if all_bool:
            return out.astype(bool)
        if all_int:
            return out.astype(np.int64)
        if all_float:
            return out.astype(np.float64)
    except (ValueError, TypeError, OverflowError):
        return out
    return out


def tighten_batch(batch) -> Any:
    """Typed ingest (Tick Forge): apply the SAME strict object->typed
    conversion the expression evaluator already uses on its results to a
    batch's ingest columns, so stateless chains start from dense numeric
    arrays instead of boxed rows.  Acceptance rules are exactly
    ``_tighten``'s — a column converts only when EVERY element is a plain
    bool / int64-range int / float (Pointer, None, Error, big ints, and
    mixed bool+int columns all stay object) — so no value the interpreter
    would keep boxed ever changes representation silently."""
    from pathway_tpu.engine.batch import DiffBatch

    obj = {
        name: col
        for name, col in batch.columns.items()
        if col.dtype == object and len(col)
    }
    if not obj:
        return batch
    cols = dict(batch.columns)
    changed = False
    for name, col in obj.items():
        tight = _tighten(col)
        if tight is not col:
            cols[name] = tight
            changed = True
    if not changed:
        return batch
    return DiffBatch(batch.keys, batch.diffs, cols)


_CMP_OPS = {"==", "!=", "<", "<=", ">", ">="}
_BOOL_OPS = {"&", "|", "^"}


def _binary(op: str, left: np.ndarray, right: np.ndarray) -> np.ndarray:
    lnum, rnum = _is_numeric(left), _is_numeric(right)
    if lnum and rnum:
        with np.errstate(all="ignore"):
            if op == "/":
                l = left.astype(np.float64)
                r = right.astype(np.float64)
                bad = right == 0
                if bad.any():
                    from pathway_tpu.internals.errors import record_error

                    for _ in range(int(np.sum(bad))):
                        record_error(ZeroDivisionError("division by zero"))
                    res = np.where(bad, np.nan, np.divide(l, np.where(bad, 1, r)))
                    out = res.astype(object)
                    out[np.asarray(bad)] = ERROR
                    return out
                return np.divide(l, r)
            if op in ("//", "%"):
                bad = right == 0
                fn = np.floor_divide if op == "//" else np.mod
                if bad.any():
                    from pathway_tpu.internals.errors import record_error

                    for _ in range(int(np.sum(bad))):
                        record_error(ZeroDivisionError("division by zero"))
                    res = fn(left, np.where(bad, 1, right))
                    out = res.astype(object)
                    out[np.asarray(bad)] = ERROR
                    return out
                return fn(left, right)
            if op == "**":
                if left.dtype.kind in "iu" and right.dtype.kind in "iu":
                    if (right < 0).any():
                        return np.power(left.astype(float), right.astype(float))
                return np.power(left, right)
            if op in _CMP_OPS:
                return _BINARY_NP[op](left, right)
            if op in _BOOL_OPS:
                if left.dtype == bool and right.dtype == bool:
                    return _BINARY_NP[op](left, right)
                return _BINARY_NP[op](left, right)
            if op == "@":
                return _elementwise(operator.matmul, left, right)
            return _BINARY_NP[op](left, right)
    # object path
    fn = _BINARY_PY[op]
    return _tighten(_elementwise(fn, left, right))


def _py_eq(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return bool(np.array_equal(a, b))
    return a == b


_BINARY_NP: dict[str, Callable] = {
    "<<": np.left_shift,
    ">>": np.right_shift,
    "+": np.add,
    "-": np.subtract,
    "*": np.multiply,
    "==": np.equal,
    "!=": np.not_equal,
    "<": np.less,
    "<=": np.less_equal,
    ">": np.greater,
    ">=": np.greater_equal,
    "&": np.bitwise_and,
    "|": np.bitwise_or,
    "^": np.bitwise_xor,
}

_BINARY_PY: dict[str, Callable] = {
    "<<": operator.lshift,
    ">>": operator.rshift,
    "+": operator.add,
    "-": operator.sub,
    "*": operator.mul,
    "/": operator.truediv,
    "//": operator.floordiv,
    "%": operator.mod,
    "**": operator.pow,
    "@": operator.matmul,
    "==": _py_eq,
    "!=": lambda a, b: not _py_eq(a, b),
    "<": operator.lt,
    "<=": operator.le,
    ">": operator.gt,
    ">=": operator.ge,
    "&": operator.and_,
    "|": operator.or_,
    "^": operator.xor,
}


def eval_expr(e: expr.ColumnExpression, ctx: EvalContext) -> np.ndarray:
    n = ctx.n
    if isinstance(e, InternalColRef):
        return ctx.fetch(e)
    if isinstance(e, expr.ColumnConstExpression):
        return _full(n, e._value)
    if isinstance(e, expr.ColumnBinaryOpExpression):
        return _binary(e._op, eval_expr(e._left, ctx), eval_expr(e._right, ctx))
    if isinstance(e, expr.ColumnUnaryOpExpression):
        a = eval_expr(e._expr, ctx)
        if e._op == "-":
            return -a if _is_numeric(a) else _elementwise(operator.neg, a)
        if e._op == "~":
            if a.dtype == bool:
                return ~a
            # object columns of bools (optional bool etc.) are logical not;
            # ints are bitwise (reference: Not on Bool, Neg semantics)
            def inv(v):
                if isinstance(v, (bool, np.bool_)):
                    return not v
                return operator.inv(v)

            return _tighten(_elementwise(inv, a))
        if e._op == "abs":
            return np.abs(a) if _is_numeric(a) else _elementwise(abs, a)
        raise NotImplementedError(e._op)
    if isinstance(e, expr.IfElseExpression):
        cond = eval_expr(e._if, ctx)
        cond_b = cond.astype(bool) if cond.dtype != object else np.array(
            [bool(c) for c in cond]
        )
        idx_t = np.nonzero(cond_b)[0]
        idx_f = np.nonzero(~cond_b)[0]
        then_v = eval_expr(e._then, _subset_ctx(ctx, idx_t))
        else_v = eval_expr(e._else, _subset_ctx(ctx, idx_f))
        if (
            then_v.dtype == else_v.dtype
            and then_v.dtype != object
        ):
            out = np.empty(n, dtype=then_v.dtype)
        else:
            out = np.empty(n, dtype=object)
        out[idx_t] = then_v
        out[idx_f] = else_v
        return _tighten(out) if out.dtype == object else out
    if isinstance(e, expr.CoalesceExpression):
        out = eval_expr(e._args[0], ctx)
        if out.dtype != object:
            return out
        out = out.copy()
        for arg in e._args[1:]:
            missing = np.array([v is None for v in out])
            if not missing.any():
                break
            idx = np.nonzero(missing)[0]
            sub = eval_expr(arg, _subset_ctx(ctx, idx))
            out[idx] = sub
        return _tighten(out)
    if isinstance(e, expr.RequireExpression):
        # deps first; the value evaluates ONLY on rows where every dep is
        # non-None (lazy like IfElse — an eager evaluation would poison
        # rows whose dep is legitimately None, e.g. diff's first row)
        missing = np.zeros(n, dtype=bool)
        for arg in e._args:
            a = eval_expr(arg, ctx)
            if a.dtype == object:
                missing |= np.array([v is None for v in a])
        if not missing.any():
            return eval_expr(e._val, ctx)
        out = np.empty(n, dtype=object)
        out[:] = None
        idx = np.nonzero(~missing)[0]
        if len(idx):
            sub = eval_expr(e._val, _subset_ctx(ctx, idx))
            out[idx] = sub
        return out
    if isinstance(e, expr.FillErrorExpression):
        val = eval_expr(e._expr, ctx)
        if val.dtype != object:
            return val
        bad = np.array([isinstance(v, Error) for v in val])
        if not bad.any():
            return val
        idx = np.nonzero(bad)[0]
        repl = eval_expr(e._replacement, _subset_ctx(ctx, idx))
        out = val.copy()
        out[idx] = repl
        return _tighten(out)
    if isinstance(e, expr.IsNoneExpression):
        a = eval_expr(e._expr, ctx)
        if a.dtype != object:
            return np.zeros(n, dtype=bool)
        return np.array([v is None for v in a])
    if isinstance(e, expr.IsNotNoneExpression):
        a = eval_expr(e._expr, ctx)
        if a.dtype != object:
            return np.ones(n, dtype=bool)
        return np.array([v is not None for v in a])
    if isinstance(e, expr.UnwrapExpression):
        a = eval_expr(e._expr, ctx)
        if a.dtype == object:
            for v in a:
                if v is None:
                    raise ValueError("cannot unwrap if there is None value")
        return a
    if isinstance(e, expr.CastExpression):
        return _cast(e._target, eval_expr(e._expr, ctx))
    if isinstance(e, expr.ConvertExpression):
        return _convert(e._target, eval_expr(e._expr, ctx), e._unwrap)
    if isinstance(e, expr.DeclareTypeExpression):
        return eval_expr(e._expr, ctx)
    if isinstance(e, expr.ToStringExpression):
        a = eval_expr(e._expr, ctx)
        return _elementwise(_to_string, a)
    if isinstance(e, expr.MakeTupleExpression):
        # unbox typed columns first: tuple VALUES keep the engine-wide
        # python-scalar representation (the sharded exchange packers and
        # value hashing key off exact element types)
        arrays = _unbox_rows([eval_expr(a, ctx) for a in e._args])
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = tuple(a[i] for a in arrays)
        return out
    if isinstance(e, expr.GetExpression):
        a = eval_expr(e._expr, ctx)
        idx = eval_expr(e._index, ctx)
        default = eval_expr(e._default, ctx)
        if e._check_if_exists:
            return _elementwise(_get_with_default, a, idx, default)
        return _elementwise(_get_strict, a, idx)
    if isinstance(e, expr.PointerExpression):
        arrays = [eval_expr(a, ctx) for a in e._args]
        inst = eval_expr(e._instance, ctx) if e._instance is not None else None
        out = np.empty(n, dtype=object)
        if not e._optional and inst is None and arrays:
            # hot path: batch key derivation in the native kernel
            from pathway_tpu.internals.api import ref_scalars_columns

            hashed = ref_scalars_columns(list(arrays), n)
            for i in range(n):
                out[i] = Pointer(int(hashed[i]))
            return out
        for i in range(n):
            vals = tuple(a[i] for a in arrays)
            if e._optional and any(v is None for v in vals):
                out[i] = None
                continue
            if inst is not None:
                # reference Key::for_values_with_instance: the instance is
                # part of the hashed values AND supplies the shard bits
                from pathway_tpu.internals.api import ref_scalar_with_instance

                p = ref_scalar_with_instance(*vals, instance=inst[i])
            else:
                p = ref_scalar(*vals)
            out[i] = p
        return out
    if isinstance(e, expr.MethodCallExpression):
        arrays = [eval_expr(a, ctx) for a in e._args]
        if e._vector_fn is not None and all(_is_numeric(a) for a in arrays):
            try:
                return e._vector_fn(*arrays)
            except Exception:
                pass
        fn = e._scalar_fn
        if e._propagate_none:

            def wrapped(first, *rest, _fn=fn):
                if first is None:
                    return None
                return _fn(first, *rest)

            return _tighten(_elementwise(wrapped, *arrays))
        return _tighten(_elementwise(fn, *arrays))
    if isinstance(e, (expr.AsyncApplyExpression,)):
        return _eval_async_apply(e, ctx)
    if isinstance(e, expr.BatchApplyExpression):
        from pathway_tpu.internals.errors import record_error

        arrays = _unbox_rows([eval_expr(a, ctx) for a in e._args])
        kw_arrays = {
            k: _unbox_rows([eval_expr(v, ctx)])[0]
            for k, v in e._kwargs.items()
        }
        out = np.empty(n, dtype=object)
        # rows with None (propagate_none) or ERROR inputs bypass the fn,
        # matching the scalar/async apply semantics
        ok_idx = []
        for i in range(n):
            row = [a[i] for a in arrays] + [v[i] for v in kw_arrays.values()]
            if any(isinstance(v, Error) for v in row):
                out[i] = ERROR
            elif e._propagate_none and any(v is None for v in row):
                out[i] = None
            else:
                ok_idx.append(i)
        max_bs = e._max_batch_size or max(len(ok_idx), 1)
        pos = 0
        while pos < len(ok_idx):
            chunk = ok_idx[pos : pos + max_bs]
            args = [[a[i] for i in chunk] for a in arrays]
            kwargs = {
                k: [v[i] for i in chunk] for k, v in kw_arrays.items()
            }
            try:
                results = e._fn(*args, **kwargs)
                if len(results) != len(chunk):
                    raise ValueError(
                        f"batched UDF returned {len(results)} results for "
                        f"{len(chunk)} inputs"
                    )
                for i, r in zip(chunk, results):
                    out[i] = r
            except Exception as exc:
                record_error(exc, user=True)
                for i in chunk:
                    out[i] = ERROR
            pos += max_bs
        return _coerce_to_dtype(out, e._return_type)
    if isinstance(e, expr.ApplyExpression):
        arrays = _unbox_rows([eval_expr(a, ctx) for a in e._args])
        kw_arrays = {
            k: _unbox_rows([eval_expr(v, ctx)])[0]
            for k, v in e._kwargs.items()
        }
        out = np.empty(n, dtype=object)
        for i in range(n):
            args = [a[i] for a in arrays]
            kwargs = {k: v[i] for k, v in kw_arrays.items()}
            if e._propagate_none and any(a is None for a in args):
                out[i] = None
                continue
            if any(isinstance(a, Error) for a in args) or any(
                isinstance(v, Error) for v in kwargs.values()
            ):
                out[i] = ERROR
                continue
            try:
                out[i] = e._fn(*args, **kwargs)
            except Exception as exc:
                from pathway_tpu.internals.errors import record_error

                record_error(exc, user=True)
                out[i] = ERROR
        return _coerce_to_dtype(out, e._return_type)
    if isinstance(e, expr.ReducerExpression):
        raise RuntimeError(
            "reducers can only be used inside groupby(...).reduce(...)"
        )
    if isinstance(e, expr.ColumnReference):
        raise RuntimeError(
            f"unresolved column reference {e!r} — expression used outside "
            "of its table context"
        )
    raise NotImplementedError(f"cannot evaluate {type(e).__name__}")


def _eval_async_apply(e: expr.AsyncApplyExpression, ctx: EvalContext) -> np.ndarray:
    import asyncio

    # the coroutines may run on a helper thread (run_async_blocking when a
    # loop already runs here); capture the error-log scope so their errors
    # still land in the right local log
    from pathway_tpu.internals import errors as _err

    _scope = _err._active_scope()
    n = ctx.n
    arrays = _unbox_rows([eval_expr(a, ctx) for a in e._args])
    kw_arrays = {
        k: _unbox_rows([eval_expr(v, ctx)])[0]
        for k, v in e._kwargs.items()
    }

    async def run_all():
        async def one(i):
            args = [a[i] for a in arrays]
            kwargs = {k: v[i] for k, v in kw_arrays.items()}
            if e._propagate_none and any(a is None for a in args):
                return None
            if any(isinstance(a, Error) for a in args) or any(
                isinstance(v, Error) for v in kwargs.values()
            ):
                return ERROR
            try:
                return await e._fn(*args, **kwargs)
            except Exception as exc:
                from pathway_tpu.internals.errors import record_error

                record_error(exc, user=True, scope=_scope)
                return ERROR

        return await asyncio.gather(*[one(i) for i in range(n)])

    from pathway_tpu.internals.udfs import run_async_blocking

    results = run_async_blocking(run_all)
    out = np.empty(n, dtype=object)
    for i, r in enumerate(results):
        out[i] = r
    return _coerce_to_dtype(out, e._return_type)


def _coerce_to_dtype(out: np.ndarray, target: dt.DType) -> np.ndarray:
    if target.strip_optional() == dt.JSON:
        # engine-boundary Json serialization (reference: python Json ->
        # serde on the PyO3 crossing): datetimes become ISO strings etc.
        from pathway_tpu.internals.json import normalize_json

        def norm(v):
            if v is None or isinstance(v, Error):
                return v
            return normalize_json(v)

        return _elementwise(norm, out)
    storage = target.np_dtype
    if storage != np.dtype(object) and out.dtype == object:
        # ERROR poison and None must survive coercion: astype(bool) would
        # silently turn the (truthy) Error object into True and None into
        # False, losing the poison/optionality
        if any(v is None or isinstance(v, Error) for v in out):
            return out
        try:
            return out.astype(storage)
        except (ValueError, TypeError):
            return out
    return out


def _to_string(v: Any) -> str:
    if isinstance(v, Json):
        return v.to_string()
    if isinstance(v, bool):
        return "True" if v else "False"
    if isinstance(v, pd.Timestamp):
        # reference rendering (src/engine/time.rs Display): T-separated,
        # 9-digit nanoseconds, colonless +0000 offset for aware values
        from pathway_tpu.internals.expressions.date_time import _strftime_one

        fmt = "%Y-%m-%dT%H:%M:%S.%f"
        if v.tzinfo is not None:
            fmt += "%z"
        return _strftime_one(v, fmt)
    return str(v)


def _json_access(value: Any, index: Any):
    """(found, item) for JSON-pointer-style access: str key into an object,
    non-negative in-range int index into an array; anything else is a miss
    (reference: src/engine/expression.rs JsonGetItem — no Python negative
    indexing, no wraparound)."""
    if isinstance(value, dict):
        if isinstance(index, str) and index in value:
            return True, value[index]
        return False, None
    if isinstance(value, list):
        if (
            isinstance(index, int)
            and not isinstance(index, bool)
            and 0 <= index < len(value)
        ):
            return True, value[index]
        return False, None
    return False, None


def _get_with_default(container: Any, index: Any, default: Any) -> Any:
    if isinstance(index, np.integer):
        index = int(index)
    if isinstance(container, Json):
        found, item = _json_access(container.value, index)
        if not found:
            if default is None or isinstance(default, Json):
                return default
            return Json(default)  # raw dict/list default coerces to Json
        return Json(item)
    try:
        return container[index]
    except Exception:
        return default


def _get_strict(container: Any, index: Any) -> Any:
    if isinstance(index, np.integer):
        index = int(index)
    if isinstance(container, Json):
        # total access: a miss yields JSON null so chains like
        # data["a"]["b"] propagate (reference test_json.py get_item tests)
        found, item = _json_access(container.value, index)
        return Json(item) if found else Json.NULL
    return container[index]


def _cast(target: dt.DType, a: np.ndarray) -> np.ndarray:
    t = target.strip_optional()
    if t == dt.INT:
        if _is_numeric(a):
            return a.astype(np.int64)
        return _tighten(_elementwise(lambda v: None if v is None else int(v), a))
    if t == dt.FLOAT:
        if _is_numeric(a):
            return a.astype(np.float64)
        return _tighten(_elementwise(lambda v: None if v is None else float(v), a))
    if t == dt.BOOL:
        if _is_numeric(a):
            return a.astype(bool)
        return _tighten(_elementwise(lambda v: None if v is None else bool(v), a))
    if t == dt.STR:
        return _elementwise(lambda v: None if v is None else _to_string(v), a)
    return a


def _convert(target: dt.DType, a: np.ndarray, unwrap: bool) -> np.ndarray:
    def fn(v):
        if v is None:
            if unwrap:
                raise ValueError("cannot unwrap if there is None value")
            return None
        if isinstance(v, Json):
            # engine-strict (unlike the isinstance-based UDF-level Json.as_*):
            # bools never convert to int/float, floats never to int
            # (reference test_json.py as_int/as_float wrong-value tests)
            jv = v.value
            if jv is None:
                if unwrap:
                    raise ValueError("cannot unwrap if there is None value")
                return None
            if target == dt.INT:
                if isinstance(jv, bool) or not isinstance(jv, int):
                    raise ValueError(f"Cannot convert Json {jv!r} to int")
                return jv
            if target == dt.FLOAT:
                if isinstance(jv, bool) or not isinstance(jv, (int, float)):
                    raise ValueError(f"Cannot convert Json {jv!r} to float")
                return float(jv)
            if target == dt.STR:
                if not isinstance(jv, str):
                    raise ValueError(f"Cannot convert Json {jv!r} to str")
                return jv
            if target == dt.BOOL:
                if not isinstance(jv, bool):
                    raise ValueError(f"Cannot convert Json {jv!r} to bool")
                return jv
        if target == dt.INT:
            if isinstance(v, bool) or not isinstance(v, (int, np.integer)):
                raise ValueError(f"{v!r} is not an int")
            return int(v)
        if target == dt.FLOAT:
            if isinstance(v, bool) or not isinstance(v, (int, float, np.number)):
                raise ValueError(f"{v!r} is not a float")
            return float(v)
        if target == dt.STR:
            if not isinstance(v, str):
                raise ValueError(f"{v!r} is not a str")
            return v
        if target == dt.BOOL:
            if not isinstance(v, (bool, np.bool_)):
                raise ValueError(f"{v!r} is not a bool")
            return bool(v)
        return v

    return _tighten(_elementwise(fn, a))


def _subset_ctx(ctx: EvalContext, idx: np.ndarray) -> EvalContext:
    return EvalContext(
        ctx.keys[idx],
        [{n: c[idx] for n, c in cols.items()} for cols in ctx.column_sets],
    )
