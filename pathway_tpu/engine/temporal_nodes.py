"""Temporal engine nodes: session assignment, interval/asof/asof-now joins.

TPU-engine equivalents of the reference's temporal machinery
(/root/reference/src/engine/dataflow/operators/time_column.rs for behaviors —
see BufferNode/ForgetNode/FreezeNode in nodes.py — and the table-level
desugarings of python/pathway/stdlib/temporal/). The reference compiles
interval/asof joins down to bucketed equijoins + filters on differential
collections; here each temporal node keeps keyed columnar state and restates
only the equality-groups touched per microbatch tick, which is the same
incremental contract (diff in → diff out) on the columnar engine.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, Sequence

import numpy as np

from pathway_tpu.engine.arrangement import Arrangement, Rows
from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import (
    Node,
    NodeExec,
    _concat_inputs,
    _fallback_counter,
    _none_col,
    _state_rowwise_env,
)
from pathway_tpu.internals.api import (
    Pointer,
    ref_scalar,
    ref_scalars_columns,
)
from pathway_tpu.internals.errors import record_error


# ---------------------------------------------------------------------------
# Session window assignment


class SessionAssignNode(Node):
    """Assign (window_start, window_end) to every row by merging adjacent rows
    (sorted by the time column, per instance) whenever `predicate(a, b)` holds
    or `b - a < max_gap` (reference: _SessionWindow,
    python/pathway/stdlib/temporal/_window.py:65).

    Output: same universe as input, columns ["_pw_window_start",
    "_pw_window_end"]. Incremental: per-instance full restate on touch, diffed
    against previously emitted assignments.
    """

    is_stateful = True

    def __init__(
        self,
        input: Node,
        key_col: str,
        instance_col: str | None,
        predicate: Callable[[Any, Any], bool] | None,
        max_gap: Any | None,
    ):
        super().__init__([input], ["_pw_window_start", "_pw_window_end"])
        self.key_col = key_col
        self.instance_col = instance_col
        self.predicate = predicate
        self.max_gap = max_gap

    def make_exec(self):
        return SessionAssignExec(self)


class SessionAssignExec(NodeExec):
    """Per-instance session buffers live in an Arrangement (jk = hashed
    instance, cols = [time, instance]): a tick derives instance keys with
    the C batch hasher, appends the delta, probes only the touched
    instances and restates their groupings.  The dict path survives as
    the differential-testing oracle (PATHWAY_STATE_ROWWISE=1) and the
    exception escape hatch."""

    def __init__(self, node: SessionAssignNode):
        super().__init__(node)
        in_cols = node.inputs[0].column_names
        self.k_idx = in_cols.index(node.key_col)
        self.i_idx = (
            in_cols.index(node.instance_col) if node.instance_col else None
        )
        # rowwise oracle/fallback state: inst -> {rowkey: t}
        self.instances: dict[Any, dict[int, Any]] = {}
        # keyed by the INSTANCE VALUE on both paths (the arrangement keeps
        # the instance value as a column, so the fallback can carry this
        # map over untouched — what was emitted must never be recomputed)
        self.emitted: dict[Any, dict[int, tuple]] = {}
        self.arr = Arrangement(2)  # cols: [time, instance value]
        self._rowwise = False
        self._fallback_reason: str | None = None
        self._m_fallbacks = _fallback_counter()
        if _state_rowwise_env():
            self._to_rowwise("env")

    # --- session grouping (shared by both paths) -------------------------

    def _grouped_rows(self, rows: dict[int, Any]) -> dict[int, tuple]:
        """rows: {rowkey: t} -> {rowkey: (window_start, window_end)}."""
        order = sorted(rows.items(), key=lambda kv: (kv[1], kv[0]))
        out: dict[int, tuple] = {}
        node = self.node
        group: list[tuple[int, Any]] = []

        def flush():
            if not group:
                return
            start = group[0][1]
            end = group[-1][1]
            for k, _t in group:
                out[k] = (start, end)

        for k, t in order:
            if group:
                prev_t = group[-1][1]
                if node.predicate is not None:
                    same = bool(node.predicate(prev_t, t))
                else:
                    same = (t - prev_t) < node.max_gap
                if not same:
                    flush()
                    group = []
            group.append((k, t))
        flush()
        return out

    def _grouped(self, inst) -> dict[int, tuple]:
        return self._grouped_rows(self.instances.get(inst, {}))

    def _emit_diffs(self, touched_keys, new_by_key) -> list[DiffBatch]:
        # two phases: build every diff first, mutate self.emitted only
        # after — an exception mid-loop must not record rows as emitted
        # that the caller then discards (the fallback retry diffs against
        # self.emitted, so it must exactly mirror what downstream holds)
        out_rows: list[tuple[int, int, tuple]] = []
        for tk in touched_keys:
            new_vals = new_by_key[tk]
            emitted = self.emitted.get(tk, ())
            for k in set(emitted) | set(new_vals):
                old = emitted.get(k) if emitted else None
                new = new_vals.get(k)
                if old == new:
                    continue
                if old is not None:
                    out_rows.append((k, -1, old))
                if new is not None:
                    out_rows.append((k, 1, new))
        for tk in touched_keys:
            self.emitted[tk] = dict(new_by_key[tk])
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]

    # --- fallback / oracle management -----------------------------------

    def _view_by_jk(
        self, rows: Rows
    ) -> tuple[dict[int, dict[int, Any]], dict[int, Any]]:
        """Probed entries -> ({jk: {rowkey: t}}, {jk: instance value})
        (count>0 only)."""
        view: dict[int, dict[int, Any]] = {}
        inst_of: dict[int, Any] = {}
        if not len(rows):
            return view, inst_of
        ts = rows.cols[0].tolist()
        insts = rows.cols[1].tolist()
        jks = rows.jk.tolist()
        keys = rows.key.tolist()
        counts = rows.count.tolist()
        for i in range(len(jks)):
            if counts[i] > 0:
                view.setdefault(jks[i], {})[keys[i]] = ts[i]
                inst_of[jks[i]] = insts[i]
        return view, inst_of

    def _to_rowwise(self, reason: str) -> None:
        self._rowwise = True
        self._fallback_reason = reason
        self._m_fallbacks.labels(type(self).__name__, reason).inc()
        rows = self.arr.entries()
        if len(rows):
            ts = rows.cols[0].tolist()
            insts = rows.cols[1].tolist()
            keys = rows.key.tolist()
            counts = rows.count.tolist()
            for i in range(len(keys)):
                if counts[i] > 0:
                    self.instances.setdefault(insts[i], {})[keys[i]] = ts[i]
        # self.emitted is inst-keyed on both paths and mirrors exactly
        # what downstream holds — carry it over UNTOUCHED (recomputing it
        # from post-delta state would swallow the failed tick's diffs)
        self.arr = Arrangement(2)

    # --- operator snapshots ---------------------------------------------

    def arranged_state(self):
        if self._rowwise:
            return None
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("node", "arr", "instances", "emitted")
            and not k.startswith("_m_")
        }
        return residual, {"arr": self.arr}

    def load_arranged_state(self, residual, arrangements) -> None:
        self.__dict__.update(residual)
        self.arr = arrangements["arr"]
        self.instances = {}
        # emitted is derived state: recompute per stored instance
        view, inst_of = self._view_by_jk(self.arr.entries())
        self.emitted = {
            inst_of[jk]: self._grouped_rows(rows)
            for jk, rows in view.items()
        }
        if _state_rowwise_env():
            self._rowwise = False  # residual was snapshotted columnar
            self._to_rowwise("env")

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        if not self._rowwise and "arr" not in state and self.instances:
            # legacy monolith snapshot (pre-arrangement): seed the
            # arrangement from the restored dicts; emitted is already
            # inst-keyed and carries over as-is
            insts: list = []
            keys: list = []
            ts: list = []
            for inst, rows in self.instances.items():
                for k, t in rows.items():
                    insts.append(inst)
                    keys.append(k)
                    ts.append(t)
            inst_col = np.empty(len(insts), dtype=object)
            inst_col[:] = insts
            t_col = np.empty(len(ts), dtype=object)
            t_col[:] = ts
            self.arr = Arrangement(2)
            self.arr.append(
                ref_scalars_columns([inst_col], len(insts)),
                np.asarray(keys, dtype=np.uint64),
                np.ones(len(insts), dtype=np.int64),
                [t_col, inst_col],
            )
            self.instances = {}

    # --- columnar path ---------------------------------------------------

    def _process_arranged(self, b: DiffBatch) -> list[DiffBatch]:
        n = len(b)
        cols = list(b.columns.values())
        inst_col = cols[self.i_idx] if self.i_idx is not None else _none_col(n)
        jks = ref_scalars_columns([inst_col], n)
        tcol = cols[self.k_idx]
        order = np.argsort(jks, kind="stable")
        jks_s = jks[order]
        boundary = np.empty(n, dtype=bool)
        boundary[0] = True
        boundary[1:] = jks_s[1:] != jks_s[:-1]
        starts = np.nonzero(boundary)[0]
        touched = jks_s[starts]  # sorted unique
        # representative instance VALUE per touched jk (emission state is
        # inst-keyed so the fallback can carry it across paths)
        touched_inst = inst_col[order[starts]].tolist()
        # post-delta state is all this node needs (emission diffs against
        # self.emitted): append first, then probe the touched instances.
        # Safe under the exception fallback: the dict apply is idempotent
        # (insert overwrites, retract pops), so the rowwise retry
        # re-applying this delta over the materialized post-delta state
        # cannot double-count — and _emit_diffs defers its mutations, so
        # self.emitted still mirrors what downstream actually received.
        self.arr.append(jks, b.keys, b.diffs, [tcol, inst_col])
        view, _inst_of = self._view_by_jk(self.arr.probe(touched))
        new_by_key = {
            inst: self._grouped_rows(view.get(int(jk), {}))
            for jk, inst in zip(touched.tolist(), touched_inst)
        }
        return self._emit_diffs(list(new_by_key), new_by_key)

    # --- rowwise oracle / fallback ---------------------------------------

    def _process_rowwise(self, inputs) -> list[DiffBatch]:
        touched: dict[Any, None] = {}
        for b in inputs[0]:
            for k, d, vals in b.iter_rows():
                inst = vals[self.i_idx] if self.i_idx is not None else None
                rows = self.instances.setdefault(inst, {})
                if d > 0:
                    rows[k] = vals[self.k_idx]
                else:
                    rows.pop(k, None)
                touched[inst] = None
        new_by_key = {inst: self._grouped(inst) for inst in touched}
        return self._emit_diffs(list(touched), new_by_key)

    def process(self, t, inputs):
        if self._rowwise:
            return self._process_rowwise(inputs)
        b = _concat_inputs(inputs[0], self.node.inputs[0].column_names)
        if not len(b):
            return []
        try:
            return self._process_arranged(b)
        except Exception:
            import logging

            logging.getLogger("pathway_tpu").exception(
                "session-assign columnar path failed; falling back to the "
                "rowwise path for node %s", self.node
            )
            self._to_rowwise("exception")
            return self._process_rowwise(inputs)


# ---------------------------------------------------------------------------
# Temporal pair joins (interval / asof): shared state + restate machinery


class _TimedSide:
    """Rows of one join side, grouped by equality key, sorted by time —
    the rowwise dict representation.  In the arranged engine it survives
    as the differential-testing oracle's state, the exception fallback's
    state, AND the per-tick *view* the columnar path materializes for
    touched groups only (probe → view → apply delta → restate), so both
    paths share one apply/sort semantics."""

    __slots__ = ("by_jk",)

    def __init__(self):
        # jk -> {rowkey: (time, vals, count)}
        self.by_jk: dict[int, dict[int, list]] = {}

    def apply(self, jk: int, k: int, d: int, time: Any, vals: tuple):
        rows = self.by_jk.setdefault(jk, {})
        e = rows.get(k)
        if e is None:
            if d != 0:
                rows[k] = [time, vals, d]
        else:
            e[2] += d
            if d > 0:
                e[0], e[1] = time, vals
            if e[2] == 0:
                del rows[k]
        if not rows:
            self.by_jk.pop(jk, None)

    def sorted_rows(self, jk: int) -> list[tuple[Any, int, tuple]]:
        rows = self.by_jk.get(jk, {})
        return sorted(
            (
                (time, k, vals)
                for k, (time, vals, c) in rows.items()
                if c > 0
            ),
            key=lambda r: (r[0], r[1]),
        )


class _ArrangedSide:
    """One side's buffered rows in a columnar Arrangement — jk = hashed
    on-columns, rowkey = row id, cols = the side's value columns."""

    __slots__ = ("arr",)

    def __init__(self, n_cols: int, arr: Arrangement | None = None):
        self.arr = arr if arr is not None else Arrangement(n_cols)

    def view(self, rows: Rows) -> _TimedSide:
        """Materialize probed entries as a dict view (touched groups
        only) that _TimedSide.apply/sorted_rows can drive."""
        side = _TimedSide()
        if not len(rows):
            return side
        cols = [c.tolist() for c in rows.cols]
        jks = rows.jk.tolist()
        keys = rows.key.tolist()
        counts = rows.count.tolist()
        by_jk = side.by_jk
        vals_it = zip(*cols) if cols else iter([()] * len(jks))
        for jk, k, c, vals in zip(jks, keys, counts, vals_it):
            by_jk.setdefault(jk, {})[k] = [None, tuple(vals), c]
        return side


class _TemporalJoinExecBase(NodeExec):
    """Touched-group restate: like JoinExec (nodes.py) but match rules involve
    the time columns and unmatched rows are tracked per row, not per group.

    State lives in per-side Arrangements: a tick derives both sides' join
    keys with the C batch hasher, probes only the touched keys (one
    searchsorted pair per segment), materializes those groups as a dict
    view, overlays the delta through the SAME apply the rowwise oracle
    uses, and restates.  The arrangement commit happens last, so the
    exception fallback (and the PATHWAY_STATE_ROWWISE oracle) always sees
    consistent pre-tick state."""

    def __init__(self, node):
        super().__init__(node)
        lcols = node.inputs[0].column_names
        rcols = node.inputs[1].column_names
        self.l_on_idx = [lcols.index(c) for c in node.left_on]
        self.r_on_idx = [rcols.index(c) for c in node.right_on]
        self.lt_idx = lcols.index(node.left_time)
        self.rt_idx = rcols.index(node.right_time)
        self.n_l = len(lcols)
        self.n_r = len(rcols)
        self._rowwise = False
        self._fallback_reason: str | None = None
        self._m_fallbacks = _fallback_counter()
        if _state_rowwise_env():
            self._rowwise = True
            self._fallback_reason = "env"
            self._m_fallbacks.labels(type(self).__name__, "env").inc()
            self.left: Any = _TimedSide()
            self.right: Any = _TimedSide()
        else:
            self.left = _ArrangedSide(self.n_l)
            self.right = _ArrangedSide(self.n_r)

    def _jk(self, vals: tuple, idx: list[int]) -> int:
        return int(ref_scalar(*(vals[i] for i in idx)))

    def _outputs_for_jk(self, jk, lrows, rrows) -> dict[int, tuple]:
        """Current output rows for one join key given both sides' sorted
        row lists [(time, rowkey, vals), ...]."""
        raise NotImplementedError

    def _pad_left(self, lk: int, lvals: tuple) -> tuple[int, tuple]:
        okey = int(ref_scalar(Pointer(lk), None))
        return okey, lvals + (None,) * self.n_r + (Pointer(lk), None)

    def _pad_right(self, rk: int, rvals: tuple) -> tuple[int, tuple]:
        okey = int(ref_scalar(None, Pointer(rk)))
        return okey, (None,) * self.n_l + rvals + (None, Pointer(rk))

    def _pair(self, lk: int, lvals: tuple, rk: int, rvals: tuple):
        okey = int(ref_scalar(Pointer(lk), Pointer(rk)))
        return okey, lvals + rvals + (Pointer(lk), Pointer(rk))

    # --- fallback / oracle management -----------------------------------

    def _to_rowwise(self, reason: str) -> None:
        self._rowwise = True
        self._fallback_reason = reason
        self._m_fallbacks.labels(type(self).__name__, reason).inc()
        for attr, t_idx in (("left", self.lt_idx), ("right", self.rt_idx)):
            arranged = getattr(self, attr)
            side = arranged.view(arranged.arr.entries())
            for rows in side.by_jk.values():
                for e in rows.values():
                    e[0] = e[1][t_idx]
            setattr(self, attr, side)

    # --- operator snapshots ---------------------------------------------

    def arranged_state(self):
        if self._rowwise:
            return None
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k not in ("node", "left", "right") and not k.startswith("_m_")
        }
        return residual, {"left": self.left.arr, "right": self.right.arr}

    def load_arranged_state(self, residual, arrangements) -> None:
        self.__dict__.update(residual)
        self.left = _ArrangedSide(self.n_l, arrangements["left"])
        self.right = _ArrangedSide(self.n_r, arrangements["right"])
        if _state_rowwise_env():
            self._rowwise = False  # residual was snapshotted columnar
            self._to_rowwise("env")

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        if not self._rowwise and isinstance(self.left, _TimedSide):
            # legacy monolith snapshot (pre-arrangement dict sides): seed
            # per-side arrangements so the columnar path continues with
            # the restored state instead of silently ignoring it
            self.left = self._seed_side(self.left, self.n_l)
            self.right = self._seed_side(self.right, self.n_r)

    @staticmethod
    def _seed_side(side: _TimedSide, n_cols: int) -> _ArrangedSide:
        jks: list[int] = []
        keys: list[int] = []
        counts: list[int] = []
        vals_rows: list[tuple] = []
        for jk, rows in side.by_jk.items():
            for k, (_t, vals, c) in rows.items():
                jks.append(jk)
                keys.append(k)
                counts.append(c)
                vals_rows.append(vals)
        arranged = _ArrangedSide(n_cols)
        if jks:
            cols = []
            for ci in range(n_cols):
                col = np.empty(len(vals_rows), dtype=object)
                col[:] = [v[ci] for v in vals_rows]
                cols.append(col)
            arranged.arr.append(
                np.asarray(jks, dtype=np.uint64),
                np.asarray(keys, dtype=np.uint64),
                np.asarray(counts, dtype=np.int64),
                cols,
            )
        return arranged

    # --- emission (shared) ------------------------------------------------

    def _emit(self, touched, before, after) -> list[DiffBatch]:
        from pathway_tpu.engine.batch import _values_eq

        out_rows: list[tuple[int, int, tuple]] = []
        for jk in touched:
            aft = after[jk]
            bef = before[jk]
            for okey, vals in bef.items():
                new = aft.get(okey)
                if new is None or not _values_eq(vals, new):
                    out_rows.append((okey, -1, vals))
            for okey, vals in aft.items():
                old = bef.get(okey)
                if old is None or not _values_eq(old, vals):
                    out_rows.append((okey, 1, vals))
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]

    # --- columnar path ---------------------------------------------------

    def _batch_jks(self, b: DiffBatch, on_idx: list[int]) -> np.ndarray:
        cols = list(b.columns.values())
        return ref_scalars_columns([cols[i] for i in on_idx], len(b))

    def _process_arranged(self, lb, rb) -> list[DiffBatch]:
        jks_l = (
            self._batch_jks(lb, self.l_on_idx)
            if len(lb)
            else np.empty(0, np.uint64)
        )
        jks_r = (
            self._batch_jks(rb, self.r_on_idx)
            if len(rb)
            else np.empty(0, np.uint64)
        )
        touched_arr = np.unique(np.concatenate([jks_l, jks_r]))
        # probe pre-tick state for the touched keys; the dict view carries
        # times lazily (filled from the stored vals below)
        view_l = self.left.view(self.left.arr.probe(touched_arr))
        view_r = self.right.view(self.right.arr.probe(touched_arr))
        for side, t_idx in ((view_l, self.lt_idx), (view_r, self.rt_idx)):
            for rows in side.by_jk.values():
                for e in rows.values():
                    e[0] = e[1][t_idx]
        touched = [int(j) for j in touched_arr.tolist()]
        before = {
            jk: self._outputs_for_jk(
                jk, view_l.sorted_rows(jk), view_r.sorted_rows(jk)
            )
            for jk in touched
        }
        # overlay the delta through the oracle's own apply
        lrows_py = list(lb.iter_rows()) if len(lb) else []
        rrows_py = list(rb.iter_rows()) if len(rb) else []
        for (k, d, vals), jk in zip(lrows_py, jks_l.tolist()):
            view_l.apply(jk, k, d, vals[self.lt_idx], vals)
        for (k, d, vals), jk in zip(rrows_py, jks_r.tolist()):
            view_r.apply(jk, k, d, vals[self.rt_idx], vals)
        after = {
            jk: self._outputs_for_jk(
                jk, view_l.sorted_rows(jk), view_r.sorted_rows(jk)
            )
            for jk in touched
        }
        out = self._emit(touched, before, after)
        # commit the delta into arranged state only after the pure
        # computation succeeded (the exception fallback must see pre-tick
        # state); stage both sides before committing either
        staged_l = self.left.arr.stage(
            jks_l, lb.keys, lb.diffs, list(lb.columns.values())
        ) if len(lb) else None
        staged_r = self.right.arr.stage(
            jks_r, rb.keys, rb.diffs, list(rb.columns.values())
        ) if len(rb) else None
        self.left.arr.commit(staged_l)
        self.right.arr.commit(staged_r)
        return out

    # --- rowwise oracle / fallback ---------------------------------------

    def _process_rowwise(self, lb, rb) -> list[DiffBatch]:
        touched: dict[int, None] = {}
        l_updates, r_updates = [], []
        for k, d, vals in lb.iter_rows():
            jk = self._jk(vals, self.l_on_idx)
            touched[jk] = None
            l_updates.append((jk, k, d, vals[self.lt_idx], vals))
        for k, d, vals in rb.iter_rows():
            jk = self._jk(vals, self.r_on_idx)
            touched[jk] = None
            r_updates.append((jk, k, d, vals[self.rt_idx], vals))
        before = {
            jk: self._outputs_for_jk(
                jk, self.left.sorted_rows(jk), self.right.sorted_rows(jk)
            )
            for jk in touched
        }
        for jk, k, d, time, vals in l_updates:
            self.left.apply(jk, k, d, time, vals)
        for jk, k, d, time, vals in r_updates:
            self.right.apply(jk, k, d, time, vals)
        after = {
            jk: self._outputs_for_jk(
                jk, self.left.sorted_rows(jk), self.right.sorted_rows(jk)
            )
            for jk in touched
        }
        return self._emit(touched, before, after)

    def process(self, t, inputs):
        lb = _concat_inputs(inputs[0], self.node.inputs[0].column_names)
        rb = _concat_inputs(inputs[1], self.node.inputs[1].column_names)
        if not len(lb) and not len(rb):
            return []
        if self._rowwise:
            return self._process_rowwise(lb, rb)
        try:
            return self._process_arranged(lb, rb)
        except Exception:
            import logging

            logging.getLogger("pathway_tpu").exception(
                "temporal-join columnar path failed; falling back to the "
                "rowwise path for node %s", self.node
            )
            self._to_rowwise("exception")
            return self._process_rowwise(lb, rb)


def _join_out_cols(left: Node, right: Node) -> list[str]:
    return (
        ["l." + c for c in left.column_names]
        + ["r." + c for c in right.column_names]
        + ["_left_id", "_right_id"]
    )


class IntervalJoinNode(Node):
    """Pairs (l, r) with equal on-columns and
    l.time + lower <= r.time <= l.time + upper
    (reference: stdlib/temporal/_interval_join.py interval_join — there
    desugared into bucketed equijoins; here a dedicated incremental node).
    """

    is_stateful = True

    def __init__(
        self,
        left: Node,
        right: Node,
        left_on: Sequence[str],
        right_on: Sequence[str],
        left_time: str,
        right_time: str,
        lower: Any,
        upper: Any,
        mode: str,  # inner | left | right | outer
    ):
        super().__init__([left, right], _join_out_cols(left, right))
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.left_time = left_time
        self.right_time = right_time
        self.lower = lower
        self.upper = upper
        self.mode = mode

    def make_exec(self):
        return IntervalJoinExec(self)


class IntervalJoinExec(_TemporalJoinExecBase):
    def _outputs_for_jk(self, jk, lrows, rrows) -> dict[int, tuple]:
        node = self.node
        out: dict[int, tuple] = {}
        r_times = [r[0] for r in rrows]
        matched_right: set[int] = set()
        for lt, lk, lvals in lrows:
            lo = bisect.bisect_left(r_times, lt + node.lower)
            hi = bisect.bisect_right(r_times, lt + node.upper)
            if lo < hi:
                for rt, rk, rvals in rrows[lo:hi]:
                    matched_right.add(rk)
                    okey, vals = self._pair(lk, lvals, rk, rvals)
                    out[okey] = vals
            elif node.mode in ("left", "outer"):
                okey, vals = self._pad_left(lk, lvals)
                out[okey] = vals
        if node.mode in ("right", "outer"):
            for rt, rk, rvals in rrows:
                if rk not in matched_right:
                    okey, vals = self._pad_right(rk, rvals)
                    out[okey] = vals
        return out


class AsofJoinNode(Node):
    """As-of join: each left row matches the single best right row per
    `direction` (reference: stdlib/temporal/_asof_join.py).

    direction: 'backward' (largest r.t <= l.t), 'forward' (smallest
    r.t >= l.t), 'nearest'. mode: left | right | outer — 'outer' emits every
    left row (matched or padded) plus every right row that is nobody's match.
    """

    is_stateful = True

    def __init__(
        self,
        left: Node,
        right: Node,
        left_on: Sequence[str],
        right_on: Sequence[str],
        left_time: str,
        right_time: str,
        direction: str,
        mode: str,
    ):
        # _pw_self_t / _pw_side = the perspective row's OWN time and side
        # (the reference's synthetic `t` and `side` output columns;
        # side=False for left-perspective rows, True for right)
        super().__init__(
            [left, right],
            _join_out_cols(left, right) + ["_pw_self_t", "_pw_side"],
        )
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.left_time = left_time
        self.right_time = right_time
        self.direction = direction
        self.mode = mode

    def make_exec(self):
        return AsofJoinExec(self)


def _asof_inclusive(direction: str, mode: str, probe_side: str) -> bool:
    """Whether an other-side row at the SAME time as the probe matches.

    The reference decides ties by its merged sort order (t, side ^
    right_first, id) with right_first = (BACKWARD and LEFT) or (FORWARD
    and RIGHT) — a same-time other-side row only matches when that order
    puts it on the probed side of the row (_asof_join.py:258-292)."""
    right_first = (direction == "backward" and mode == "left") or (
        direction == "forward" and mode == "right"
    )
    other_before = right_first if probe_side == "l" else not right_first
    if direction == "backward":
        return other_before
    if direction == "forward":
        return not other_before
    return True  # nearest: distance ties resolved in _asof_pick


def _asof_pick(
    rows: list[tuple[Any, int, tuple]],
    times: list[Any],
    t: Any,
    direction: str,
    inclusive: bool = True,
):
    """Best match among `rows` (sorted by time) for a probe at time t."""
    if not rows:
        return None
    if direction == "backward":
        i = (
            bisect.bisect_right(times, t) - 1
            if inclusive
            else bisect.bisect_left(times, t) - 1
        )
        return rows[i] if i >= 0 else None
    if direction == "forward":
        i = (
            bisect.bisect_left(times, t)
            if inclusive
            else bisect.bisect_right(times, t)
        )
        return rows[i] if i < len(rows) else None
    # nearest — a distance tie picks the later row (reference:
    # select_nearest uses prev only when strictly closer)
    i = bisect.bisect_left(times, t) - 1
    j = bisect.bisect_left(times, t)
    prev_r = rows[i] if i >= 0 else None
    next_r = rows[j] if j < len(rows) else None
    if prev_r is None:
        return next_r
    if next_r is None:
        return prev_r
    return prev_r if (t - prev_r[0]) < (next_r[0] - t) else next_r


class AsofJoinExec(_TemporalJoinExecBase):
    def _outputs_for_jk(self, jk, lrows, rrows) -> dict[int, tuple]:
        node = self.node
        out: dict[int, tuple] = {}
        l_times = [r[0] for r in lrows]
        r_times = [r[0] for r in rrows]
        # output keys mix the side into the hash — a left row and a right row
        # can share a raw row id (e.g. two fixture tables), so plain lk/rk
        # keys would collide and silently drop rows
        if node.mode in ("left", "outer"):
            for lt, lk, lvals in lrows:
                okey = int(ref_scalar(Pointer(lk), 0))
                m = _asof_pick(
                    rrows, r_times, lt, node.direction,
                    _asof_inclusive(node.direction, node.mode, "l"),
                )
                if m is not None:
                    _rt, rk, rvals = m
                    out[okey] = lvals + rvals + (
                        Pointer(lk), Pointer(rk), lt, False,
                    )
                else:
                    out[okey] = lvals + (None,) * self.n_r + (
                        Pointer(lk), None, lt, False,
                    )
        if node.mode in ("right", "outer"):
            # the direction stays the SAME from the right row's perspective
            # (backward = latest left at-or-before the right row's time) —
            # outer emits every right-perspective row, matched or not
            # (reference: _asof_join merges the m0 and m1 perspectives)
            for rt, rk, rvals in rrows:
                okey = int(ref_scalar(Pointer(rk), 1))
                m = _asof_pick(
                    lrows, l_times, rt, node.direction,
                    _asof_inclusive(node.direction, node.mode, "r"),
                )
                if m is not None:
                    _lt, lk, lvals = m
                    out[okey] = lvals + rvals + (
                        Pointer(lk), Pointer(rk), rt, True,
                    )
                else:
                    out[okey] = (None,) * self.n_l + rvals + (
                        None, Pointer(rk), rt, True,
                    )
        return out


class AsofNowJoinNode(Node):
    """`asof_now` join: left is a query stream — each left insertion is joined
    against the right side's state *at that moment* and the result is never
    revised by later right-side updates (reference:
    stdlib/temporal/_asof_now_join.py; engine analog: the as-of-now query path
    of use_external_index, src/engine/dataflow.rs:2694). Left retractions do
    retract their previously-emitted results. mode: inner | left."""

    is_stateful = True

    def __init__(
        self,
        left: Node,
        right: Node,
        left_on: Sequence[str],
        right_on: Sequence[str],
        mode: str,
        id_from: str | None = None,
    ):
        super().__init__([left, right], _join_out_cols(left, right))
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.mode = mode
        self.id_from = id_from  # "left": output rows keyed by query row id

    def make_exec(self):
        return AsofNowJoinExec(self)


_U64 = 0xFFFFFFFFFFFFFFFF


class AsofNowJoinExec(NodeExec):
    """Dict compute state + arrangement-backed persistence ledgers (the
    PR-7 State Ledger protocol): the right side's buffered rows and the
    per-query emitted rows mirror into two Arrangements as append-only
    deltas, so snapshots write bytes ∝ churn and recovery mmap-rebuilds
    instead of unpickling a monolith.  ``PATHWAY_STATE_ROWWISE=1``
    disables the ledgers — the monolithic ``state_dict`` pickle is the
    differential oracle for the ledger path."""

    def __init__(self, node: AsofNowJoinNode):
        super().__init__(node)
        lcols = node.inputs[0].column_names
        rcols = node.inputs[1].column_names
        self.l_on_idx = [lcols.index(c) for c in node.left_on]
        self.r_on_idx = [rcols.index(c) for c in node.right_on]
        self.n_r = len(rcols)
        # right state: jk -> {rowkey: (vals, count)}
        self.right: dict[int, dict[int, list]] = {}
        # what each left row key emitted: lk -> list[(okey, vals)]
        self.emitted_by_left: dict[int, list[tuple[int, tuple]]] = {}
        self._ledger_on = not _state_rowwise_env()
        # ledger arrangements (persistence only, never probed on the hot
        # path): right rows keyed (hashed on-cols, row key), emissions
        # keyed (left row key, output key) with exact ints in the cols
        self.arr_right = Arrangement(self.n_r)
        self.arr_emit = Arrangement(3)  # cols: [lk, okey, vals tuple]

    # --- persistence ledger ----------------------------------------------

    def _emit_ledger_ops(
        self,
        ops: list[tuple[int, int, int, tuple]],  # (lk, okey, diff, vals)
    ) -> None:
        if not ops or not self._ledger_on:
            return
        n = len(ops)
        jks = np.fromiter(
            (lk & _U64 for lk, _o, _d, _v in ops), dtype=np.uint64, count=n
        )
        keys = np.fromiter(
            (o & _U64 for _lk, o, _d, _v in ops), dtype=np.uint64, count=n
        )
        diffs = np.fromiter(
            (d for _lk, _o, d, _v in ops), dtype=np.int64, count=n
        )
        lk_col = np.empty(n, dtype=object)
        lk_col[:] = [lk for lk, _o, _d, _v in ops]
        ok_col = np.empty(n, dtype=object)
        ok_col[:] = [o for _lk, o, _d, _v in ops]
        val_col = np.empty(n, dtype=object)
        val_col[:] = [v for _lk, _o, _d, v in ops]
        self.arr_emit.append(jks, keys, diffs, [lk_col, ok_col, val_col])

    def arranged_state(self):
        if not self._ledger_on:
            return None
        residual = {
            k: v
            for k, v in self.__dict__.items()
            if k
            not in ("node", "right", "emitted_by_left", "arr_right", "arr_emit")
            and not k.startswith("_m_")
        }
        return residual, {"right": self.arr_right, "emit": self.arr_emit}

    def load_arranged_state(self, residual, arrangements) -> None:
        self.__dict__.update(residual)
        self.arr_right = arrangements["right"]
        self.arr_emit = arrangements["emit"]
        # rebuild the dict compute state; jks recomputed from the stored
        # values with the compute path's own hash, so signedness of the
        # arrangement grouping key never leaks into lookups
        self.right = {}
        rows = self.arr_right.entries()
        if len(rows):
            cols = [c.tolist() for c in rows.cols]
            keys = rows.key.tolist()
            counts = rows.count.tolist()
            for i in range(len(keys)):
                if counts[i] == 0:
                    continue
                vals = tuple(c[i] for c in cols)
                jk = int(ref_scalar(*(vals[j] for j in self.r_on_idx)))
                self.right.setdefault(jk, {})[keys[i]] = [vals, counts[i]]
        self.emitted_by_left = {}
        rows = self.arr_emit.entries()
        if len(rows):
            lks = rows.cols[0].tolist()
            okeys = rows.cols[1].tolist()
            vals_l = rows.cols[2].tolist()
            counts = rows.count.tolist()
            for i in range(len(lks)):
                if counts[i] > 0:
                    self.emitted_by_left.setdefault(int(lks[i]), []).append(
                        (int(okeys[i]), vals_l[i])
                    )
        if _state_rowwise_env():
            # env oracle: drop the ledgers, snapshot monolithically
            self._ledger_on = False
            self.arr_right = Arrangement(self.n_r)
            self.arr_emit = Arrangement(3)

    def load_state(self, state: dict) -> None:
        super().load_state(state)
        # legacy (pre-ledger) monolith snapshot: seed the ledgers from
        # the restored dicts so the next incremental snapshot covers the
        # preexisting state instead of silently dropping it
        if (
            self._ledger_on
            and getattr(self, "arr_right", None) is not None
            and len(self.arr_right) == 0
            and (self.right or self.emitted_by_left)
        ):
            r_ops: list[tuple[int, int, tuple]] = []
            for _jk, rows in self.right.items():
                for k, (vals, c) in rows.items():
                    r_ops.append((k, c, vals))
            if r_ops:
                n = len(r_ops)
                jks = np.fromiter(
                    (
                        int(ref_scalar(*(v[i] for i in self.r_on_idx)))
                        & _U64
                        for _k, _c, v in r_ops
                    ),
                    dtype=np.uint64,
                    count=n,
                )
                keys = np.fromiter(
                    (k & _U64 for k, _c, _v in r_ops),
                    dtype=np.uint64,
                    count=n,
                )
                diffs = np.fromiter(
                    (c for _k, c, _v in r_ops), dtype=np.int64, count=n
                )
                cols = []
                for ci in range(self.n_r):
                    col = np.empty(n, dtype=object)
                    col[:] = [v[ci] for _k, _c, v in r_ops]
                    cols.append(col)
                self.arr_right.append(jks, keys, diffs, cols)
            e_ops = [
                (lk, okey, 1, vals)
                for lk, emitted in self.emitted_by_left.items()
                for okey, vals in emitted
            ]
            self._emit_ledger_ops(e_ops)

    def process(self, t, inputs):
        # right updates first: queries arriving at tick T see right state of T
        for b in inputs[1]:
            n = len(b)
            if n and self._ledger_on:
                # the right ledger IS the input delta: append verbatim
                cols = list(b.columns.values())
                self.arr_right.append(
                    ref_scalars_columns(
                        [cols[i] for i in self.r_on_idx], n
                    ),
                    b.keys,
                    b.diffs,
                    cols,
                )
            for k, d, vals in b.iter_rows():
                jk = int(ref_scalar(*(vals[i] for i in self.r_on_idx)))
                rows = self.right.setdefault(jk, {})
                e = rows.get(k)
                if e is None:
                    if d != 0:
                        rows[k] = [vals, d]
                else:
                    e[1] += d
                    if d > 0:
                        e[0] = vals
                    if e[1] <= 0:
                        del rows[k]
                if not rows:
                    self.right.pop(jk, None)
        out_rows: list[tuple[int, int, tuple]] = []
        ledger_ops: list[tuple[int, int, int, tuple]] = []
        for b in inputs[0]:
            for lk, d, lvals in b.iter_rows():
                if d < 0:
                    for okey, vals in self.emitted_by_left.pop(lk, []):
                        out_rows.append((okey, -1, vals))
                        ledger_ops.append((lk, okey, -1, vals))
                    continue
                jk = int(ref_scalar(*(lvals[i] for i in self.l_on_idx)))
                rrows = self.right.get(jk, {})
                emitted: list[tuple[int, tuple]] = []
                use_lk = self.node.id_from == "left"
                # a re-insert replaces this query's previous emissions in
                # the dict — mirror the replacement into the ledger
                for okey, vals in self.emitted_by_left.get(lk, ()):
                    ledger_ops.append((lk, okey, -1, vals))
                if use_lk and len(rrows) > 1:
                    # id=left.id promises ONE output row per query row; two
                    # matches would silently collapse under the same key.
                    # Recorded (not raised) so non-terminate_on_error runs
                    # keep going with the row poisoned/skipped, matching
                    # GroupByExec's reducer-error contract; terminate_on_
                    # error runs re-raise it as a ValueError when the run
                    # terminates (like every recorded error, it does not
                    # abort an unbounded stream mid-run).
                    record_error(
                        ValueError(
                            "asof_now_join with id=pw.left.id: query row "
                            f"matched {len(rrows)} rows; the id contract "
                            "requires at most one match per query"
                        ),
                        str(self.node),
                    )
                    self.emitted_by_left[lk] = []
                    continue
                if rrows:
                    for rk, (rvals, _c) in rrows.items():
                        okey = lk if use_lk else int(
                            ref_scalar(Pointer(lk), Pointer(rk))
                        )
                        vals = lvals + rvals + (Pointer(lk), Pointer(rk))
                        emitted.append((okey, vals))
                elif self.node.mode == "left":
                    vals = lvals + (None,) * self.n_r + (Pointer(lk), None)
                    emitted.append((lk, vals))
                for okey, vals in emitted:
                    out_rows.append((okey, 1, vals))
                    ledger_ops.append((lk, okey, 1, vals))
                self.emitted_by_left[lk] = emitted
        self._emit_ledger_ops(ledger_ops)
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]
