"""Tick Forge: trace stateless operator chains into fused XLA programs.

The interpreter (engine/runtime.py) walks the exec graph every tick and
dispatches one numpy kernel per operator — at 1M-row ticks a
map→filter→map chain pays one full memory pass per expression node.
Following the full-compilation approach of Julia→TPU (PAPERS.md,
https://arxiv.org/pdf/1810.09868), this module segments the node graph
into maximal chains of *stateless, fixed-schema* operators
(StreamMap/select expression eval, Filter, Reindex with numeric keys,
Concat fan-in) and lowers each chain's expression trees into ONE pure
``jax.jit``-ted function over columnar device arrays.  Filters lower to
masks (the traced program is shape-stable; the host epilogue compresses),
object/string columns pass through host-side untouched, and anything the
tracer cannot prove equivalent — UDFs, async exprs, Pointer-producing
expressions, object-dtype inputs — marks a chain boundary and falls back
to the per-node interpreter, per tick, with identical semantics.

Shape bucketing: programs are cached per (segment id, padded row-count
bucket, input dtype tuple).  Row counts pad up the same power-of-two
ladder the Surge Gate micro-batcher already releases batches on
(serving/config.py ``batch_buckets``), so steady-state serving flushes
and steady ingest ticks hit the cache on nearly every tick; padded rows
are sliced away (map) or masked out (filter) on the host before the
batch continues downstream.

GroupBy's semigroup fast path (count/sum/avg) can also run its partial
aggregation as a jitted ``segment_sum`` program (``semigroup_partials``).
On this box's CPU backend that is a measured LOSS — XLA CPU lowers
scatter-add ~40x slower than numpy 2.0's ``np.ufunc.at`` at 1M rows —
so the device path is opt-in via ``PATHWAY_COMPILED_GROUPBY=1`` and
auto-enables only on real accelerator backends, where scatter lands on
the vector units and the decision flips (TPU-KNN's peak-FLOP/s argument,
https://arxiv.org/pdf/2206.14286).

Knobs:
  PATHWAY_COMPILED_TICK=0     escape hatch — byte-identical interpreter
  PATHWAY_COMPILED_MIN_ROWS   smallest batch worth dispatching (def 64)
  PATHWAY_COMPILED_GROUPBY    1/0 force the device semigroup partials
                              (default: auto — off on cpu backends)

Metrics: pathway_engine_compile_cache_{hits,misses}_total,
pathway_engine_compile_seconds, pathway_engine_compile_fallbacks_total
{reason}; per-segment ``compiled`` flags ride /debug/graph.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.expression_eval import InternalColRef
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr

logger = logging.getLogger("pathway_tpu")


# ---------------------------------------------------------------------------
# knobs


def compiled_tick_enabled() -> bool:
    """Default ON; PATHWAY_COMPILED_TICK=0 restores the byte-identical
    interpreter path (re-read per Runtime like engine_threads)."""
    return os.environ.get("PATHWAY_COMPILED_TICK", "1") != "0"


def compiled_min_rows() -> int:
    """Batches below this size skip the device dispatch — jit-call
    overhead beats fusion wins on tiny ticks."""
    raw = os.environ.get("PATHWAY_COMPILED_MIN_ROWS", "")
    try:
        return max(1, int(raw)) if raw else 64
    except ValueError:
        return 64


def compiled_groupby_enabled() -> bool:
    """Device semigroup partials: explicit 1/0 wins; default auto —
    enabled only when the default jax backend is a real accelerator
    (XLA CPU scatter-add measured ~40x slower than np.add.at here)."""
    raw = os.environ.get("PATHWAY_COMPILED_GROUPBY", "")
    if raw:
        return raw != "0"
    try:
        import jax

        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def row_bucket(n: int) -> int:
    """Power-of-two pad bucket — the same ladder Surge Gate's
    micro-batcher releases batches on (serving/config.py), so gated
    serving flushes land on a handful of buckets."""
    b = 8
    while b < n:
        b <<= 1
    return b


# ---------------------------------------------------------------------------
# metrics (process-wide registry; label children cached at module level)


def _metrics():
    global _HITS, _MISSES, _COMPILE_HIST, _FALLBACKS
    if _HITS is None:
        from pathway_tpu.observability import REGISTRY

        _HITS = REGISTRY.counter(
            "pathway_engine_compile_cache_hits_total",
            "compiled-tick programs reused from the shape-bucketed cache",
        )
        _MISSES = REGISTRY.counter(
            "pathway_engine_compile_cache_misses_total",
            "compiled-tick cache misses (trace+compile, or a negative "
            "entry recording a non-lowerable dtype tuple)",
        )
        _COMPILE_HIST = REGISTRY.histogram(
            "pathway_engine_compile_seconds",
            "wall time spent tracing+compiling one segment program",
        )
        _FALLBACKS = REGISTRY.counter(
            "pathway_engine_compile_fallbacks_total",
            "ticks a planned segment ran on the interpreter instead",
            labelnames=("reason",),
        )
    return _HITS, _MISSES, _COMPILE_HIST, _FALLBACKS


_HITS = _MISSES = _COMPILE_HIST = _FALLBACKS = None


class NotCompilable(Exception):
    """This expression/segment cannot be lowered (reason in args[0])."""

    @property
    def reason(self) -> str:
        return self.args[0]


# ---------------------------------------------------------------------------
# structural classification (build-time; shared with the Graph Doctor)

# operators with exact XLA equivalents under the engine's numpy
# semantics.  /, //, %, ** are excluded: their ERROR-poison semantics
# (record_error + per-row poison on zero divisors) have no pure
# counterpart; << >> excluded (negative shift counts are UB and differ
# across backends); @ is object-valued.
_OK_BINOPS = frozenset({"+", "-", "*", "==", "!=", "<", "<=", ">", ">=",
                        "&", "|", "^"})
_CMP_OPS = frozenset({"==", "!=", "<", "<=", ">", ">="})
_BITS_OPS = frozenset({"&", "|", "^"})
_ARITH_OPS = frozenset({"+", "-", "*"})

_CAST_TARGETS = (dt.INT, dt.FLOAT, dt.BOOL)


def classify_expr(e: expr.ColumnExpression) -> str | None:
    """``None`` when the expression is *structurally* lowerable (dtype
    feasibility is still decided per tick against the concrete batch);
    otherwise a short reason used by the planner and the Graph Doctor's
    ``compile-boundary`` rule."""
    if isinstance(e, InternalColRef):
        if e._name == "id":
            return "id column (Pointer-valued)"
        if e._input_index != 0:
            return "multi-input column reference"
        return None
    if isinstance(e, expr.ColumnConstExpression):
        v = e._value
        if isinstance(v, bool):
            return None
        if isinstance(v, int):
            return None if -(2**63) <= v < 2**63 else "big-int constant"
        if isinstance(v, float):
            return None
        return f"object constant ({type(v).__name__})"
    if isinstance(e, expr.ColumnBinaryOpExpression):
        if e._op not in _OK_BINOPS:
            return f"operator {e._op!r} (interpreter-only semantics)"
        return classify_expr(e._left) or classify_expr(e._right)
    if isinstance(e, expr.ColumnUnaryOpExpression):
        if e._op not in ("-", "~", "abs"):
            return f"unary operator {e._op!r}"
        return classify_expr(e._expr)
    if isinstance(e, expr.IfElseExpression):
        return (
            classify_expr(e._if)
            or classify_expr(e._then)
            or classify_expr(e._else)
        )
    if isinstance(e, expr.CoalesceExpression):
        # numeric first arg short-circuits in the interpreter
        return classify_expr(e._args[0])
    if isinstance(e, (expr.FillErrorExpression, expr.UnwrapExpression)):
        return classify_expr(e._expr)
    if isinstance(e, expr.RequireExpression):
        r = classify_expr(e._val)
        if r:
            return r
        for a in e._args:
            r = classify_expr(a)
            if r:
                return r
        return None
    if isinstance(e, expr.CastExpression):
        if e._target.strip_optional() not in _CAST_TARGETS:
            return f"cast to {e._target}"
        return classify_expr(e._expr)
    if isinstance(e, expr.DeclareTypeExpression):
        return classify_expr(e._expr)
    if isinstance(e, (expr.IsNoneExpression, expr.IsNotNoneExpression)):
        return classify_expr(e._expr)
    if isinstance(e, expr.AsyncApplyExpression):
        return "async UDF"
    if isinstance(e, (expr.BatchApplyExpression, expr.ApplyExpression)):
        return "UDF (pw.apply)"
    if isinstance(e, expr.MethodCallExpression):
        return "method call (host-side scalar/vector fn)"
    if isinstance(e, expr.PointerExpression):
        return "pointer derivation (host-side key hash)"
    if isinstance(
        e,
        (
            expr.MakeTupleExpression,
            expr.GetExpression,
            expr.ToStringExpression,
            expr.ConvertExpression,
        ),
    ):
        return "object-valued expression"
    return f"unsupported expression ({type(e).__name__})"


def _is_bare_ref(e: expr.ColumnExpression) -> bool:
    return isinstance(e, InternalColRef) and e._name != "id"


def classify_node(node: Any) -> tuple[bool, str | None]:
    """(chain-member-eligible, reason-if-not).  Structural only; used by
    the planner and the ``compile-boundary`` doctor rule.  Input/Output
    nodes return a non-user-actionable reason the rule filters out."""
    from pathway_tpu.engine.nodes import (
        ConcatNode,
        FilterNode,
        InputNode,
        OutputNode,
        ReindexNode,
        RowwiseNode,
    )

    if isinstance(node, RowwiseNode):
        if len(node.inputs) > 1:
            return False, "stateful (multi-input aligned select)"
        if not node.deterministic:
            return False, "non-deterministic expressions (cached replay)"
        for e in node.exprs.values():
            if _is_bare_ref(e):
                continue
            r = classify_expr(e)
            if r:
                return False, r
        return True, None
    if isinstance(node, FilterNode):
        r = classify_expr(node.predicate)
        return (False, r) if r else (True, None)
    if isinstance(node, ReindexNode):
        r = classify_expr(node.key_expr)
        return (False, r) if r else (True, None)
    if isinstance(node, ConcatNode):
        return True, None
    if isinstance(node, (InputNode, OutputNode)):
        return False, "__io__"
    if getattr(node, "is_stateful", False):
        return False, f"stateful operator ({type(node).__name__})"
    return False, f"unsupported operator ({type(node).__name__})"


def _has_compute(node: Any) -> bool:
    """A node worth paying a device round-trip for: real expression work
    (not a pure projection/rename) or a filter/reindex."""
    from pathway_tpu.engine.nodes import (
        FilterNode,
        ReindexNode,
        RowwiseNode,
    )

    if isinstance(node, (FilterNode, ReindexNode)):
        return True
    if isinstance(node, RowwiseNode):
        return any(not _is_bare_ref(e) for e in node.exprs.values())
    return False


# ---------------------------------------------------------------------------
# lowering: expression tree -> jnp thunk (+ static result dtype)
#
# A lowered value is one of
#   ("host", src)        passthrough of external input column `src`
#   ("dev", thunk, dt)   thunk(inp, memo) -> jnp array during tracing
#   ("const", v, dt)     scalar literal (materialized lazily; the
#                        interpreter materializes via _full, so consts
#                        promote as ARRAYS — mirrored via result_type)
# Thunks are memoized by identity per trace so a chain column referenced
# twice lowers to one subgraph (XLA would CSE anyway; this bounds trace
# time for deep chains).

_I64 = np.dtype(np.int64)
_F64 = np.dtype(np.float64)
_BOOL = np.dtype(bool)


def _ev(entry: tuple, inp: dict, memo: dict):
    import jax.numpy as jnp

    kind = entry[0]
    if kind == "host":
        return inp[entry[1]]
    if kind == "const":
        n = inp["__n__"]
        return jnp.full((n,), entry[1], dtype=entry[2])
    thunk = entry[1]
    key = id(thunk)
    r = memo.get(key)
    if r is None:
        r = thunk(inp, memo)
        memo[key] = r
    return r


def _entry_dtype(
    entry: tuple, dtypes: dict[str, np.dtype], where: str
) -> np.dtype:
    if entry[0] == "host":
        d = dtypes[entry[1]]
        if d.kind not in "bifu":
            raise NotCompilable(f"object column {entry[1]!r} ({where})")
        return d
    return entry[2]


def _check_mix(ld: np.dtype, rd: np.dtype) -> None:
    # numpy's uint64/int64 promotion (-> float64) is a trap neither side
    # should fall into silently; and bool arithmetic promotes to int in
    # jax but stays bool in numpy — both are boundaries, not bugs.
    if {ld.kind, rd.kind} == {"u", "i"}:
        raise NotCompilable("mixed signed/unsigned operands")


def _lower(
    e: expr.ColumnExpression,
    env: dict[str, tuple],
    dtypes: dict[str, np.dtype],
    used: "dict[str, None]",
) -> tuple:
    """Lower `e` against the symbolic column environment; returns an
    entry tuple.  Raises NotCompilable — callers fall back per tick."""
    import jax.numpy as jnp

    def dev(entry) -> tuple[Callable, np.dtype]:
        """(thunk, dtype) for any entry — host refs lift to device
        inputs, consts materialize against the batch length."""
        d = _entry_dtype(entry, dtypes, "referenced")
        if entry[0] == "host":
            used[entry[1]] = None
        return (lambda inp, memo, _e=entry: _ev(_e, inp, memo)), d

    if isinstance(e, InternalColRef):
        if e._name == "id":
            raise NotCompilable("id column (Pointer-valued)")
        entry = env.get(e._name)
        if entry is None:
            raise NotCompilable(f"unknown column {e._name!r}")
        # bare refs stay symbolic: host passthroughs never cross the
        # device (object/string columns legally ride along untouched);
        # consumers that lift to the device run their own dtype checks
        # via dev()
        return entry
    if isinstance(e, expr.ColumnConstExpression):
        v = e._value
        if isinstance(v, bool):
            return ("const", bool(v), _BOOL)
        if isinstance(v, int) and not isinstance(v, bool):
            if not -(2**63) <= v < 2**63:
                raise NotCompilable("big-int constant")
            return ("const", int(v), _I64)
        if isinstance(v, float):
            return ("const", float(v), _F64)
        raise NotCompilable(f"object constant ({type(v).__name__})")
    if isinstance(e, expr.ColumnBinaryOpExpression):
        op = e._op
        if op not in _OK_BINOPS:
            raise NotCompilable(f"operator {op!r}")
        lf, ld = dev(_lower(e._left, env, dtypes, used))
        rf, rd = dev(_lower(e._right, env, dtypes, used))
        _check_mix(ld, rd)
        if op in _ARITH_OPS:
            if ld.kind not in "iuf" or rd.kind not in "iuf":
                raise NotCompilable(f"arithmetic on {ld}/{rd}")
            out = np.result_type(ld, rd)
        elif op in _BITS_OPS:
            if ld.kind == "b" and rd.kind == "b":
                out = _BOOL
            elif ld.kind in "iu" and rd.kind in "iu":
                out = np.result_type(ld, rd)
            else:
                raise NotCompilable(f"bitwise op on {ld}/{rd}")
        else:  # comparison
            out = _BOOL
        common = out if op not in _CMP_OPS else np.result_type(ld, rd)
        _J_BIN = {
            "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
            "==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
            "<=": jnp.less_equal, ">": jnp.greater, ">=":
            jnp.greater_equal, "&": jnp.bitwise_and,
            "|": jnp.bitwise_or, "^": jnp.bitwise_xor,
        }
        fn = _J_BIN[op]

        def thunk(inp, memo, _lf=lf, _rf=rf, _c=common, _fn=fn):
            lo = _lf(inp, memo).astype(_c)
            ro = _rf(inp, memo).astype(_c)
            return _fn(lo, ro)

        return ("dev", thunk, out)
    if isinstance(e, expr.ColumnUnaryOpExpression):
        af, ad = dev(_lower(e._expr, env, dtypes, used))
        if e._op == "-":
            if ad.kind not in "if":
                raise NotCompilable(f"negation on {ad}")
            return ("dev", lambda inp, memo: -af(inp, memo), ad)
        if e._op == "abs":
            if ad.kind not in "ifu":
                raise NotCompilable(f"abs on {ad}")
            import jax.numpy as _jnp

            return ("dev", lambda inp, memo: _jnp.abs(af(inp, memo)), ad)
        if e._op == "~":
            if ad.kind == "b":
                return (
                    "dev",
                    lambda inp, memo: ~af(inp, memo),
                    _BOOL,
                )
            if ad.kind in "iu":
                return ("dev", lambda inp, memo: ~af(inp, memo), ad)
            raise NotCompilable(f"invert on {ad}")
        raise NotCompilable(f"unary operator {e._op!r}")
    if isinstance(e, expr.IfElseExpression):
        cf, cd = dev(_lower(e._if, env, dtypes, used))
        tf, td = dev(_lower(e._then, env, dtypes, used))
        ef, ed = dev(_lower(e._else, env, dtypes, used))
        if td == ed:
            out = td
        elif td.kind in "iuf" and ed.kind in "iuf":
            # interpreter: object array of mixed ints/floats _tightens
            # to float64/int64 = numpy promotion of the two
            _check_mix(td, ed)
            out = np.result_type(td, ed)
        else:
            raise NotCompilable(f"if_else branches {td}/{ed}")

        def thunk(inp, memo, _cf=cf, _tf=tf, _ef=ef, _o=out):
            import jax.numpy as _jnp

            c = _cf(inp, memo).astype(bool)
            return _jnp.where(
                c, _tf(inp, memo).astype(_o), _ef(inp, memo).astype(_o)
            )

        return ("dev", thunk, out)
    if isinstance(e, expr.CoalesceExpression):
        first = _lower(e._args[0], env, dtypes, used)
        # non-object dtype short-circuits in the interpreter
        _entry_dtype(first, dtypes, "coalesce")
        return first
    if isinstance(e, expr.FillErrorExpression):
        inner = _lower(e._expr, env, dtypes, used)
        _entry_dtype(inner, dtypes, "fill_error")
        return inner
    if isinstance(e, expr.UnwrapExpression):
        inner = _lower(e._expr, env, dtypes, used)
        _entry_dtype(inner, dtypes, "unwrap")
        return inner
    if isinstance(e, expr.RequireExpression):
        # numeric deps are never None: require == its value
        for a in e._args:
            _entry_dtype(_lower(a, env, dtypes, used), dtypes, "require")
        return _lower(e._val, env, dtypes, used)
    if isinstance(e, expr.CastExpression):
        t = e._target.strip_optional()
        af, ad = dev(_lower(e._expr, env, dtypes, used))
        if ad.kind not in "bifu":
            raise NotCompilable(f"cast from {ad}")
        if t == dt.INT:
            out = _I64
        elif t == dt.FLOAT:
            out = _F64
        elif t == dt.BOOL:
            out = _BOOL
        else:
            raise NotCompilable(f"cast to {t}")
        return (
            "dev",
            lambda inp, memo, _o=out: af(inp, memo).astype(_o),
            out,
        )
    if isinstance(e, expr.DeclareTypeExpression):
        return _lower(e._expr, env, dtypes, used)
    if isinstance(
        e, (expr.IsNoneExpression, expr.IsNotNoneExpression)
    ):
        af, _ad = dev(_lower(e._expr, env, dtypes, used))
        val = isinstance(e, expr.IsNotNoneExpression)

        def thunk(inp, memo, _af=af, _v=val):
            import jax.numpy as _jnp

            a = _af(inp, memo)
            return _jnp.full(a.shape, _v, dtype=bool)

        return ("dev", thunk, _BOOL)
    r = classify_expr(e)
    raise NotCompilable(r or f"unsupported ({type(e).__name__})")


# ---------------------------------------------------------------------------
# segment program: one jitted fn per (segment, dtype tuple); jax's own
# shape cache handles the bucket dimension, our table counts it


class _Program:
    """Compiled form of one segment for one input-dtype signature."""

    __slots__ = (
        "in_cols", "dev_out", "host_out", "has_mask", "has_keys", "fn",
        "out_names",
    )

    def __init__(self, in_cols, dev_out, host_out, has_mask, has_keys,
                 fn, out_names):
        self.in_cols = in_cols      # ordered device input column names
        self.dev_out = dev_out      # [(name, position-in-fn-result)]
        self.host_out = host_out    # [(name, external src col)]
        self.has_mask = has_mask
        self.has_keys = has_keys
        self.fn = fn
        self.out_names = out_names  # final column order


def _build_program(
    chain: Sequence[Any],
    external_cols: Sequence[str],
    dtypes: dict[str, np.dtype],
) -> _Program:
    """Lower the chain against concrete input dtypes into one jitted
    program.  Raises NotCompilable when this dtype signature cannot be
    proven equivalent (the caller negative-caches it)."""
    import jax
    from pathway_tpu.engine.nodes import (
        ConcatNode,
        FilterNode,
        ReindexNode,
        RowwiseNode,
    )

    env: dict[str, tuple] = {c: ("host", c) for c in external_cols}
    masks: list[tuple] = []
    key_entry: tuple | None = None
    used: dict[str, None] = {}

    for node in chain:
        if isinstance(node, ConcatNode):
            continue  # concat + column select happen host-side
        if isinstance(node, RowwiseNode):
            new_env: dict[str, tuple] = {}
            for out_name, e in node.exprs.items():
                new_env[out_name] = _lower(e, env, dtypes, used)
            env = new_env
        elif isinstance(node, FilterNode):
            entry = _lower(node.predicate, env, dtypes, used)
            d = _entry_dtype(entry, dtypes, "filter predicate")
            if d.kind not in "bifu":
                raise NotCompilable(f"filter predicate dtype {d}")
            if entry[0] == "host":
                # bare-column predicates never pass through dev(), so
                # the device input must be registered here or the traced
                # fn would KeyError on its first dispatch
                used[entry[1]] = None
            masks.append(entry)
        elif isinstance(node, ReindexNode):
            entry = _lower(node.key_expr, env, dtypes, used)
            d = _entry_dtype(entry, dtypes, "reindex keys")
            if d.kind not in "iu" or d.itemsize != 8:
                raise NotCompilable(f"reindex key dtype {d}")
            if entry[0] == "host":
                used[entry[1]] = None  # same as bare-column predicates
            key_entry = entry
        else:  # pragma: no cover - planner never includes others
            raise NotCompilable(f"operator {type(node).__name__}")

    tail = chain[-1]
    out_names = list(tail.column_names)
    dev_out: list[tuple[str, int]] = []
    host_out: list[tuple[str, str]] = []
    dev_entries: list[tuple] = []
    for name in out_names:
        entry = env[name]
        # force consts through the device so literal columns come back
        # with _full's exact dtypes; host refs stay host
        if entry[0] == "host":
            host_out.append((name, entry[1]))
        else:
            _entry_dtype(entry, dtypes, f"output {name!r}")
            dev_out.append((name, len(dev_entries)))
            dev_entries.append(entry)

    if not dev_entries and not masks and key_entry is None:
        raise NotCompilable("no device computation (pure projection)")

    in_cols = list(used.keys())
    if not in_cols:
        # constant-only programs have no batch-length anchor
        raise NotCompilable("constant-only computation")
    n_dev = len(dev_entries)
    mask_entries = list(masks)
    key_e = key_entry

    def fn(*arrays):
        import jax.numpy as jnp

        inp = dict(zip(in_cols, arrays))
        inp["__n__"] = arrays[0].shape[0]
        memo: dict = {}
        outs = [_ev(en, inp, memo) for en in dev_entries]
        if mask_entries:
            m = _ev(mask_entries[0], inp, memo).astype(bool)
            for en in mask_entries[1:]:
                m = m & _ev(en, inp, memo).astype(bool)
            outs.append(m)
        if key_e is not None:
            outs.append(_ev(key_e, inp, memo))
        return tuple(outs)

    with jax.experimental.enable_x64():
        jfn = jax.jit(fn)

    return _Program(
        in_cols,
        dev_out,
        host_out,
        bool(mask_entries),
        key_e is not None,
        jfn,
        out_names,
    )


# ---------------------------------------------------------------------------
# the runtime-facing segment


class SegmentRunner:
    """One planned chain: head inputs -> fused program -> tail output.

    Holds the per-(bucket, dtype-tuple) program cache; every tick either
    dispatches the jitted program (pad -> run -> slice/mask) or falls
    back to running the chain's own interpreter execs — the very same
    NodeExec objects the interpreter would use, so alternating between
    paths is always safe (members are stateless)."""

    _FALLBACK = object()  # negative cache entry

    def __init__(self, seg_id: int, nodes: Sequence[Any], execs: dict):
        from pathway_tpu.engine.nodes import ConcatNode

        self.seg_id = seg_id
        self.nodes = list(nodes)
        self.execs = execs
        self.head = nodes[0]
        self.tail = nodes[-1]
        self.concat_head = isinstance(self.head, ConcatNode)
        if self.concat_head:
            self.external_cols = list(self.head.column_names)
            self.chain = self.nodes  # concat itself is skipped in build
        else:
            self.external_cols = list(self.head.inputs[0].column_names)
            self.chain = self.nodes
        self._cache: dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self.compiled_ticks = 0
        self.fallback_ticks = 0
        self.broken = False  # permanent fallback after a runtime error
        self._min_rows = compiled_min_rows()

    # --- runtime hooks ----------------------------------------------------

    def gather(self, produced: dict) -> list[list[DiffBatch]]:
        return [produced.get(inp.id, []) for inp in self.head.inputs]

    def process(self, t: int, inputs: list[list[DiffBatch]]) -> list[DiffBatch]:
        # gate on the raw input lengths BEFORE paying the head-batch
        # concat: a broken (or chronically small-tick) segment must not
        # add a full memory pass on top of the interpreter redoing the
        # same concat inside the head exec
        n = sum(len(b) for batches in inputs for b in batches)
        if not n:
            return []
        if self.broken or n < self._min_rows:
            return self._interpret(t, inputs)
        batch = self._head_batch(inputs)
        try:
            out = self._run_compiled(t, batch, inputs)
        except NotCompilable as nc:
            _metrics()[3].labels(nc.reason[:60]).inc()
            self._journal_fallback(t, nc.reason[:60])
            return self._interpret(t, inputs)
        except Exception:
            # any real failure disables the segment permanently: the
            # interpreter is always correct, and a flapping device path
            # would otherwise log per tick
            logger.warning(
                "compiled tick: segment %d failed; falling back to the "
                "interpreter permanently for this run",
                self.seg_id,
                exc_info=True,
            )
            self.broken = True
            _metrics()[3].labels("error").inc()
            self._journal_fallback(t, "error", permanent=True)
            return self._interpret(t, inputs)
        if out is None:
            return self._interpret(t, inputs)
        self.compiled_ticks += 1
        return out

    def _journal_fallback(
        self, t: int, reason: str, permanent: bool = False
    ) -> None:
        """Incident-journal a compiled-segment fallback ONCE per
        (segment, reason) — the fallback counter ticks every tick, the
        journal records the state transition."""
        seen = getattr(self, "_journaled_reasons", None)
        if seen is None:
            seen = self._journaled_reasons = set()
        if reason in seen:
            return
        seen.add(reason)
        from pathway_tpu.observability.journal import record as journal_record

        journal_record(
            "compile-fallback",
            f"segment {self.seg_id} fell back to the interpreter "
            f"({reason})",
            tick=t,
            segment=self.seg_id,
            reason=reason,
            permanent=permanent,
        )

    # --- paths ------------------------------------------------------------

    def _head_batch(self, inputs: list[list[DiffBatch]]) -> DiffBatch:
        from pathway_tpu.engine.nodes import _concat_inputs

        if not self.concat_head:
            return _concat_inputs(
                list(inputs[0]), self.external_cols
            )
        parts = [
            b.select_columns(self.external_cols)
            for batches in inputs
            for b in batches
            if len(b)
        ]
        if not parts:
            return DiffBatch.empty(self.external_cols)
        return DiffBatch.concat(parts)

    def _interpret(
        self, t: int, inputs: list[list[DiffBatch]]
    ) -> list[DiffBatch]:
        """Run the chain on its own interpreter execs (identical to the
        un-segmented engine, including per-node error-log scopes)."""
        from pathway_tpu.internals.errors import set_exec_scope

        self.fallback_ticks += 1
        local: dict[int, list[DiffBatch]] = {}
        for pos, inp in enumerate(self.head.inputs):
            local[inp.id] = list(inputs[pos])
        for node in self.nodes:
            ex = self.execs[node.id]
            ins = [local.get(i.id, []) for i in node.inputs]
            set_exec_scope(getattr(node, "_error_scope", None))
            try:
                local[node.id] = ex.process(t, ins)
            finally:
                set_exec_scope(None)
        return local[self.tail.id]

    def _run_compiled(
        self, t: int, batch: DiffBatch, inputs: list[list[DiffBatch]]
    ) -> list[DiffBatch] | None:
        import jax

        prog, bucket_key = self._program_for(batch)
        n = len(batch)
        bucket = bucket_key[0]
        ins = []
        for name in prog.in_cols:
            col = batch.columns[name]
            if bucket > n:
                pad = np.zeros(bucket - n, dtype=col.dtype)
                col = np.concatenate([col, pad])
            ins.append(col)
        # roofline attribution (observability/tickscope.py): measured
        # monotonic wall per program execution, against the FLOP estimate
        # registered at build time in _program_for. The np.asarray calls
        # stay inside the window — device->host sync is part of what the
        # tick actually waits for.
        _rt0 = time.perf_counter()
        with jax.experimental.enable_x64():
            res = prog.fn(*ins)
            outs = [np.asarray(r) for r in res]
        try:
            from pathway_tpu.observability import tickscope as _ts

            _ts.roofline().observe(
                "compiled_tick",
                f"seg_{'-'.join(prog.in_cols)}_rows{bucket}",
                time.perf_counter() - _rt0,
            )
        except Exception:  # pragma: no cover - defensive
            pass
        pos = len(prog.dev_out)
        mask = None
        new_keys = None
        if prog.has_mask:
            mask = outs[pos]
            pos += 1
        if prog.has_keys:
            new_keys = outs[pos]
        for _name, i in prog.dev_out:
            if outs[i].shape != (bucket,):
                raise NotCompilable("non-columnar program output")
        keys = batch.keys
        diffs = batch.diffs
        if new_keys is not None:
            nk = new_keys[:n]
            if nk.dtype.kind == "i" and len(nk) and (nk < 0).any():
                # the interpreter raises OverflowError assigning a
                # negative key into the uint64 key column; reproduce by
                # letting it
                raise NotCompilable("negative reindex key")
            keys = nk.astype(np.uint64)
        if mask is not None:
            idx = np.flatnonzero(mask[:n])
            if len(idx) == 0:
                return []
            keys = keys[idx]
            diffs = diffs[idx]
            cols = {}
            for name, i in prog.dev_out:
                cols[name] = outs[i][idx]
            for name, src in prog.host_out:
                cols[name] = batch.columns[src][idx]
        else:
            cols = {}
            for name, i in prog.dev_out:
                cols[name] = outs[i][:n]
            for name, src in prog.host_out:
                cols[name] = batch.columns[src]
        ordered = {name: cols[name] for name in prog.out_names}
        return [DiffBatch(keys, diffs, ordered)]

    def _program_for(self, batch: DiffBatch) -> tuple[_Program, tuple]:
        hits, misses, compile_hist, _fb = _metrics()
        # the dtype signature covers every external column the chain may
        # reference; lowering decides which of them go to the device
        dkey = tuple(
            batch.columns[c].dtype.str if c in batch.columns else "?"
            for c in self.external_cols
        )
        bucket = row_bucket(len(batch))
        key = (bucket, dkey)
        with self._lock:
            entry = self._cache.get(key)
        if entry is self._FALLBACK:
            hits.inc()
            raise NotCompilable("cached non-lowerable dtype signature")
        if entry is not None:
            hits.inc()
            return entry, key
        misses.inc()
        dtypes = {c: batch.columns[c].dtype for c in batch.columns}
        for c in self.external_cols:
            if batch.columns[c].ndim != 1:
                with self._lock:
                    self._cache[key] = self._FALLBACK
                raise NotCompilable(f"multi-dim column {c!r}")
        t0 = time.perf_counter()
        try:
            prog = _build_program(self.chain, self.external_cols, dtypes)
        except NotCompilable:
            with self._lock:
                self._cache[key] = self._FALLBACK
            raise
        compile_hist.observe(time.perf_counter() - t0)
        with self._lock:
            self._cache[key] = prog
        self._register_with_ledger(prog, bucket, dtypes)
        self._register_roofline(prog, bucket, dtypes)
        return prog, key

    def _register_with_ledger(self, prog: _Program, bucket: int, dtypes):
        """Hand the freshly-built segment program to the Lowering Ledger
        (analysis/lowering.py): ``prove_lowering`` can then AOT-check
        the exact jitted tick this process runs against the TPU rules,
        device-free. Best-effort — the ledger must never break a tick."""
        try:
            import jax

            from pathway_tpu.analysis import lowering as ledger

            args = tuple(
                jax.ShapeDtypeStruct((bucket,), dtypes[c])
                for c in prog.in_cols
            )
            name = (
                f"seg_{'-'.join(prog.in_cols)}_rows{bucket}"
            )
            ledger.register_program(
                name,
                prog.fn,
                args,
                meta={
                    "rows": bucket,
                    "in_cols": list(prog.in_cols),
                    "out_cols": [c for c, _ in prog.dev_out],
                },
            )
        except Exception:  # pragma: no cover - defensive
            pass

    def _register_roofline(self, prog: _Program, bucket: int, dtypes):
        """Register the program's per-call FLOP estimate (XLA cost
        analysis over abstract args — no execution) with the Tick Scope
        roofline, keyed exactly like _run_compiled's observe calls.
        Best-effort: a backend without a cost model just means zero
        registered FLOPs, which the tickscope-coverage doctor rule
        surfaces rather than this path crashing a tick."""
        try:
            import jax

            from pathway_tpu.observability import tickscope as _ts

            args = tuple(
                jax.ShapeDtypeStruct((bucket,), dtypes[c])
                for c in prog.in_cols
            )
            with jax.experimental.enable_x64():
                flops, nbytes = _ts.estimate_program_cost(prog.fn, *args)
            _ts.roofline().register(
                "compiled_tick",
                f"seg_{'-'.join(prog.in_cols)}_rows{bucket}",
                flops,
                nbytes,
            )
        except Exception:  # pragma: no cover - defensive
            pass


# ---------------------------------------------------------------------------
# planning


class CompiledPlan:
    def __init__(self, segments: list[SegmentRunner]):
        self.segments = segments
        self.by_tail: dict[int, SegmentRunner] = {
            s.tail.id: s for s in segments
        }
        self.member_ids: set[int] = {
            n.id for s in segments for n in s.nodes if n is not s.tail
        }

    def segment_of(self, node_id: int) -> SegmentRunner | None:
        for s in self.segments:
            if any(n.id == node_id for n in s.nodes):
                return s
        return None


def plan_segments(
    order: Sequence[Any], execs: dict
) -> CompiledPlan | None:
    """Greedy maximal-chain segmentation over the runtime's topo order.

    A chain starts at any structurally compilable node and extends while
    the current tail has exactly ONE consumer, that consumer's only
    input is the tail, and the consumer is itself compilable.  Chains
    with no real compute (pure projections/renames) are skipped — a
    device round-trip for a dict re-label is pure loss."""
    if not compiled_tick_enabled():
        return None
    from pathway_tpu.engine.nodes import ConcatNode

    consumers: dict[int, list[Any]] = {n.id: [] for n in order}
    for node in order:
        for inp in node.inputs:
            if inp.id in consumers:
                consumers[inp.id].append(node)

    assigned: set[int] = set()
    segments: list[SegmentRunner] = []
    seg_id = 0
    for node in order:
        if node.id in assigned:
            continue
        ok, _ = classify_node(node)
        if not ok:
            continue
        chain = [node]
        cur = node
        while True:
            cons = consumers.get(cur.id, [])
            if len(cons) != 1:
                break
            nxt = cons[0]
            if nxt.id in assigned or isinstance(nxt, ConcatNode):
                break
            if len(nxt.inputs) != 1 or nxt.inputs[0] is not cur:
                break
            ok, _ = classify_node(nxt)
            if not ok:
                break
            chain.append(nxt)
            cur = nxt
        # a bare Concat head with no chain after it is just the
        # interpreter's concat; segments must contain real compute
        if not any(_has_compute(n) for n in chain):
            continue
        if isinstance(chain[0], ConcatNode) and len(chain) == 1:
            continue
        for n in chain:
            assigned.add(n.id)
        segments.append(SegmentRunner(seg_id, chain, execs))
        seg_id += 1
    if not segments:
        return None
    return CompiledPlan(segments)


# ---------------------------------------------------------------------------
# GroupBy semigroup partials (count/sum/avg) as one jitted program.
#
# np.add.at-equivalent: dcounts[g] = sum(diffs | code==g) and, per
# argument column, part[g] = sum(arr * diffs | code==g).  Exact for
# int64 (wrap-around matches), order-differs-within-group for float64
# (the engine's float contract is allclose).  Opt-in on CPU — see
# module docstring for the measured scatter numbers.

_SEMIGROUP_CACHE: dict[tuple, Any] = {}
_SEMIGROUP_LOCK = threading.Lock()


def semigroup_partials(
    codes: np.ndarray,
    diffs: np.ndarray,
    args: Sequence[np.ndarray | None],
    nu: int,
) -> tuple[np.ndarray, list[np.ndarray | None]]:
    """Device twin of the bulk-groupby scatter pass.  ``args`` is
    positionally aligned with the reducer specs (None = count/multiset,
    no partial).  Only int64/float64 argument columns are supported —
    callers keep the numpy path otherwise."""
    import jax

    hits, misses, compile_hist, _fb = _metrics()
    n = len(codes)
    nb = row_bucket(n)
    gb = row_bucket(nu)  # groups ride the same pad ladder as rows
    arg_sig = tuple(
        None if a is None else np.dtype(a.dtype).str for a in args
    )
    for a in args:
        if a is not None and a.dtype not in (_I64, _F64):
            raise NotCompilable(f"semigroup arg dtype {a.dtype}")
    key = (nb, gb, arg_sig)
    with _SEMIGROUP_LOCK:
        fn = _SEMIGROUP_CACHE.get(key)
    if fn is None:
        misses.inc()
        t0 = time.perf_counter()
        arg_dts = [
            np.dtype(a.dtype) for a in args if a is not None
        ]

        def build(codes_a, diffs_a, *arg_arrays):
            import jax.numpy as jnp

            dcounts = jax.ops.segment_sum(
                diffs_a, codes_a, num_segments=gb
            )
            parts = []
            for a, d in zip(arg_arrays, arg_dts):
                w = (a * diffs_a.astype(d)) if d == _F64 else (a * diffs_a)
                parts.append(
                    jax.ops.segment_sum(w, codes_a, num_segments=gb)
                )
            return (dcounts, *parts)

        with jax.experimental.enable_x64():
            fn = jax.jit(build)
        with _SEMIGROUP_LOCK:
            _SEMIGROUP_CACHE[key] = fn
        compile_hist.observe(time.perf_counter() - t0)
    else:
        hits.inc()

    pad = nb - n
    codes_p = codes.astype(np.int32)
    diffs_p = np.asarray(diffs, dtype=np.int64)
    if pad:
        codes_p = np.concatenate(
            [codes_p, np.zeros(pad, dtype=np.int32)]
        )
        diffs_p = np.concatenate([diffs_p, np.zeros(pad, dtype=np.int64)])
    arg_in = []
    for a in args:
        if a is None:
            continue
        ap = np.ascontiguousarray(a)
        if pad:
            ap = np.concatenate([ap, np.zeros(pad, dtype=ap.dtype)])
        arg_in.append(ap)
    with jax.experimental.enable_x64():
        res = fn(codes_p, diffs_p, *arg_in)
        res = [np.asarray(r) for r in res]
    dcounts = res[0][:nu]
    out: list[np.ndarray | None] = []
    i = 1
    for a in args:
        if a is None:
            out.append(None)
        else:
            out.append(res[i][:nu])
            i += 1
    return dcounts, out
