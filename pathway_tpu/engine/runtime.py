"""Microbatch runtime: the tick loop.

TPU-native replacement for the reference's timely worker main loop
(/root/reference/src/engine/dataflow.rs:5962-6173): instead of N OS worker
threads stepping a distributed dataflow, one driver advances a totally-ordered
logical clock (u64 ms, like the reference's src/engine/timestamp.rs). Each tick
drains connector sessions, then pushes columnar diff batches through the node
graph in topological order. Device-heavy nodes (embedders, indexes, numeric
kernels) dispatch into jitted XLA programs; multi-chip runs shard those nodes
over a jax Mesh (pathway_tpu/parallel) rather than spawning more workers.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from pathway_tpu.engine.batch import END_OF_TIME, DiffBatch
import concurrent.futures as _cf

from pathway_tpu.engine.nodes import (
    InputExec,
    InputNode,
    Node,
    NodeExec,
    OutputNode,
)


def annotate_live_columns(order: Sequence[Node]) -> None:
    """Backward column-liveness pass: sets node._live_cols to the set of
    output columns any consumer may read, or None for "all" (the safe
    default). Lets JoinExec skip materializing the `_left_id`/`_right_id`
    Pointer columns on bulk ticks when no downstream expression references
    them — per-row Pointer boxing dominated the bulk join profile
    (reference analog: differential's arrangements never materialize
    unused columns either; they are demand-built from traces)."""
    from pathway_tpu.engine.expression_eval import InternalColRef
    from pathway_tpu.engine.nodes import FilterNode, RowwiseNode

    live: dict[int, set | None] = {n.id: set() for n in order}

    def demand(node: Node, cols: set | None) -> None:
        if cols is None:
            live[node.id] = None
        elif live[node.id] is not None:
            live[node.id] |= cols  # type: ignore[operator]

    def expr_refs(exprs, n_inputs: int) -> list[set]:
        sets: list[set] = [set() for _ in range(n_inputs)]

        def walk(e):
            if isinstance(e, InternalColRef):
                if e._name != "id" and 0 <= e._input_index < n_inputs:
                    sets[e._input_index].add(e._name)
                return
            for c in e._children:
                walk(c)

        for e in exprs:
            walk(e)
        return sets

    # roots (no consumers in `order`) may be captured externally: all live
    has_consumer = {inp.id for node in order for inp in node.inputs}
    for node in order:
        if node.id not in has_consumer:
            live[node.id] = None

    for node in reversed(order):
        if isinstance(node, RowwiseNode):
            per_input = expr_refs(node.exprs.values(), len(node.inputs))
            for pos, inp in enumerate(node.inputs):
                demand(inp, per_input[pos])
        elif isinstance(node, FilterNode):
            refs = expr_refs([node.predicate], 1)[0]
            own = live[node.id]
            demand(
                node.inputs[0], None if own is None else (refs | own)
            )
        else:
            for inp in node.inputs:
                demand(inp, None)

    for node in order:
        # merge with any annotation from another Runtime over the same
        # graph nodes (interactive mode builds overlapping runtimes):
        # liveness only ever widens, so concurrent annotation can cost
        # optimization but never correctness
        prev = getattr(node, "_live_cols", ())
        new = live[node.id]
        if prev is None or new is None:
            node._live_cols = None
        elif prev == ():  # never annotated
            node._live_cols = new
        else:
            node._live_cols = prev | new


def collect_nodes(outputs: Sequence[Node]) -> list[Node]:
    """Tree-shake + topological order (inputs first)."""
    order: list[Node] = []
    seen: set[int] = set()

    def visit(node: Node):
        if node.id in seen:
            return
        seen.add(node.id)
        for inp in node.inputs:
            visit(inp)
        order.append(node)

    for out in outputs:
        visit(out)
    return order


class InputSession:
    """Thread-safe staging area connector threads feed
    (reference: InputSession/UpsertSession, src/connectors/adaptors.rs:27-42;
    the mpsc sender + poller pattern of src/connectors/mod.rs:426)."""

    # priority classes (Surge Gate): 0 = interactive serving queries,
    # 1 = bulk ingest/backfill. When an interactive session has data,
    # the streaming loop defers draining bulk sessions for a bounded
    # number of ticks so query latency is not paid behind a backfill.
    PRIORITY_INTERACTIVE = 0
    PRIORITY_BULK = 1

    def __init__(self, column_names: Sequence[str]):
        self.column_names = list(column_names)
        self.priority = self.PRIORITY_BULK
        self._lock = threading.Lock()
        self._rows: list[tuple[int, int, tuple]] = []
        self._upserts: dict[int, tuple | None] = {}
        self._last_upserted: dict[int, tuple] = {}
        self.finished = False
        self._wake: Callable[[], None] | None = None
        # offset marker protocol: a source may enqueue its offset snapshot
        # atomically WITH the rows it covers (insert_batch); drain() then
        # surfaces the marker only once those rows have left the session, so
        # persisted offsets can never run ahead of the logged input
        # (reference: offsets recorded under the same frontier as the input
        # snapshot, src/persistence/state.rs + src/connectors/offset.rs)
        self._pending_offsets: Any = None
        self.last_offsets: Any = None

    def hot(self) -> bool:
        """Data pending now, or (for gated sessions) queued upstream in
        the micro-batcher and about to land."""
        if self.has_data():
            return True
        backlog = getattr(self, "backlog", None)
        return backlog is not None and backlog() > 0

    def insert(self, key: int, values: tuple) -> None:
        with self._lock:
            self._rows.append((key, 1, values))
        self._notify()

    def remove(self, key: int, values: tuple) -> None:
        with self._lock:
            self._rows.append((key, -1, values))
        self._notify()

    def upsert(self, key: int, values: tuple | None) -> None:
        """None value = delete (reference: UpsertSession)."""
        with self._lock:
            self._upserts[key] = values
        self._notify()

    def insert_batch(
        self, rows: Iterable[tuple[int, int, tuple]], offsets: Any = None
    ) -> None:
        """Atomically enqueue a group of rows plus the offset snapshot that
        covers them — one drain observes both or neither."""
        with self._lock:
            self._rows.extend(rows)
            if offsets is not None:
                self._pending_offsets = offsets
        self._notify()

    def close(self) -> None:
        with self._lock:
            self.finished = True
        self._notify()

    def _notify(self):
        if self._wake is not None:
            self._wake()

    def has_data(self) -> bool:
        with self._lock:
            return bool(self._rows) or bool(self._upserts)

    def drain(
        self, max_rows: int | None = None
    ) -> list[tuple[int, int, tuple]]:
        """Take pending rows. ``max_rows`` bounds the take (Surge Gate
        bulk chunking: a backfill burst must not block a serving tick
        longer than one chunk) — a partial drain returns a prefix of the
        row log (then a bounded slice of pending upserts) and leaves the
        offset marker pending, so persisted offsets can never run ahead
        of ticked input."""
        with self._lock:
            partial = max_rows is not None and (
                len(self._rows) + len(self._upserts) > max_rows
            )
            if partial:
                take = min(len(self._rows), max_rows)
                rows = self._rows[:take]
                self._rows = self._rows[take:]
                upserts: dict[int, tuple | None] = {}
                if not self._rows:
                    # row log exhausted: spend the remaining budget on
                    # upserts (insertion order) so upsert-fed bulk
                    # sources are chunk-bounded too
                    for k in list(self._upserts)[: max_rows - take]:
                        upserts[k] = self._upserts.pop(k)
            else:
                rows = self._rows
                self._rows = []
                upserts = self._upserts
                self._upserts = {}
                if self._pending_offsets is not None:
                    self.last_offsets = self._pending_offsets
                    self._pending_offsets = None
        for k, vals in upserts.items():
            old = self._last_upserted.get(k)
            if old is not None:
                rows.append((k, -1, old))
            if vals is not None:
                rows.append((k, 1, vals))
                self._last_upserted[k] = vals
            else:
                self._last_upserted.pop(k, None)
        return rows


class StaticSource:
    """Bounded source with explicit event times (test fixtures, files read
    once)."""

    def __init__(self, column_names: Sequence[str]):
        self.column_names = list(column_names)

    def events(self) -> Iterable[tuple[int, DiffBatch]]:
        raise NotImplementedError


class StreamingSource:
    """Unbounded (or long-running) source: runs a thread feeding an
    InputSession."""

    def __init__(self, column_names: Sequence[str]):
        self.column_names = list(column_names)
        self.session = InputSession(column_names)

    def start(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        pass


class RuntimeStats:
    """Prober-style counters (reference: ProberStats src/engine/graph.rs:533,
    connector monitors src/connectors/monitoring.rs) — fed to the
    Prometheus endpoint and the TUI monitor."""

    def __init__(self):
        self.ticks = 0
        self.current_time = 0
        self.rows_in: dict[int, int] = {}  # input node id -> rows ingested
        self.rows_out: dict[int, int] = {}  # output node id -> rows emitted
        self.node_rows: dict[int, int] = {}  # node id -> rows produced
        self.node_ns: dict[int, int] = {}  # node id -> cumulative process ns
        self.last_tick_ns = 0
        self.started_at = _time.time()

    def snapshot(self) -> dict:
        return {
            "ticks": self.ticks,
            "current_time": self.current_time,
            "rows_in_total": sum(self.rows_in.values()),
            "rows_out_total": sum(self.rows_out.values()),
            "last_tick_ns": self.last_tick_ns,
            "uptime_s": _time.time() - self.started_at,
        }


class Runtime:
    def __init__(
        self,
        outputs: Sequence[Node],
        *,
        autocommit_ms: int = 50,
        on_tick: Callable[[int], None] | None = None,
        worker_threads: bool = True,
        distributed: bool | None = None,
    ):
        self.order = collect_nodes(outputs)
        # error-log nodes (and everything downstream of them) run LAST:
        # at the final tick every other node processes + flushes first, so
        # the log drain sees final-tick errors and its consumers' on_end
        # callbacks still fire after their last on_change (stable
        # partition — moved nodes only consume already-processed outputs)
        _late = set()
        for node in self.order:
            if type(node).__name__ == "ErrorLogNode" or any(
                inp.id in _late for inp in node.inputs
            ):
                _late.add(node.id)
        if _late:
            self.order = [n for n in self.order if n.id not in _late] + [
                n for n in self.order if n.id in _late
            ]
        annotate_live_columns(self.order)
        # multi-process engine (DCN rung): stateful sharded execs exchange
        # host rows over the TCP mesh and ticks run in lockstep across the
        # process group (reference: timely workers over the TCP mesh,
        # src/engine/dataflow/config.rs:88-121). Inner runtimes (iterate,
        # interactive) pass distributed=False — they must not join the
        # group's barrier cadence.
        from pathway_tpu.parallel.host_exchange import dcn_active

        # created BEFORE the failure listener below can fire: the mesh
        # replays already-detected failures synchronously at
        # registration, and _on_peer_failure sets this event
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.dcn = dcn_active() if distributed is None else (
            distributed and dcn_active()
        )
        self.host_mesh = None
        if self.dcn:
            from pathway_tpu.parallel.host_exchange import get_host_mesh

            self.host_mesh = get_host_mesh()
            # Phoenix Mesh: learn about a dead peer at DETECTION time
            # (reader EOF, send failure, liveness timeout) instead of
            # inside the next gather — serving flips to stale reads and
            # the streaming loop wakes immediately so the pending
            # barrier surfaces the HostMeshError without waiting out an
            # autocommit interval
            self.host_mesh.add_failure_listener(self._on_peer_failure)
            # EVERY stateful operator type has a cross-process exchange
            # wrapper (engine/dcn.py), mirroring the reference's universal
            # Exchange pact — groupby/join partition by key, dedup by
            # instance, sort by instance (global sorts centralize),
            # ix by pointer target, update_rows/set-ops by row key,
            # buffer/forget/freeze all-gather their watermark,
            # gradual_broadcast/external_index replicate the small side,
            # iterate centralizes its fixpoint.
        for node in self.order:
            node._dcn = self.dcn
        self.execs: dict[int, NodeExec] = {
            node.id: node.make_exec() for node in self.order
        }
        # Tick Forge: fuse stateless operator chains into jitted XLA
        # programs (engine/compile.py). Planning failures are never
        # fatal — the interpreter path below is always complete.
        # PATHWAY_COMPILED_TICK=0 skips planning entirely (byte-
        # identical interpreter).
        self.compiled_plan = None
        try:
            from pathway_tpu.engine.compile import plan_segments

            self.compiled_plan = plan_segments(self.order, self.execs)
        except Exception:
            import logging

            logging.getLogger("pathway_tpu").warning(
                "compiled-tick planning failed; running interpreted",
                exc_info=True,
            )
        self.autocommit_ms = autocommit_ms
        self.on_tick = on_tick
        self.current_time = 0
        self._tick_count = 0
        self.stats = RuntimeStats()
        has_consumer = {inp.id for node in self.order for inp in node.inputs}
        self._sinks = [n for n in self.order if n.id not in has_consumer]
        # engine-level mesh sharding: per-tick frontier consensus rides a
        # tiny device all-reduce (reference: timely progress broadcast,
        # SURVEY §5.8 — "frontier consensus → tiny all-reduce")
        from pathway_tpu.parallel.mesh import get_engine_mesh

        self.engine_mesh = get_engine_mesh()
        self.global_frontier = 0
        self.frontier_syncs = 0
        self._frontier_base: int | None = None
        # OTLP operator-latency histogram (no-op without a metrics SDK)
        from pathway_tpu.internals.telemetry import get_metrics

        self._otel_metrics = get_metrics()
        self._otel_on = self._otel_metrics.enabled
        self._node_names = {n.id: type(n).__name__ for n in self.order}
        # Flight Recorder: per-operator tick-time histogram on the
        # process-wide registry (labels prebound per node — the per-tick
        # cost is one lock + bisect; idle autocommit ticks are skipped so
        # ~0-sample ticks don't swamp the distribution). The `/metrics`
        # endpoint serves these as pathway_operator_tick_seconds_bucket.
        from pathway_tpu.observability import REGISTRY
        from pathway_tpu.observability.registry import log_linear_buckets

        # sub-millisecond floor (1 us): Tick Forge compiled ticks finish
        # in 10-100 us — the registry's default 0.1 ms floor flattened
        # them all into the lowest bucket, hiding the 2.5-4.7x speedup
        # from every quantile
        _tick_hist = REGISTRY.histogram(
            "pathway_operator_tick_seconds",
            "per-operator processing time per tick that moved rows, "
            "by operator type",
            labelnames=("operator",),
            buckets=log_linear_buckets(lo=1e-6, hi=64.0, per_octave=4),
        )
        self._tick_hist_children = {
            n.id: _tick_hist.labels(self._node_names[n.id])
            for n in self.order
        }
        # Trace Weaver: per-tick and per-operator spans. The tick span
        # adopts the oldest pending REST request's context (or, in
        # lockstep mode, the group traceparent the barrier agreed on), so
        # the dataflow work serving a request lands in its trace.
        from pathway_tpu.observability.tracing import get_tracer

        self._tracer = get_tracer()
        self._tick_traceparent: str | None = None  # lockstep: set per round
        self.http_server = None  # set by start_http_server when attached
        # Fault Forge (chaos testing): None unless PATHWAY_FAULTS is set,
        # so the per-tick cost is one attribute check
        from pathway_tpu.testing import faults

        self._fault_plan = faults.active()
        # Tick Scope (observability/tickscope.py): per-runtime flight
        # recorder. Per-runtime, NOT process-global — iterate/interactive
        # spin nested runtimes whose inner ticks would otherwise corrupt
        # the outer tick's record. Disabled (PATHWAY_TICKSCOPE=0) the hot
        # loop pays one `is None` check per node and nothing else.
        from pathway_tpu.observability import tickscope as _tickscope_mod

        self._tickscope = _tickscope_mod.make_recorder(self)
        self._ts_entries: list | None = None
        # intra-tick worker parallelism (reference: PATHWAY_THREADS timely
        # workers, src/engine/dataflow/config.rs:63-86): independent nodes
        # of one topo level process concurrently on a thread pool. Each
        # exec is touched by exactly one thread per tick; the win comes
        # from branches whose hot work releases the GIL (numpy/jax/IO).
        if worker_threads:
            from pathway_tpu.internals.config import engine_threads

            n_threads = engine_threads()
        else:
            n_threads = 1
        self._pool = None
        self._levels: list[list[Any]] | None = None
        if n_threads > 1:
            level_of: dict[int, int] = {}
            levels: list[list[Any]] = []
            for node in self.order:
                lvl = (
                    max((level_of[i.id] for i in node.inputs), default=-1) + 1
                )
                level_of[node.id] = lvl
                while len(levels) <= lvl:
                    levels.append([])
                levels[lvl].append(node)
            # sinks run user callbacks — keep them serialized on their own
            # levels so pre-existing callbacks need not be thread-safe
            split: list[list[Any]] = []
            for lv in levels:
                sinks = [n for n in lv if isinstance(n, OutputNode)]
                rest = [n for n in lv if not isinstance(n, OutputNode)]
                if rest:
                    split.append(rest)
                for s in sinks:
                    split.append([s])
            levels = split
            if any(len(lv) > 1 for lv in levels):
                self._levels = levels
                self._pool = _cf.ThreadPoolExecutor(
                    max_workers=min(n_threads, 16),
                    thread_name_prefix="pathway-worker",
                )

    def _on_peer_failure(self, peer: int, reason: str) -> None:
        """FailureListener (called from mesh internal threads): the
        surviving group drains its in-flight tick — completed ticks are
        already durably committed per tick — and exits for a supervised
        whole-group restart from the latest group-committed snapshot
        generation. While that happens, the Surge Gate serves stale."""
        if not getattr(self, "_phoenix_active", True):
            # this run already finished: a peer exiting after a clean
            # group shutdown is the normal end of the job, not a
            # failure to recover from
            return
        import logging

        logging.getLogger("pathway_tpu").warning(
            "runtime: peer %d failed (%s); draining for supervised "
            "group restart",
            peer,
            reason,
        )
        from pathway_tpu.serving import degrade

        degrade.enter_recovery(f"peer {peer} failed: {reason}")
        self._wake.set()

    # --- core tick ------------------------------------------------------------

    def _process_node(self, node, t, produced, injected, final, stats):
        runner = None
        if self.compiled_plan is not None:
            if node.id in self.compiled_plan.member_ids:
                # produced inside its segment; the tail emits for it
                # (members are stateless with no on_end work)
                produced[node.id] = []
                return
            runner = self.compiled_plan.by_tail.get(node.id)
        ex = self.execs[node.id]
        has_injected = (
            isinstance(ex, InputExec) and injected and node.id in injected
        )
        # Tick Scope: entries is None when the recorder is off — that
        # one check is the entire disabled-path cost. compiled_ticks is
        # sampled around the call to tag the entry compiled-vs-interpreted
        # (SegmentRunner only bumps it on a successful jitted run).
        ts_entries = self._ts_entries
        seg_c0 = (
            runner.compiled_ticks
            if (ts_entries is not None and runner is not None)
            else 0
        )
        # the operator clock starts BEFORE injection: batch tightening
        # (expression_eval.tighten_batch) is the single biggest cost of
        # an ingest tick and it belongs to the InputNode, not to the
        # unattributed gap between stage sum and tick wall
        t0 = _time.perf_counter_ns()
        if has_injected:
            for b in injected[node.id]:
                ex.inject(b)
        inputs = (
            runner.gather(produced)
            if runner is not None
            else [produced.get(inp.id, []) for inp in node.inputs]
        )
        from pathway_tpu.internals.errors import set_exec_scope

        set_exec_scope(getattr(node, "_error_scope", None))
        # operator span only when the node has work this tick — idle
        # autocommit passes must not flood the span ring
        span = (
            self._tracer.span(
                f"op.{self._node_names[node.id]}",
                node=f"{node.name}_{node.id}",
            )
            if self._tracer.enabled and (has_injected or any(inputs))
            else None
        )
        try:
            if span is not None:
                with span:
                    out = (
                        runner.process(t, inputs)
                        if runner is not None
                        else ex.process(t, inputs)
                    )
                    if final:
                        out = list(out) + list(ex.on_end())
                    span.set_attribute(
                        "rows", sum(len(b) for b in out)
                    )
            else:
                out = (
                    runner.process(t, inputs)
                    if runner is not None
                    else ex.process(t, inputs)
                )
                if final:
                    out = list(out) + list(ex.on_end())
        finally:
            set_exec_scope(None)
        produced[node.id] = out
        nrows = sum(len(b) for b in out)
        if nrows:
            stats.node_rows[node.id] = stats.node_rows.get(node.id, 0) + nrows
        node_ns = _time.perf_counter_ns() - t0
        stats.node_ns[node.id] = stats.node_ns.get(node.id, 0) + node_ns
        if nrows or any(inputs):
            # only ticks that did work: idle 50 ms autocommit ticks
            # would swamp the latency distribution with ~0 samples
            self._tick_hist_children[node.id].observe(node_ns / 1e9)
            if self._otel_on:
                self._otel_metrics.record_operator_latency(
                    self._node_names[node.id], node_ns
                )
            if ts_entries is not None:
                # list.append is GIL-atomic — safe from pool threads
                ts_entries.append(
                    (
                        node.id,
                        t0,
                        t0 + node_ns,
                        sum(len(b) for b in inputs),
                        nrows,
                        runner is not None
                        and runner.compiled_ticks > seg_c0,
                    )
                )
        if isinstance(ex, InputExec) and nrows:
            stats.rows_in[node.id] = stats.rows_in.get(node.id, 0) + nrows

    def tick(self, t: int, injected: dict[int, list[DiffBatch]] | None = None) -> None:
        """Process one logical time: push diffs through all nodes in topo
        order. `injected` maps input-node id -> batches. The whole tick
        runs under an ``engine.tick`` span parented on the trace being
        served (pending REST request, or the barrier-agreed group trace
        in lockstep mode) so per-operator child spans attribute the
        tick's work to that request."""
        if not self._tracer.enabled:
            self._tick_inner(t, injected)
            return
        from pathway_tpu.observability import tracing

        parent = tracing.parse_traceparent(self._tick_traceparent)
        if parent is None:
            parent = tracing.pending_context()
        with self._tracer.span(
            "engine.tick", parent=parent, root=True, t=t
        ):
            self._tick_inner(t, injected)

    def _tick_inner(
        self, t: int, injected: dict[int, list[DiffBatch]] | None
    ) -> None:
        self.current_time = t
        produced: dict[int, list[DiffBatch]] = {}
        final = t >= END_OF_TIME
        if self._fault_plan is not None and not final:
            self._fault_plan.on_tick(t, "head")
        stats = self.stats
        self._ts_entries = self._tickscope.begin_tick(t)
        tick_start = _time.perf_counter_ns()
        if self._pool is not None and self._levels is not None:
            import contextvars as _cv

            traced = self._tracer.enabled
            for level in self._levels:
                if len(level) == 1:
                    self._process_node(
                        level[0], t, produced, injected, final, stats
                    )
                    continue
                futures = [
                    # pool threads don't inherit the tick span's
                    # contextvars; run each node in a fresh copy of the
                    # submitting context so operator spans nest correctly
                    self._pool.submit(
                        _cv.copy_context().run,
                        self._process_node,
                        node, t, produced, injected, final, stats,
                    )
                    if traced
                    else self._pool.submit(
                        self._process_node,
                        node, t, produced, injected, final, stats,
                    )
                    for node in level
                ]
                # fail-stop: wait for the WHOLE level first so no sibling
                # keeps producing side effects after the error propagates
                _cf.wait(futures)
                for f in futures:
                    exc = f.exception()
                    if exc is not None:
                        raise exc
        else:
            for node in self.order:
                self._process_node(node, t, produced, injected, final, stats)
        for node in self._sinks:
            consumed = sum(
                len(b) for inp in node.inputs for b in produced.get(inp.id, [])
            )
            if consumed:
                stats.rows_out[node.id] = (
                    stats.rows_out.get(node.id, 0) + consumed
                )
        stats.ticks += 1
        stats.current_time = t if not final else stats.current_time
        stats.last_tick_ns = _time.perf_counter_ns() - tick_start
        self._tickscope.end_tick(self._ts_entries, stats.last_tick_ns)
        self._ts_entries = None
        self._tick_count += 1
        if self._fault_plan is not None and not final:
            # "tail" kills land AFTER this tick's node processing but
            # BEFORE the persistence driver commits it — the group-
            # visible mid-tick death the chaos matrix exercises
            self._fault_plan.on_tick(t, "tail")
        if self.engine_mesh is not None and not final:
            self.global_frontier = self._frontier_consensus(t)
        if self.on_tick is not None:
            self.on_tick(t)

    # --- static run -----------------------------------------------------------

    def run_static(self) -> None:
        """Run all static sources to completion, merging events by time
        (deterministic 'batch mode' — reference PersistenceMode::Batch).
        Multi-process: tick times are agreed by a min-barrier over the host
        mesh, so every process ticks the same logical times in lockstep —
        DCN execs then exchange exactly one partition per (channel, tick,
        peer) and the barrier doubles as the frontier consensus."""
        events: list[tuple[int, int, DiffBatch]] = []  # (time, node_id, batch)
        for node in self.order:
            if isinstance(node, InputNode) and isinstance(
                node.source, StaticSource
            ):
                for t, batch in node.source.events():
                    events.append((t, node.id, batch))
        events.sort(key=lambda e: e[0])
        i = 0
        n = len(events)
        if self.host_mesh is None:
            while i < n:
                t = events[i][0]
                injected: dict[int, list[DiffBatch]] = {}
                while i < n and events[i][0] == t:
                    injected.setdefault(events[i][1], []).append(events[i][2])
                    i += 1
                self.tick(t, injected)
            self.tick(END_OF_TIME)
            return
        while True:
            local_next = events[i][0] if i < n else END_OF_TIME
            vals = self.host_mesh.barrier(("tick", local_next))
            # the barrier frames carried every process's traceparent:
            # adopt the group's pick so all processes' tick spans (and
            # their DCN exchanges) land in ONE trace
            self._tick_traceparent = self.host_mesh.group_traceparent()
            t = min(v[1] for v in vals.values())
            if t >= END_OF_TIME:
                break
            injected = {}
            while i < n and events[i][0] == t:
                injected.setdefault(events[i][1], []).append(events[i][2])
                i += 1
            self.tick(t, injected)
            self.global_frontier = t
        self.tick(END_OF_TIME)

    # --- streaming run --------------------------------------------------------

    def run_streaming(self) -> None:
        """Drive streaming sources: connector threads feed InputSessions; every
        autocommit interval a tick assigns a wall-clock logical time (even ms,
        like reference Timestamp::new_from_current_time)."""
        sources: list[tuple[InputNode, StreamingSource]] = []
        static_events: list[tuple[int, int, DiffBatch]] = []
        for node in self.order:
            if isinstance(node, InputNode):
                if isinstance(node.source, StreamingSource):
                    node.source.session._wake = lambda: self._wake.set()
                    sources.append((node, node.source))
                elif isinstance(node.source, StaticSource):
                    for t, batch in node.source.events():
                        static_events.append((t, node.id, batch))
        for _node, src in sources:
            src.start()
        if self.host_mesh is not None:
            self._run_streaming_lockstep(sources, static_events)
            return
        # feed all static data at the first tick
        last_t = 0
        if static_events:
            injected: dict[int, list[DiffBatch]] = {}
            for _t, nid, batch in static_events:
                injected.setdefault(nid, []).append(batch)
            last_t = self._now_ms()
            self.tick(last_t, injected)
        # Surge Gate priority classes: while an interactive session (REST
        # queries behind a gate) is hot — rows pending, or queued in its
        # micro-batcher — bulk ingest/backfill sessions drain at most
        # BULK_CHUNK rows per tick, so serving ticks never stall behind
        # an unbounded backfill batch. Chunking (vs skipping) keeps
        # ingest starvation-free: every tick still moves bulk rows.
        from pathway_tpu.internals.config import serving_bulk_chunk

        BULK_CHUNK = serving_bulk_chunk()
        while not self._stop.is_set():
            self._wake.wait(timeout=self.autocommit_ms / 1000.0)
            self._wake.clear()
            injected = {}
            any_data = False
            all_done = True
            # re-read priorities every tick: the SurgeGate marks its
            # session interactive from the connector thread, possibly
            # after this loop already started
            hot = any(
                src.session.hot()
                for _node, src in sources
                if getattr(src.session, "priority", 1)
                == InputSession.PRIORITY_INTERACTIVE
            )
            for node, src in sources:
                sess = src.session
                limit = (
                    BULK_CHUNK
                    if (
                        hot
                        and getattr(sess, "priority", 1)
                        != InputSession.PRIORITY_INTERACTIVE
                        and not sess.finished
                    )
                    else None
                )
                rows = sess.drain(limit)
                if rows:
                    any_data = True
                    injected[node.id] = [
                        DiffBatch.from_rows(rows, src.column_names)
                    ]
                if sess.has_data():
                    # chunk leftover: re-tick promptly instead of waiting
                    # out the autocommit interval
                    self._wake.set()
                if not sess.finished:
                    all_done = False
            if any_data:
                t = max(self._now_ms(), last_t + 2)
                last_t = t
                self.tick(t, injected)
            if all_done and not any_data:
                break
        for _node, src in sources:
            src.stop()
        self.tick(END_OF_TIME)

    def _run_streaming_lockstep(self, sources, static_events) -> None:
        """Streaming loop for the multi-process engine: every autocommit
        interval the group exchanges (proposed time, has-data, all-done)
        over the host mesh; if anyone has data, EVERY process ticks at the
        min proposed time (possibly with empty input), so DCN exchanges
        and the per-tick frontier stay aligned. Termination needs group
        consensus: all sources finished everywhere and no data in flight."""
        first_static: dict[int, list[DiffBatch]] | None = None
        if static_events:
            first_static = {}
            for _t, nid, batch in static_events:
                first_static.setdefault(nid, []).append(batch)
        last_t = 0
        while True:
            if first_static is None:
                self._wake.wait(timeout=self.autocommit_ms / 1000.0)
                self._wake.clear()
            injected: dict[int, list[DiffBatch]] = (
                first_static if first_static is not None else {}
            )
            any_data = bool(injected)
            all_done = True
            for node, src in sources:
                rows = src.session.drain()
                if rows:
                    any_data = True
                    injected.setdefault(node.id, []).append(
                        DiffBatch.from_rows(rows, src.column_names)
                    )
                if not src.session.finished:
                    all_done = False
            first_static = None
            # stop() must be group-coordinated: a process leaving the
            # lockstep cadence unilaterally would strand peers at their
            # next gather. Any process's stop request stops the group at
            # this round, BEFORE the tick, so the final END tick pairs up.
            vals = self.host_mesh.barrier(
                (
                    "stream",
                    self._now_ms(),
                    any_data,
                    all_done,
                    self._stop.is_set(),
                )
            )
            group_stop = any(v[4] for v in vals.values())
            group_any = any(v[2] for v in vals.values())
            group_done = all(v[3] for v in vals.values())
            self._tick_traceparent = self.host_mesh.group_traceparent()
            if group_any:
                # rows already drained from sessions advanced their offset
                # markers — they must be ticked (and so logged) even when
                # stopping, or a post-restart seek would skip them
                t = max(min(v[1] for v in vals.values()), last_t + 2)
                last_t = t
                self.tick(t, injected)
                self.global_frontier = t
            if group_stop or (group_done and not group_any):
                break
        for _node, src in sources:
            src.stop()
        self.tick(END_OF_TIME)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def _frontier_consensus(self, t: int) -> int:
        """min-all-reduce of the local clock across engine shards. Times are
        wall-clock ms (> int32), so the collective carries the offset from
        the first tick (x64 stays disabled)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pathway_tpu.parallel.collectives import frontier_allreduce

        mesh, axis = self.engine_mesh
        if self._frontier_base is None:
            self._frontier_base = t
        rel = t - self._frontier_base
        if rel > (1 << 30):
            # re-base before the int32 payload could overflow (~24.8 days
            # of uptime); the consensus value is monotone either way
            self._frontier_base = t
            rel = 0
        n = mesh.shape[axis]
        local = jax.device_put(
            jnp.full((n,), rel, jnp.int32), NamedSharding(mesh, P(axis))
        )
        ft = frontier_allreduce(local, mesh, axis)
        self.frontier_syncs += 1
        return int(np.asarray(ft)[0]) + self._frontier_base

    @staticmethod
    def _now_ms() -> int:
        # even ms only — odd timestamps are reserved for intermediate
        # "alt-neu" steps (reference: src/engine/timestamp.rs:20-32)
        return (int(_time.time() * 1000) // 2) * 2

    def run(self) -> None:
        has_streaming = any(
            isinstance(node, InputNode)
            and isinstance(node.source, StreamingSource)
            for node in self.order
        )
        self._phoenix_active = True
        try:
            if has_streaming:
                self.run_streaming()
            else:
                self.run_static()
        finally:
            # peers exiting after this point are a clean group shutdown,
            # not a failure (the mesh singleton outlives the run)
            self._phoenix_active = False
            if self._pool is not None:
                self._pool.shutdown(wait=True, cancel_futures=True)
                self._pool = None
                self._levels = None  # reused Runtime runs sequentially
