"""Reducer accumulators for incremental groupby/reduce.

Parity with the reference reducer set (/root/reference/src/engine/reduce.rs:22-38
and src/engine/dataflow.rs:3113-3400): Count, IntSum/FloatSum/ArraySum, Unique,
Min/ArgMin, Max/ArgMax, SortedTuple, Tuple, Any, Earliest, Latest, Avg,
Ndarray, Stateful. Semigroup reducers (count/sum/avg) keep O(1) state and
retract by subtraction; order-dependent ones keep a multiset and restate on
change — the engine recomputes only touched groups per tick, the microbatch
analog of differential's `reduce_abelian`."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.api import ERROR, Pointer


@dataclass
class ReducerSpec:
    """Build-time description: which accumulator over which input columns."""

    kind: str
    arg_cols: tuple[str, ...] = ()
    skip_nones: bool = False
    # reference groupby(_skip_errors=True) default: ERROR args are simply
    # skipped; with False they poison the aggregate while present
    skip_errors: bool = True
    fn: Callable | None = None  # stateful combine fn
    extra: dict = field(default_factory=dict)

    def make(self) -> "Accumulator":
        return _FACTORY[self.kind](self)


class Accumulator:
    # net count of ERROR-bearing rows in this aggregate (+diff/-diff), so a
    # retracted/corrected poison row un-poisons the group
    poisoned_count = 0

    def __init__(self, spec: ReducerSpec):
        self.spec = spec

    def update(self, args: tuple, diff: int, key: int, time: int) -> None:
        raise NotImplementedError

    def value(self) -> Any:
        raise NotImplementedError


class CountAcc(Accumulator):
    def __init__(self, spec):
        super().__init__(spec)
        self.c = 0

    def update(self, args, diff, key, time):
        if self.spec.arg_cols and self.spec.skip_nones and args[0] is None:
            return
        self.c += diff

    def value(self):
        return self.c


class SumAcc(Accumulator):
    def __init__(self, spec):
        super().__init__(spec)
        self.s: Any = 0
        self.n = 0

    def update(self, args, diff, key, time):
        v = args[0]
        if v is None:
            if self.spec.skip_nones:
                return
            v = 0
        if isinstance(v, np.ndarray):
            # keep a multiset and np.sum at read time: numpy's pairwise
            # summation is the reference result (sequential += drifts,
            # e.g. 1.1+4.1+7.1 != np.sum([...])); counts cancel on
            # retraction so memory stays O(distinct arrays)
            if not isinstance(self.s, dict):
                # any scalar sum accumulated before the first array rides
                # along and re-adds at read time
                self.scalar_carry = self.s
                self.s = {}
            k = _hashable(v)
            c = self.s.get(k, 0) + diff
            if c == 0:
                self.s.pop(k, None)
            else:
                self.s[k] = c
        elif isinstance(self.s, dict):
            self.scalar_carry = getattr(self, "scalar_carry", 0) + v * diff
        else:
            self.s = self.s + v * diff
        self.n += diff

    def value(self):
        if isinstance(self.s, dict):
            arrs = []
            for k, c in self.s.items():
                arrs.extend([_unhashable(k)] * max(c, 0))
            carry = getattr(self, "scalar_carry", 0)
            if not arrs:
                return carry
            total = np.sum(np.stack(arrs), axis=0)
            if isinstance(carry, (int, float)) and carry == 0:
                return total
            return total + carry
        return self.s


class AvgAcc(Accumulator):
    def __init__(self, spec):
        super().__init__(spec)
        self.s = 0.0
        self.c = 0

    def update(self, args, diff, key, time):
        v = args[0]
        if v is None:
            if self.spec.skip_nones:
                return
        self.s += float(v) * diff
        self.c += diff

    def value(self):
        if self.c == 0:
            return ERROR
        return self.s / self.c


class _MultisetAcc(Accumulator):
    """Keeps a multiset of argument tuples with counts."""

    def __init__(self, spec):
        super().__init__(spec)
        self.items: dict[Any, int] = {}

    def _k(self, args: tuple, key: int, time: int) -> Any:
        return args

    def update(self, args, diff, key, time):
        if self.spec.skip_nones and args[0] is None:
            return
        k = self._k(args, key, time)
        c = self.items.get(k, 0) + diff
        if c == 0:
            self.items.pop(k, None)
        else:
            self.items[k] = c

    def update_bulk(self, argcols: list[list], diffs: list[int]) -> None:
        """Apply one group's slice of a batch in a single tight loop (the
        columnar groupby path, engine/nodes.py). ERROR args feed
        poisoned_count exactly like the per-row path; returns nothing —
        state mutates in place."""
        items = self.items
        skip = self.spec.skip_nones
        skip_err = self.spec.skip_errors
        for k in zip(*argcols, diffs):
            d = k[-1]
            args = k[:-1]
            if skip and args[0] is None:
                continue
            if any(a is ERROR for a in args):
                if not skip_err:
                    self.poisoned_count += d
                continue
            c = items.get(args, 0) + d
            if c == 0:
                items.pop(args, None)
            else:
                items[args] = c


def _sort_key(v: Any) -> Any:
    # heterogeneous-safe sort key
    return (str(type(v).__name__), v) if not isinstance(v, (int, float, bool)) else (
        "num",
        v,
    )


class MinAcc(_MultisetAcc):
    def value(self):
        if not self.items:
            return ERROR
        return min((k[0] for k in self.items), key=_sort_key)


class MaxAcc(_MultisetAcc):
    def value(self):
        if not self.items:
            return ERROR
        return max((k[0] for k in self.items), key=_sort_key)


class ArgMinAcc(_MultisetAcc):
    # args = (value, arg); ties on the value break on the SMALLEST arg by
    # its stable sort key — never hash(), which is PYTHONHASHSEED-salted
    # and would make results differ between process runs
    def value(self):
        if not self.items:
            return ERROR
        best_val = min((k[0] for k in self.items), key=_sort_key)
        bk = _sort_key(best_val)
        return min(
            (k[1] for k in self.items if _sort_key(k[0]) == bk),
            key=_sort_key,
        )


class ArgMaxAcc(_MultisetAcc):
    def value(self):
        if not self.items:
            return ERROR
        best_val = max((k[0] for k in self.items), key=_sort_key)
        bk = _sort_key(best_val)
        return min(
            (k[1] for k in self.items if _sort_key(k[0]) == bk),
            key=_sort_key,
        )


class UniqueAcc(_MultisetAcc):
    def value(self):
        vals = {k[0] for k in self.items}
        if len(vals) != 1:
            # recorded by the groupby exec -> poisons the cell AND fails a
            # terminate_on_error run (reference: unique() panics on
            # non-unique groups)
            raise ValueError(
                "More than one distinct value passed to the unique reducer"
            )
        return next(iter(vals))


class AnyAcc(Accumulator):
    """'Some' value — the one belonging to the smallest row key, so every
    any() column of a group comes from the SAME row (reference relies on
    this: joining a reduce of any(pet), any(owner), any(age) back against
    the source matches exactly one row)."""

    def __init__(self, spec):
        super().__init__(spec)
        self.rows: dict[Any, list] = {}  # row key -> [value, count]

    def update(self, args, diff, key, time):
        if self.spec.skip_nones and args[0] is None:
            return
        e = self.rows.get(key)
        if e is None:
            if diff != 0:
                self.rows[key] = [args[0], diff]
        else:
            e[1] += diff
            if diff > 0:
                e[0] = args[0]
            if e[1] == 0:
                del self.rows[key]

    def value(self):
        if not self.rows:
            return ERROR
        k = min(self.rows, key=_sort_key)
        return self.rows[k][0]


class _KeyedMultisetAcc(Accumulator):
    """Multiset of (order_key, value) for ordered collection reducers."""

    def __init__(self, spec):
        super().__init__(spec)
        self.items: dict[Any, int] = {}

    def update(self, args, diff, key, time):
        v = args[0]
        if self.spec.skip_nones and v is None:
            return
        # the order key may itself be an ndarray (sort_by over an array
        # column) — store its hashable, orderable form
        k = (_hashable(key), _hashable(v))
        c = self.items.get(k, 0) + diff
        if c == 0:
            self.items.pop(k, None)
        else:
            self.items[k] = c

    def _expanded(self):
        out = []
        for (key, v), c in self.items.items():
            out.extend([(key, _unhashable(v))] * max(c, 0))
        return out


def _hashable(v: Any) -> Any:
    if isinstance(v, np.ndarray):
        # order key FIRST after the tag so sorted() orders arrays by their
        # contents; equality/hashing additionally uses the raw BYTES so
        # NaN-holding arrays still cancel on retraction (nan != nan would
        # otherwise split the multiset keys)
        import math

        flat = np.ravel(v).tolist()
        if v.dtype.kind == "f":
            order = tuple(
                (1, 0.0) if math.isnan(x) else (0, float(x)) for x in flat
            )
        else:
            order = tuple(flat)
        return (
            "__ndarray__",
            order,
            str(v.dtype),
            v.shape,
            v.tobytes(),
        )
    if isinstance(v, list):
        return ("__tuple__", tuple(_hashable(x) for x in v))
    if isinstance(v, tuple):
        # sort tokens are (sort_value, key) tuples that may carry arrays
        return tuple(_hashable(x) for x in v)
    return v


def _unhashable(v: Any) -> Any:
    if isinstance(v, tuple) and len(v) == 5 and v[0] == "__ndarray__":
        return (
            np.frombuffer(v[4], dtype=np.dtype(v[2])).reshape(v[3]).copy()
        )
    if isinstance(v, tuple) and len(v) == 4 and v[0] == "__ndarray__":
        # older snapshot encoding (pre-bytes)
        return np.array(v[1], dtype=np.dtype(v[2])).reshape(v[3])
    if isinstance(v, tuple) and len(v) == 2 and v[0] == "__tuple__":
        return tuple(_unhashable(x) for x in v[1])
    if isinstance(v, tuple):
        # plain tuples are encoded element-wise without a tag; decode any
        # nested ndarray/list markers the same way
        return tuple(_unhashable(x) for x in v)
    return v


class TupleAcc(_KeyedMultisetAcc):
    def value(self):
        items = sorted(self._expanded(), key=lambda kv: kv[0])
        return tuple(v for _, v in items)


class SortedTupleAcc(_KeyedMultisetAcc):
    def value(self):
        items = [v for _, v in self._expanded()]
        return tuple(sorted(items, key=_sort_key))


class NdarrayAcc(_KeyedMultisetAcc):
    def value(self):
        items = sorted(self._expanded(), key=lambda kv: kv[0])
        return np.array([v for _, v in items])


class EarliestAcc(Accumulator):
    def __init__(self, spec):
        super().__init__(spec)
        self.items: dict[Any, int] = {}

    def update(self, args, diff, key, time):
        k = (time, key, _hashable(args[0]))
        c = self.items.get(k, 0) + diff
        if c == 0:
            self.items.pop(k, None)
        else:
            self.items[k] = c

    def value(self):
        if not self.items:
            return ERROR
        t, k, v = min(self.items, key=lambda x: (x[0], x[1]))
        return _unhashable(v)


class LatestAcc(EarliestAcc):
    def value(self):
        if not self.items:
            return ERROR
        t, k, v = max(self.items, key=lambda x: (x[0], x[1]))
        return _unhashable(v)


class StatefulAcc(Accumulator):
    """Custom non-retractable accumulator
    (reference: stateful_reduce, src/engine/dataflow/operators/stateful_reduce.rs)."""

    def __init__(self, spec):
        super().__init__(spec)
        self.state: Any = None
        self.many = spec.extra.get("many", False)

    def update(self, args, diff, key, time):
        if diff < 0:
            raise RuntimeError(
                "stateful reducers do not support retractions "
                "(append-only input required)"
            )
        assert self.spec.fn is not None
        if self.many:
            self.state = self.spec.fn(self.state, [(args, diff)])
        else:
            self.state = self.spec.fn(self.state, *args)

    def value(self):
        return self.state


class CustomAccAcc(Accumulator):
    """BaseCustomAccumulator-driven reducer (reference: udf_reducer,
    internals/custom_reducers.py). Accumulators implementing ``retract``
    apply retractions incrementally; those that don't trigger a full
    recomputation of the group from the retained row multiset (the
    reference's non-retractable fallback)."""

    def __init__(self, spec):
        super().__init__(spec)
        self.cls = spec.extra["cls"]
        self.acc: Any = None
        from pathway_tpu.internals.custom_reducers import (
            BaseCustomAccumulator,
        )

        self._has_retract = (
            getattr(self.cls, "retract", None) is not None
            and self.cls.retract is not BaseCustomAccumulator.retract
        )
        self.n = 0  # net row count (emptiness signal, O(1))
        # retained row multiset — ONLY for the non-retract rebuild path
        self.rows: dict[tuple, int] = {}
        self._dirty = False

    def _apply_rows(self, args, diff):
        k = tuple(_hashable(a) for a in args)
        c = self.rows.get(k, 0) + diff
        if c == 0:
            self.rows.pop(k, None)
        else:
            self.rows[k] = c

    def update(self, args, diff, key, time):
        self.n += diff
        if not self._has_retract:
            self._apply_rows(args, diff)
        if diff > 0 and not self._dirty:
            other = self.cls.from_row(list(args))
            for _ in range(diff):
                if self.acc is None:
                    self.acc = self.cls.from_row(list(args))
                else:
                    self.acc.update(other)
        elif diff < 0 and self._has_retract:
            other = self.cls.from_row(list(args))
            for _ in range(-diff):
                if self.acc is None:
                    raise RuntimeError("retraction before insertion")
                self.acc.retract(other)
        elif diff < 0:
            # no retract: rebuild lazily in value(), at most once per tick
            self._dirty = True

    def _rebuild(self):
        self.acc = None
        for k, c in self.rows.items():
            row = [_unhashable(x) for x in k]
            other = self.cls.from_row(row)
            for _ in range(c):
                if self.acc is None:
                    self.acc = self.cls.from_row(row)
                else:
                    self.acc.update(other)
        self._dirty = False

    def value(self):
        if self._dirty:
            self._rebuild()
        if self.acc is None or self.n <= 0:
            return None
        return self.acc.compute_result()


_FACTORY: dict[str, Callable[[ReducerSpec], Accumulator]] = {
    "custom_acc": CustomAccAcc,
    "count": CountAcc,
    "sum": SumAcc,
    "avg": AvgAcc,
    "min": MinAcc,
    "max": MaxAcc,
    "argmin": ArgMinAcc,
    "argmax": ArgMaxAcc,
    "unique": UniqueAcc,
    "any": AnyAcc,
    "tuple": TupleAcc,
    "sorted_tuple": SortedTupleAcc,
    "ndarray": NdarrayAcc,
    "earliest": EarliestAcc,
    "latest": LatestAcc,
    "stateful": StatefulAcc,
}
