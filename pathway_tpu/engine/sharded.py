"""Key-sharded execution of stateful engine operators over a device mesh.

This is the engine-level data parallelism of the reference — every worker
owns the slice of keys whose shard bits map to it, and an Exchange moves each
record to its owner before stateful work (reference:
src/engine/value.rs:38,94 `ShardPolicy`/SHARD_MASK,
src/engine/dataflow/operators.rs:128,432 Exchange pact,
src/engine/dataflow/config.rs:63-121 worker topology). Here the workers are
mesh shards: each stateful exec is split into n_shards sub-execs with
disjoint keyed state, and rows are routed by the low 16 bits of their group
key. Numeric rows travel through a real `lax.all_to_all` over ICI
(parallel/exchange.py); host-only payloads (strings/json) take the
equivalent host partition path (multi-host deployments would move these over
DCN — SURVEY §5.8).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import (
    BufferExec,
    GroupByExec,
    JoinExec,
    NodeExec,
    SortExec,
)

# Minimum rows per batch before the device all-to-all path is worth the
# dispatch overhead; tests lower this to force the collective.
DEVICE_EXCHANGE_MIN_ROWS = 512

SHARD_MASK = 0xFFFF  # low 16 bits route the row (reference value.rs:38)


def shard_of(gks: np.ndarray, n_shards: int) -> np.ndarray:
    """THE ownership function: device-mesh sharded execs, the DCN
    router (engine/dcn.py `_DcnRouter`), and the serving plane's
    corpus sharding (parallel/replicate.py `corpus_shard_of` — Shard
    Harbor replica×shard ownership) all route by this same jk-hash
    partition, so a key's owner is one agreed fact across every
    layer."""
    return ((gks.astype(np.uint64) & np.uint64(SHARD_MASK)) % np.uint64(
        n_shards
    )).astype(np.int32)


def exchange_facts(node: Any) -> list[tuple[str, tuple[str, ...]]]:
    """Static description of the exchange edges the sharded engine inserts
    in front of `node`: [(input label, routing key columns)]. Empty for
    operators that never re-route rows. Consumed by the Graph Doctor's
    shard-safety and graph-stats rules — kept HERE so the facts stay next
    to the exec classes that implement the exchanges (a new Sharded*Exec
    must register its routing contract in the same file)."""
    from pathway_tpu.engine import nodes as _n

    if isinstance(node, _n.GroupByNode):
        return [("input", node.key_columns())]
    if isinstance(node, _n.JoinNode):
        return [
            ("left", tuple(node.left_on)),
            ("right", tuple(node.right_on)),
        ]
    if isinstance(node, _n.SortNode):
        # instance-less sorts are one global order and never shard
        # (SortNode.make_exec builds a plain SortExec for them)
        if node.instance_col is not None:
            return [("input", (node.instance_col,))]
        return []
    if isinstance(node, _n.BufferNode):
        # ShardedBufferExec routes by row key; the watermark is global
        return [("input", ("id",))]
    return []


def _pack_scalar_column(col: np.ndarray):
    """One numeric device array + rebuild spec for a scalar column, or
    None when ineligible."""
    from pathway_tpu.parallel.exchange import packable

    orig = col.dtype
    arr = col
    if arr.dtype == object:
        # type-homogeneous python scalars only: a mixed int/float
        # column would come back type-changed after the round trip
        # and hash to different group keys than the host path
        t0 = type(arr[0])
        if t0 not in (int, float, bool) or not all(
            type(v) is t0 for v in arr
        ):
            return None
        try:
            arr = np.asarray(arr.tolist())
        except (TypeError, ValueError, OverflowError):
            return None
    if arr.dtype.kind == "f" and arr.dtype.itemsize < 4:
        arr = arr.astype(np.float32)
    if arr.dtype.kind in "iu" and arr.dtype.itemsize < 8:
        arr = arr.astype(np.int64)
    if not packable(arr):
        return None
    return [arr], ("scalar", orig)


def _pack_tuple_column(col: np.ndarray):
    """Fixed-arity tuples of homogeneous numeric scalars decompose into
    one device array per position (window ids like (instance, start, end)
    ride ICI instead of forcing the whole batch onto the host path)."""
    from pathway_tpu.parallel.exchange import packable

    v0 = col[0]
    arity = len(v0)
    if arity == 0:
        return None
    elem_types = [type(v) for v in v0]
    if any(t not in (int, float, bool) for t in elem_types):
        return None
    for v in col:
        if type(v) is not tuple or len(v) != arity:
            return None
        for x, t in zip(v, elem_types):
            if type(x) is not t:
                return None
    arrays = []
    for pos in range(arity):
        a = np.asarray([v[pos] for v in col])
        if a.dtype.kind == "f" and a.dtype.itemsize < 4:
            a = a.astype(np.float32)
        if a.dtype.kind in "iu" and a.dtype.itemsize < 8:
            a = a.astype(np.int64)
        if not packable(a):
            return None
        arrays.append(a)
    return arrays, ("tuple", elem_types)


def _rebuild_column(arrays: list[np.ndarray], spec) -> np.ndarray:
    kind, info = spec
    if kind == "scalar":
        return arrays[0].astype(info)
    lists = [a.tolist() for a in arrays]  # python scalars, like host path
    out = np.empty(len(lists[0]), dtype=object)
    for i, vals in enumerate(zip(*lists)):
        out[i] = tuple(
            t(v) for t, v in zip(info, vals)
        )  # restore bools: int arrays round-trip python bools as ints
    return out


def _batch_numeric_columns(b: DiffBatch):
    """[(device arrays, rebuild spec)] per value column, or None if any
    column holds payloads that cannot ride the device path (strings/json/
    nested or ragged tuples stay host-side). The spec lets the receiver
    restore the exact representation the host-partition path would have
    kept, so both paths feed identical columns downstream."""
    out = []
    for col in b.columns.values():
        if col.dtype == object:
            if not len(col):
                return None
            packed = (
                _pack_tuple_column(col)
                if type(col[0]) is tuple
                else _pack_scalar_column(col)
            )
        else:
            packed = _pack_scalar_column(col)
        if packed is None:
            return None
        out.append(packed)
    return out


class _ShardRouter:
    """Shared routing logic: split each incoming batch into per-shard
    sub-batches, over the device mesh when rows are numeric."""

    def __init__(self, mesh: Any, axis: str):
        self.mesh = mesh
        self.axis = axis
        self.n_shards = mesh.shape[axis]
        self.device_exchanges = 0  # observability: collectives actually run
        # Flight Recorder: rows routed per destination shard (the `shard`
        # label lets a multi-host Prometheus aggregate skew across the
        # whole mesh) + which transport carried them. Children prebound —
        # routing is on the per-batch hot path.
        from pathway_tpu.observability import REGISTRY

        rows = REGISTRY.counter(
            "pathway_shard_rows_total",
            "rows routed to each shard by the sharded-exec exchange",
            labelnames=("shard",),
        )
        self._m_shard_rows = [
            rows.labels(str(s)) for s in range(self.n_shards)
        ]
        self._m_exchanges = REGISTRY.counter(
            "pathway_shard_exchanges_total",
            "exchange batches, by transport (device=lax.all_to_all over "
            "ICI, host=numpy partition)",
            labelnames=("transport",),
        )

    def route(
        self, b: DiffBatch, dest: np.ndarray
    ) -> list[DiffBatch | None]:
        """Returns one sub-batch per shard (None where empty)."""
        numeric = (
            _batch_numeric_columns(b)
            if len(b) >= DEVICE_EXCHANGE_MIN_ROWS
            else None
        )
        if numeric is not None:
            out = self._route_device(b, dest, numeric)
            self._m_exchanges.labels("device").inc()
        else:
            out = self._route_host(b, dest)
            self._m_exchanges.labels("host").inc()
        for s, sub in enumerate(out):
            if sub is not None:
                self._m_shard_rows[s].inc(len(sub))
        return out

    def _route_host(self, b, dest):
        out: list[DiffBatch | None] = [None] * self.n_shards
        for s in range(self.n_shards):
            m = dest == s
            if m.any():
                out[s] = b.mask(m)
        return out

    def _route_device(self, b, dest, numeric_cols):
        from pathway_tpu.parallel.exchange import exchange_rows

        self.device_exchanges += 1
        arrays = [b.keys, b.diffs]
        for col_arrays, _spec in numeric_cols:
            arrays.extend(col_arrays)
        blocks = exchange_rows(arrays, dest, self.mesh, self.axis)
        names = b.column_names
        out: list[DiffBatch | None] = [None] * self.n_shards
        for s, cols in enumerate(blocks):
            if not len(cols[0]):
                continue
            # restore each column to its pre-exchange representation
            # (object columns back to native python scalars, tuple
            # columns re-zipped, typed columns back to their original
            # dtype) so sharded results are identical to the
            # host-partition and unsharded paths
            columns = {}
            pos = 2
            for name, (col_arrays, spec) in zip(names, numeric_cols):
                take = len(col_arrays)
                columns[name] = _rebuild_column(
                    list(cols[pos : pos + take]), spec
                )
                pos += take
            out[s] = DiffBatch(cols[0], cols[1], columns)
        return out


class _ShardedExec(NodeExec):
    """Shared scaffolding for per-shard execs: a router, one inner exec
    per shard, the partition loop, and shard-state (de)serialization."""

    inner_cls: Any = None

    def __init__(self, node, mesh: Any, axis: str = "data"):
        super().__init__(node)
        self.router = _ShardRouter(mesh, axis)
        self.shards = [
            self.inner_cls(node) for _ in range(self.router.n_shards)
        ]

    def _partition(self, batches, dests_fn) -> list[list[DiffBatch]]:
        parts: list[list[DiffBatch]] = [[] for _ in self.shards]
        for b in batches:
            if not len(b):
                continue
            for s, sub in enumerate(self.router.route(b, dests_fn(b))):
                if sub is not None:
                    parts[s].append(sub)
        return parts

    def state_dict(self) -> dict:
        # router holds the (unpicklable) mesh; shard states carry the data
        return {"shards": [ex.state_dict() for ex in self.shards]}

    def load_state(self, state: dict) -> None:
        for ex, st in zip(self.shards, state["shards"]):
            if st:
                ex.load_state(st)

    # --- incremental (arrangement-backed) snapshots ---------------------
    # Mirror of engine/dcn.py's _InnerArrangedMixin for the device-mesh
    # layer: delegate the State Ledger protocol to every shard's inner
    # exec, namespacing each shard's arrangement parts as "s<i>.<name>"
    # so segment identity (and so bytes ∝ churn) is stable per shard
    # across restarts.  Without this, device-mesh runs fell back to the
    # monolithic state_dict pickle — the ROADMAP-verified gap that also
    # blocked fast replica hydration of sharded graphs.

    def enable_state_ledger(self) -> None:
        for ex in self.shards:
            hook = getattr(ex, "enable_state_ledger", None)
            if hook is not None:
                hook()

    def arranged_state(self):
        per_shard = []
        for ex in self.shards:
            fn = getattr(ex, "arranged_state", None)
            arranged = fn() if fn is not None else None
            if arranged is None:
                # ANY shard on the monolith path forces the whole exec
                # monolithic — a mixed snapshot could not restore
                # consistently (the generation names one blob per node)
                return None
            per_shard.append(arranged)
        arrs: dict[str, Any] = {}
        for i, (_res, shard_arrs) in enumerate(per_shard):
            for name, arr in shard_arrs.items():
                arrs[f"s{i}.{name}"] = arr
        return (
            {"__shard_residuals__": [res for res, _a in per_shard]},
            arrs,
        )

    def check_arranged_state(self, residual, arrangements) -> bool:
        """Pre-mutation restore validation (persistence glue calls this
        before ANY exec mutates).  A snapshot taken under a DIFFERENT
        shard count no longer forces the log-replay fallback: Shard
        Flux re-partitions the per-shard arrangements by the new
        jk-hash ownership at load time (elastic/planner.py), so a
        PATHWAY_ENGINE_SHARDS change restores with zero replay."""
        shards = residual.get("__shard_residuals__")
        return isinstance(shards, list) and len(shards) >= 1

    def load_arranged_state(self, residual, arrangements) -> None:
        residuals = residual["__shard_residuals__"]
        per: list[dict] = [{} for _ in residuals]
        for key, arr in arrangements.items():
            shard, _, name = key.partition(".")
            per[int(shard[1:])][name] = arr
        if len(residuals) != len(self.shards):
            # elastic restore (Shard Flux): the snapshot's N-shard
            # partition re-splits to this run's M shards by the same
            # jk-hash ownership the router uses — state moves, the log
            # does not replay
            from pathway_tpu.elastic.planner import (
                repartition_shard_states,
            )

            n_old = len(residuals)
            residuals, per, stats = repartition_shard_states(
                residuals, per, len(self.shards)
            )
            import logging

            logging.getLogger("pathway_tpu").info(
                "elastic restore: re-partitioned %d-shard snapshot to "
                "%d shards (%d rows, %d moved) for %s",
                n_old,
                len(self.shards),
                stats["total_rows"],
                stats["moved_rows"],
                type(self).__name__,
            )
        for ex, res, shard_arrs in zip(self.shards, residuals, per):
            ex.load_arranged_state(res, shard_arrs)


class ShardedGroupByExec(_ShardedExec):
    """groupby-reduce with per-shard disjoint state: rows are exchanged to
    the shard owning their group key, each shard reduces independently
    (reference: group_by_table reindex-to-grouping-key + Exchange,
    src/engine/dataflow.rs:3404)."""

    inner_cls = GroupByExec

    def _dests(self, b: DiffBatch) -> np.ndarray:
        ex = self.shards[0]
        simple = not self.node.set_id and ex.inst_idx is None
        if simple:
            gks = np.asarray(ex._group_keys_batch(b), dtype=np.uint64)
        else:
            cols = list(b.columns.values())
            gks = np.fromiter(
                (
                    ex._group_key(tuple(c[i] for c in cols))
                    & 0xFFFFFFFFFFFFFFFF
                    for i in range(len(b))
                ),
                dtype=np.uint64,
                count=len(b),
            )
        return shard_of(gks, self.router.n_shards)

    def process(self, t, inputs):
        parts = self._partition(inputs[0], self._dests)
        out: list[DiffBatch] = []
        for ex, sub_batches in zip(self.shards, parts):
            if sub_batches:
                out.extend(ex.process(t, [sub_batches]))
        return out

    def shard_group_keys(self) -> list[set[int]]:
        """Per-shard owned group keys — disjoint by construction (used by
        tests and the state snapshotter)."""
        return [set(ex.groups.keys()) for ex in self.shards]


class ShardedJoinExec(_ShardedExec):
    """Equijoin with per-shard disjoint state: both sides exchange on the
    join-key hash so matching rows co-locate (reference: join_tables
    arrange+join_core after Exchange, src/engine/dataflow.rs:2740,2834)."""

    inner_cls = JoinExec

    def _dests(self, b: DiffBatch, on_idx, side_tag: str) -> np.ndarray:
        # route by the EXACT join keys the inner exec arranges by
        # (_batch_jks: null on-columns get per-row private keys, same
        # contract as the DCN router) — hashing the raw columns instead
        # would pile every null-keyed row onto the hash(None...) shard.
        # The per-shard exec re-derives jks for its partition: routing
        # needs them before the split, and NodeExec.process takes whole
        # batches — threading precomputed jks through would change the
        # exec interface for one extra C hash pass.
        jks = np.asarray(
            self.shards[0]._batch_jks(b, on_idx, side_tag),
            dtype=np.uint64,
        )
        return shard_of(jks, self.router.n_shards)

    def process(self, t, inputs):
        l_on = self.shards[0].l_on_idx
        r_on = self.shards[0].r_on_idx
        lparts = self._partition(
            inputs[0], lambda b: self._dests(b, l_on, "l")
        )
        rparts = self._partition(
            inputs[1], lambda b: self._dests(b, r_on, "r")
        )
        out: list[DiffBatch] = []
        for ex, lsub, rsub in zip(self.shards, lparts, rparts):
            if lsub or rsub:
                out.extend(ex.process(t, [lsub, rsub]))
        return out


class ShardedBufferExec(_ShardedExec):
    """Temporal buffer with per-shard held state: rows route to the shard
    owning their row key; the release watermark (max time seen) is a
    GLOBAL property, combined across shards every tick — the decentralized
    redesign of the reference's single-worker buffer (the anti-pattern at
    src/engine/dataflow/operators/time_column.rs:44-47, which pins all
    postponed state on one worker)."""

    inner_cls = BufferExec

    def _dests(self, b: DiffBatch) -> np.ndarray:
        return shard_of(np.asarray(b.keys, dtype=np.uint64), self.router.n_shards)

    def process(self, t, inputs):
        cur_idx = self.shards[0].cur_idx
        batch_max = None
        for b in inputs[0]:
            if not len(b):
                continue
            # global watermark: the max current-time over the WHOLE batch
            # (all shards), not just the rows a shard happens to own
            for v in b.columns[self.node.inputs[0].column_names[cur_idx]]:
                if v is not None and (batch_max is None or v > batch_max):
                    batch_max = v
        parts = self._partition(inputs[0], self._dests)
        if batch_max is not None:
            for ex in self.shards:
                if ex.max_seen is None or batch_max > ex.max_seen:
                    ex.max_seen = batch_max
        out: list[DiffBatch] = []
        for ex, sub_batches in zip(self.shards, parts):
            if sub_batches or batch_max is not None:
                out.extend(ex.process(t, [sub_batches]))
        return out

    def on_end(self):
        out: list[DiffBatch] = []
        for ex in self.shards:
            out.extend(ex.on_end())
        return out

    def shard_touched_keys(self) -> list[set[int]]:
        """Keys each shard has ever held or released — the distribution
        evidence tests assert on (held empties after the final flush)."""
        return [
            set(ex.held.keys()) | set(ex.released) for ex in self.shards
        ]


class ShardedSortExec(_ShardedExec):
    """prev/next maintenance sharded by INSTANCE: each instance's sorted
    order lives wholly on the shard owning the instance hash, so pointer
    maintenance parallelizes across instances (reference: prev_next
    instance co-location, src/engine/dataflow/operators/prev_next.rs).
    Instance-less sorts never take this path — SortNode.make_exec builds
    a plain SortExec for them (one global order cannot shard)."""

    inner_cls = SortExec

    def __init__(self, node, mesh: Any, axis: str = "data"):
        super().__init__(node, mesh, axis)
        self._i_idx = self.shards[0].i_idx

    def _dests(self, b: DiffBatch) -> np.ndarray:
        if self._i_idx is None:
            return np.zeros(len(b), dtype=np.int32)
        from pathway_tpu.internals.api import ref_scalars_columns

        inst_col = list(b.columns.values())[self._i_idx]
        insts = np.asarray(
            ref_scalars_columns([inst_col], len(b)), dtype=np.uint64
        )
        return shard_of(insts, self.router.n_shards)

    def process(self, t, inputs):
        parts = self._partition(inputs[0], self._dests)
        out: list[DiffBatch] = []
        for ex, sub_batches in zip(self.shards, parts):
            if sub_batches:
                out.extend(ex.process(t, [sub_batches]))
        return out

    def shard_instances(self) -> list[set]:
        return [set(ex.instances.keys()) for ex in self.shards]
