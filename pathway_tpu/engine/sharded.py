"""Key-sharded execution of stateful engine operators over a device mesh.

This is the engine-level data parallelism of the reference — every worker
owns the slice of keys whose shard bits map to it, and an Exchange moves each
record to its owner before stateful work (reference:
src/engine/value.rs:38,94 `ShardPolicy`/SHARD_MASK,
src/engine/dataflow/operators.rs:128,432 Exchange pact,
src/engine/dataflow/config.rs:63-121 worker topology). Here the workers are
mesh shards: each stateful exec is split into n_shards sub-execs with
disjoint keyed state, and rows are routed by the low 16 bits of their group
key. Numeric rows travel through a real `lax.all_to_all` over ICI
(parallel/exchange.py); host-only payloads (strings/json) take the
equivalent host partition path (multi-host deployments would move these over
DCN — SURVEY §5.8).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import GroupByExec, JoinExec, NodeExec

# Minimum rows per batch before the device all-to-all path is worth the
# dispatch overhead; tests lower this to force the collective.
DEVICE_EXCHANGE_MIN_ROWS = 512

SHARD_MASK = 0xFFFF  # low 16 bits route the row (reference value.rs:38)


def shard_of(gks: np.ndarray, n_shards: int) -> np.ndarray:
    return ((gks.astype(np.uint64) & np.uint64(SHARD_MASK)) % np.uint64(
        n_shards
    )).astype(np.int32)


def _batch_numeric_columns(
    b: DiffBatch,
) -> list[tuple[np.ndarray, np.dtype]] | None:
    """(typed view, ORIGINAL dtype) of every value column, or None if any
    column holds non-numeric payloads (strings/json/tuples stay host-side).
    The original dtype lets the receiver restore the exact representation
    the host-partition path would have kept, so both paths feed identical
    columns downstream."""
    from pathway_tpu.parallel.exchange import packable

    out: list[tuple[np.ndarray, np.dtype]] = []
    for col in b.columns.values():
        orig = col.dtype
        arr = col
        if arr.dtype == object:
            if not len(arr):
                return None
            # type-homogeneous python scalars only: a mixed int/float
            # column would come back type-changed after the round trip
            # and hash to different group keys than the host path
            t0 = type(arr[0])
            if t0 not in (int, float, bool) or not all(
                type(v) is t0 for v in arr
            ):
                return None
            try:
                arr = np.asarray(arr.tolist())
            except (TypeError, ValueError, OverflowError):
                return None
        if arr.dtype.kind == "f" and arr.dtype.itemsize < 4:
            arr = arr.astype(np.float32)
        if arr.dtype.kind in "iu" and arr.dtype.itemsize < 8:
            arr = arr.astype(np.int64)
        if not packable(arr):
            return None
        out.append((arr, orig))
    return out


class _ShardRouter:
    """Shared routing logic: split each incoming batch into per-shard
    sub-batches, over the device mesh when rows are numeric."""

    def __init__(self, mesh: Any, axis: str):
        self.mesh = mesh
        self.axis = axis
        self.n_shards = mesh.shape[axis]
        self.device_exchanges = 0  # observability: collectives actually run

    def route(
        self, b: DiffBatch, dest: np.ndarray
    ) -> list[DiffBatch | None]:
        """Returns one sub-batch per shard (None where empty)."""
        numeric = (
            _batch_numeric_columns(b)
            if len(b) >= DEVICE_EXCHANGE_MIN_ROWS
            else None
        )
        if numeric is not None:
            return self._route_device(b, dest, numeric)
        return self._route_host(b, dest)

    def _route_host(self, b, dest):
        out: list[DiffBatch | None] = [None] * self.n_shards
        for s in range(self.n_shards):
            m = dest == s
            if m.any():
                out[s] = b.mask(m)
        return out

    def _route_device(self, b, dest, numeric_cols):
        from pathway_tpu.parallel.exchange import exchange_rows

        self.device_exchanges += 1
        arrays = [b.keys, b.diffs] + [a for a, _orig in numeric_cols]
        blocks = exchange_rows(arrays, dest, self.mesh, self.axis)
        names = b.column_names
        origs = [orig for _a, orig in numeric_cols]
        out: list[DiffBatch | None] = [None] * self.n_shards
        for s, cols in enumerate(blocks):
            if not len(cols[0]):
                continue
            columns = {
                # restore each column to its pre-exchange representation
                # (object columns back to native python scalars, typed
                # columns back to their original dtype) so sharded results
                # are identical to the host-partition and unsharded paths
                name: arr.astype(orig)
                for name, arr, orig in zip(names, cols[2:], origs)
            }
            out[s] = DiffBatch(cols[0], cols[1], columns)
        return out


class ShardedGroupByExec(NodeExec):
    """groupby-reduce with per-shard disjoint state: rows are exchanged to
    the shard owning their group key, each shard reduces independently
    (reference: group_by_table reindex-to-grouping-key + Exchange,
    src/engine/dataflow.rs:3404)."""

    def __init__(self, node, mesh: Any, axis: str = "data"):
        super().__init__(node)
        self.router = _ShardRouter(mesh, axis)
        self.shards = [GroupByExec(node) for _ in range(self.router.n_shards)]

    def _dests(self, b: DiffBatch) -> np.ndarray:
        ex = self.shards[0]
        simple = not self.node.set_id and ex.inst_idx is None
        if simple:
            gks = np.asarray(ex._group_keys_batch(b), dtype=np.uint64)
        else:
            cols = list(b.columns.values())
            gks = np.fromiter(
                (
                    ex._group_key(tuple(c[i] for c in cols))
                    & 0xFFFFFFFFFFFFFFFF
                    for i in range(len(b))
                ),
                dtype=np.uint64,
                count=len(b),
            )
        return shard_of(gks, self.router.n_shards)

    def process(self, t, inputs):
        parts: list[list[DiffBatch]] = [[] for _ in self.shards]
        for b in inputs[0]:
            if not len(b):
                continue
            for s, sub in enumerate(self.router.route(b, self._dests(b))):
                if sub is not None:
                    parts[s].append(sub)
        out: list[DiffBatch] = []
        for ex, sub_batches in zip(self.shards, parts):
            if sub_batches:
                out.extend(ex.process(t, [sub_batches]))
        return out

    def shard_group_keys(self) -> list[set[int]]:
        """Per-shard owned group keys — disjoint by construction (used by
        tests and the state snapshotter)."""
        return [set(ex.groups.keys()) for ex in self.shards]

    def state_dict(self) -> dict:
        # router holds the (unpicklable) mesh; shard states carry the data
        return {"shards": [ex.state_dict() for ex in self.shards]}

    def load_state(self, state: dict) -> None:
        for ex, st in zip(self.shards, state["shards"]):
            if st:
                ex.load_state(st)


class ShardedJoinExec(NodeExec):
    """Equijoin with per-shard disjoint state: both sides exchange on the
    join-key hash so matching rows co-locate (reference: join_tables
    arrange+join_core after Exchange, src/engine/dataflow.rs:2740,2834)."""

    def __init__(self, node, mesh: Any, axis: str = "data"):
        super().__init__(node)
        self.router = _ShardRouter(mesh, axis)
        self.shards = [JoinExec(node) for _ in range(self.router.n_shards)]

    def _dests(self, b: DiffBatch, on_cols: Sequence[str]) -> np.ndarray:
        from pathway_tpu.internals.api import ref_scalars_columns

        cols = [b.columns[c] for c in on_cols]
        jks = np.asarray(
            ref_scalars_columns(cols, len(b)), dtype=np.uint64
        )
        return shard_of(jks, self.router.n_shards)

    def process(self, t, inputs):
        lparts: list[list[DiffBatch]] = [[] for _ in self.shards]
        rparts: list[list[DiffBatch]] = [[] for _ in self.shards]
        for b in inputs[0]:
            if len(b):
                for s, sub in enumerate(
                    self.router.route(b, self._dests(b, self.node.left_on))
                ):
                    if sub is not None:
                        lparts[s].append(sub)
        for b in inputs[1]:
            if len(b):
                for s, sub in enumerate(
                    self.router.route(b, self._dests(b, self.node.right_on))
                ):
                    if sub is not None:
                        rparts[s].append(sub)
        out: list[DiffBatch] = []
        for ex, lsub, rsub in zip(self.shards, lparts, rparts):
            if lsub or rsub:
                out.extend(ex.process(t, [lsub, rsub]))
        return out

    def state_dict(self) -> dict:
        return {"shards": [ex.state_dict() for ex in self.shards]}

    def load_state(self, state: dict) -> None:
        for ex, st in zip(self.shards, state["shards"]):
            if st:
                ex.load_state(st)
