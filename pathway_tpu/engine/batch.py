"""Columnar diff batches — the unit of dataflow in the TPU microbatch engine.

TPU-native re-design of the reference's rowwise `Collection<S, (Key, Value)>`
streams (reference: src/engine/dataflow.rs:174-186 `Values`, :526 `Table`):
instead of boxed row tuples flowing through timely channels, each logical tick
moves a struct-of-arrays batch (uint64 key column + typed value columns +
int64 diff weights). Numeric columns are dense numpy arrays that map directly
onto device buffers; strings/json stay host-side object arrays.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator, Mapping, Sequence

import numpy as np

END_OF_TIME = 1 << 62


def _obj_column(values: Sequence[Any]) -> np.ndarray:
    """Object-dtype column via one C-level slice assignment (a per-row
    Python loop here was a hot spot of the engine ingest path). Falls back
    to the loop when numpy would broadcast the elements instead of storing
    them (equal-length tuples/ndarrays become a 2-D RHS and raise)."""
    out = np.empty(len(values), dtype=object)
    try:
        out[:] = values if isinstance(values, (list, tuple)) else list(values)
    except ValueError:
        for i, v in enumerate(values):
            out[i] = v
    return out


def make_column(values: Sequence[Any], np_dtype: Any = None) -> np.ndarray:
    """Build a column array; object dtype is element-safe for tuples/arrays."""
    if isinstance(values, np.ndarray) and np_dtype is None:
        return values
    if np_dtype is None or np.dtype(np_dtype) == np.dtype(object):
        return _obj_column(values)
    try:
        return np.asarray(values, dtype=np_dtype)
    except (ValueError, TypeError, OverflowError):
        return _obj_column(values)


class DiffBatch:
    """keys: uint64[n]; diffs: int64[n] (+1 insert / -1 retract);
    columns: name -> array[n]."""

    __slots__ = ("keys", "diffs", "columns")

    def __init__(
        self,
        keys: np.ndarray,
        diffs: np.ndarray,
        columns: Mapping[str, np.ndarray],
    ):
        self.keys = np.asarray(keys, dtype=np.uint64)
        self.diffs = np.asarray(diffs, dtype=np.int64)
        self.columns = dict(columns)

    # --- constructors ---------------------------------------------------------

    @staticmethod
    def empty(column_names: Iterable[str]) -> "DiffBatch":
        return DiffBatch(
            np.empty(0, dtype=np.uint64),
            np.empty(0, dtype=np.int64),
            {name: np.empty(0, dtype=object) for name in column_names},
        )

    @staticmethod
    def from_rows(
        rows: Sequence[tuple[int, int, tuple]],
        column_names: Sequence[str],
    ) -> "DiffBatch":
        """rows: (key, diff, values-tuple)"""
        n = len(rows)
        if n == 0:
            return DiffBatch.empty(column_names)
        # transpose once at C speed instead of a per-row/per-column loop
        keys_t, diffs_t, vals_t = zip(*rows)
        keys = np.fromiter(keys_t, dtype=np.uint64, count=n)
        diffs = np.fromiter(diffs_t, dtype=np.int64, count=n)
        if column_names:
            cols = [_obj_column(col) for col in zip(*vals_t)]
        else:
            cols = []
        return DiffBatch(keys, diffs, dict(zip(column_names, cols)))

    # --- basics ---------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.keys)

    @property
    def column_names(self) -> list[str]:
        return list(self.columns.keys())

    def row_values(self, i: int) -> tuple:
        return tuple(col[i] for col in self.columns.values())

    def iter_rows(self) -> Iterator[tuple[int, int, tuple]]:
        # one C-level transpose instead of per-row generator expressions;
        # numeric columns yield Python scalars (tolist), matching what the
        # batch hashers serialize
        n = len(self.keys)
        cols = [c.tolist() for c in self.columns.values()]
        vals: Iterable[tuple] = zip(*cols) if cols else ((),) * n
        return zip(self.keys.tolist(), self.diffs.tolist(), vals)

    def mask(self, m: np.ndarray) -> "DiffBatch":
        return DiffBatch(
            self.keys[m],
            self.diffs[m],
            {name: col[m] for name, col in self.columns.items()},
        )

    def take(self, idx: np.ndarray) -> "DiffBatch":
        return DiffBatch(
            self.keys[idx],
            self.diffs[idx],
            {name: col[idx] for name, col in self.columns.items()},
        )

    def with_columns(self, columns: Mapping[str, np.ndarray]) -> "DiffBatch":
        return DiffBatch(self.keys, self.diffs, columns)

    def rename(self, mapping: Mapping[str, str]) -> "DiffBatch":
        return DiffBatch(
            self.keys,
            self.diffs,
            {mapping.get(name, name): col for name, col in self.columns.items()},
        )

    def select_columns(self, names: Sequence[str]) -> "DiffBatch":
        return DiffBatch(self.keys, self.diffs, {n: self.columns[n] for n in names})

    @staticmethod
    def concat(batches: Sequence["DiffBatch"]) -> "DiffBatch":
        batches = [b for b in batches if len(b)]
        if not batches:
            return DiffBatch.empty([])
        if len(batches) == 1:
            return batches[0]
        names = batches[0].column_names
        return DiffBatch(
            np.concatenate([b.keys for b in batches]),
            np.concatenate([b.diffs for b in batches]),
            {
                n: concat_columns([b.columns[n] for b in batches])
                for n in names
            },
        )

    def consolidate(self) -> "DiffBatch":
        """Merge rows with equal (key, values), summing diffs; drop zeros.
        (reference analog: differential `consolidate`)."""
        if len(self) <= 1:
            if len(self) == 1 and self.diffs[0] == 0:
                return self.mask(np.zeros(1, dtype=bool))
            return self
        from pathway_tpu.internals.native import get_native

        nat = get_native()
        cols = list(self.columns.values())
        if nat is not None:
            # native path: group by (key, 64-bit value hash) — the value
            # hash stands in for full value equality within one batch.
            # Numeric columns go through tolist() so the C serializer sees
            # exact PyLong/PyFloat (np scalars would bounce back to python)
            hash_cols = tuple(
                c.tolist() if c.dtype != object else c for c in cols
            )
            vhashes = nat.hash_columns(hash_cols, len(self))
            idx_b, diff_b = nat.consolidate(
                np.ascontiguousarray(self.keys).tobytes(),
                vhashes,
                np.ascontiguousarray(self.diffs).tobytes(),
            )
            idx = np.frombuffer(idx_b, dtype=np.int64)
            out = self.take(idx)
            out.diffs = np.frombuffer(diff_b, dtype=np.int64).copy()
            return out
        # pure-python fallback: same grouping rule as the native kernel —
        # (key, serialized value bytes) — so results do not depend on
        # whether the .so built
        from pathway_tpu.internals.api import _value_bytes

        acc: dict[tuple[int, bytes], list] = {}
        order: list[tuple[int, bytes]] = []
        for i in range(len(self.keys)):
            gk = (int(self.keys[i]), _value_bytes(tuple(c[i] for c in cols)))
            entry = acc.get(gk)
            if entry is None:
                acc[gk] = [int(self.diffs[i]), i]
                order.append(gk)
            else:
                entry[0] += int(self.diffs[i])
        keep = [acc[gk][1] for gk in order if acc[gk][0] != 0]
        diffs_new = [acc[gk][0] for gk in order if acc[gk][0] != 0]
        idx = np.asarray(keep, dtype=np.int64)
        out = self.take(idx)
        out.diffs = np.asarray(diffs_new, dtype=np.int64)
        return out


def uniform_element_spec(
    col: np.ndarray,
) -> tuple[np.dtype, tuple[int, ...]] | None:
    """Column introspection for the wire codec: if every element of an
    object column is an ndarray of one dtype and shape (embedding rows,
    tuple-packed vectors), return ``(dtype, shape)`` so the codec can
    ship them as a single stacked raw block instead of a pickle.
    ``None`` means the column is not uniform (mixed types, ragged
    arrays, or empty — an empty column has no element to describe)."""
    n = len(col)
    if n == 0:
        return None
    first = col[0]
    if not isinstance(first, np.ndarray) or first.dtype == object:
        return None
    dtype, shape = first.dtype, first.shape
    for i in range(1, n):
        el = col[i]
        if (
            not isinstance(el, np.ndarray)
            or el.dtype != dtype
            or el.shape != shape
        ):
            return None
    return dtype, shape


def concat_columns(parts: Sequence[np.ndarray]) -> np.ndarray:
    """Dtype-preserving column concat: same-dtype parts concatenate
    directly; mixed dtypes go through object arrays so values are never
    silently promoted (an int64 batch concatenated with a float64 one
    used to floatify the ints mid-tick; arrangement state outlives the
    tick and shares this helper)."""
    parts = [p for p in parts if len(p)]
    if not parts:
        return np.empty(0, dtype=object)
    if len(parts) == 1:
        return parts[0]
    d0 = parts[0].dtype
    if all(p.dtype == d0 for p in parts[1:]):
        return np.concatenate(parts)
    return np.concatenate([p.astype(object) for p in parts])


def _values_eq(a: tuple, b: tuple) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            if not (
                isinstance(x, np.ndarray)
                and isinstance(y, np.ndarray)
                and x.shape == y.shape
                and bool(np.all(x == y))
            ):
                return False
        else:
            try:
                if not (x == y or (x is None and y is None)):
                    return False
            except (ValueError, TypeError):
                if x is not y:
                    return False
    return True


class TableState:
    """Materialized current state of a stream: key -> row values tuple.

    The engine analog of a differential arrangement
    (reference: external/differential-dataflow arrangements) reduced to the
    totally-ordered microbatch setting: state is only ever the *current*
    consolidated frontier."""

    __slots__ = ("column_names", "rows")

    def __init__(self, column_names: Sequence[str]):
        self.column_names = list(column_names)
        self.rows: dict[int, tuple] = {}

    def apply(self, batch: DiffBatch) -> None:
        for k, d, vals in batch.iter_rows():
            if d > 0:
                self.rows[k] = vals
            elif d < 0:
                self.rows.pop(k, None)

    def snapshot_batch(self) -> DiffBatch:
        rows = [(k, 1, v) for k, v in self.rows.items()]
        return DiffBatch.from_rows(rows, self.column_names)

    def get(self, key: int) -> tuple | None:
        return self.rows.get(key)

    def __len__(self) -> int:
        return len(self.rows)


class MultisetState:
    """key -> (values, count) — supports multiplicity >1 (after non-injective
    reindex) and clean retraction."""

    __slots__ = ("column_names", "rows")

    def __init__(self, column_names: Sequence[str]):
        self.column_names = list(column_names)
        self.rows: dict[int, list] = {}  # key -> [values, count]

    def apply_row(self, k: int, d: int, vals: tuple) -> None:
        entry = self.rows.get(k)
        if entry is None:
            if d != 0:
                self.rows[k] = [vals, d]
        else:
            entry[1] += d
            entry[0] = vals if d > 0 else entry[0]
            if entry[1] == 0:
                del self.rows[k]

    def apply(self, batch: DiffBatch) -> None:
        for k, d, vals in batch.iter_rows():
            self.apply_row(k, d, vals)

    def get(self, key: int) -> tuple | None:
        e = self.rows.get(key)
        return e[0] if e else None

    def __len__(self) -> int:
        return len(self.rows)
