"""External index node: streams data-side updates into an index object and
answers query-side rows with top-k matches.

Reference: use_external_index_as_of_now (src/engine/dataflow.rs:2694) +
operators/external_index.rs — there, queries broadcast to all workers and each
worker searches its shard. Here the index lives on-device (one jitted top-k
over the whole corpus, sharded over the mesh when configured), so the
broadcast/merge happens inside XLA over ICI instead of timely channels.
"""

from __future__ import annotations

from typing import Any, Protocol, Sequence

import numpy as np

from pathway_tpu.engine.batch import END_OF_TIME, DiffBatch
from pathway_tpu.engine.nodes import Node, NodeExec, _concat_inputs
from pathway_tpu.internals.api import Pointer
from pathway_tpu.internals.errors import record_error


class IndexImpl(Protocol):
    """Host-side index protocol (device work happens inside search)."""

    def upsert(self, key: int, data: Any, metadata: Any) -> None: ...

    def remove(self, key: int) -> None: ...

    def search(
        self, queries: Sequence[tuple[Any, int, Any]]
    ) -> list[tuple[tuple[int, float], ...]]:
        """queries: (data, k, filter) triples → per query a tuple of
        (row_key, score) sorted best-first."""
        ...


class ExternalIndexNode(Node):
    """inputs: [data_node(cols: _data, _meta), query_node(cols: _q, _k, _filter)]
    output: query universe, column _pw_index_reply (tuple of (ptr, score))."""

    REPLY = "_pw_index_reply"

    def __init__(
        self,
        data_node: Node,
        query_node: Node,
        index_factory: Any,
        as_of_now: bool = True,
    ):
        super().__init__([data_node, query_node], [self.REPLY])
        self.index_factory = index_factory
        self.as_of_now = as_of_now

    def _make_local_exec(self):
        return ExternalIndexExec(self)

    def make_exec(self):
        if getattr(self, "_dcn", False):
            from pathway_tpu.engine.dcn import DcnExternalIndexExec

            return DcnExternalIndexExec(self)
        return self._make_local_exec()


class ExternalIndexExec(NodeExec):
    def __init__(self, node: ExternalIndexNode):
        super().__init__(node)
        self.index: IndexImpl = node.index_factory()
        # Flight Recorder: end-to-end KNN serving latency (host rows in →
        # device top-k → host replies), the BASELINE.md "KNN p50" metric,
        # labeled by index implementation. Prebound once per exec.
        from pathway_tpu.observability import REGISTRY

        index_label = type(self.index).__name__
        self._m_query_seconds = REGISTRY.histogram(
            "pathway_knn_query_seconds",
            "index search batch latency (all queries of one tick batch)",
            labelnames=("index",),
        ).labels(index_label)
        self._m_queries = REGISTRY.counter(
            "pathway_knn_queries_total",
            "queries answered, by index implementation",
            labelnames=("index",),
        ).labels(index_label)
        self._m_updates = REGISTRY.counter(
            "pathway_knn_index_updates_total",
            "upserts/removals applied to the index corpus",
            labelnames=("index",),
        ).labels(index_label)
        from pathway_tpu.serving import metrics as serving_metrics

        self._m_expired = serving_metrics.expired_counter().labels("knn")
        dcols = node.inputs[0].column_names
        qcols = node.inputs[1].column_names
        self.d_data = dcols.index("_data")
        self.d_meta = dcols.index("_meta") if "_meta" in dcols else None
        self.q_data = qcols.index("_q")
        self.q_k = qcols.index("_k") if "_k" in qcols else None
        self.q_filter = qcols.index("_filter") if "_filter" in qcols else None
        # live queries (for full `query` mode re-answers) / emitted replies
        self.live_queries: dict[int, tuple] = {}
        self.emitted: dict[int, tuple] = {}
        # Phoenix degradation: this exec's corpus is the "last hydrated
        # index snapshot" degraded serving answers from — register it
        # (weakly) and keep the staleness clock fresh per tick
        from pathway_tpu.serving import degrade as _degrade

        self._degrade = _degrade
        _degrade.register_index_reader(self)
        # Replica Shield: when this process is the replication WRITER
        # (PATHWAY_REPL_PORT set), every tick's consolidated corpus
        # deltas stream to the read replicas (parallel/replicate.py);
        # the resolved None costs one attribute check per tick otherwise
        from pathway_tpu.parallel import replicate as _replicate

        self._repl = _replicate.publisher()

    def state_dict(self) -> dict:
        # indexes holding device arrays expose their own host-side snapshot;
        # pure-python indexes (BM25) pickle wholesale
        if hasattr(self.index, "state_dict"):
            index_state = ("dict", self.index.state_dict())
        else:
            index_state = ("pickle", self.index)
        return {
            "live_queries": self.live_queries,
            "emitted": self.emitted,
            "index_state": index_state,
        }

    def load_state(self, state: dict) -> None:
        self.live_queries = dict(state["live_queries"])
        self.emitted = dict(state["emitted"])
        kind, payload = state["index_state"]
        if kind == "dict":
            self.index.load_state(payload)
        else:
            self.index = payload

    def _answer(self, items: list[tuple[int, tuple]]) -> dict[int, tuple]:
        """items: (query_key, qvals) → reply tuples."""
        triples = []
        for _k, vals in items:
            q = vals[self.q_data]
            k = int(vals[self.q_k]) if self.q_k is not None else 3
            flt = vals[self.q_filter] if self.q_filter is not None else None
            triples.append((q, k, flt))
        import time as _time

        from pathway_tpu.observability.tracing import get_tracer

        # Trace Weaver: the device top-k child span — with the embed and
        # HTTP spans this completes the per-request serving breakdown
        with get_tracer().span(
            "knn.search",
            index=type(self.index).__name__,
            queries=len(triples),
        ) as sp:
            t0 = _time.perf_counter()
            try:
                results = self.index.search(triples)
            except Exception as exc:
                record_error(exc, str(self.node))
                results = [() for _ in triples]
        self._m_query_seconds.observe(
            _time.perf_counter() - t0, exemplar=sp.trace_id
        )
        self._m_queries.inc(len(triples))
        out = {}
        for (qk, _vals), matches in zip(items, results):
            out[qk] = tuple(
                (Pointer(mk), float(score)) for mk, score in matches
            )
        return out

    def process(self, t, inputs):
        node = self.node
        data_changed = False
        # corpus mutation races a concurrent degraded-mode stale search
        # (replay ticks rebuild state while the REST handler reads it):
        # the shared guard serializes them. Uncontended cost is one
        # RLock acquire per tick.
        repl_rows: list[tuple[int, int, tuple]] = []
        with self._degrade.index_guard:
            for b in inputs[0]:
                for k, d, vals in b.iter_rows():
                    data_changed = True
                    self._m_updates.inc()
                    if d > 0:
                        meta = (
                            vals[self.d_meta]
                            if self.d_meta is not None
                            else None
                        )
                        try:
                            self.index.upsert(k, vals[self.d_data], meta)
                        except Exception as exc:
                            record_error(exc, str(node))
                            continue  # a row the writer's index rejected
                            # must not reach the replicas either
                        if self._repl is not None:
                            repl_rows.append((k, 1, (vals[self.d_data], meta)))
                    else:
                        self.index.remove(k)
                        if self._repl is not None:
                            repl_rows.append((k, -1, (None, None)))
        # the engine is ticking this node: whatever the corpus now holds
        # is as fresh as the stream — restart the staleness clock
        self._degrade.mark_fresh()
        if self._repl is not None and t < END_OF_TIME:
            # consolidated per-tick deltas to the read replicas; idle
            # ticks publish an empty marker so replica freshness tracks
            # the writer's tick cadence, not just corpus churn
            from pathway_tpu.parallel.replicate import consolidate_rows

            batches = []
            if repl_rows:
                batches.append(
                    DiffBatch.from_rows(
                        consolidate_rows(repl_rows), ("_data", "_meta")
                    )
                )
            self._repl.publish(t, batches)
        # Surge Gate deadline propagation: queries whose REST deadline
        # already expired answer empty WITHOUT a device search — the
        # client got its 504, so the top-k would burn a batch slot for a
        # response nobody reads (the empty reply keeps the output
        # universe aligned for downstream row-wise stages).
        from pathway_tpu.serving import deadline as _deadline

        to_answer: list[tuple[int, tuple]] = []
        expired_keys: list[int] = []
        retracted: list[int] = []
        for b in inputs[1]:
            for k, d, vals in b.iter_rows():
                if d > 0:
                    if _deadline.expired(k):
                        self._m_expired.inc()
                        expired_keys.append(k)
                        continue
                    if not node.as_of_now:
                        self.live_queries[k] = vals
                    to_answer.append((k, vals))
                else:
                    self.live_queries.pop(k, None)
                    retracted.append(k)
        if not node.as_of_now and data_changed:
            # re-answer every live query against the new index state
            answered_keys = {k for k, _ in to_answer}
            for k, vals in self.live_queries.items():
                if k not in answered_keys:
                    to_answer.append((k, vals))
        out_rows: list[tuple[int, int, tuple]] = []
        for k in retracted:
            old = self.emitted.pop(k, None)
            if old is not None:
                out_rows.append((k, -1, old))
        replies: dict[int, tuple] = {k: () for k in expired_keys}
        if to_answer:
            replies.update(self._answer(to_answer))
        if replies:
            for k, reply in replies.items():
                new = (reply,)
                old = self.emitted.get(k)
                if old == new:
                    continue
                if old is not None:
                    out_rows.append((k, -1, old))
                out_rows.append((k, 1, new))
                self.emitted[k] = new
        if not out_rows:
            return []
        return [DiffBatch.from_rows(out_rows, self.node.column_names)]
