"""Cross-process exec wrappers: keyed state spanning engine processes.

Stateful operators exchange rows to the process owning their key before
doing stateful work, exactly like the reference's Exchange pact over
timely's TCP mesh (reference: src/engine/dataflow/operators.rs:128,432;
external/timely-dataflow/communication/src/networking.rs:16-33). Rows are
routed by the low shard bits of the group/join key hash
(src/engine/value.rs:38 SHARD_MASK), so each process's inner exec holds a
disjoint key range; within a process the inner exec may further shard
over the device mesh (engine/sharded.py). Every process calls process()
for every node at every lockstep tick (runtime.py), so each (channel,
tick, src->dst) pair carries exactly one message — possibly an empty
partition — and gather() knows exactly how many to wait for.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import NodeExec
from pathway_tpu.engine.sharded import shard_of


class _DcnRouter:
    """Partition batches by owning process and swap partitions over the
    host mesh; merge arrivals in process-id order (deterministic)."""

    def __init__(self, channel: str):
        from pathway_tpu.parallel.host_exchange import get_host_mesh

        self.mesh = get_host_mesh()
        self.channel = channel
        self.n = self.mesh.n
        self.pid = self.mesh.pid
        self.exchanges = 0  # observability, mirrors _ShardRouter counter

    def partition(
        self, batches: Sequence[DiffBatch], dests_fn
    ) -> list[list[DiffBatch]]:
        parts: list[list[DiffBatch]] = [[] for _ in range(self.n)]
        for b in batches:
            if not len(b):
                continue
            dest = dests_fn(b)
            for p in range(self.n):
                m = dest == p
                if m.any():
                    parts[p].append(b if m.all() else b.mask(m))
        return parts

    def exchange(
        self, t: int, parts: list[list[DiffBatch]]
    ) -> list[DiffBatch]:
        self.exchanges += 1
        for p in range(self.n):
            if p != self.pid:
                self.mesh.send(p, self.channel, t, parts[p])
        got = self.mesh.gather(self.channel, t)
        merged = list(parts[self.pid])
        for src in sorted(got):
            merged.extend(got[src])
        return merged


class DcnGroupByExec(NodeExec):
    """groupby-reduce whose keyed state spans processes: rows go to the
    process owning their group key; the local exec (possibly device-mesh
    sharded) reduces its disjoint range (reference: group_by_table after
    Exchange, src/engine/dataflow.rs:3404)."""

    def __init__(self, node):
        super().__init__(node)
        self.inner = node._make_local_exec()
        self.router = _DcnRouter(f"gb{node.id}")
        # ticks at or below this time are already covered by restored
        # state: drop them AFTER the exchange (the exchange itself must
        # still run so channel/tick pairing stays aligned group-wide) —
        # the receiver-side half of the reference's "all workers flushed
        # up to T" consensus (src/persistence/state.rs:291)
        self.replay_floor = -1
        # stateless probe for group-key derivation (no rows ever applied)
        self._probe = (
            self.inner.shards[0]
            if hasattr(self.inner, "shards")
            else self.inner
        )

    def _gks(self, b: DiffBatch) -> np.ndarray:
        probe = self._probe
        simple = not self.node.set_id and probe.inst_idx is None
        if simple:
            return np.asarray(probe._group_keys_batch(b), dtype=np.uint64)
        cols = list(b.columns.values())
        return np.fromiter(
            (
                probe._group_key(tuple(c[i] for c in cols))
                & 0xFFFFFFFFFFFFFFFF
                for i in range(len(b))
            ),
            dtype=np.uint64,
            count=len(b),
        )

    def _dests(self, b: DiffBatch) -> np.ndarray:
        return shard_of(self._gks(b), self.router.n)

    def process(self, t, inputs):
        parts = self.router.partition(inputs[0], self._dests)
        local = self.router.exchange(t, parts)
        if t <= self.replay_floor:
            return []  # restored state already covers this tick
        return self.inner.process(t, [local])

    def owned_group_keys(self) -> set[int]:
        if hasattr(self.inner, "shard_group_keys"):
            return set().union(*self.inner.shard_group_keys())
        return set(self.inner.groups.keys())

    def on_end(self):
        return self.inner.on_end()

    def state_dict(self):
        return {"inner": self.inner.state_dict()}

    def load_state(self, state):
        if state.get("inner"):
            self.inner.load_state(state["inner"])


class DcnJoinExec(NodeExec):
    """Equijoin whose build/probe state spans processes: both sides route
    by join-key hash so matches co-locate (reference: join_tables
    arrange+join_core after Exchange, src/engine/dataflow.rs:2740)."""

    def __init__(self, node):
        super().__init__(node)
        self.inner = node._make_local_exec()
        self.lrouter = _DcnRouter(f"jl{node.id}")
        self.rrouter = _DcnRouter(f"jr{node.id}")
        self.replay_floor = -1  # see DcnGroupByExec.replay_floor
        lcols = node.inputs[0].column_names
        rcols = node.inputs[1].column_names
        self._l_on = [lcols.index(c) for c in node.left_on]
        self._r_on = [rcols.index(c) for c in node.right_on]
        # probe JoinExec for join-key derivation: the routing hash MUST be
        # the exact _batch_jks contract the inner exec groups by, or DCN
        # routing silently diverges from local state
        self._probe = (
            self.inner.shards[0]
            if hasattr(self.inner, "shards")
            else self.inner
        )

    def _dests(self, b: DiffBatch, on_idx: list[int]) -> np.ndarray:
        jks = np.asarray(
            self._probe._batch_jks(b, on_idx), dtype=np.uint64
        )
        return shard_of(jks, self.lrouter.n)

    def process(self, t, inputs):
        lparts = self.lrouter.partition(
            inputs[0], lambda b: self._dests(b, self._l_on)
        )
        rparts = self.rrouter.partition(
            inputs[1], lambda b: self._dests(b, self._r_on)
        )
        local_l = self.lrouter.exchange(t, lparts)
        local_r = self.rrouter.exchange(t, rparts)
        if t <= self.replay_floor:
            return []  # restored state already covers this tick
        return self.inner.process(t, [local_l, local_r])

    def on_end(self):
        return self.inner.on_end()

    def state_dict(self):
        return {"inner": self.inner.state_dict()}

    def load_state(self, state):
        if state.get("inner"):
            self.inner.load_state(state["inner"])
