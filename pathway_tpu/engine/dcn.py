"""Cross-process exec wrappers: keyed state spanning engine processes.

Stateful operators exchange rows to the process owning their key before
doing stateful work, exactly like the reference's Exchange pact over
timely's TCP mesh (reference: src/engine/dataflow/operators.rs:128,432;
external/timely-dataflow/communication/src/networking.rs:16-33). Rows are
routed by the low shard bits of the group/join key hash
(src/engine/value.rs:38 SHARD_MASK), so each process's inner exec holds a
disjoint key range; within a process the inner exec may further shard
over the device mesh (engine/sharded.py). Every process calls process()
for every node at every lockstep tick (runtime.py), so each (channel,
tick, src->dst) pair carries exactly one message — possibly an empty
partition — and gather() knows exactly how many to wait for.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import NodeExec
from pathway_tpu.engine.sharded import shard_of


class _DcnRouter:
    """Partition batches by owning process and swap partitions over the
    host mesh; merge arrivals in process-id order (deterministic)."""

    def __init__(self, channel: str):
        from pathway_tpu.observability.tracing import get_tracer
        from pathway_tpu.parallel.host_exchange import get_host_mesh

        self.mesh = get_host_mesh()
        self.channel = channel
        self.n = self.mesh.n
        self.pid = self.mesh.pid
        self.exchanges = 0  # observability, mirrors _ShardRouter counter
        self._tracer = get_tracer()

    def partition(
        self, batches: Sequence[DiffBatch], dests_fn
    ) -> list[list[DiffBatch]]:
        """Split each batch by destination process with ONE stable
        argsort + segment-bound search per batch, instead of n_procs
        boolean mask + ``b.mask(m)`` passes. The stable sort keeps the
        original row order inside every partition, so receivers apply
        rows in the same order the old masking produced."""
        parts: list[list[DiffBatch]] = [[] for _ in range(self.n)]
        for b in batches:
            if not len(b):
                continue
            dest = np.asarray(dests_fn(b))
            first = int(dest[0])
            if bool((dest == first).all()):
                # the overwhelmingly common case once upstream data is
                # already sharded: the whole batch has one owner
                parts[first].append(b)
                continue
            order = np.argsort(dest, kind="stable")
            bounds = np.searchsorted(
                dest[order], np.arange(self.n + 1)
            )
            for p in range(self.n):
                lo, hi = bounds[p], bounds[p + 1]
                if hi > lo:
                    parts[p].append(b.take(order[lo:hi]))
        return parts

    def _all_to_all(self, span_name: str, t: int, payload_for) -> dict:
        """Traced send-to-all + gather: the wire hop — frames carry this
        span's traceparent (host_exchange stamps every frame); the lowest
        received remote traceparent is attached so a cross-process trace
        is inspectable from either side."""
        with self._tracer.span(
            span_name, channel=self.channel, tick=t
        ) as sp:
            for p in range(self.n):
                if p != self.pid:
                    self.mesh.send(p, self.channel, t, payload_for(p))
            got = self.mesh.gather(self.channel, t)
            remote = self.mesh.take_gather_tps(self.channel, t)
            if remote:
                sp.set_attribute(
                    "remote_traceparent", remote[min(remote)]
                )
        return got

    def exchange_keep_src(
        self, t: int, parts: list[list[DiffBatch]]
    ) -> list[tuple[int, list[DiffBatch]]]:
        """Swap partitions; result is (src, batches) in GLOBAL pid order —
        every process then applies one tick's rows in the identical order,
        so order-sensitive state (last-write-wins triplets, acceptors)
        agrees group-wide. The src tags let ops route results back home."""
        self.exchanges += 1
        got = self._all_to_all("dcn.exchange", t, lambda p: parts[p])
        return [
            (p, parts[p] if p == self.pid else got.get(p, []))
            for p in range(self.n)
        ]

    def exchange(
        self, t: int, parts: list[list[DiffBatch]]
    ) -> list[DiffBatch]:
        return [
            b for _src, bs in self.exchange_keep_src(t, parts) for b in bs
        ]

    def exchange_scalar(self, t: int, value: Any) -> list[Any]:
        """All-gather one picklable value per process (pid order)."""
        self.exchanges += 1
        got = self._all_to_all("dcn.exchange_scalar", t, lambda p: value)
        got[self.pid] = value
        return [got[p] for p in sorted(got)]


DCN_INNER_KEY = "__dcn_inner__"  # wrapper residual nesting contract —
DCN_EXTRA_KEY = "__dcn_extra__"  # shared with the elastic resharder
# (elastic/mesh.py), which must peel and re-wrap these exact keys when
# it re-partitions a rank's arranged blob for a new topology


class _InnerArrangedMixin:
    """Delegates the incremental-snapshot protocol (PR-7 State Ledger)
    to the wrapped inner exec, so DCN-wrapped operators get
    arrangement-backed segment snapshots instead of pickling their inner
    state monolithically through the wrapper's ``state_dict``.  The
    wrapper's own cross-process bookkeeping (e.g. the origin tracker)
    rides in the residual under reserved keys; the arrangements pass
    through untouched, keeping segment identity (and so bytes ∝ churn)
    stable across the wrapper boundary."""

    def _wrapper_residual(self) -> dict:
        return {}

    def _load_wrapper_residual(self, extra: dict) -> None:
        pass

    def enable_state_ledger(self) -> None:
        """The persistence driver arms ledger-keeping execs before any
        tick runs; forward the arming through the wrapper so a
        DCN-wrapped GroupBy keeps its ledger too."""
        hook = getattr(self.inner, "enable_state_ledger", None)
        if hook is not None:
            hook()

    def arranged_state(self):
        inner_fn = getattr(self.inner, "arranged_state", None)
        arranged = inner_fn() if inner_fn is not None else None
        if arranged is None:
            return None  # inner snapshots monolithically (state_dict)
        residual, arrs = arranged
        return (
            {
                DCN_INNER_KEY: residual,
                DCN_EXTRA_KEY: self._wrapper_residual(),
            },
            arrs,
        )

    def check_arranged_state(self, residual, arrangements) -> bool:
        """Pre-mutation restore validation passes through the wrapper
        to the inner exec (e.g. a sharded inner validating its shard
        count against the snapshot's)."""
        check = getattr(self.inner, "check_arranged_state", None)
        if check is None:
            return True
        return check(
            residual.get(DCN_INNER_KEY, residual), arrangements
        )

    def load_arranged_state(self, residual, arrangements) -> None:
        if DCN_INNER_KEY in residual:
            self._load_wrapper_residual(residual.get(DCN_EXTRA_KEY, {}))
            self.inner.load_arranged_state(
                residual[DCN_INNER_KEY], arrangements
            )
        else:
            # a snapshot written single-process then restored under DCN
            # cannot occur (the group restores its own per-process
            # stores), but a bare-residual blob still belongs to the
            # inner exec — never to the wrapper
            self.inner.load_arranged_state(residual, arrangements)


class DcnGroupByExec(_InnerArrangedMixin, NodeExec):
    """groupby-reduce whose keyed state spans processes: rows go to the
    process owning their group key; the local exec (possibly device-mesh
    sharded) reduces its disjoint range (reference: group_by_table after
    Exchange, src/engine/dataflow.rs:3404)."""

    def __init__(self, node):
        super().__init__(node)
        self.inner = node._make_local_exec()
        self.router = _DcnRouter(f"gb{node.id}")
        # ticks at or below this time are already covered by restored
        # state: drop them AFTER the exchange (the exchange itself must
        # still run so channel/tick pairing stays aligned group-wide) —
        # the receiver-side half of the reference's "all workers flushed
        # up to T" consensus (src/persistence/state.rs:291)
        self.replay_floor = -1
        # stateless probe for group-key derivation (no rows ever applied)
        self._probe = (
            self.inner.shards[0]
            if hasattr(self.inner, "shards")
            else self.inner
        )

    def _gks(self, b: DiffBatch) -> np.ndarray:
        probe = self._probe
        simple = not self.node.set_id and probe.inst_idx is None
        if simple:
            return np.asarray(probe._group_keys_batch(b), dtype=np.uint64)
        cols = list(b.columns.values())
        return np.fromiter(
            (
                probe._group_key(tuple(c[i] for c in cols))
                & 0xFFFFFFFFFFFFFFFF
                for i in range(len(b))
            ),
            dtype=np.uint64,
            count=len(b),
        )

    def _dests(self, b: DiffBatch) -> np.ndarray:
        return shard_of(self._gks(b), self.router.n)

    def process(self, t, inputs):
        parts = self.router.partition(inputs[0], self._dests)
        local = self.router.exchange(t, parts)
        if t <= self.replay_floor:
            return []  # restored state already covers this tick
        return self.inner.process(t, [local])

    def owned_group_keys(self) -> set[int]:
        if hasattr(self.inner, "shard_group_keys"):
            return set().union(*self.inner.shard_group_keys())
        return set(self.inner.groups.keys())

    def on_end(self):
        return self.inner.on_end()

    def state_dict(self):
        return {"inner": self.inner.state_dict()}

    def load_state(self, state):
        if state.get("inner"):
            self.inner.load_state(state["inner"])


class DcnJoinExec(_InnerArrangedMixin, NodeExec):
    """Equijoin whose build/probe state spans processes: both sides route
    by join-key hash so matches co-locate (reference: join_tables
    arrange+join_core after Exchange, src/engine/dataflow.rs:2740)."""

    def __init__(self, node):
        super().__init__(node)
        self.inner = node._make_local_exec()
        self.lrouter = _DcnRouter(f"jl{node.id}")
        self.rrouter = _DcnRouter(f"jr{node.id}")
        self.replay_floor = -1  # see DcnGroupByExec.replay_floor
        lcols = node.inputs[0].column_names
        rcols = node.inputs[1].column_names
        self._l_on = [lcols.index(c) for c in node.left_on]
        self._r_on = [rcols.index(c) for c in node.right_on]
        # probe JoinExec for join-key derivation: the routing hash MUST be
        # the exact _batch_jks contract the inner exec groups by, or DCN
        # routing silently diverges from local state
        self._probe = (
            self.inner.shards[0]
            if hasattr(self.inner, "shards")
            else self.inner
        )

    def _dests(self, b: DiffBatch, on_idx: list[int]) -> np.ndarray:
        jks = np.asarray(
            self._probe._batch_jks(b, on_idx), dtype=np.uint64
        )
        return shard_of(jks, self.lrouter.n)

    def process(self, t, inputs):
        lparts = self.lrouter.partition(
            inputs[0], lambda b: self._dests(b, self._l_on)
        )
        rparts = self.rrouter.partition(
            inputs[1], lambda b: self._dests(b, self._r_on)
        )
        local_l = self.lrouter.exchange(t, lparts)
        local_r = self.rrouter.exchange(t, rparts)
        if t <= self.replay_floor:
            return []  # restored state already covers this tick
        return self.inner.process(t, [local_l, local_r])

    def on_end(self):
        return self.inner.on_end()

    def state_dict(self):
        return {"inner": self.inner.state_dict()}

    def load_state(self, state):
        if state.get("inner"):
            self.inner.load_state(state["inner"])


# ---------------------------------------------------------------------------
# Generic stateful exchange (VERDICT r4 item 2): every remaining stateful
# operator type gets a cross-process wrapper, mirroring the reference's
# universal Exchange pact (external/timely-dataflow/timely/src/dataflow/
# channels/pact.rs:56-59; src/engine/dataflow/operators.rs:415 Reshard).
# Routing disciplines:
#   "key"   — partition rows by an operator-specific key hash; the inner
#             exec owns a disjoint key range (groupby/join discipline)
#   "bcast" — replicate this input on every process (small side inputs:
#             gradual_broadcast thresholds, external-index corpus)
#   "p0"    — centralize this input on process 0 (inherently global state:
#             instance-less sort, iterate fixpoints)
#   "local" — no exchange (rows already live where their state lives)
#
# Placement contract: an op whose output universe is FRESH (dedup, iterate,
# update_rows — new keys or a new key set) may leave results on the process
# that computed them; union across processes is the result. An op whose
# output universe is an INPUT's universe (ix, set-ops, sort, buffer,
# gradual_broadcast, external_index) must emit each row on the process
# where that input row lives, or downstream aligned row-wise execs would
# see half a row — so those ops either keep the universe-owning side local
# (replicating the other side) or exchange results back to their origin.

_U64 = 0xFFFFFFFFFFFFFFFF


class _DcnStatefulExec(_InnerArrangedMixin, NodeExec):
    """Shared plumbing: build the node's local exec, route each input per
    its spec, feed the merged partitions through. Output rows are emitted
    on the process owning their key — per-process outputs union to the
    single-process result, the same contract as DcnGroupByExec."""

    def __init__(self, node, specs, tag: str):
        super().__init__(node)
        self.inner = node._make_local_exec()
        self.replay_floor = -1  # see DcnGroupByExec.replay_floor
        if getattr(self.inner, "persist_standalone", False):
            self.persist_standalone = True
        self.specs = list(specs)
        self.routers = [
            None if s == "local" else _DcnRouter(f"{tag}{i}n{node.id}")
            for i, s in enumerate(self.specs)
        ]
        self.n = next((r.n for r in self.routers if r is not None), 1)

    def _dests(self, i: int, b: DiffBatch) -> np.ndarray:
        raise NotImplementedError

    def process(self, t, inputs):
        local: list[list[DiffBatch]] = []
        for i, (spec, router, batches) in enumerate(
            zip(self.specs, self.routers, inputs)
        ):
            if spec == "local":
                local.append(list(batches))
                continue
            if spec == "bcast":
                nonempty = [b for b in batches if len(b)]
                parts = [list(nonempty) for _ in range(router.n)]
            elif spec == "p0":
                parts = [[] for _ in range(router.n)]
                parts[0] = [b for b in batches if len(b)]
            else:  # "key"
                parts = router.partition(
                    batches, lambda b, i=i: self._dests(i, b)
                )
            local.append(router.exchange(t, parts))
        if t <= self.replay_floor:
            return []
        return self.inner.process(t, local)

    def on_end(self):
        return self.inner.on_end()

    def state_dict(self):
        return {"inner": self.inner.state_dict()}

    def load_state(self, state):
        if state.get("inner"):
            self.inner.load_state(state["inner"])


def _rowkey_dests(b: DiffBatch, n: int) -> np.ndarray:
    return shard_of(np.asarray(b.keys, dtype=np.uint64), n)


class _OriginTracker:
    """row key -> feeding process, maintained by diffs: insert after full
    retraction re-homes the key, full retraction frees the entry (deferred
    to flush_dead so the retraction's own output row still routes home)."""

    def __init__(self):
        self.entries: dict[int, list] = {}  # key -> [origin_pid, count]

    def observe(self, src: int, batches: list[DiffBatch]) -> None:
        """numpy batch update keyed on ``np.unique`` of the batch keys
        (the per-row Python dict loop ran on every tick). Semantics
        match the old row-wise scan exactly: a key is re-homed to
        ``src`` iff some positive diff lands while the running count is
        <= 0 — for keys this batch creates, the first row already names
        ``src``, so only their total matters; for existing keys the
        revive test needs the within-key running sum, computed from one
        stable sort + cumsum."""
        entries = self.entries
        for b in batches:
            n = len(b)
            if n == 0:
                continue
            diffs = np.ascontiguousarray(b.diffs, dtype=np.int64)
            uniq, inv = np.unique(b.keys, return_inverse=True)
            totals = np.zeros(len(uniq), dtype=np.int64)
            np.add.at(totals, inv, diffs)
            c0 = np.empty(len(uniq), dtype=np.int64)
            ukeys = uniq.tolist()
            known = [entries.get(k) for k in ukeys]
            needs_scan = False
            for j, e in enumerate(known):
                c0[j] = e[1] if e is not None else 0
                needs_scan = needs_scan or e is not None
            if needs_scan:
                # within-key inclusive running sums in original row order
                order = np.argsort(inv, kind="stable")
                sd = diffs[order]
                si = inv[order]
                csum = np.cumsum(sd)
                starts = np.searchsorted(si, np.arange(len(uniq)))
                base = np.zeros(len(uniq), dtype=np.int64)
                base[1:] = csum[starts[1:] - 1]
                prefix_before = (csum - base[si]) - sd
                row_revive = (sd > 0) & ((c0[si] + prefix_before) <= 0)
                revived = np.zeros(len(uniq), dtype=bool)
                np.logical_or.at(revived, si[row_revive], True)
            for j, (k, e) in enumerate(zip(ukeys, known)):
                if e is None:
                    entries[k] = [src, int(totals[j])]
                else:
                    if revived[j]:
                        e[0] = src
                    e[1] += int(totals[j])

    def flush_dead(self) -> None:
        dead = [k for k, e in self.entries.items() if e[1] <= 0]
        for k in dead:
            del self.entries[k]

    def dests(self, b: DiffBatch, default: int) -> np.ndarray:
        """Per-unique-key dict lookups fanned back out through the
        ``np.unique`` inverse (was a per-row generator)."""
        n = len(b)
        if n == 0:
            return np.empty(0, dtype=np.int32)
        entries = self.entries
        uniq, inv = np.unique(b.keys, return_inverse=True)
        owners = np.empty(len(uniq), dtype=np.int32)
        for j, k in enumerate(uniq.tolist()):
            e = entries.get(k)
            owners[j] = e[0] if e is not None else default
        return owners[inv]

    def state_dict(self) -> dict:
        return {k: list(v) for k, v in self.entries.items()}

    def load_state(self, state: dict) -> None:
        self.entries = {int(k): list(v) for k, v in state.items()}


class DcnDeduplicateExec(_DcnStatefulExec):
    """Rows route by instance-key hash — the process owning an instance
    holds its accepted value (reference: deduplicate over Exchange,
    src/engine/dataflow.rs:3514). Output keys ARE instance hashes (a fresh
    universe), so results may stay on their owner."""

    def __init__(self, node):
        super().__init__(node, ["key"], "dd")
        self._inst_cols = list(node.instance_cols)

    def _dests(self, i, b):
        from pathway_tpu.internals.api import ref_scalar

        cols = [b.columns[c] for c in self._inst_cols]
        ks = np.fromiter(
            (
                int(ref_scalar(*(col[r] for col in cols))) & _U64
                for r in range(len(b))
            ),
            dtype=np.uint64,
            count=len(b),
        )
        return shard_of(ks, self.n)


class _DcnReturnHomeExec(_InnerArrangedMixin, NodeExec):
    """Base for ops whose OUTPUT universe preserves input row keys while
    their state needs exchanged inputs: inputs route per `dest_for`, every
    arrival records its feeding process in an _OriginTracker, and output
    rows are exchanged BACK to that process so downstream aligned selects
    see whole rows (placement contract above)."""

    def __init__(self, node, tag: str):
        super().__init__(node)
        self.inner = node._make_local_exec()
        self.replay_floor = -1
        if getattr(self.inner, "persist_standalone", False):
            self.persist_standalone = True
        self.routers = [
            _DcnRouter(f"{tag}{i}n{node.id}") for i in range(len(node.inputs))
        ]
        self.back = _DcnRouter(f"{tag}bn{node.id}")
        self.n = self.routers[0].n
        self.origins = _OriginTracker()

    def dest_for(self, i: int, b: DiffBatch) -> np.ndarray:
        raise NotImplementedError

    def process(self, t, inputs):
        local: list[list[DiffBatch]] = []
        for i, (router, batches) in enumerate(zip(self.routers, inputs)):
            parts = router.partition(
                batches, lambda b, i=i: self.dest_for(i, b)
            )
            merged: list[DiffBatch] = []
            for src, bs in router.exchange_keep_src(t, parts):
                self.origins.observe(src, bs)
                merged.extend(bs)
            local.append(merged)
        out = (
            [] if t <= self.replay_floor else list(self.inner.process(t, local))
        )
        homed = self.back.exchange(
            t,
            self.back.partition(
                out, lambda b: self.origins.dests(b, self.back.pid)
            ),
        )
        self.origins.flush_dead()
        return homed

    def on_end(self):
        # runs after the lockstep cadence ends — no exchange possible; the
        # wrapped ops emit nothing new on flush
        return self.inner.on_end()

    # the wrapper's origin tracker is keyed state too: it rides in the
    # arranged residual (small — one entry per live row key fed from a
    # FOREIGN process, which upstream sharding keeps rare)
    def _wrapper_residual(self) -> dict:
        return {"origin": self.origins.state_dict()}

    def _load_wrapper_residual(self, extra: dict) -> None:
        self.origins.load_state(extra.get("origin", {}))

    def state_dict(self):
        return {
            "inner": self.inner.state_dict(),
            "origin": self.origins.state_dict(),
        }

    def load_state(self, state):
        if state.get("inner"):
            self.inner.load_state(state["inner"])
        self.origins.load_state(state.get("origin", {}))


class DcnSortExec(_DcnReturnHomeExec):
    """Each instance's sorted order lives wholly on the process owning the
    instance hash (reference: prev_next instance co-location,
    src/engine/dataflow/operators/prev_next.rs); an instance-less sort is
    one global order, centralized on process 0. prev/next rows return to
    the process each input row arrived from."""

    def __init__(self, node):
        super().__init__(node, "srt")

    def dest_for(self, i, b):
        if self.node.instance_col is None:
            return np.zeros(len(b), dtype=np.int32)
        from pathway_tpu.internals.api import ref_scalar

        col = b.columns[self.node.instance_col]
        ks = np.fromiter(
            (int(ref_scalar(v)) & _U64 for v in col),
            dtype=np.uint64,
            count=len(b),
        )
        return shard_of(ks, self.n)


class DcnUpdateRowsExec(_DcnReturnHomeExec):
    """Both sides route by row key so the left/right rows of one key
    co-locate for the override decision; the merged row then returns to
    the process that fed the key (output keys are the UNION of the input
    key sets, so downstream aligned consumers need them home)."""

    def __init__(self, node):
        super().__init__(node, "ur")

    def dest_for(self, i, b):
        return _rowkey_dests(b, self.n)


class DcnUniverseSetOpExec(_DcnStatefulExec):
    """The left (universe-owning) side stays local; the other key sets
    replicate, so membership counting is process-local and output rows
    stay where their left row lives (placement contract above)."""

    def __init__(self, node):
        super().__init__(
            node, ["local"] + ["bcast"] * (len(node.inputs) - 1), "us"
        )


class DcnIxExec(_DcnStatefulExec):
    """The indexer (universe-owning) side stays local; the indexed table
    replicates on every process, so each lookup answers locally and the
    result row stays on its indexer row's process (placement contract
    above — the reference instead exchanges both sides and re-exchanges
    the result, operators.rs ix arrange+join)."""

    def __init__(self, node):
        super().__init__(node, ["local", "bcast"], "ix")


class DcnGradualBroadcastExec(_DcnStatefulExec):
    """Data rows stay local; the tiny (lower, value, upper) threshold table
    replicates everywhere so every process sweeps the same triplet
    (reference: gradual_broadcast's broadcasted apx counter,
    src/engine/dataflow/operators/gradual_broadcast.rs)."""

    def __init__(self, node):
        super().__init__(node, ["local", "bcast"], "gb")


class DcnExternalIndexExec(_DcnStatefulExec):
    """The index side replicates on every process (each holds the full
    corpus, device-mesh sharded locally); queries stay local and answer
    as-of-now against the replica (reference: external index operator,
    src/engine/dataflow/operators/external_index.rs)."""

    def __init__(self, node):
        super().__init__(node, ["bcast", "local"], "xi")


class DcnIterateExec(_DcnReturnHomeExec):
    """Fixpoint iteration centralizes on process 0: iterate bodies are
    arbitrary subgraphs whose per-depth runtimes cannot yet join the
    lockstep cadence, so inputs funnel to one process and the fixpoint
    runs there. Bodies commonly PRESERVE input keys, so result rows are
    exchanged back to each key's feeding process (keys the body invented
    stay on process 0). Correct, not scale-out — iterate-heavy jobs
    should shard by instance upstream."""

    def __init__(self, node):
        super().__init__(node, "it")

    def dest_for(self, i, b):
        return np.zeros(len(b), dtype=np.int32)


class DcnWatermarkExec(_InnerArrangedMixin, NodeExec):
    """Buffer/Forget/Freeze: per-row state needs no co-location (a row and
    its retraction always arrive on the same process), but the release
    watermark — max over the current-time column — is GLOBAL. Every tick
    the local watermark is all-gathered and the inner exec advanced to the
    group max, then re-released (reference: time_column.rs postpone/forget
    consult the broadcast frontier of the time column)."""

    def __init__(self, node):
        super().__init__(node)
        self.inner = node._make_local_exec()
        self.router = _DcnRouter(f"wm{node.id}")
        self.replay_floor = -1

    def _shards(self):
        inner = self.inner
        return inner.shards if hasattr(inner, "shards") else [inner]

    def process(self, t, inputs):
        out = [] if t <= self.replay_floor else list(
            self.inner.process(t, inputs)
        )
        local_wm = None
        for ex in self._shards():
            wm = ex.max_seen
            if wm is not None and (local_wm is None or wm > local_wm):
                local_wm = wm
        for wm in self.router.exchange_scalar(t, local_wm):
            if wm is not None and (local_wm is None or wm > local_wm):
                local_wm = wm
        advanced = False
        for ex in self._shards():
            if local_wm is not None and (
                ex.max_seen is None or local_wm > ex.max_seen
            ):
                ex.max_seen = local_wm
                advanced = True
        if advanced and t > self.replay_floor:
            # an empty process() re-runs the release scan under the
            # advanced watermark (Freeze has no release scan: no-op)
            out.extend(self.inner.process(t, [[]]))
        return out

    def on_end(self):
        return self.inner.on_end()

    def state_dict(self):
        return {"inner": self.inner.state_dict()}

    def load_state(self, state):
        if state.get("inner"):
            self.inner.load_state(state["inner"])
