"""pathway_tpu — a TPU-native incremental dataflow & RAG framework.

A from-scratch re-design of the capabilities of the reference Pathway framework
(/root/reference): declarative `Table` API over live data, incremental
microbatch engine, connectors, temporal/indexing/ML stdlib, and an LLM xpack —
with the compute-heavy paths (embedders, KNN indexes, rerankers, numeric
kernels) running on TPU via jax/XLA/Pallas and scaling over device meshes via
`jax.sharding` instead of worker processes.
"""

from __future__ import annotations

import pathway_tpu.reducers as reducers
from pathway_tpu import analysis, debug, demo, io, udfs
from pathway_tpu.internals import (
    UDF,
    ColumnExpression,
    ColumnReference,
    DateTimeNaive,
    DateTimeUtc,
    Duration,
    GroupedJoinResult,
    GroupedTable,
    Joinable,
    JoinMode,
    JoinResult,
    Json,
    MonitoringLevel,
    PathwayType as Type,
    PersistenceMode,
    Pointer,
    PyObjectWrapper,
    Schema,
    SchemaProperties,
    Table,
    TableLike,
    __version__,
    apply,
    apply_async,
    apply_with_type,
    assert_table_has_schema,
    cast,
    coalesce,
    column_definition,
    declare_type,
    fill_error,
    global_error_log,
    groupby,
    if_else,
    join,
    join_inner,
    join_left,
    join_outer,
    join_right,
    left,
    local_error_log,
    make_tuple,
    require,
    right,
    run,
    run_all,
    schema_builder,
    schema_from_csv,
    schema_from_dict,
    schema_from_types,
    this,
    udf,
    unwrap,
    wrap_py_object,
)
from pathway_tpu.internals.custom_reducers import BaseCustomAccumulator
from pathway_tpu.internals.iterate import iterate, iterate_universe
from pathway_tpu.internals.yaml_loader import load_yaml
import pathway_tpu.persistence as persistence
import pathway_tpu.universes as universes
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer
from pathway_tpu.internals.joins import OuterJoinResult
from pathway_tpu.stdlib.temporal._interval_join import IntervalJoinResult
from pathway_tpu.stdlib.temporal._window_join import WindowJoinResult
from pathway_tpu.stdlib.temporal._asof_join import AsofJoinResult
from pathway_tpu.internals.row_transformer import (
    ClassArg,
    attribute,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)
from pathway_tpu.internals.sql import sql


def __getattr__(name: str):
    # stdlib subpackages load lazily so the core import stays light and
    # avoids circular imports (xpacks -> internals -> stdlib)
    import importlib

    if name in (
        "graphs",
        "indexing",
        "ml",
        "ordered",
        "stateful",
        "statistical",
        "temporal",
        "utils",
        "viz",
        "xpacks",
    ):
        module = importlib.import_module(f"pathway_tpu.stdlib.{name}") if name != "xpacks" else importlib.import_module("pathway_tpu.xpacks")
        globals()[name] = module
        return module
    raise AttributeError(name)


def set_license_key(key: str | None) -> None:
    """No-op: this framework has no license gating (reference:
    src/engine/license.rs — intentionally not reproduced)."""


def set_monitoring_config(*, server_endpoint: str | None = None, **kwargs) -> None:
    from pathway_tpu.internals import config

    config.pathway_config.monitoring_server = server_endpoint


def enable_interactive_mode() -> None:
    """Compatibility no-op: pw.live / LiveTable work without prior opt-in
    here (reference gates interactive mode, internals/interactive.py)."""


class TableSlice:
    pass


from pathway_tpu.internals.interactive import LiveTable, live  # noqa: E402


def table_transformer(*args, **kwargs):
    """Decorator marking a function as a table→table transformer
    (reference: internals/table_transformer.py). Pass-through."""

    def wrap(fn):
        return fn

    if args and callable(args[0]):
        return args[0]
    return wrap


__all__ = [
    "__version__",
    "analysis",
    "udfs",
    "graphs",
    "utils",
    "debug",
    "demo",
    "indexing",
    "ml",
    "apply",
    "udf",
    "UDF",
    "apply_async",
    "apply_with_type",
    "declare_type",
    "cast",
    "GroupedTable",
    "GroupedJoinResult",
    "iterate",
    "iterate_universe",
    "JoinResult",
    "JoinMode",
    "AsyncTransformer",
    "AsofJoinResult",
    "IntervalJoinResult",
    "OuterJoinResult",
    "WindowJoinResult",
    "pandas_transformer",
    "universes",
    "ClassArg",
    "attribute",
    "input_attribute",
    "input_method",
    "method",
    "output_attribute",
    "transformer",
    "reducers",
    "schema_from_types",
    "schema_from_dict",
    "schema_from_csv",
    "schema_builder",
    "column_definition",
    "Table",
    "TableLike",
    "TableSlice",
    "ColumnReference",
    "ColumnExpression",
    "Schema",
    "SchemaProperties",
    "Pointer",
    "PyObjectWrapper",
    "wrap_py_object",
    "MonitoringLevel",
    "this",
    "left",
    "right",
    "Joinable",
    "coalesce",
    "require",
    "sql",
    "run",
    "run_all",
    "if_else",
    "make_tuple",
    "unwrap",
    "fill_error",
    "assert_table_has_schema",
    "Type",
    "io",
    "temporal",
    "statistical",
    "stateful",
    "ordered",
    "DateTimeNaive",
    "DateTimeUtc",
    "Duration",
    "Json",
    "BaseCustomAccumulator",
    "PersistenceMode",
    "persistence",
    "join",
    "join_inner",
    "join_left",
    "join_right",
    "join_outer",
    "groupby",
    "set_license_key",
    "set_monitoring_config",
    "global_error_log",
    "local_error_log",
    "load_yaml",
    "enable_interactive_mode",
    "LiveTable",
    "table_transformer",
]
