"""On-demand debugging surfaces: thread dumps, graph tables, profiling.

``thread_stack_dump`` is the tool the BENCH_r05 hung-probe investigation
was missing — eight TPU probes spent 90 s inside backend init with zero
visibility into *where*; a GET /debug/threads against a live process
answers that in one request. ``take_profile`` wraps ``jax.profiler``
trace capture (guarded — callers surface 501 when unavailable instead of
crashing the serving process).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Any


def thread_stack_dump() -> str:
    """Human-readable stack of every live Python thread."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out: list[str] = [
        f"=== thread dump: {len(frames)} thread(s), "
        f"pid={__import__('os').getpid()} ===",
    ]
    for ident, frame in sorted(frames.items(), key=lambda kv: kv[0] or 0):
        t = by_ident.get(ident)
        name = t.name if t is not None else "<unknown>"
        daemon = " daemon" if t is not None and t.daemon else ""
        out.append(f"\n--- Thread {name!r} (ident={ident}{daemon}) ---")
        out.extend(
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        )
    return "\n".join(out) + "\n"


def graph_table(runtime: Any) -> list[dict]:
    """Per-node rows/ns/backlog rows for /debug/graph — the JSON twin of
    the TUI operator table (internals/monitoring.py)."""
    if runtime is None:
        return []
    from pathway_tpu.engine.nodes import InputNode
    from pathway_tpu.engine.runtime import StreamingSource

    stats = runtime.stats
    plan = getattr(runtime, "compiled_plan", None)
    seg_of: dict[int, object] = {}
    if plan is not None:
        for seg in plan.segments:
            for n in seg.nodes:
                seg_of[n.id] = seg
    rows = []
    for node in runtime.order:
        backlog = 0
        if isinstance(node, InputNode) and isinstance(
            getattr(node, "source", None), StreamingSource
        ):
            session = node.source.session
            with session._lock:
                backlog = len(session._rows) + len(session._upserts)
        row = {
            "id": node.id,
            "name": f"{node.name}_{node.id}",
            "type": type(node).__name__,
            "rows": stats.node_rows.get(node.id, 0),
            "ns": stats.node_ns.get(node.id, 0),
            "rows_in": stats.rows_in.get(node.id, 0),
            "rows_out": stats.rows_out.get(node.id, 0),
            "backlog": backlog,
        }
        # Tick Forge: which fused segment (if any) this node rides, and
        # how often the segment actually dispatched compiled vs fell
        # back to the interpreter (tail carries the counters; member
        # rows/ns are attributed to the tail)
        seg = seg_of.get(node.id)
        row["compiled"] = seg is not None and not seg.broken
        if seg is not None:
            row["segment"] = seg.seg_id
            if node.id == seg.tail.id:
                row["segment_tail"] = True
                row["compiled_ticks"] = seg.compiled_ticks
                row["fallback_ticks"] = seg.fallback_ticks
        rows.append(row)
    return rows


class ProfilerUnavailable(RuntimeError):
    """jax (or its profiler) is not importable / not functional here."""


def _get_profiler() -> Any | None:
    try:
        import jax.profiler as profiler

        if hasattr(profiler, "start_trace") and hasattr(
            profiler, "stop_trace"
        ):
            return profiler
    except Exception:
        pass
    return None


_profile_lock = threading.Lock()


def take_profile(seconds: float, logdir: str | None = None) -> str:
    """Capture a jax profiler trace for `seconds`; returns the trace
    directory. Raises ProfilerUnavailable when jax/profiler is absent and
    ValueError on a bad duration. Serialized — concurrent requests would
    fight over the single global profiler session."""
    seconds = float(seconds)
    if not 0.0 < seconds <= 120.0:
        raise ValueError("seconds must be in (0, 120]")
    profiler = _get_profiler()
    if profiler is None:
        raise ProfilerUnavailable(
            "jax.profiler is unavailable in this process"
        )
    if logdir is None:
        import tempfile

        logdir = tempfile.mkdtemp(prefix="pathway_profile_")
    if not _profile_lock.acquire(blocking=False):
        raise RuntimeError("a profile capture is already in progress")
    try:
        profiler.start_trace(logdir)
        try:
            time.sleep(seconds)
        finally:
            profiler.stop_trace()
    finally:
        _profile_lock.release()
    return logdir
