"""Fleet Lens federation — one observability plane for the whole mesh.

Every member (writer, standby, replicas, router) already serves its own
``/metrics``, ``/debug/events`` and ``/debug/trace``; this module is the
read side that stitches them into one view.  The router (and
``GroupSupervisor``) mount it as:

* ``/fleet/metrics`` — member-labeled aggregation of every member's
  exposition body.  Each sample gains a ``member="<name>"`` label and
  each family keeps exactly one HELP/TYPE line, so the merged body
  passes :func:`validate_exposition` — one scrape target for the whole
  plane.
* ``/fleet/events`` — the members' incident journals merged into a
  single (incarnation, wall, tick)-ordered timeline.  This is the feed
  chaos benches measure takeover/reshard windows from: the system's own
  record, not a bench-side stopwatch.
* ``/fleet/trace`` — cross-member Chrome-trace stitch.  Each member
  becomes a Perfetto process (distinct integer ``pid`` + a
  ``process_name`` metadata event); pass ``trace_id`` to cut one
  request's path across router → replica → writer out of the merged
  stream.  The result passes :func:`validate_chrome_trace`.

All fetches use stdlib ``urllib`` with short timeouts; a dead member
degrades to ``pathway_fleet_member_up{member=...} 0`` (metrics) or an
entry in ``errors`` (events/trace) — federation never raises because
one member is mid-crash.  That property is load-bearing: the chaos
bench scrapes `/fleet/*` WHILE it SIGKILLs members.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Iterable, Mapping

from pathway_tpu.observability.exposition import parse_exposition
from pathway_tpu.observability.registry import escape_label_value, format_value

DEFAULT_TIMEOUT_S = 2.0

#: reserved label injected into every federated sample.
MEMBER_LABEL = "member"


def _fetch(url: str, timeout: float) -> bytes:
    req = urllib.request.Request(url, headers={"Accept": "*/*"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read()


def _normalize_members(
    members: Mapping[str, str] | Iterable[tuple[str, str]],
) -> list[tuple[str, str]]:
    """(name, base_url) pairs with trailing slashes trimmed."""
    if isinstance(members, Mapping):
        items = list(members.items())
    else:
        items = list(members)
    return [(str(n), str(u).rstrip("/")) for (n, u) in items]


def members_from_env(env: Mapping[str, str] | None = None) -> list[
    tuple[str, str]
]:
    """``PATHWAY_FLEET_MEMBERS``: comma-separated ``name=http://h:p``
    entries (bare URLs get positional ``member<i>`` names) — the fleet a
    monitoring server's ``/fleet/*`` endpoints federate over.  The group
    supervisor stamps this into every rank's environment so any rank's
    monitoring port answers for the whole group."""
    import os

    raw = (env or os.environ).get("PATHWAY_FLEET_MEMBERS", "")
    out: list[tuple[str, str]] = []
    for i, part in enumerate(p.strip() for p in raw.split(",")):
        if not part:
            continue
        name, eq, url = part.partition("=")
        if not eq:
            name, url = f"member{i}", part
        out.append((name.strip(), url.strip().rstrip("/")))
    return out


# --- /fleet/metrics ---------------------------------------------------------


def federate_metrics(
    members: Mapping[str, str] | Iterable[tuple[str, str]],
    timeout: float = DEFAULT_TIMEOUT_S,
    local: tuple[str, str] | None = None,
) -> tuple[str, dict[str, str]]:
    """Merge every member's ``/metrics`` body into one member-labeled
    exposition text.  ``local`` is an optional (name, body) pair for the
    federating process itself (the router scrapes itself in-process
    rather than over HTTP).  Returns (text, errors-by-member); the text
    passes ``validate_exposition`` regardless of which members failed.
    """
    members = _normalize_members(members)
    errors: dict[str, str] = {}
    bodies: list[tuple[str, str]] = []
    if local is not None:
        bodies.append((local[0], local[1]))
    up: dict[str, int] = {}
    for name, base in members:
        try:
            bodies.append(
                (name, _fetch(f"{base}/metrics", timeout).decode("utf-8"))
            )
            up[name] = 1
        except Exception as exc:  # noqa: BLE001 — any member failure degrades
            errors[name] = f"{type(exc).__name__}: {exc}"
            up[name] = 0
    if local is not None:
        up.setdefault(local[0], 1)

    # family name → (type, help, [(member, Sample), ...]); first member
    # to expose a family wins its TYPE/HELP (mismatches recorded, the
    # first type kept so the merged body stays self-consistent).
    fams: dict[str, dict[str, Any]] = {}
    order: list[str] = []
    for member, body in bodies:
        parsed, perrs = parse_exposition(body)
        if perrs:
            errors[member] = "; ".join(perrs[:4])
        for fname, fam in parsed.items():
            ent = fams.get(fname)
            if ent is None:
                ent = {"type": fam.type, "help": fam.help, "samples": []}
                fams[fname] = ent
                order.append(fname)
            elif fam.type != "untyped" and ent["type"] == "untyped":
                ent["type"] = fam.type
            for s in fam.samples:
                ent["samples"].append((member, s))

    lines: list[str] = []
    for fname in order:
        ent = fams[fname]
        if ent["help"]:
            lines.append(f"# HELP {fname} {ent['help']}")
        if ent["type"] != "untyped":
            lines.append(f"# TYPE {fname} {ent['type']}")
        seen: set[tuple[str, tuple[tuple[str, str], ...]]] = set()
        for member, s in ent["samples"]:
            labels = dict(s.labels)
            labels[MEMBER_LABEL] = member
            key = (s.name, tuple(sorted(labels.items())))
            if key in seen:
                continue
            seen.add(key)
            lines.append(_render_sample(s.name, labels, s.value))

    lines.append("# HELP pathway_fleet_member_up member scrape success")
    lines.append("# TYPE pathway_fleet_member_up gauge")
    for name in sorted(up):
        lines.append(
            _render_sample(
                "pathway_fleet_member_up", {MEMBER_LABEL: name}, float(up[name])
            )
        )
    return "\n".join(lines) + "\n", errors


def _render_sample(name: str, labels: dict[str, str], value: float) -> str:
    if labels:
        # keep `le`/`quantile` last so bucket lines read naturally
        keys = sorted(labels, key=lambda k: (k in ("le", "quantile"), k))
        body = ",".join(f'{k}="{escape_label_value(labels[k])}"' for k in keys)
        return f"{name}{{{body}}} {format_value(value)}"
    return f"{name} {format_value(value)}"


# --- /fleet/events ----------------------------------------------------------


def federate_events(
    members: Mapping[str, str] | Iterable[tuple[str, str]],
    timeout: float = DEFAULT_TIMEOUT_S,
    local: list[dict[str, Any]] | None = None,
) -> dict[str, Any]:
    """Merge member ``/debug/events`` journals (plus the federator's own
    ``local`` events) into one (incarnation, wall, tick)-ordered
    timeline.  Monotonic stamps are per-process and deliberately NOT
    used for cross-member ordering."""
    members = _normalize_members(members)
    errors: dict[str, str] = {}
    merged: list[dict[str, Any]] = []
    seen_members: list[str] = []
    for ev in local or []:
        merged.append(dict(ev))
    for name, base in members:
        try:
            raw = json.loads(_fetch(f"{base}/debug/events", timeout))
        except Exception as exc:  # noqa: BLE001
            errors[name] = f"{type(exc).__name__}: {exc}"
            continue
        events = raw.get("events", raw) if isinstance(raw, dict) else raw
        if not isinstance(events, list):
            errors[name] = "malformed events payload"
            continue
        seen_members.append(name)
        for ev in events:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev.setdefault("member", name)
            merged.append(ev)

    def _key(ev: dict[str, Any]):
        tick = ev.get("tick")
        return (
            int(ev.get("incarnation") or 0),
            float(ev.get("wall") or 0.0),
            -1 if tick is None else int(tick),
            str(ev.get("member", "")),
            int(ev.get("seq") or 0),
        )

    merged.sort(key=_key)
    return {"members": seen_members, "events": merged, "errors": errors}


def window_from_events(
    events: list[dict[str, Any]],
    start_kinds: Iterable[str],
    end_kinds: Iterable[str],
    min_incarnation: int = 0,
) -> dict[str, Any] | None:
    """Wall-clock window from the first start-kind event to the LAST
    end-kind event at/after it.  This is how chaos benches derive
    takeover/reshard windows from `/fleet/events` alone: e.g. first
    ``stream-disconnect`` → last ``caught-up`` with the new incarnation.
    Returns {start_wall, end_wall, seconds, start_event, end_event} or
    None when either edge is missing."""
    starts = set(start_kinds)
    ends = set(end_kinds)
    start_ev: dict[str, Any] | None = None
    end_ev: dict[str, Any] | None = None
    for ev in events:
        if int(ev.get("incarnation") or 0) < min_incarnation:
            continue
        kind = ev.get("kind")
        wall = float(ev.get("wall") or 0.0)
        if kind in starts and (start_ev is None or wall < start_ev["wall"]):
            start_ev = ev
    if start_ev is None:
        return None
    for ev in events:
        if int(ev.get("incarnation") or 0) < min_incarnation:
            continue
        wall = float(ev.get("wall") or 0.0)
        if (
            ev.get("kind") in ends
            and wall >= float(start_ev.get("wall") or 0.0)
            and (end_ev is None or wall > end_ev["wall"])
        ):
            end_ev = ev
    if end_ev is None:
        return None
    start_w = float(start_ev["wall"])
    end_w = float(end_ev["wall"])
    return {
        "start_wall": start_w,
        "end_wall": end_w,
        "seconds": max(end_w - start_w, 0.0),
        "start_event": start_ev,
        "end_event": end_ev,
    }


# --- /fleet/trace -----------------------------------------------------------


def stitch_traces(
    members: Mapping[str, str] | Iterable[tuple[str, str]],
    trace_id: str | None = None,
    timeout: float = DEFAULT_TIMEOUT_S,
    local: tuple[str, dict[str, Any]] | None = None,
) -> dict[str, Any]:
    """Merge member Chrome-trace docs into one Perfetto-loadable doc.
    Each member gets a distinct integer ``pid`` and a ``process_name``
    metadata event so the UI shows one track group per member; with
    ``trace_id`` only that trace's spans survive the cut.  The result
    passes ``validate_chrome_trace``."""
    members = _normalize_members(members)
    errors: dict[str, str] = {}
    docs: list[tuple[str, dict[str, Any]]] = []
    if local is not None:
        docs.append(local)
    for name, base in members:
        try:
            doc = json.loads(_fetch(f"{base}/debug/trace", timeout))
        except Exception as exc:  # noqa: BLE001
            errors[name] = f"{type(exc).__name__}: {exc}"
            continue
        if isinstance(doc, dict):
            docs.append((name, doc))
        else:
            errors[name] = "malformed trace payload"

    events: list[dict[str, Any]] = []
    member_names: list[str] = []
    exemplars: list[Any] = []
    for pid, (name, doc) in enumerate(docs, start=1):
        member_names.append(name)
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": name},
            }
        )
        other = doc.get("otherData")
        if isinstance(other, dict):
            ex = other.get("exemplars")
            if isinstance(ex, list):
                exemplars.extend(ex)
        for ev in doc.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M":
                continue  # replaced by the per-member process_name above
            if trace_id is not None:
                args = ev.get("args")
                if not (
                    isinstance(args, dict)
                    and str(args.get("trace_id", "")) == str(trace_id)
                ):
                    continue
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)

    # stable cross-member order for span events (metadata stays first)
    meta = [e for e in events if e.get("ph") == "M"]
    spans = [e for e in events if e.get("ph") != "M"]
    spans.sort(key=lambda e: (float(e.get("ts") or 0.0), int(e.get("pid") or 0)))
    return {
        "traceEvents": meta + spans,
        "displayTimeUnit": "ms",
        "otherData": {
            "members": member_names,
            "trace_id": trace_id,
            "errors": errors,
            "exemplars": exemplars,
        },
    }


def federate_ticks(
    members: Mapping[str, str] | Iterable[tuple[str, str]],
    timeout: float = DEFAULT_TIMEOUT_S,
    local: tuple[str, dict[str, Any]] | None = None,
    channel_edges: Iterable[
        tuple[tuple[str, str], tuple[str, str], float]
    ] = (),
) -> dict[str, Any]:
    """Tick Scope across the fleet: pull every member's ``/debug/tick``
    and stitch the per-rank exec DAGs into one fleet-wide critical path
    (observability/tickscope.py ``stitch_ranks``). ``channel_edges``
    optionally adds exchange hops as
    ``((member, node), (member, node), wait_seconds)`` — without them
    the rank DAGs are disjoint and the fleet critical path is the
    slowest member's chain, which is exactly the lockstep-tick answer
    when channel waits are unmeasured."""
    from pathway_tpu.observability.tickscope import stitch_ranks

    members = _normalize_members(members)
    errors: dict[str, str] = {}
    docs: dict[str, dict[str, Any]] = {}
    if local is not None:
        docs[local[0]] = local[1]
    for name, base in members:
        try:
            doc = json.loads(_fetch(f"{base}/debug/tick", timeout))
        except Exception as exc:  # noqa: BLE001
            errors[name] = f"{type(exc).__name__}: {exc}"
            continue
        if isinstance(doc, dict):
            docs[name] = doc
        else:
            errors[name] = "malformed tick payload"

    rank_names = sorted(docs)
    rank_of = {name: i for i, name in enumerate(rank_names)}
    rank_durations: dict[int, dict[str, float]] = {}
    rank_edges: dict[int, list[tuple[str, str]]] = {}
    per_member: dict[str, Any] = {}
    for name in rank_names:
        last = docs[name].get("last") or {}
        ops = last.get("operators") or []
        rank_durations[rank_of[name]] = {
            op["node"]: float(op.get("wall_ms", 0.0)) / 1e3
            for op in ops
            if isinstance(op, dict) and "node" in op
        }
        rank_edges[rank_of[name]] = [
            (s, d)
            for e in (last.get("edges") or [])
            if isinstance(e, (list, tuple)) and len(e) == 2
            for s, d in [(str(e[0]), str(e[1]))]
        ]
        per_member[name] = {
            "tick_wall_ms": last.get("wall_ms"),
            "critical_path": last.get("critical_path"),
        }
    stitched = [
        ((rank_of[sm], sn), (rank_of[dm], dn), float(w))
        for (sm, sn), (dm, dn), w in channel_edges
        if sm in rank_of and dm in rank_of
    ]
    total_s, path = stitch_ranks(rank_durations, rank_edges, stitched)
    return {
        "members": per_member,
        "errors": errors,
        "critical_path": {
            "total_ms": round(total_s * 1e3, 6),
            "stages": [
                f"{rank_names[r]}:{node}" for r, node in path
            ],
        },
    }
