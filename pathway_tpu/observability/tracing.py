"""Trace Weaver — end-to-end request tracing with a built-in recorder.

A self-contained tracer: spans land in a bounded in-memory ring buffer
with W3C ``traceparent`` generate/parse, monotonic-clock timestamps, and
parent/child links — no external SDK required (the reference forwards a
W3C trace_parent across the Python/engine boundary so build and engine
spans share one trace, src/engine/telemetry.rs + python_api.rs:3343; we
do the same across REST → embed → KNN → tick → host-mesh). When the host
application configures a real OpenTelemetry SDK TracerProvider, every
span is dual-emitted through it as well, so OTLP pipelines see the same
tree.

Surfaces: ``/debug/trace?seconds=N`` on the monitoring server returns
Chrome trace-event JSON (loadable in Perfetto), ``pw.debug.trace()`` /
``pw.debug.trace_tree()`` for notebooks, and a slow-query log (root
spans over ``PATHWAY_TRACE_SLOW_MS`` dumped with their full child
breakdown). Disable with ``PATHWAY_TRACING=0`` — a disabled tracer hands
out a shared no-op span, so the per-hop cost is one attribute check.

Cross-request attribution: the REST server registers each in-flight
request's span context keyed by its row key (``register_pending``); the
engine tick adopts the oldest pending context as its parent, so operator
/ embed / KNN spans that serve the request share its trace id. Across
processes the host mesh stamps every frame with the sender's
propagation traceparent, and the lockstep tick barrier agrees on one
group-wide tick trace (parallel/host_exchange.py).
"""

from __future__ import annotations

import contextvars
import logging
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

logger = logging.getLogger("pathway_tpu")

# wall-clock anchor for the monotonic clock: span timestamps are
# perf_counter_ns offsets from one anchor, so they are strictly ordered
# within the process and immune to wall-clock steps.
#
# CLOCK CONTRACT (PR-18 audit): every DURATION in this module is a
# difference of two perf_counter_ns reads; wall time appears only as
# this one anchor, captured once at import, used for display/export
# epochs (start_unix_ns, chrome_trace ts, trailing-window cutoffs
# computed as anchored-monotonic). Freezing or stepping time.time()
# after import must not change any measured duration — enforced by the
# frozen-wall-clock regression test in tests/test_tickscope.py.
_ANCHOR_NS = time.time_ns() - time.perf_counter_ns()

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def otel_sdk_provider_active(signal: str = "metrics") -> bool:
    """True when the host application configured a REAL OpenTelemetry SDK
    provider for `signal` ("metrics" or "trace"). The bare OTel API (all
    this image ships) hands out proxy providers that accept-and-drop
    every record — not worth the per-call overhead. One helper shared by
    the metrics exporter (internals/telemetry.py) and the tracer's
    dual-emit gate."""
    try:
        if signal == "trace":
            from opentelemetry import trace as _api

            provider = _api.get_tracer_provider()
        else:
            from opentelemetry import metrics as _api

            provider = _api.get_meter_provider()
        return type(provider).__module__.startswith("opentelemetry.sdk")
    except Exception:
        return False


@dataclass(frozen=True)
class SpanContext:
    """The propagated identity of a span: what crosses process/host
    boundaries inside a ``traceparent`` header or mesh frame."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str  # 16 lowercase hex chars
    flags: int = 1

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"


def parse_traceparent(header: Any) -> SpanContext | None:
    """Parse a W3C traceparent header; None on anything malformed (the
    contract: a bad header mints a fresh root rather than erroring)."""
    if not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":  # forbidden version value
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id, int(flags, 16))


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


@dataclass
class SpanRecord:
    """One finished span in the ring buffer."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_unix_ns: int  # anchored monotonic, ns since epoch
    duration_ns: int
    thread: int
    attributes: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_unix_ns": self.start_unix_ns,
            "duration_ns": self.duration_ns,
            "thread": self.thread,
            "attributes": dict(self.attributes),
        }


# ambient span context of the current thread/task (contextvars follow
# asyncio tasks natively; the engine thread pool copies contexts
# explicitly — runtime.py)
_current: contextvars.ContextVar[SpanContext | None] = contextvars.ContextVar(
    "pathway_trace_ctx", default=None
)


class _NoopSpan:
    """Shared do-nothing span — what a disabled tracer hands out."""

    __slots__ = ()
    context: SpanContext | None = None
    trace_id: str | None = None

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


class Span:
    """A live span: context manager that records into the tracer's ring
    on exit (and mirrors into an OTel SDK span when one is configured)."""

    __slots__ = (
        "_tracer",
        "name",
        "context",
        "parent_id",
        "ingress",
        "attributes",
        "_start_perf",
        "start_unix_ns",
        "_token",
        "_otel_cm",
        "_otel_span",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        context: SpanContext,
        parent_id: str | None,
        attributes: dict[str, Any],
        ingress: bool = False,
    ):
        self._tracer = tracer
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.ingress = ingress
        self.attributes = attributes
        self._token: Any = None
        self._otel_cm: Any = None
        self._otel_span: Any = None

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value
        if self._otel_span is not None:
            # keep the dual-emitted OTel span's view identical to ours
            try:
                self._otel_span.set_attribute(key, value)
            except Exception:
                pass

    def __enter__(self) -> "Span":
        self._start_perf = time.perf_counter_ns()
        self.start_unix_ns = _ANCHOR_NS + self._start_perf
        self._token = _current.set(self.context)
        otel = self._tracer._otel_tracer_if_active()
        if otel is not None:
            try:
                self._otel_cm = otel.start_as_current_span(self.name)
                sp = self._otel_cm.__enter__()
                for k, v in self.attributes.items():
                    try:
                        sp.set_attribute(k, v)
                    except Exception:
                        pass
                # surface OUR ids on the mirrored span so OTLP backends
                # can join against /debug/trace output
                sp.set_attribute("pathway.trace_id", self.context.trace_id)
                sp.set_attribute("pathway.span_id", self.context.span_id)
                self._otel_span = sp
            except Exception:
                self._otel_cm = None
                self._otel_span = None
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        duration_ns = time.perf_counter_ns() - self._start_perf
        if exc_type is not None:
            self.attributes["error"] = exc_type.__name__
        if self._otel_cm is not None:
            try:
                self._otel_cm.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        _current.reset(self._token)
        self._tracer._record(self, duration_ns)
        return False


class Tracer:
    """Bounded-ring span recorder + W3C context propagation."""

    def __init__(
        self, capacity: int | None = None, enabled: bool | None = None
    ):
        if enabled is None:
            enabled = os.environ.get("PATHWAY_TRACING", "1") != "0"
        self.enabled = bool(enabled)
        if capacity is None:
            try:
                capacity = int(os.environ.get("PATHWAY_TRACE_BUFFER", "8192"))
            except ValueError:
                capacity = 8192
        self._spans: deque[SpanRecord] = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        slow = os.environ.get("PATHWAY_TRACE_SLOW_MS", "")
        try:
            self.slow_ms: float | None = float(slow) if slow else None
        except ValueError:
            self.slow_ms = None
        self._otel: Any = None  # cached OTel tracer once a SDK is seen
        self._otel_next_probe = 0.0  # monotonic deadline for a re-probe

    # --- span creation ----------------------------------------------------

    def span(
        self,
        name: str,
        *,
        parent: SpanContext | None = None,
        root: bool = False,
        ingress: bool = False,
        **attributes: Any,
    ) -> Span | _NoopSpan:
        """Create a span. `parent` pins an explicit parent context (e.g.
        parsed from an incoming traceparent); `root=True` forces a fresh
        trace even when an ambient span is active; otherwise the span
        nests under the current thread/task context. ``ingress=True``
        marks a span that enters this process from outside (an HTTP
        request joining a caller's trace): it is slow-log eligible even
        though its parent lives in another service, where a plain child
        span is covered by its local root."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None and not root:
            parent = _current.get()
        if parent is not None:
            ctx = SpanContext(parent.trace_id, _new_span_id(), parent.flags)
            parent_id = parent.span_id
        else:
            ctx = SpanContext(_new_trace_id(), _new_span_id(), 1)
            parent_id = None
        return Span(
            self, name, ctx, parent_id, dict(attributes), ingress=ingress
        )

    def _otel_tracer_if_active(self) -> Any:
        """OTel dual-emit gate (mirrors internals/telemetry.get_metrics —
        an SDK configured after startup still turns emission on). The
        negative verdict is cached for a few seconds: spans open in the
        engine's per-operator hot loop, and a full provider probe (an
        import attempt when opentelemetry is absent!) per span would
        violate the near-zero-overhead contract."""
        if self._otel is not None:
            return self._otel
        now = time.monotonic()
        if now < self._otel_next_probe:
            return None
        self._otel_next_probe = now + 5.0
        if otel_sdk_provider_active("trace"):
            try:
                from opentelemetry import trace as _api

                self._otel = _api.get_tracer("pathway_tpu")
            except Exception:
                self._otel = None
        return self._otel

    def _record(self, span: Span, duration_ns: int) -> None:
        rec = SpanRecord(
            name=span.name,
            trace_id=span.context.trace_id,
            span_id=span.context.span_id,
            parent_id=span.parent_id,
            start_unix_ns=span.start_unix_ns,
            duration_ns=duration_ns,
            thread=threading.get_ident(),
            attributes=span.attributes,
        )
        with self._lock:
            self._spans.append(rec)
        slow = self.slow_ms
        if (
            slow is not None
            and (rec.parent_id is None or span.ingress)
            and duration_ns >= slow * 1e6
        ):
            try:
                logger.warning(
                    "slow trace %s: %s took %.1f ms (threshold %.1f ms)\n%s",
                    rec.trace_id,
                    rec.name,
                    duration_ns / 1e6,
                    slow,
                    self.format_tree(rec.trace_id),
                )
            except Exception:
                pass

    # --- inspection -------------------------------------------------------

    def spans(self, seconds: float | None = None) -> list[SpanRecord]:
        """Snapshot of the ring, oldest first; `seconds` keeps only spans
        that ENDED within the trailing window."""
        with self._lock:
            recs = list(self._spans)
        if seconds is not None:
            cutoff = (_ANCHOR_NS + time.perf_counter_ns()) - int(
                seconds * 1e9
            )
            recs = [
                r for r in recs if r.start_unix_ns + r.duration_ns >= cutoff
            ]
        return recs

    def clear(self) -> None:
        """Test hook: drop every recorded span."""
        with self._lock:
            self._spans.clear()

    def format_tree(
        self, trace_id: str, seconds: float | None = None
    ) -> str:
        """Human-readable parent/child breakdown of one trace."""
        recs = [r for r in self.spans(seconds) if r.trace_id == trace_id]
        if not recs:
            return f"(no spans recorded for trace {trace_id})"
        by_parent: dict[str | None, list[SpanRecord]] = {}
        span_ids = {r.span_id for r in recs}
        for r in recs:
            # a parent that fell out of the ring (or lives in another
            # process) still gets its orphan rendered at the root level
            key = r.parent_id if r.parent_id in span_ids else None
            by_parent.setdefault(key, []).append(r)
        lines: list[str] = []

        def walk(parent: str | None, depth: int) -> None:
            for r in sorted(
                by_parent.get(parent, []), key=lambda r: r.start_unix_ns
            ):
                attrs = ", ".join(
                    f"{k}={v}" for k, v in sorted(r.attributes.items())
                )
                lines.append(
                    "  " * depth
                    + f"{r.name} {r.duration_ns / 1e6:.2f} ms"
                    + (f" [{attrs}]" if attrs else "")
                )
                walk(r.span_id, depth + 1)

        walk(None, 0)
        return "\n".join(lines)

    def chrome_trace(self, seconds: float | None = None) -> dict:
        """Spans as Chrome trace-event JSON (the `traceEvents` dialect
        Perfetto and chrome://tracing load). Complete ("X") events carry
        trace/span/parent ids in `args`; histogram exemplars ride along
        under `otherData` so metrics link back to traces."""
        pid = os.getpid()
        process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0") or 0)
        events: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": f"pathway process {process_id}"},
            }
        ]
        for r in self.spans(seconds):
            args = {k: _jsonable(v) for k, v in r.attributes.items()}
            args["trace_id"] = r.trace_id
            args["span_id"] = r.span_id
            if r.parent_id:
                args["parent_id"] = r.parent_id
            events.append(
                {
                    "name": r.name,
                    "cat": "pathway",
                    "ph": "X",
                    "ts": r.start_unix_ns / 1e3,  # microseconds
                    "dur": r.duration_ns / 1e3,
                    "pid": pid,
                    "tid": r.thread,
                    "args": args,
                }
            )
        exemplars: list[dict] = []
        try:
            from pathway_tpu.observability.registry import REGISTRY

            exemplars = REGISTRY.exemplars()
        except Exception:
            pass
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "process": process_id,
                "exemplars": exemplars,
            },
        }


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# --- Chrome trace-event schema validator ----------------------------------
# (mirrors observability/exposition.py: an in-repo conformance check so
# tests can assert /debug/trace output is loadable before a human ever
# drags it into Perfetto)

_KNOWN_PHASES = frozenset("XBEiIMCbnesftPNDOvRp")


def validate_chrome_trace(data: Any) -> list[str]:
    """Conformance check of a Chrome trace-event document; returns a list
    of violations (empty = ok). Accepts both the object form
    ({"traceEvents": [...]}) and the bare array form."""
    errors: list[str] = []
    if isinstance(data, dict):
        events = data.get("traceEvents")
        if not isinstance(events, list):
            return ["top-level 'traceEvents' must be a list"]
    elif isinstance(data, list):
        events = data
    else:
        return ["document must be an object with traceEvents or an array"]
    for i, ev in enumerate(events):
        where = f"event {i}"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ph, str) or ph not in _KNOWN_PHASES:
            errors.append(f"{where}: unknown phase {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: missing/empty name")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                errors.append(f"{where}: {key} must be an integer")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: ts must be a non-negative number")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(
                    f"{where}: X event needs a non-negative dur"
                )
        args = ev.get("args")
        if args is not None and not isinstance(args, dict):
            errors.append(f"{where}: args must be an object")
    return errors


# --- ambient context helpers ----------------------------------------------


def current_context() -> SpanContext | None:
    return _current.get()


def current_traceparent() -> str | None:
    ctx = _current.get()
    return ctx.traceparent() if ctx is not None else None


# --- in-flight request registry -------------------------------------------
# The REST server registers each awaiting request's span context under
# its row key; the engine tick adopts the OLDEST pending context as its
# parent so the dataflow work that serves the request lands in its
# trace. (With several concurrent requests one tick can only belong to
# one trace — the oldest waiter wins; the others still get their HTTP
# root span and response-header traceparent.)

_pending_lock = threading.Lock()
_pending: dict[int, SpanContext] = {}


def register_pending(key: int, ctx: SpanContext | None) -> None:
    if ctx is None:
        return
    with _pending_lock:
        _pending[key] = ctx


def unregister_pending(key: int) -> None:
    with _pending_lock:
        _pending.pop(key, None)


def pending_context() -> SpanContext | None:
    with _pending_lock:
        return next(iter(_pending.values()), None)


def pending_traceparent() -> str | None:
    ctx = pending_context()
    return ctx.traceparent() if ctx is not None else None


def propagation_traceparent() -> str | None:
    """What crosses a process boundary: the ambient span context when one
    is active (operator work mid-tick), else the oldest pending request
    (the tick-scheduling barrier runs outside any span)."""
    return current_traceparent() or pending_traceparent()


_GLOBAL_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _GLOBAL_TRACER
