"""TPU/JAX gauges for the Flight Recorder.

Bridges ``jax.monitoring`` (compile events emitted by jit/pjit) and
per-device memory stats onto the metrics registry, plus a
``pathway_build_info`` info-style metric carrying platform/backend
labels. Everything here is defensive: the gauges must never *initialize*
a backend (the hung-probe failure mode BENCH_r05 recorded was 90 s spent
inside backend init — a scrape that triggered init would hang the same
way), and must degrade to absent series when jax or a given hook is
unavailable.
"""

from __future__ import annotations

import threading
from typing import Any

from pathway_tpu.observability.registry import (
    REGISTRY,
    MetricsRegistry,
    sanitize_metric_name,
)

_install_lock = threading.Lock()
_installed_on: set[int] = set()


def _backend_if_initialized() -> Any | None:
    """The already-initialized default jax backend, or None. Never
    triggers backend initialization itself."""
    try:
        from jax._src import xla_bridge

        backends = getattr(xla_bridge, "_backends", None)
        if not backends:
            return None
        import jax

        return jax.local_devices()
    except Exception:
        return None


def install_jax_metrics(registry: MetricsRegistry | None = None) -> None:
    """Idempotent per registry; safe to call without jax installed."""
    registry = registry or REGISTRY
    with _install_lock:
        if id(registry) in _installed_on:
            return
        _installed_on.add(id(registry))

    _install_build_info(registry)
    _install_compile_hooks(registry)
    _install_device_memory(registry)


def _install_build_info(registry: MetricsRegistry) -> None:
    import platform as _platform

    try:
        from pathway_tpu import __version__ as pw_version
    except Exception:
        pw_version = "unknown"
    try:
        import jax

        jax_version = getattr(jax, "__version__", "unknown")
    except Exception:
        jax_version = "absent"

    info = registry.gauge(
        "pathway_build_info",
        "constant 1; build/runtime identity in labels (platform/backend "
        "resolve once jax initializes — scraping never forces init)",
        labelnames=("version", "python", "jax", "platform", "backend"),
    )
    state = {"platform": "uninitialized", "backend": "uninitialized"}

    def _collect() -> None:
        if state["platform"] == "uninitialized":
            devices = _backend_if_initialized()
            if devices:
                # retire the placeholder series, or a scrape that raced
                # backend init would expose two build_info identities
                info.remove(
                    pw_version,
                    _platform.python_version(),
                    jax_version,
                    state["platform"],
                    state["backend"],
                )
                state["platform"] = devices[0].platform
                state["backend"] = getattr(
                    devices[0], "device_kind", devices[0].platform
                )
        info.labels(
            pw_version,
            _platform.python_version(),
            jax_version,
            state["platform"],
            state["backend"],
        ).set(1)

    registry.register_collector(_collect)


def _install_compile_hooks(registry: MetricsRegistry) -> None:
    """jit compile count/seconds via jax.monitoring listeners. jax emits
    duration events for tracing/compilation (event names vary by
    version); we keep a per-event breakdown plus a compile rollup."""
    try:
        import jax.monitoring as jmon
    except Exception:
        return
    events_total = registry.counter(
        "pathway_jax_events_total",
        "jax.monitoring events observed, by event key",
        labelnames=("event",),
    )
    durations_total = registry.counter(
        "pathway_jax_event_duration_seconds_total",
        "cumulative seconds of jax.monitoring duration events, by event key",
        labelnames=("event",),
    )
    compile_count = registry.counter(
        "pathway_jax_compilations_total",
        "jit/pjit compilations observed via jax.monitoring",
    )
    compile_seconds = registry.counter(
        "pathway_jax_compile_seconds_total",
        "cumulative seconds spent in jit/pjit compilation",
    )

    def _is_compile(event: str) -> bool:
        e = event.lower()
        return "compil" in e or "backend_compile" in e

    def on_event(event: str, **kwargs: Any) -> None:
        try:
            events_total.labels(sanitize_metric_name(event)).inc()
        except Exception:
            pass

    def on_duration(event: str, duration_secs: float, **kwargs: Any) -> None:
        try:
            key = sanitize_metric_name(event)
            events_total.labels(key).inc()
            durations_total.labels(key).inc(max(0.0, float(duration_secs)))
            if _is_compile(event):
                compile_count.inc()
                compile_seconds.inc(max(0.0, float(duration_secs)))
        except Exception:
            pass

    try:
        jmon.register_event_listener(on_event)
        jmon.register_event_duration_secs_listener(on_duration)
    except Exception:
        pass


def _install_device_memory(registry: MetricsRegistry) -> None:
    mem = registry.gauge(
        "pathway_device_memory_bytes",
        "per-device memory stats from device.memory_stats() (absent until "
        "the backend initializes; CPU backends report no stats)",
        labelnames=("device", "kind"),
    )
    ndev = registry.gauge(
        "pathway_jax_local_devices",
        "local jax device count (0 until the backend initializes)",
    )

    def _collect() -> None:
        devices = _backend_if_initialized()
        ndev.set(len(devices) if devices else 0)
        if not devices:
            return
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            dev_label = f"{d.platform}:{d.id}"
            for kind in (
                "bytes_in_use",
                "peak_bytes_in_use",
                "bytes_limit",
                "largest_free_block_bytes",
            ):
                if kind in stats:
                    mem.labels(dev_label, kind).set(float(stats[kind]))

    registry.register_collector(_collect)
