"""Fleet Lens incident journal — a structured, bounded, atomically
persisted record of the events that define the fleet's failure story.

Chaos benches used to measure takeover and reshard windows with
bench-side stopwatches; the system itself kept no record.  This module
is the system's own record: every plane appends structured events —
standby takeover, zombie fencing, router ejection/readmission, reshard
phase transitions, incarnation bumps, mid-decode deadline drops,
compiled-segment fallbacks, recovery windows — each stamped with
(incarnation, tick, wall clock, monotonic clock), held in a bounded
ring, surfaced at ``/debug/events`` (monitoring server, replica HTTP,
router) and merged fleet-wide at ``/fleet/events``.

Two durability properties:

* **Crash-surviving**: with ``PATHWAY_JOURNAL_PATH`` set the ring is
  persisted via tmp+rename (throttled — the hot path never waits on
  fsync), so a restarted member picks its own past back up; a SIGKILLed
  member that never flushed is reconstructed from its PEERS' events
  (the fencing/takeover records every survivor journals about it).
* **Postmortem bundle**: FAULT_EXIT paths (testing/faults.py) and
  unhandled exceptions (``install_crash_hooks``) write a single-file
  bundle — journal tail + last spans + metrics snapshot + thread dump —
  via tmp+rename, so the last words of a dying process are readable
  even when nothing scraped it in time.

Wall-clock stamps are what cross processes (the fleet merge orders by
(incarnation, wall)); the monotonic stamp is only meaningful within one
process and rides along for intra-member deltas.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterable

_DEPTH_ENV = "PATHWAY_JOURNAL_DEPTH"
_PATH_ENV = "PATHWAY_JOURNAL_PATH"
_MEMBER_ENV = "PATHWAY_JOURNAL_MEMBER"
_FLUSH_MS_ENV = "PATHWAY_JOURNAL_FLUSH_MS"
_POSTMORTEM_DIR_ENV = "PATHWAY_POSTMORTEM_DIR"


def default_member() -> str:
    """This process's member identity in fleet-merged timelines.
    Explicit ``PATHWAY_JOURNAL_MEMBER`` wins; otherwise the serving-plane
    role env vars name the member the way the router and supervisor
    already do."""
    explicit = os.environ.get(_MEMBER_ENV, "")
    if explicit:
        return explicit
    rid = os.environ.get("PATHWAY_REPLICA_ID", "")
    if rid:
        return f"replica-{rid}"
    if os.environ.get("PATHWAY_REPL_PORT", ""):
        return "writer"
    pid = os.environ.get("PATHWAY_PROCESS_ID", "")
    if pid:
        return f"rank-{pid}"
    return f"proc-{os.getpid()}"


def _env_incarnation() -> int:
    try:
        return int(os.environ.get("PATHWAY_MESH_INCARNATION", "0") or 0)
    except ValueError:
        return 0


@dataclass
class JournalEvent:
    """One incident-journal entry.  ``wall`` (unix seconds) is the
    cross-member ordering clock; ``mono`` (``time.monotonic()``) is only
    comparable within the emitting process."""

    seq: int
    kind: str
    detail: str
    member: str
    incarnation: int
    tick: int | None
    wall: float
    mono: float
    data: dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> dict[str, Any]:
        return {
            "seq": self.seq,
            "kind": self.kind,
            "detail": self.detail,
            "member": self.member,
            "incarnation": self.incarnation,
            "tick": self.tick,
            "wall": self.wall,
            "mono": self.mono,
            "data": dict(self.data),
        }


class IncidentJournal:
    """Bounded ring of :class:`JournalEvent` with optional tmp+rename
    persistence and the fatal-exit postmortem bundle."""

    def __init__(
        self,
        capacity: int | None = None,
        path: str | None = None,
        member: str | None = None,
    ):
        if capacity is None:
            try:
                capacity = int(os.environ.get(_DEPTH_ENV, "1024") or 1024)
            except ValueError:
                capacity = 1024
        self.capacity = max(int(capacity), 8)
        self.path = path if path is not None else os.environ.get(
            _PATH_ENV, ""
        ) or None
        self.member = member or default_member()
        try:
            flush_ms = float(os.environ.get(_FLUSH_MS_ENV, "500") or 500)
        except ValueError:
            flush_ms = 500.0
        self._flush_s = max(flush_ms, 0.0) / 1000.0
        self._lock = threading.Lock()
        self._ring: deque[JournalEvent] = deque(maxlen=self.capacity)
        self._seq = 0
        self._last_persist = 0.0
        self._dirty = False
        if self.path:
            self._load()

    # --- recording --------------------------------------------------------

    def record(
        self,
        kind: str,
        detail: str = "",
        *,
        tick: int | None = None,
        incarnation: int | None = None,
        member: str | None = None,
        persist: bool = False,
        **data: Any,
    ) -> JournalEvent:
        """Append one event (thread-safe; never raises).  ``persist=True``
        forces an immediate atomic flush — takeover/fencing records must
        survive the very next SIGKILL."""
        if incarnation is None:
            incarnation = _env_incarnation()
        ev = JournalEvent(
            seq=0,
            kind=str(kind),
            detail=str(detail),
            member=member or self.member,
            incarnation=int(incarnation),
            tick=None if tick is None else int(tick),
            wall=time.time(),
            mono=time.monotonic(),
            data={k: _jsonable(v) for k, v in data.items()},
        )
        with self._lock:
            self._seq += 1
            ev.seq = self._seq
            self._ring.append(ev)
            self._dirty = True
        if self.path:
            try:
                if persist or (
                    time.monotonic() - self._last_persist >= self._flush_s
                ):
                    self.flush()
            except Exception:
                pass
        return ev

    # --- inspection -------------------------------------------------------

    def events(
        self,
        kinds: Iterable[str] | None = None,
        since_seq: int = 0,
    ) -> list[dict[str, Any]]:
        with self._lock:
            recs = list(self._ring)
        want = set(kinds) if kinds is not None else None
        return [
            e.as_dict()
            for e in recs
            if e.seq > since_seq and (want is None or e.kind in want)
        ]

    def tail(self, n: int = 50) -> list[dict[str, Any]]:
        with self._lock:
            recs = list(self._ring)[-max(int(n), 0):]
        return [e.as_dict() for e in recs]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # --- persistence (tmp+rename, same idiom as standby's position file) --

    def flush(self) -> None:
        """Atomically persist the ring to ``self.path`` (no-op without a
        path).  Safe to call from signal/exit paths."""
        if not self.path:
            return
        with self._lock:
            if not self._dirty:
                return
            recs = [e.as_dict() for e in self._ring]
            self._dirty = False
        body = "\n".join(json.dumps(r) for r in recs) + "\n"
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                f.write(body)
            os.replace(tmp, self.path)
            self._last_persist = time.monotonic()
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _load(self) -> None:
        """Restore the persisted tail (crash-surviving): restored events
        keep their original stamps, marked ``restored`` so consumers can
        tell a pre-crash record from this incarnation's."""
        try:
            with open(self.path) as f:
                lines = f.read().splitlines()
        except OSError:
            return
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
                data = dict(r.get("data") or {})
                data["restored"] = True
                ev = JournalEvent(
                    seq=0,
                    kind=str(r["kind"]),
                    detail=str(r.get("detail", "")),
                    member=str(r.get("member", self.member)),
                    incarnation=int(r.get("incarnation", 0)),
                    tick=r.get("tick"),
                    wall=float(r.get("wall", 0.0)),
                    mono=float(r.get("mono", 0.0)),
                    data=data,
                )
            except (KeyError, TypeError, ValueError):
                continue
            self._seq += 1
            ev.seq = self._seq
            self._ring.append(ev)

    # --- postmortem bundle ------------------------------------------------

    def postmortem(
        self,
        reason: str,
        exc: BaseException | None = None,
        directory: str | None = None,
    ) -> str | None:
        """Write the fatal-exit bundle — journal tail + last spans +
        metrics snapshot + thread dump — via tmp+rename.  Every
        ingredient is best-effort: a broken scrape must not mask the
        exit code.  Returns the bundle path (None when nowhere to
        write)."""
        directory = directory or os.environ.get(_POSTMORTEM_DIR_ENV, "")
        if not directory and self.path:
            directory = os.path.join(
                os.path.dirname(os.path.abspath(self.path)), "postmortem"
            )
        if not directory:
            return None
        bundle: dict[str, Any] = {
            "reason": str(reason),
            "member": self.member,
            "pid": os.getpid(),
            "incarnation": _env_incarnation(),
            "wall": time.time(),
            "mono": time.monotonic(),
        }
        if exc is not None:
            import traceback

            bundle["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(
                    traceback.format_exception(type(exc), exc, exc.__traceback__)
                ),
            }
        bundle["journal"] = self.tail(self.capacity)
        try:
            from pathway_tpu.observability.tracing import get_tracer

            bundle["spans"] = [
                r.to_dict() for r in get_tracer().spans()[-256:]
            ]
        except Exception:
            bundle["spans"] = []
        try:
            from pathway_tpu.observability.registry import REGISTRY

            bundle["metrics"] = REGISTRY.render()
        except Exception:
            bundle["metrics"] = ""
        try:
            from pathway_tpu.observability.debug import thread_stack_dump

            bundle["threads"] = thread_stack_dump()
        except Exception:
            bundle["threads"] = ""
        name = (
            f"postmortem-{_fs_safe(self.member)}-{os.getpid()}-"
            f"{int(time.time() * 1000)}.json"
        )
        path = os.path.join(directory, name)
        tmp = f"{path}.tmp"
        try:
            os.makedirs(directory, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(bundle, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        try:
            self.flush()
        except Exception:
            pass
        return path


def _fs_safe(s: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "_" for c in s)


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


# --- process-global journal -------------------------------------------------

_journal: IncidentJournal | None = None
_journal_lock = threading.Lock()


def journal() -> IncidentJournal:
    """The process-wide incident journal (lazily constructed from the
    PATHWAY_JOURNAL_* env)."""
    global _journal
    if _journal is None:
        with _journal_lock:
            if _journal is None:
                _journal = IncidentJournal()
    return _journal


def reset_journal() -> None:
    """Test hook: flush and forget the process-global journal (the next
    :func:`journal` call re-reads the env)."""
    global _journal
    with _journal_lock:
        if _journal is not None:
            try:
                _journal.flush()
            except Exception:
                pass
        _journal = None


def record(kind: str, detail: str = "", **kwargs: Any) -> JournalEvent:
    """Convenience: ``journal().record(...)`` — the one-liner every
    plane's event sites call."""
    return journal().record(kind, detail, **kwargs)


# --- crash hooks ------------------------------------------------------------

_hooks_installed = False
_hooks_lock = threading.Lock()


def install_crash_hooks() -> None:
    """Chain a postmortem-bundle writer into ``sys.excepthook`` and
    ``threading.excepthook`` (idempotent).  The original hooks still run
    — this only ADDS the bundle, it never swallows the traceback."""
    global _hooks_installed
    with _hooks_lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    import sys

    prev_sys = sys.excepthook
    prev_thread = threading.excepthook

    def _sys_hook(exc_type, exc, tb):
        try:
            journal().record(
                "unhandled-exception",
                f"{exc_type.__name__}: {exc}",
                persist=True,
            )
            journal().postmortem("unhandled-exception", exc)
        except Exception:
            pass
        prev_sys(exc_type, exc, tb)

    def _thread_hook(args):
        try:
            if args.exc_type is not SystemExit:
                journal().record(
                    "unhandled-exception",
                    f"{args.exc_type.__name__}: {args.exc_value} "
                    f"(thread {getattr(args.thread, 'name', '?')})",
                    persist=True,
                )
                journal().postmortem(
                    "unhandled-thread-exception", args.exc_value
                )
        except Exception:
            pass
        prev_thread(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _thread_hook
