"""Flight Recorder — process-wide observability for pathway_tpu.

One registry (``REGISTRY``) collects counters/gauges/histograms from
every layer (engine tick loop, KNN serving, embedder batches, REST
handlers, host exchange, sharded routing); the monitoring server
(internals/monitoring_server.py) renders it at ``/metrics`` and serves
the debug surfaces (``/debug/threads``, ``/debug/graph``,
``/debug/profile``, ``/debug/trace``). The Trace Weaver
(``observability/tracing.py``) adds end-to-end request tracing on top:
a built-in span ring buffer with W3C traceparent propagation across
every serving hop and the host mesh. Fleet Lens (PR 17) extends the
plane fleet-wide: SLO signal rings (``observability/signals.py``,
``/debug/signals``), the crash-surviving incident journal
(``observability/journal.py``, ``/debug/events``), and federation
(``observability/fleet.py``: ``/fleet/metrics``, ``/fleet/events``,
``/fleet/trace`` on the router). Tick Scope (PR 18,
``observability/tickscope.py``, ``/debug/tick``) goes below the
route-level spans: a per-runtime flight recorder attributing every
tick to its operators (wall/rows/compiled-vs-interpreted + critical
path), a resident-bytes memory ledger across execs/KV pools/replica
indexes, and roofline MFU per kernel family. See README
"Observability" for the metric inventory, signal/SLO knobs, journal
event schema, tracing guide, and the tick-profiling contract.
"""

from pathway_tpu.observability.registry import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    escape_label_value,
    get_registry,
    log_linear_buckets,
    sanitize_metric_name,
)
from pathway_tpu.observability.exposition import (
    parse_exposition,
    validate_exposition,
)
from pathway_tpu.observability.debug import (
    ProfilerUnavailable,
    graph_table,
    take_profile,
    thread_stack_dump,
)
from pathway_tpu.observability.jax_metrics import install_jax_metrics
from pathway_tpu.observability.journal import (
    IncidentJournal,
    JournalEvent,
    install_crash_hooks,
    journal,
    reset_journal,
)
from pathway_tpu.observability.signals import (
    SignalRing,
    SignalSampler,
    arm_sampler,
    get_sampler,
    reset_sampler,
    slo_targets,
)
from pathway_tpu.observability.fleet import (
    federate_events,
    federate_metrics,
    federate_ticks,
    members_from_env,
    stitch_traces,
    window_from_events,
)
from pathway_tpu.observability.tickscope import (
    Roofline,
    TickScope,
    coverage_status,
    critical_path,
    estimate_program_cost,
    memory_snapshot,
    peak_flops,
    recorder,
    register_memory_provider,
    roofline,
    stitch_ranks,
    wire_snapshot,
)
from pathway_tpu.observability.tracing import (
    SpanContext,
    Tracer,
    current_traceparent,
    get_tracer,
    otel_sdk_provider_active,
    parse_traceparent,
    validate_chrome_trace,
)

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "IncidentJournal",
    "JournalEvent",
    "MetricsRegistry",
    "ProfilerUnavailable",
    "Roofline",
    "SignalRing",
    "SignalSampler",
    "SpanContext",
    "TickScope",
    "Tracer",
    "arm_sampler",
    "coverage_status",
    "critical_path",
    "current_traceparent",
    "estimate_program_cost",
    "escape_label_value",
    "federate_events",
    "federate_metrics",
    "federate_ticks",
    "members_from_env",
    "get_registry",
    "get_sampler",
    "get_tracer",
    "graph_table",
    "install_crash_hooks",
    "install_jax_metrics",
    "journal",
    "log_linear_buckets",
    "memory_snapshot",
    "otel_sdk_provider_active",
    "parse_exposition",
    "parse_traceparent",
    "peak_flops",
    "recorder",
    "register_memory_provider",
    "reset_journal",
    "reset_sampler",
    "roofline",
    "sanitize_metric_name",
    "slo_targets",
    "stitch_ranks",
    "stitch_traces",
    "take_profile",
    "thread_stack_dump",
    "validate_chrome_trace",
    "validate_exposition",
    "window_from_events",
]
