"""Flight Recorder — process-wide observability for pathway_tpu.

One registry (``REGISTRY``) collects counters/gauges/histograms from
every layer (engine tick loop, KNN serving, embedder batches, REST
handlers, host exchange, sharded routing); the monitoring server
(internals/monitoring_server.py) renders it at ``/metrics`` and serves
the debug surfaces (``/debug/threads``, ``/debug/graph``,
``/debug/profile``). See README "Observability" for the metric
inventory and scrape config.
"""

from pathway_tpu.observability.registry import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    escape_label_value,
    get_registry,
    log_linear_buckets,
    sanitize_metric_name,
)
from pathway_tpu.observability.exposition import (
    parse_exposition,
    validate_exposition,
)
from pathway_tpu.observability.debug import (
    ProfilerUnavailable,
    graph_table,
    take_profile,
    thread_stack_dump,
)
from pathway_tpu.observability.jax_metrics import install_jax_metrics

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ProfilerUnavailable",
    "escape_label_value",
    "get_registry",
    "graph_table",
    "install_jax_metrics",
    "log_linear_buckets",
    "parse_exposition",
    "sanitize_metric_name",
    "take_profile",
    "thread_stack_dump",
    "validate_exposition",
]
