"""Process-wide metrics registry — the Flight Recorder's core.

Counters, gauges, and log-linear-bucket histograms with Prometheus text
exposition (format 0.0.4, the dialect the reference engine serves from
src/engine/http_server.rs). One process-wide ``REGISTRY`` feeds the
``/metrics`` endpoint (internals/monitoring_server.py); hot paths across
engine/io/xpacks bind label children once and observe per batch, so the
per-tick cost is a lock + bisect, never string formatting.

Histograms use log-linear buckets (HdrHistogram style: linear subdivision
within each power-of-two octave), which keeps relative quantile error
bounded by 1/per_octave across the whole 0.1 ms .. 64 s serving range —
the p50/p95/p99 numbers BASELINE.md tracks are estimated from these
buckets (``Histogram.quantile``), and Prometheus re-derives them
server-side from the ``_bucket`` series.
"""

from __future__ import annotations

import math
import re
import threading
import time
from bisect import bisect_left
from typing import Any, Callable, Iterable, Mapping, Sequence

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Coerce an arbitrary string into a legal metric name."""
    out = _SANITIZE_RE.sub("_", str(name))
    if not out or not _NAME_RE.match(out):
        out = "_" + out
    return out


def escape_label_value(value: Any) -> str:
    """Prometheus label-value escaping: backslash, double quote, newline.
    User-controlled strings (table/node names, routes, model ids) pass
    through here before interpolation, so a quote in a table name cannot
    corrupt the exposition output."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


def log_linear_buckets(
    lo: float = 1e-4, hi: float = 64.0, per_octave: int = 4
) -> tuple[float, ...]:
    """Bucket upper bounds: each power-of-two octave [b, 2b) split into
    ``per_octave`` linear sub-buckets (HdrHistogram layout). The default
    spans 0.1 ms .. 64 s in ~78 buckets — wide enough for a sub-ms device
    top-k and a 60 s hung backend init in the same histogram, with
    quantile interpolation error bounded by one sub-bucket (≤25%)."""
    bounds: list[float] = []
    base = lo
    while base < hi:
        for j in range(1, per_octave + 1):
            bounds.append(base * (1.0 + j / per_octave))
        base *= 2.0
    # float steps can land a hair past hi; keep one terminal bucket at hi
    out = sorted({round(b, 12) for b in bounds if b <= hi * (1 + 1e-9)})
    if not out or out[-1] < hi:
        out.append(float(hi))
    return tuple(out)


def _label_key(
    labelnames: Sequence[str], args: Sequence[Any], kwargs: Mapping[str, Any]
) -> tuple[str, ...]:
    if kwargs:
        if args:
            raise ValueError("pass label values positionally OR by name")
        try:
            args = [kwargs[n] for n in labelnames]
        except KeyError as exc:
            raise ValueError(
                f"missing label {exc.args[0]!r}; expected {labelnames}"
            ) from exc
    if len(args) != len(labelnames):
        raise ValueError(
            f"expected {len(labelnames)} label value(s) {labelnames}, "
            f"got {len(args)}"
        )
    return tuple(str(a) for a in args)


class _Metric:
    """Shared labeled-family scaffolding."""

    type_name = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], Any] = {}

    def labels(self, *args: Any, **kwargs: Any):
        key = _label_key(self.labelnames, args, kwargs)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child(key)
                self._children[key] = child
        return child

    def remove(self, *args: Any, **kwargs: Any) -> None:
        """Drop one label child (e.g. a placeholder series that has been
        superseded). No-op when the child does not exist."""
        key = _label_key(self.labelnames, args, kwargs)
        with self._lock:
            self._children.pop(key, None)

    def _unlabeled(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; use .labels()"
            )
        return self.labels()

    def _make_child(self, key: tuple[str, ...]):
        raise NotImplementedError

    def _render_label_str(self, key: tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{n}="{escape_label_value(v)}"'
            for n, v in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def samples(self) -> Iterable[str]:
        raise NotImplementedError

    def render(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {escape_help(self.help)}",
            f"# TYPE {self.name} {self.type_name}",
        ]
        lines.extend(self.samples())
        return lines


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self.value += amount

    def set_total(self, value: float) -> None:
        """Bridge hook: adopt an externally-maintained monotone total
        (RuntimeStats promotion). Not part of the user-facing API."""
        with self._lock:
            self.value = float(value)


class Counter(_Metric):
    type_name = "counter"

    def _make_child(self, key):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    def samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            with child._lock:
                value = child.value
            yield (
                f"{self.name}{self._render_label_str(key)} "
                f"{format_value(value)}"
            )


class _GaugeChild:
    __slots__ = ("_lock", "value", "fn")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0
        self.fn: Callable[[], float] | None = None

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)
            self.fn = None

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self.fn = fn

    def current(self) -> float:
        fn = self.fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:
                return float("nan")
        return self.value


class Gauge(_Metric):
    type_name = "gauge"

    def _make_child(self, key):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._unlabeled().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._unlabeled().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._unlabeled().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._unlabeled().set_function(fn)

    def samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            yield (
                f"{self.name}{self._render_label_str(key)} "
                f"{format_value(child.current())}"
            )


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "counts", "sum", "count", "exemplar")

    def __init__(self, bounds: tuple[float, ...]):
        self._lock = threading.Lock()
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self.sum = 0.0
        self.count = 0
        # most recent (trace_id, value, unix_seconds) exemplar — links the
        # latency distribution back to a concrete trace in the Trace
        # Weaver ring (served under /debug/trace "otherData.exemplars";
        # the 0.0.4 text exposition has no exemplar syntax, so /metrics
        # output is unchanged)
        self.exemplar: tuple[str, float, float] | None = None

    def observe(self, value: float, exemplar: str | None = None) -> None:
        idx = bisect_left(self._bounds, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1
            if exemplar is not None:
                self.exemplar = (str(exemplar), float(value), time.time())

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0..1) from bucket counts by linear
        interpolation within the target bucket."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1]")
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return float("nan")
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= rank and c > 0:
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = (
                    self._bounds[i]
                    if i < len(self._bounds)
                    else self._bounds[-1]
                )
                if hi <= lo:
                    return hi
                frac = (rank - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self._bounds[-1]


class Histogram(_Metric):
    type_name = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] | None = None,
    ):
        super().__init__(name, help, labelnames)
        if buckets is None:
            buckets = log_linear_buckets()
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError("bucket bounds must be strictly increasing")
        self.bounds = bounds

    def _make_child(self, key):
        return _HistogramChild(self.bounds)

    def observe(self, value: float, exemplar: str | None = None) -> None:
        self._unlabeled().observe(value, exemplar)

    def quantile(self, q: float) -> float:
        return self._unlabeled().quantile(q)

    def samples(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            with child._lock:
                counts = list(child.counts)
                total = child.count
                vsum = child.sum
            cum = 0
            for bound, c in zip(self.bounds, counts):
                cum += c
                extra = f'le="{format_value(bound)}"'
                yield (
                    f"{self.name}_bucket"
                    f"{self._render_label_str(key, extra)} {cum}"
                )
            inf_extra = 'le="+Inf"'
            yield (
                f"{self.name}_bucket"
                f"{self._render_label_str(key, inf_extra)} {total}"
            )
            yield (
                f"{self.name}_sum{self._render_label_str(key)} "
                f"{format_value(vsum)}"
            )
            yield f"{self.name}_count{self._render_label_str(key)} {total}"


class MetricsRegistry:
    """Name-keyed metric store. ``counter``/``gauge``/``histogram`` are
    get-or-create (idempotent across Runtime constructions in one
    process); collectors run just before each render so scrape-time
    bridges (RuntimeStats, device memory) stay pull-based."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], None]] = []

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}"
                    )
                buckets = kwargs.get("buckets")
                if buckets is not None and existing.bounds != tuple(
                    sorted(float(b) for b in buckets)
                ):
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {existing.bounds}"
                    )
                return existing
            metric = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str, labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str, labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] | None = None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def register_collector(self, fn: Callable[[], None]) -> None:
        """``fn()`` runs at the start of every ``render()``; exceptions are
        swallowed (a broken bridge must not take down the scrape)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def exemplars(self) -> list[dict]:
        """Every histogram child's most recent exemplar: which trace id
        last contributed to which latency series (Trace Weaver's
        metrics→traces link)."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        out: list[dict] = []
        for m in metrics:
            if not isinstance(m, Histogram):
                continue
            with m._lock:
                items = sorted(m._children.items())
            for key, child in items:
                ex = child.exemplar
                if ex is None:
                    continue
                out.append(
                    {
                        "metric": m.name,
                        "labels": dict(zip(m.labelnames, key)),
                        "trace_id": ex[0],
                        "value": ex[1],
                        "time_unix": ex[2],
                    }
                )
        return out

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self) -> None:
        """Test hook: drop every metric and collector."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
