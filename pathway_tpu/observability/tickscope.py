"""Tick Scope — per-operator flight recorder, memory ledger, and
roofline attribution for every tick.

Fleet Lens (PR 17) can say *that* a plane is slow; this module says
*why* and *where the bytes live*. Three legs share one file because
they share one clock and one registry:

* **Flight recorder** — an always-on, bounded-overhead ring of per-tick
  records. Every tick the runtime (engine/runtime.py) appends one entry
  per exec that ran: monotonic wall time, rows in/out, and whether the
  work went through a Tick Forge compiled segment or the interpreter
  (the segment tail accounts for its whole fused chain). Off
  (``PATHWAY_TICKSCOPE=0``) the hot loop pays exactly one ``is None``
  check per node. The per-tick critical path over the exec DAG is
  computed lazily at snapshot time (:func:`critical_path`), never on
  the tick itself, and stitches across ranks through exchange channels
  (:func:`stitch_ranks`). :meth:`TickScope.chrome_trace` renders the
  ring as Perfetto-loadable trace events with **one track per exec**.

* **Memory ledger** — per-arrangement / per-exec resident-bytes
  accounting. Execs report through ``NodeExec.memory_ledger()``
  (arrangement segments, GroupBy ledger doubling, monolith pickles
  under ``deep=1``); other planes register providers
  (:func:`register_memory_provider`): the KV page pools + host mirror
  (generate/kv_cache.py), replica index bytes (serving/replica.py).
  Everything lands as ``pathway_tickscope_resident_bytes{owner,part}``
  and in the ``/debug/tick`` surface, so the ROADMAP's columnar-memory
  refactor starts from measured owners, not guesses.

* **Roofline attribution** — per-compiled-program FLOP estimates from
  XLA cost analysis (``fn.lower(...).compile().cost_analysis()``, the
  TPU-KNN peak-FLOP/s recipe, https://arxiv.org/pdf/2206.14286) over
  measured monotonic wall time gives achieved FLOP/s and MFU per
  kernel family: ``topk`` (stdlib/indexing), ``paged_attention``
  (generate/scheduler), ``compiled_tick`` (engine/compile). On CPU the
  same math runs today and pins the accounting; the day a TPU lights
  up only the peak changes (``PATHWAY_PEAK_FLOPS`` or the per-platform
  table below).

Knobs::

    PATHWAY_TICKSCOPE        1 (default) records; 0 disables the ring
    PATHWAY_TICKSCOPE_RING   ticks kept per runtime (default 128)
    PATHWAY_PEAK_FLOPS       peak FLOP/s for MFU (overrides the table)
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Hashable, Iterable, Mapping, Sequence

__all__ = [
    "TickScope",
    "TickRecord",
    "critical_path",
    "stitch_ranks",
    "recorder",
    "register_memory_provider",
    "unregister_memory_provider",
    "memory_snapshot",
    "exec_memory_ledger",
    "roofline",
    "Roofline",
    "estimate_program_cost",
    "peak_flops",
    "coverage_status",
    "wire_tap",
    "wire_snapshot",
    "reset",
]


def enabled_from_env() -> bool:
    return os.environ.get("PATHWAY_TICKSCOPE", "1") not in ("0", "false", "")


def _ring_size() -> int:
    try:
        return max(1, int(os.environ.get("PATHWAY_TICKSCOPE_RING", "128")))
    except ValueError:
        return 128


# ---------------------------------------------------------------------------
# metric families (lazy — importing this module must not touch the registry
# until something actually records)

_metrics_lock = threading.Lock()
_metrics: tuple | None = None


def _tickscope_metrics():
    global _metrics
    if _metrics is not None:
        return _metrics
    with _metrics_lock:
        if _metrics is not None:
            return _metrics
        from pathway_tpu.observability.registry import (
            REGISTRY,
            log_linear_buckets,
        )

        resident = REGISTRY.gauge(
            "pathway_tickscope_resident_bytes",
            "resident bytes per memory-ledger owner and part (exec "
            "arrangements, GroupBy ledger doubling, KV pools + host "
            "mirror, replica index, monolith pickles)",
            labelnames=("owner", "part"),
        )
        wire_bytes = REGISTRY.counter(
            "pathway_tickscope_wire_bytes_total",
            "encoded mesh-frame bytes per exchange channel (tapped in "
            "parallel/wire.encode_frame callers)",
            labelnames=("channel",),
        )
        wire_rows = REGISTRY.counter(
            "pathway_tickscope_wire_rows_total",
            "rows shipped per exchange channel",
            labelnames=("channel",),
        )
        mfu = REGISTRY.gauge(
            "pathway_tickscope_mfu",
            "achieved model-FLOP utilization per kernel family: "
            "(cost-analysis FLOPs / measured monotonic wall) / peak "
            "FLOP/s (PATHWAY_PEAK_FLOPS or the per-platform table)",
            labelnames=("family",),
        )
        flops = REGISTRY.counter(
            "pathway_tickscope_flops_total",
            "estimated FLOPs executed per kernel family (XLA cost "
            "analysis x call count)",
            labelnames=("family",),
        )
        # sub-millisecond floor: compiled ticks finish in 10-100 us —
        # the default 1e-4 floor would flatten them into one bucket
        kernel_seconds = REGISTRY.histogram(
            "pathway_tickscope_kernel_seconds",
            "measured wall per roofline-attributed kernel call",
            labelnames=("family",),
            buckets=log_linear_buckets(lo=1e-6, hi=64.0, per_octave=4),
        )
        cp_seconds = REGISTRY.gauge(
            "pathway_tickscope_critical_path_seconds",
            "critical-path time of the most recent recorded tick",
        )
        REGISTRY.register_collector(_collect)
        _metrics = (
            resident, wire_bytes, wire_rows, mfu, flops, kernel_seconds,
            cp_seconds,
        )
        return _metrics


def _collect() -> None:
    """Registry collector: promote ledger/roofline state to gauges at
    scrape time — the tick loop never pays for metric formatting."""
    m = _metrics
    if m is None:  # pragma: no cover - collector armed implies metrics
        return
    resident, _wb, _wr, mfu, flops, _ks, cp = m
    snap = memory_snapshot(deep=False)
    for owner, parts in snap["owners"].items():
        for part, nbytes in parts.items():
            resident.labels(owner, part).set(float(nbytes))
    for family, fam in roofline().snapshot().items():
        mfu.labels(family).set(fam["mfu"])
        flops.labels(family).set_total(fam["flops_total"])
    rec = recorder()
    if rec is not None:
        last = rec.last()
        if last is not None:
            total_s, _path = rec.record_critical_path(last)
            cp.set(total_s)


# ---------------------------------------------------------------------------
# critical path (pure — property-tested over random DAGs)


def critical_path(
    durations: Mapping[Hashable, float],
    edges: Iterable[tuple[Hashable, Hashable]],
    edge_weights: Mapping[tuple[Hashable, Hashable], float] | None = None,
) -> tuple[float, list[Hashable]]:
    """Longest duration-weighted source-to-sink path in a DAG.

    ``durations`` maps node -> node cost (seconds); ``edges`` are
    ``(src, dst)`` pairs meaning *dst consumes src*; ``edge_weights``
    optionally adds a cost to traversing an edge (an exchange channel's
    wait, a cross-rank hop). Nodes appearing only in ``edges`` count as
    zero-cost. Returns ``(total, path)`` with the path in src->dst
    order. Raises ``ValueError`` on a cycle."""
    ew = edge_weights or {}
    succs: dict[Hashable, list[Hashable]] = {}
    indeg: dict[Hashable, int] = {}
    nodes = set(durations)
    for s, d in edges:
        succs.setdefault(s, []).append(d)
        indeg[d] = indeg.get(d, 0) + 1
        nodes.add(s)
        nodes.add(d)
    best: dict[Hashable, float] = {}
    prev: dict[Hashable, Hashable | None] = {}
    ready = [n for n in nodes if indeg.get(n, 0) == 0]
    seen = 0
    while ready:
        n = ready.pop()
        seen += 1
        here = best.get(n, durations.get(n, 0.0))
        if n not in best:
            best[n] = here
            prev.setdefault(n, None)
        for d in succs.get(n, ()):
            cand = here + ew.get((n, d), 0.0) + durations.get(d, 0.0)
            if cand > best.get(d, float("-inf")):
                best[d] = cand
                prev[d] = n
            indeg[d] -= 1
            if indeg[d] == 0:
                ready.append(d)
    if seen != len(nodes):
        raise ValueError("critical_path: graph has a cycle")
    if not best:
        return 0.0, []
    end = max(best, key=lambda n: best[n])
    path: list[Hashable] = []
    cur: Hashable | None = end
    while cur is not None:
        path.append(cur)
        cur = prev.get(cur)
    path.reverse()
    return best[end], path


def stitch_ranks(
    rank_durations: Mapping[int, Mapping[Hashable, float]],
    rank_edges: Mapping[int, Iterable[tuple[Hashable, Hashable]]],
    channel_edges: Iterable[
        tuple[tuple[int, Hashable], tuple[int, Hashable], float]
    ] = (),
) -> tuple[float, list[tuple[int, Hashable]]]:
    """Cross-rank critical path: each rank's exec DAG plus exchange-
    channel edges ``((src_rank, src_node), (dst_rank, dst_node), wait)``
    stitched into one graph over ``(rank, node)`` ids — the fleet-wide
    answer to "which operator chain gates the lockstep tick"."""
    durations: dict[tuple[int, Hashable], float] = {}
    edges: list[tuple[tuple[int, Hashable], tuple[int, Hashable]]] = []
    weights: dict[tuple, float] = {}
    for rank, durs in rank_durations.items():
        for n, d in durs.items():
            durations[(rank, n)] = d
    for rank, es in rank_edges.items():
        for s, d in es:
            edges.append(((rank, s), (rank, d)))
    for src, dst, wait in channel_edges:
        edges.append((src, dst))
        weights[(src, dst)] = float(wait)
    return critical_path(durations, edges, weights)


# ---------------------------------------------------------------------------
# flight recorder


class TickRecord:
    """One recorded tick: ``entries`` is a list of
    ``(node_id, start_ns, end_ns, rows_in, rows_out, compiled)`` tuples
    in completion order (``start_ns``/``end_ns`` are perf_counter_ns —
    monotonic, comparable only within this process)."""

    __slots__ = ("t", "tick_ns", "start_ns", "entries")

    def __init__(self, t: int, tick_ns: int, start_ns: int, entries: list):
        self.t = t
        self.tick_ns = tick_ns
        self.start_ns = start_ns
        self.entries = entries


class TickScope:
    """Per-runtime flight recorder. The runtime calls ``begin_tick`` /
    ``end_tick`` around its tick and appends entry tuples between them;
    everything else (critical path, snapshots, traces) reads the ring."""

    def __init__(self, ring: int | None = None, enabled: bool | None = None):
        self.enabled = enabled_from_env() if enabled is None else enabled
        self.ring: deque[TickRecord] = deque(
            maxlen=ring if ring is not None else _ring_size()
        )
        self.ticks_recorded = 0
        self.compiled_entries = 0
        self.interpreted_entries = 0
        self._names: dict[int, str] = {}
        self._edges: list[tuple[int, int]] = []
        self._channels: list[str] = []
        self._runtime: weakref.ref | None = None
        self._cur: list | None = None
        self._cur_t = 0
        self._cur_t0 = 0

    # --- runtime hooks (hot path) --------------------------------------

    def attach(self, runtime) -> None:
        """Capture the exec DAG (names + edges) the records refer to and
        register the runtime's exec memory ledger as a provider."""
        self._runtime = weakref.ref(runtime)
        self._names = {
            n.id: f"{type(n).__name__}_{n.id}" for n in runtime.order
        }
        self._edges = [
            (inp.id, n.id) for n in runtime.order for inp in n.inputs
        ]
        self._channels = sorted(
            {
                getattr(ex, "channel", None)
                for ex in runtime.execs.values()
                if getattr(ex, "channel", None)
            }
            - {None}
        ) if runtime.execs else []
        _runtimes.add(self)
        rref = self._runtime

        def _runtime_memory(deep: bool = False) -> dict[str, int]:
            rt = rref()
            if rt is None:
                return {}
            parts: dict[str, int] = {}
            for nid, ex in rt.execs.items():
                led = exec_memory_ledger(ex, deep=deep)
                name = self._names.get(nid, str(nid))
                for part, nbytes in led.items():
                    if nbytes:
                        parts[f"{name}/{part}"] = nbytes
            return parts

        register_memory_provider("runtime", _runtime_memory)

    def begin_tick(self, t: int) -> list | None:
        """Returns the per-tick entry list (or None when disabled — the
        caller's only obligation is one ``is None`` check per node)."""
        if not self.enabled:
            return None
        self._cur = []
        self._cur_t = t
        self._cur_t0 = time.perf_counter_ns()
        return self._cur

    def end_tick(self, entries: list | None, tick_ns: int) -> None:
        if entries is None or entries is not self._cur:
            return
        self._cur = None
        if not entries and self.ticks_recorded:
            return  # idle autocommit tick: nothing to attribute
        self.ticks_recorded += 1
        for e in entries:
            if e[5]:
                self.compiled_entries += 1
            else:
                self.interpreted_entries += 1
        self.ring.append(
            TickRecord(self._cur_t, tick_ns, self._cur_t0, entries)
        )

    # --- read side ------------------------------------------------------

    def last(self) -> TickRecord | None:
        return self.ring[-1] if self.ring else None

    def records(self) -> list[TickRecord]:
        return list(self.ring)

    def record_critical_path(
        self, rec: TickRecord
    ) -> tuple[float, list[int]]:
        """Critical path of one recorded tick over the attached exec DAG
        (node durations in seconds; edges from the runtime topology)."""
        durations = {
            e[0]: (e[2] - e[1]) / 1e9 for e in rec.entries
        }
        edges = [
            (s, d) for s, d in self._edges if s in durations or d in durations
        ]
        total, path = critical_path(durations, edges)
        return total, [n for n in path if n in durations]

    def operator_rollup(self, n_ticks: int | None = None) -> dict[str, dict]:
        """Per-exec totals over the trailing ``n_ticks`` records: wall
        seconds, rows in/out, compiled vs interpreted tick counts."""
        recs = self.records()
        if n_ticks is not None:
            recs = recs[-n_ticks:]
        out: dict[str, dict] = {}
        for rec in recs:
            for nid, t0, t1, rin, rout, compiled in rec.entries:
                name = self._names.get(nid, str(nid))
                d = out.setdefault(
                    name,
                    {
                        "wall_s": 0.0,
                        "rows_in": 0,
                        "rows_out": 0,
                        "compiled_ticks": 0,
                        "interpreted_ticks": 0,
                    },
                )
                d["wall_s"] += (t1 - t0) / 1e9
                d["rows_in"] += rin
                d["rows_out"] += rout
                d["compiled_ticks" if compiled else "interpreted_ticks"] += 1
        return out

    def snapshot(
        self, *, ticks: int = 1, deep: bool = False
    ) -> dict[str, Any]:
        """The ``/debug/tick`` body: last-tick anatomy + rollup + memory
        ledger + roofline + wire channels."""
        doc: dict[str, Any] = {
            "enabled": self.enabled,
            "ticks_recorded": self.ticks_recorded,
            "ring": len(self.ring),
            "compiled_entries": self.compiled_entries,
            "interpreted_entries": self.interpreted_entries,
        }
        last = self.last()
        if last is not None:
            ops = []
            for nid, t0, t1, rin, rout, compiled in last.entries:
                ops.append(
                    {
                        "node": self._names.get(nid, str(nid)),
                        "wall_ms": round((t1 - t0) / 1e6, 6),
                        "start_ms": round((t0 - last.start_ns) / 1e6, 6),
                        "rows_in": rin,
                        "rows_out": rout,
                        "compiled": bool(compiled),
                    }
                )
            cp_total, cp_path = self.record_critical_path(last)
            ran = {e[0] for e in last.entries}
            doc["last"] = {
                "t": last.t,
                "wall_ms": round(last.tick_ns / 1e6, 6),
                "operators": ops,
                # dependency edges among the operators that ran, by name
                # — what fleet.federate_ticks stitches cross-rank
                "edges": [
                    [self._names.get(s, str(s)), self._names.get(d, str(d))]
                    for s, d in self._edges
                    if s in ran and d in ran
                ],
                "critical_path": {
                    "total_ms": round(cp_total * 1e3, 6),
                    "stages": [
                        self._names.get(n, str(n)) for n in cp_path
                    ],
                    "coverage": round(
                        cp_total / max(last.tick_ns / 1e9, 1e-12), 4
                    ),
                },
            }
        if ticks > 1:
            doc["rollup"] = self.operator_rollup(ticks)
        doc["memory"] = memory_snapshot(deep=deep)
        doc["roofline"] = roofline().snapshot()
        doc["wire"] = wire_snapshot()
        return doc

    def chrome_trace(self, n_ticks: int | None = None) -> dict:
        """The ring as Chrome trace-event JSON with ONE track per exec
        (tid = node id, named via thread_name metadata) — load in
        Perfetto next to ``/debug/trace`` output; both use the same
        anchored monotonic clock as observability/tracing.py."""
        from pathway_tpu.observability.tracing import _ANCHOR_NS

        events: list[dict] = []
        pid = os.getpid()
        seen_tids: set[int] = set()
        recs = self.records()
        if n_ticks is not None:
            recs = recs[-n_ticks:]
        for rec in recs:
            for nid, t0, t1, rin, rout, compiled in rec.entries:
                if nid not in seen_tids:
                    seen_tids.add(nid)
                    events.append(
                        {
                            "ph": "M",
                            "name": "thread_name",
                            "pid": pid,
                            "tid": nid,
                            "ts": 0,
                            "args": {
                                "name": self._names.get(nid, str(nid))
                            },
                        }
                    )
                events.append(
                    {
                        "ph": "X",
                        "name": self._names.get(nid, str(nid)),
                        "cat": "tickscope",
                        "pid": pid,
                        "tid": nid,
                        "ts": (_ANCHOR_NS + t0) / 1e3,
                        "dur": max((t1 - t0) / 1e3, 0.001),
                        "args": {
                            "t": rec.t,
                            "rows_in": rin,
                            "rows_out": rout,
                            "compiled": bool(compiled),
                        },
                    }
                )
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# every live recorder (weak — a GC'd runtime drops out): the doctor rule
# and the monitoring server read "the" recorder as the newest attached
_runtimes: "weakref.WeakSet[TickScope]" = weakref.WeakSet()
_last_recorder: weakref.ref | None = None


def make_recorder(runtime) -> TickScope:
    """Build + attach the per-runtime recorder (engine/runtime.py)."""
    global _last_recorder
    scope = TickScope()
    scope.attach(runtime)
    _last_recorder = weakref.ref(scope)
    return scope


def recorder() -> TickScope | None:
    """The most recently attached runtime's recorder, if still alive."""
    return _last_recorder() if _last_recorder is not None else None


# ---------------------------------------------------------------------------
# memory ledger

_mem_lock = threading.Lock()
_mem_providers: dict[str, Callable[[], dict[str, int]]] = {}


def register_memory_provider(
    owner: str, fn: Callable[[], dict[str, int]]
) -> None:
    """Register (or replace) a resident-bytes provider: ``fn()`` returns
    ``{part: bytes}``. Providers are pulled at scrape/snapshot time —
    they must be cheap and must not raise (exceptions are swallowed)."""
    with _mem_lock:
        _mem_providers[owner] = fn
    _tickscope_metrics()  # arm the collector on first provider


def unregister_memory_provider(owner: str) -> None:
    with _mem_lock:
        _mem_providers.pop(owner, None)


def memory_snapshot(deep: bool = False) -> dict[str, Any]:
    """All providers' parts + the top resident-byte owners.

    ``deep`` is reserved for providers that expose a costlier exact
    accounting (monolith pickle sizes); the registered callables decide
    what it means — the default pull never pickles."""
    with _mem_lock:
        providers = dict(_mem_providers)
    owners: dict[str, dict[str, int]] = {}
    for owner, fn in providers.items():
        try:
            parts = fn(deep) if _takes_deep(fn) and deep else fn()
        except Exception:
            continue
        if parts:
            owners[owner] = {k: int(v) for k, v in parts.items()}
    flat = [
        (f"{owner}/{part}", nbytes)
        for owner, parts in owners.items()
        for part, nbytes in parts.items()
    ]
    flat.sort(key=lambda kv: -kv[1])
    return {
        "owners": owners,
        "total_bytes": sum(b for _, b in flat),
        "top": flat[:10],
    }


def _takes_deep(fn) -> bool:
    try:
        import inspect

        return "deep" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def exec_memory_ledger(ex, deep: bool = False) -> dict[str, int]:
    """Resident-bytes parts of one exec. Prefers the exec's own
    ``memory_ledger`` (GroupByExec names its dict/ledger doubling);
    falls back to walking ``__dict__`` for Arrangement attributes.
    ``deep`` adds the monolith-pickle size for execs WITHOUT
    arranged_state — the exact number the snapshot-coverage rule and
    the ROADMAP's "kill the last pickle" item argue about."""
    led = getattr(ex, "memory_ledger", None)
    parts: dict[str, int] = {}
    if callable(led):
        try:
            parts = dict(led(deep=deep) or {})
        except Exception:
            parts = {}
    if not parts:
        from pathway_tpu.engine.arrangement import Arrangement

        for k, v in getattr(ex, "__dict__", {}).items():
            if isinstance(v, Arrangement):
                parts[f"arrangement:{k}"] = v.resident_bytes()
    if deep and "monolith_pickle" not in parts:
        try:
            if getattr(ex, "arranged_state", lambda: None)() is None:
                state = getattr(ex, "state_dict", lambda: None)()
                if state:
                    import pickle

                    parts["monolith_pickle"] = len(
                        pickle.dumps(
                            state, protocol=pickle.HIGHEST_PROTOCOL
                        )
                    )
        except Exception:
            pass
    return parts


# ---------------------------------------------------------------------------
# wire byte taps (parallel/host_exchange.py, parallel/replicate.py)

_wire_lock = threading.Lock()
_wire: dict[str, dict[str, int]] = {}


def wire_tap(
    channel: str, wire_bytes: int, raw_bytes: int = 0, rows: int = 0
) -> None:
    """Account one encoded data frame against its exchange channel.
    Called from the mesh sender threads — off the tick hot loop, so a
    small lock is fine here."""
    with _wire_lock:
        d = _wire.setdefault(
            channel, {"wire_bytes": 0, "raw_bytes": 0, "rows": 0, "frames": 0}
        )
        d["wire_bytes"] += int(wire_bytes)
        d["raw_bytes"] += int(raw_bytes)
        d["rows"] += int(rows)
        d["frames"] += 1
    m = _tickscope_metrics()
    m[1].labels(channel).inc(int(wire_bytes))
    if rows:
        m[2].labels(channel).inc(int(rows))


def wire_snapshot() -> dict[str, dict[str, int]]:
    with _wire_lock:
        return {ch: dict(d) for ch, d in _wire.items()}


# ---------------------------------------------------------------------------
# roofline attribution

# peak FLOP/s per jax platform when PATHWAY_PEAK_FLOPS is unset. TPU
# numbers are the published per-chip bf16 peaks; the CPU entry is a
# deliberately crude per-core estimate (2 GHz x 2 FMA x 8 f32 lanes) —
# set PATHWAY_PEAK_FLOPS for honest CPU MFU, the *achieved* FLOP/s
# column is measured either way.
_PEAK_TABLE = {
    "tpu v4": 275e12,
    "tpu v5e": 197e12,
    "tpu v5p": 459e12,
    "tpu v6e": 918e12,
}
_CPU_CORE_PEAK = 32e9


def peak_flops() -> float:
    env = os.environ.get("PATHWAY_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform == "tpu":
            kind = getattr(dev, "device_kind", "").lower()
            for name, peak in _PEAK_TABLE.items():
                if name.replace("tpu ", "") in kind:
                    return peak
            return 275e12  # unknown TPU: v4 as the conservative floor
    except Exception:
        pass
    return float(os.cpu_count() or 1) * _CPU_CORE_PEAK


def estimate_program_cost(fn, *args, **kwargs) -> tuple[float, float]:
    """(flops, bytes_accessed) per call of a jitted ``fn`` at these
    (abstract or concrete) arguments, from XLA cost analysis. Works on
    the CPU backend today — the accounting is platform-independent.
    Raises on functions without a ``lower`` method or when the backend
    returns no cost model."""
    lowered = fn.lower(*args, **kwargs)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        raise TypeError(f"unusable cost analysis: {type(cost)}")
    return (
        float(cost.get("flops", 0.0)),
        float(cost.get("bytes accessed", 0.0)),
    )


class Roofline:
    """Per-family FLOP ledger: programs register once per (family, key)
    with their per-call FLOP estimate; every execution observes wall
    time; MFU = (sum flops / sum wall) / peak."""

    def __init__(self):
        self._lock = threading.Lock()
        # family -> key -> {flops, bytes, calls, wall_s}
        self._programs: dict[str, dict[str, dict]] = {}

    def register(
        self,
        family: str,
        key: str,
        flops: float,
        bytes_accessed: float = 0.0,
        source: str = "cost_analysis",
    ) -> None:
        with self._lock:
            fam = self._programs.setdefault(family, {})
            p = fam.setdefault(
                key,
                {
                    "flops": 0.0,
                    "bytes": 0.0,
                    "calls": 0,
                    "wall_s": 0.0,
                    "source": source,
                },
            )
            p["flops"] = float(flops)
            p["bytes"] = float(bytes_accessed)
            p["source"] = source

    def known(self, family: str, key: str) -> bool:
        with self._lock:
            return key in self._programs.get(family, {})

    def observe(self, family: str, key: str, wall_s: float) -> None:
        with self._lock:
            fam = self._programs.setdefault(family, {})
            p = fam.setdefault(
                key,
                {
                    "flops": 0.0,
                    "bytes": 0.0,
                    "calls": 0,
                    "wall_s": 0.0,
                    "source": "unregistered",
                },
            )
            p["calls"] += 1
            p["wall_s"] += float(wall_s)
        _tickscope_metrics()[5].labels(family).observe(float(wall_s))

    def snapshot(self) -> dict[str, dict]:
        peak = peak_flops()
        out: dict[str, dict] = {}
        with self._lock:
            for family, fam in self._programs.items():
                flops_total = sum(
                    p["flops"] * p["calls"] for p in fam.values()
                )
                wall_total = sum(p["wall_s"] for p in fam.values())
                calls = sum(p["calls"] for p in fam.values())
                achieved = flops_total / wall_total if wall_total > 0 else 0.0
                out[family] = {
                    "programs": len(fam),
                    "calls": calls,
                    "flops_total": flops_total,
                    "wall_s": round(wall_total, 6),
                    "achieved_flops_s": achieved,
                    "peak_flops_s": peak,
                    "mfu": achieved / peak if peak > 0 else 0.0,
                }
        return out

    def samples(self, family: str) -> int:
        with self._lock:
            return sum(
                p["calls"] for p in self._programs.get(family, {}).values()
            )


_roofline = Roofline()


def roofline() -> Roofline:
    return _roofline


# ---------------------------------------------------------------------------
# doctor-rule feed (analysis/plane.py `tickscope-coverage`)

_serving_active = False


def mark_serving(active: bool = True) -> None:
    """Serving surfaces (serving/replica.py) flip this so the plane
    doctor can see a replica running with the recorder off."""
    global _serving_active
    _serving_active = bool(active)


def coverage_status() -> dict[str, Any]:
    """What the `tickscope-coverage` plane rule reads: is the recorder
    enabled, is anything serving, did any compiled plane run, and how
    many roofline samples each family has."""
    compiled_ticks = 0
    for scope in list(_runtimes):
        rt = scope._runtime() if scope._runtime is not None else None
        plan = getattr(rt, "compiled_plan", None) if rt is not None else None
        if plan is not None:
            compiled_ticks += sum(
                s.compiled_ticks for s in plan.segments
            )
    return {
        "recorder_enabled": enabled_from_env(),
        "serving_active": _serving_active
        or any(o.startswith(("replica", "serving")) for o in _mem_providers),
        "compiled_ticks": compiled_ticks,
        "roofline_samples": {
            family: _roofline.samples(family)
            for family in ("compiled_tick", "topk", "paged_attention")
        },
    }


def reset() -> None:
    """Test hook: drop providers, wire counters, roofline state and the
    serving flag (registry metric families persist — they are process-
    global counters like every other family)."""
    global _roofline, _serving_active, _last_recorder
    with _mem_lock:
        _mem_providers.clear()
    with _wire_lock:
        _wire.clear()
    _roofline = Roofline()
    _serving_active = False
    _last_recorder = None
