"""Fleet Lens SLO signals — bounded time-series rings sampled from the
process's own :class:`MetricsRegistry`, with rolling burn rates against
declared SLO targets.

The exposition endpoint (`/metrics`) answers "what is the value NOW";
the autoscaler the ROADMAP promises needs "what has it been DOING" —
shed rate climbing, WFQ backlog draining, staleness recovering after a
takeover.  This module is that feed, shipped one PR early: a per-process
sampler snapshots the key SLO series on a fixed cadence
(``PATHWAY_SIGNALS_INTERVAL_MS``, default 1000) into bounded rings
(``PATHWAY_SIGNALS_DEPTH`` points, default 600 — ten minutes at 1 Hz)
and serves them at ``/debug/signals``.

Signal inventory (sampled from metrics that already exist — the sampler
registers nothing and never mutates the registry):

===================== ======== =====================================
signal                unit     source
===================== ======== =====================================
shed_rate             fraction Δshed / (Δshed + Δadmitted)
wfq_backlog           requests pathway_serving_queue_depth (sum)
staleness_s           seconds  pathway_replica_staleness_seconds (max)
replica_occupancy     requests pathway_router_replica_inflight +
                               pathway_serving_inflight (sum)
kv_page_occupancy     fraction pathway_generate_page_pool_occupancy (max)
tok_s                 tokens/s rate(pathway_generate_tokens_total)
ttft_p50_ms, _p99_ms  ms       pathway_generate_ttft_seconds quantiles
tick_ms               ms       pathway_last_tick_seconds × 1000
tick_p99_ms           ms       pathway_operator_tick_seconds p99 × 1000
knn_p50_ms            ms       pathway_knn_query_seconds p50 × 1000
compile_hit_rate      fraction hits / (hits + misses), cumulative
ranks                 ranks    pathway_autoscale_ranks (Flux Pilot)
===================== ======== =====================================

SLO targets are declared with ``PATHWAY_SLO_*`` env knobs (see
``SLO_KNOBS``).  For a "stay below" target the burn rate is
``window_avg / target``; for a "stay above" target it is
``target / window_avg`` — either way burn > 1.0 means the SLO is being
violated over the window (``PATHWAY_SLO_WINDOW_S``, default 60).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable

from pathway_tpu.observability.registry import REGISTRY, MetricsRegistry

_INTERVAL_ENV = "PATHWAY_SIGNALS_INTERVAL_MS"
_DEPTH_ENV = "PATHWAY_SIGNALS_DEPTH"
_ENABLE_ENV = "PATHWAY_SIGNALS"
_WINDOW_ENV = "PATHWAY_SLO_WINDOW_S"

#: knob → (signal name, direction).  direction "max" = value must stay
#: at or below the target; "min" = must stay at or above it.
SLO_KNOBS: dict[str, tuple[str, str]] = {
    "PATHWAY_SLO_SHED_RATE": ("shed_rate", "max"),
    "PATHWAY_SLO_WFQ_BACKLOG": ("wfq_backlog", "max"),
    "PATHWAY_SLO_STALENESS_S": ("staleness_s", "max"),
    "PATHWAY_SLO_REPLICA_OCCUPANCY": ("replica_occupancy", "max"),
    "PATHWAY_SLO_KV_OCCUPANCY": ("kv_page_occupancy", "max"),
    "PATHWAY_SLO_TOK_S": ("tok_s", "min"),
    "PATHWAY_SLO_TTFT_P99_MS": ("ttft_p99_ms", "max"),
    "PATHWAY_SLO_TICK_P99_MS": ("tick_p99_ms", "max"),
    "PATHWAY_SLO_KNN_P50_MS": ("knn_p50_ms", "max"),
    "PATHWAY_SLO_COMPILE_HIT_RATE": ("compile_hit_rate", "min"),
}


def slo_targets(env: dict[str, str] | None = None) -> dict[str, tuple[float, str]]:
    """Declared SLO targets: signal name → (target, direction)."""
    env = os.environ if env is None else env
    out: dict[str, tuple[float, str]] = {}
    for knob, (signal, direction) in SLO_KNOBS.items():
        raw = env.get(knob, "")
        if not raw:
            continue
        try:
            out[signal] = (float(raw), direction)
        except ValueError:
            continue
    return out


class SignalRing:
    """Bounded ring of (wall, mono, value) samples."""

    def __init__(self, depth: int):
        self._ring: deque[tuple[float, float, float]] = deque(
            maxlen=max(int(depth), 2)
        )

    def append(self, wall: float, mono: float, value: float) -> None:
        self._ring.append((wall, mono, float(value)))

    def __len__(self) -> int:
        return len(self._ring)

    def last(self) -> float | None:
        return self._ring[-1][2] if self._ring else None

    def series(self, limit: int | None = None) -> list[tuple[float, float]]:
        """[(wall, value), ...] oldest-first, optionally the last
        ``limit`` points."""
        pts = list(self._ring)
        if limit is not None:
            pts = pts[-max(int(limit), 0):]
        return [(w, v) for (w, _m, v) in pts]

    def points(self) -> list[tuple[float, float]]:
        """[(mono, value), ...] oldest-first — the monotonic series the
        Flux Pilot forecaster seeds from (rates/windows must never ride
        the wall clock; see the CLOCK CONTRACT in sample_once)."""
        return [(m, v) for (_w, m, v) in self._ring]

    def window_avg(self, seconds: float, now_mono: float | None = None) -> float | None:
        """Mean over the trailing ``seconds`` (monotonic window)."""
        if not self._ring:
            return None
        if now_mono is None:
            now_mono = self._ring[-1][1]
        vals = [v for (_w, m, v) in self._ring if now_mono - m <= seconds]
        if not vals:
            return self._ring[-1][2]
        return sum(vals) / len(vals)

    def window_max(self, seconds: float, now_mono: float | None = None) -> float | None:
        if not self._ring:
            return None
        if now_mono is None:
            now_mono = self._ring[-1][1]
        vals = [v for (_w, m, v) in self._ring if now_mono - m <= seconds]
        return max(vals) if vals else self._ring[-1][2]


# --- registry readers -------------------------------------------------------
# The sampler only READS: it never creates metrics, so arming it on a
# plane that doesn't serve/generate costs nothing but empty rings.


def _children(registry: MetricsRegistry, name: str):
    m = registry.get(name)
    if m is None:
        return []
    with m._lock:
        children = list(m._children.values())
    return children


def _counter_total(registry: MetricsRegistry, name: str) -> float | None:
    kids = _children(registry, name)
    if not kids:
        return None
    return float(sum(c.value for c in kids))


def _gauge_agg(
    registry: MetricsRegistry, name: str, agg: Callable[[list[float]], float]
) -> float | None:
    kids = _children(registry, name)
    vals: list[float] = []
    for c in kids:
        try:
            vals.append(float(c.current()))
        except Exception:
            continue
    return agg(vals) if vals else None


def _hist_quantile(registry: MetricsRegistry, name: str, q: float) -> float | None:
    """Quantile over ALL children of a histogram, merged by bucket
    counts (per-child quantiles can't be averaged)."""
    m = registry.get(name)
    if m is None:
        return None
    with m._lock:
        kids = list(m._children.values())
    if not kids:
        return None
    bounds = m.bounds
    merged = [0] * (len(bounds) + 1)
    total = 0
    for c in kids:
        for i, n in enumerate(c.counts):
            merged[i] += n
        total += c.count
    if total == 0:
        return None
    rank = q * total
    cum = 0
    lo = 0.0
    for i, n in enumerate(merged):
        if n == 0:
            if i < len(bounds):
                lo = bounds[i]
            continue
        if cum + n >= rank:
            hi = bounds[i] if i < len(bounds) else lo
            frac = (rank - cum) / n
            return lo + (hi - lo) * frac
        cum += n
        if i < len(bounds):
            lo = bounds[i]
    return lo


@dataclass(frozen=True)
class SignalDef:
    name: str
    unit: str
    #: "gauge" signals read directly; "rate"/"ratio_rate" derive from
    #: counter deltas between consecutive samples.
    compute: Callable[["SignalSampler", float], float | None]


def _sig_shed_rate(s: "SignalSampler", dt: float) -> float | None:
    d_shed = s._counter_delta("pathway_serving_shed_total")
    d_adm = s._counter_delta("pathway_serving_admitted_total")
    if d_shed is None and d_adm is None:
        return None
    shed = d_shed or 0.0
    adm = d_adm or 0.0
    if shed + adm <= 0:
        return 0.0
    return shed / (shed + adm)


def _sig_tok_s(s: "SignalSampler", dt: float) -> float | None:
    d = s._counter_delta("pathway_generate_tokens_total")
    if d is None or dt <= 0:
        return None
    return d / dt


def _sig_compile_hit_rate(s: "SignalSampler", dt: float) -> float | None:
    hits = _counter_total(s.registry, "pathway_engine_compile_cache_hits_total")
    misses = _counter_total(s.registry, "pathway_engine_compile_cache_misses_total")
    if hits is None and misses is None:
        return None
    h = hits or 0.0
    m = misses or 0.0
    if h + m <= 0:
        return None
    return h / (h + m)


SIGNALS: tuple[SignalDef, ...] = (
    SignalDef("shed_rate", "fraction", _sig_shed_rate),
    SignalDef(
        "wfq_backlog",
        "requests",
        lambda s, dt: _gauge_agg(s.registry, "pathway_serving_queue_depth", sum),
    ),
    SignalDef(
        "staleness_s",
        "seconds",
        lambda s, dt: _gauge_agg(
            s.registry, "pathway_replica_staleness_seconds", max
        ),
    ),
    SignalDef(
        "replica_occupancy",
        "requests",
        lambda s, dt: _sum_non_none(
            _gauge_agg(s.registry, "pathway_router_replica_inflight", sum),
            _gauge_agg(s.registry, "pathway_serving_inflight", sum),
        ),
    ),
    SignalDef(
        "kv_page_occupancy",
        "fraction",
        lambda s, dt: _gauge_agg(
            s.registry, "pathway_generate_page_pool_occupancy", max
        ),
    ),
    SignalDef("tok_s", "tokens/s", _sig_tok_s),
    SignalDef(
        "ttft_p50_ms",
        "ms",
        lambda s, dt: _scale(
            _hist_quantile(s.registry, "pathway_generate_ttft_seconds", 0.5), 1e3
        ),
    ),
    SignalDef(
        "ttft_p99_ms",
        "ms",
        lambda s, dt: _scale(
            _hist_quantile(s.registry, "pathway_generate_ttft_seconds", 0.99), 1e3
        ),
    ),
    SignalDef(
        "tick_ms",
        "ms",
        lambda s, dt: _scale(
            _gauge_agg(s.registry, "pathway_last_tick_seconds", max), 1e3
        ),
    ),
    SignalDef(
        "tick_p99_ms",
        "ms",
        lambda s, dt: _scale(
            _hist_quantile(s.registry, "pathway_operator_tick_seconds", 0.99), 1e3
        ),
    ),
    SignalDef(
        "knn_p50_ms",
        "ms",
        lambda s, dt: _scale(
            _hist_quantile(s.registry, "pathway_knn_query_seconds", 0.5), 1e3
        ),
    ),
    SignalDef("compile_hit_rate", "fraction", _sig_compile_hit_rate),
    # Flux Pilot (autoscale/): the controller's own rank count, ringed
    # so scaling history rides the same /debug/signals feed the inputs
    # do — a burn spike lines up against the resize that answered it
    SignalDef(
        "ranks",
        "ranks",
        lambda s, dt: _gauge_agg(s.registry, "pathway_autoscale_ranks", max),
    ),
)


def _scale(v: float | None, k: float) -> float | None:
    return None if v is None else v * k


def _sum_non_none(*vals: float | None) -> float | None:
    present = [v for v in vals if v is not None]
    return sum(present) if present else None


_COUNTER_SOURCES = (
    "pathway_serving_shed_total",
    "pathway_serving_admitted_total",
    "pathway_generate_tokens_total",
)


class SignalSampler:
    """Samples the signal inventory from ``registry`` on a fixed cadence
    into per-signal :class:`SignalRing` rings and computes SLO burn
    rates.  ``sample_once()`` is public so tests and benches can drive
    it deterministically without the thread."""

    def __init__(
        self,
        interval_s: float | None = None,
        depth: int | None = None,
        registry: MetricsRegistry = REGISTRY,
    ):
        if interval_s is None:
            try:
                interval_s = (
                    float(os.environ.get(_INTERVAL_ENV, "1000") or 1000) / 1000.0
                )
            except ValueError:
                interval_s = 1.0
        if depth is None:
            try:
                depth = int(os.environ.get(_DEPTH_ENV, "600") or 600)
            except ValueError:
                depth = 600
        try:
            self.window_s = float(os.environ.get(_WINDOW_ENV, "60") or 60)
        except ValueError:
            self.window_s = 60.0
        self.interval_s = max(float(interval_s), 0.05)
        self.depth = max(int(depth), 2)
        self.registry = registry
        self.rings: dict[str, SignalRing] = {
            d.name: SignalRing(self.depth) for d in SIGNALS
        }
        self._units = {d.name: d.unit for d in SIGNALS}
        self._prev_counters: dict[str, float] = {}
        self._pending_deltas: dict[str, float | None] = {}
        self._samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- counter-delta bookkeeping (one snapshot per sample_once) ---------

    def _counter_delta(self, name: str) -> float | None:
        return self._pending_deltas.get(name)

    def _snap_counters(self) -> None:
        for name in _COUNTER_SOURCES:
            cur = _counter_total(self.registry, name)
            if cur is None:
                self._pending_deltas[name] = None
                continue
            prev = self._prev_counters.get(name)
            self._prev_counters[name] = cur
            if prev is None:
                self._pending_deltas[name] = None
            else:
                # counter reset (registry.clear in tests) → treat as fresh
                self._pending_deltas[name] = max(cur - prev, 0.0)

    # -- sampling ---------------------------------------------------------

    def sample_once(self) -> None:
        """Take one snapshot of every signal (never raises)."""
        # CLOCK CONTRACT (PR-18 audit): `wall` is display-only (the
        # timestamp shown in /debug/signals and the journal); every
        # rate/window/burn computation below uses `mono` deltas, so a
        # stepped or frozen wall clock cannot distort a signal — see
        # the frozen-wall-clock regression test in tests/test_tickscope.py
        wall = time.time()
        mono = time.monotonic()
        with self._lock:
            dt = self.interval_s if self._samples else 0.0
            if self._samples:
                last = next(
                    (r._ring[-1][1] for r in self.rings.values() if r._ring),
                    None,
                )
                if last is not None:
                    dt = max(mono - last, 1e-9)
            self._snap_counters()
            for d in SIGNALS:
                try:
                    v = d.compute(self, dt)
                except Exception:
                    v = None
                if v is not None:
                    self.rings[d.name].append(wall, mono, v)
            self._samples += 1

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                pass

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pathway-signal-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- burn rates and snapshot -----------------------------------------

    def burn_rates(self) -> dict[str, dict[str, Any]]:
        """signal → {target, direction, window_avg, burn}.  burn > 1.0
        means the SLO is being violated over the trailing window."""
        targets = slo_targets()
        out: dict[str, dict[str, Any]] = {}
        now_mono = time.monotonic()
        for signal, (target, direction) in targets.items():
            ring = self.rings.get(signal)
            avg = ring.window_avg(self.window_s, now_mono) if ring else None
            burn: float | None = None
            if avg is not None and target > 0:
                if direction == "max":
                    burn = avg / target
                else:
                    burn = target / avg if avg > 0 else float("inf")
            out[signal] = {
                "target": target,
                "direction": direction,
                "window_avg": avg,
                "burn": burn,
            }
        return out

    def snapshot(self, series_points: int = 0) -> dict[str, Any]:
        """JSON-able state for ``/debug/signals``.  ``series_points`` > 0
        includes the trailing N ring points per signal."""
        with self._lock:
            sigs: dict[str, Any] = {}
            for name, ring in self.rings.items():
                entry: dict[str, Any] = {
                    "unit": self._units[name],
                    "last": ring.last(),
                    "n": len(ring),
                    "window_avg": ring.window_avg(self.window_s),
                }
                if series_points > 0:
                    entry["series"] = [
                        [round(w, 6), v] for (w, v) in ring.series(series_points)
                    ]
                sigs[name] = entry
            samples = self._samples
        return {
            "interval_s": self.interval_s,
            "depth": self.depth,
            "window_s": self.window_s,
            "samples": samples,
            "running": self._thread is not None and self._thread.is_alive(),
            "slo": self.burn_rates(),
            "signals": sigs,
        }


# --- process-global sampler -------------------------------------------------

_sampler: SignalSampler | None = None
_sampler_lock = threading.Lock()


def signals_enabled() -> bool:
    return os.environ.get(_ENABLE_ENV, "1") not in ("0", "false", "no", "off")


def arm_sampler(start: bool = True) -> SignalSampler | None:
    """Create (and by default start) the process-global sampler.
    Returns None when disabled via ``PATHWAY_SIGNALS=0``."""
    global _sampler
    if not signals_enabled():
        return None
    with _sampler_lock:
        if _sampler is None:
            _sampler = SignalSampler()
    if start:
        _sampler.start()
    return _sampler


def get_sampler() -> SignalSampler | None:
    """The process-global sampler, or None if never armed."""
    return _sampler


def reset_sampler() -> None:
    """Test hook: stop and forget the process-global sampler."""
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            try:
                _sampler.stop()
            except Exception:
                pass
        _sampler = None
