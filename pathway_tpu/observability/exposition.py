"""Prometheus text-exposition (format 0.0.4) parser + validator.

The in-repo contract check for the `/metrics` endpoint: tier-1 scrapes
the monitoring server end-to-end and feeds the body through
``validate_exposition``, which enforces the conventions a real
Prometheus server (and promtool) would care about — sample syntax,
metric/label naming, one TYPE line per family, counters ending in
``_total``, histogram bucket monotonicity and ``_count``/``+Inf``
consistency, no duplicate (name, labelset) samples.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_VALUE_RE = re.compile(
    r"^(?:[+-]?(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][+-]?\d+)?|[+-]?Inf|NaN)$"
)


@dataclass
class Sample:
    name: str
    labels: dict[str, str]
    value: float
    line_no: int


@dataclass
class Family:
    name: str
    type: str = "untyped"
    help: str | None = None
    samples: list[Sample] = field(default_factory=list)


def _parse_labels(raw: str, line_no: int, errors: list[str]) -> dict[str, str]:
    """Parse `a="b",c="d"` honoring \\\\, \\" and \\n escapes."""
    labels: dict[str, str] = {}
    i = 0
    n = len(raw)
    while i < n:
        m = re.match(r"\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*\"", raw[i:])
        if not m:
            errors.append(f"line {line_no}: malformed label pair at {raw[i:]!r}")
            return labels
        lname = m.group(1)
        i += m.end()
        buf = []
        while i < n:
            ch = raw[i]
            if ch == "\\":
                if i + 1 >= n:
                    errors.append(f"line {line_no}: dangling escape")
                    return labels
                nxt = raw[i + 1]
                if nxt == "n":
                    buf.append("\n")
                elif nxt in ('"', "\\"):
                    buf.append(nxt)
                else:
                    errors.append(
                        f"line {line_no}: invalid escape \\{nxt} in label "
                        f"{lname!r}"
                    )
                    buf.append(nxt)
                i += 2
            elif ch == '"':
                i += 1
                break
            elif ch == "\n":
                errors.append(f"line {line_no}: raw newline in label value")
                return labels
            else:
                buf.append(ch)
                i += 1
        else:
            errors.append(f"line {line_no}: unterminated label value")
            return labels
        if lname in labels:
            errors.append(f"line {line_no}: duplicate label {lname!r}")
        labels[lname] = "".join(buf)
        rest = raw[i:].lstrip()
        if rest.startswith(","):
            i = n - len(rest) + 1
        elif rest == "":
            break
        else:
            errors.append(f"line {line_no}: junk after label value: {rest!r}")
            return labels
    return labels


def _base_family(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def parse_exposition(
    text: str,
) -> tuple[dict[str, Family], list[str]]:
    """Parse exposition text into families; returns (families, errors)."""
    errors: list[str] = []
    families: dict[str, Family] = {}
    typed: dict[str, str] = {}

    def family_for(sample_name: str) -> Family:
        base = _base_family(sample_name)
        # _bucket/_sum/_count fold into the histogram family only when one
        # was declared; otherwise the sample is its own (untyped) family
        if base in typed and typed[base] in ("histogram", "summary"):
            key = base
        else:
            key = sample_name
        fam = families.get(key)
        if fam is None:
            fam = families[key] = Family(key)
            fam.type = typed.get(key, "untyped")
        return fam

    for line_no, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) < 3 or (len(parts) < 4 and parts[1] == "TYPE"):
                errors.append(f"line {line_no}: malformed {parts[1]} line")
                continue
            kind, mname = parts[1], parts[2]
            if not _NAME_RE.match(mname):
                errors.append(
                    f"line {line_no}: invalid metric name {mname!r}"
                )
                continue
            if kind == "TYPE":
                mtype = parts[3].strip()
                if mtype not in (
                    "counter", "gauge", "histogram", "summary", "untyped"
                ):
                    errors.append(
                        f"line {line_no}: unknown type {mtype!r} for {mname}"
                    )
                if mname in typed:
                    errors.append(
                        f"line {line_no}: duplicate TYPE line for {mname}"
                    )
                typed[mname] = mtype
                fam = families.get(mname)
                if fam is None:
                    families[mname] = Family(mname, type=mtype)
                else:
                    if fam.samples:
                        errors.append(
                            f"line {line_no}: TYPE for {mname} after its "
                            "samples"
                        )
                    fam.type = mtype
            else:
                helptext = parts[3] if len(parts) > 3 else ""
                fam = families.setdefault(mname, Family(mname))
                if fam.help is not None:
                    errors.append(
                        f"line {line_no}: duplicate HELP line for {mname}"
                    )
                fam.help = helptext
            continue
        if line.startswith("#"):
            continue  # plain comment
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+(-?\d+))?\s*$", line)
        if not m:
            errors.append(f"line {line_no}: unparseable sample: {line!r}")
            continue
        name, rawlabels, rawvalue = m.group(1), m.group(2), m.group(3)
        labels = (
            _parse_labels(rawlabels, line_no, errors) if rawlabels else {}
        )
        for ln in labels:
            if not _LABEL_RE.match(ln):
                errors.append(f"line {line_no}: invalid label name {ln!r}")
        if not _VALUE_RE.match(rawvalue):
            errors.append(f"line {line_no}: invalid value {rawvalue!r}")
            continue
        value = float(rawvalue.replace("Inf", "inf"))
        family_for(name).samples.append(Sample(name, labels, value, line_no))
    return families, errors


def validate_exposition(text: str) -> list[str]:
    """Full conformance check; returns a list of violations (empty = ok)."""
    families, errors = parse_exposition(text)
    seen_samples: set[tuple[str, tuple[tuple[str, str], ...]]] = set()
    for fam in families.values():
        for s in fam.samples:
            key = (s.name, tuple(sorted(s.labels.items())))
            if key in seen_samples:
                errors.append(
                    f"line {s.line_no}: duplicate sample {s.name} "
                    f"{dict(key[1])}"
                )
            seen_samples.add(key)
        if fam.type == "counter":
            if not fam.name.endswith("_total"):
                errors.append(
                    f"counter {fam.name} should end in _total"
                )
            for s in fam.samples:
                if s.value < 0:
                    errors.append(
                        f"line {s.line_no}: counter {fam.name} is negative"
                    )
        if fam.type == "histogram":
            errors.extend(_check_histogram(fam))
    return errors


def _check_histogram(fam: Family) -> list[str]:
    errors: list[str] = []
    by_labelset: dict[tuple, dict[str, list[Sample]]] = {}
    for s in fam.samples:
        labels = {k: v for k, v in s.labels.items() if k != "le"}
        key = tuple(sorted(labels.items()))
        slot = by_labelset.setdefault(
            key, {"bucket": [], "sum": [], "count": []}
        )
        if s.name == fam.name + "_bucket":
            slot["bucket"].append(s)
        elif s.name == fam.name + "_sum":
            slot["sum"].append(s)
        elif s.name == fam.name + "_count":
            slot["count"].append(s)
        else:
            errors.append(
                f"line {s.line_no}: unexpected sample {s.name} in "
                f"histogram {fam.name}"
            )
    for key, slot in by_labelset.items():
        label_desc = dict(key) or "{}"
        if not slot["bucket"]:
            errors.append(f"{fam.name}{label_desc}: no _bucket samples")
            continue
        if len(slot["sum"]) != 1 or len(slot["count"]) != 1:
            errors.append(
                f"{fam.name}{label_desc}: needs exactly one _sum and one "
                "_count"
            )
            continue
        buckets: list[tuple[float, float, int]] = []
        has_inf = False
        for s in slot["bucket"]:
            le = s.labels.get("le")
            if le is None:
                errors.append(
                    f"line {s.line_no}: _bucket sample without le label"
                )
                continue
            if le == "+Inf":
                has_inf = True
                bound = float("inf")
            else:
                try:
                    bound = float(le)
                except ValueError:
                    errors.append(
                        f"line {s.line_no}: unparseable le={le!r}"
                    )
                    continue
            buckets.append((bound, s.value, s.line_no))
        if not has_inf:
            errors.append(f"{fam.name}{label_desc}: missing +Inf bucket")
        buckets.sort(key=lambda b: b[0])
        prev = None
        for bound, cum, line_no in buckets:
            if prev is not None and cum < prev:
                errors.append(
                    f"line {line_no}: {fam.name}{label_desc} bucket counts "
                    f"not monotone at le={bound}"
                )
            prev = cum
        if has_inf and buckets:
            inf_count = buckets[-1][1]
            total = slot["count"][0].value
            if inf_count != total:
                errors.append(
                    f"{fam.name}{label_desc}: +Inf bucket {inf_count} != "
                    f"_count {total}"
                )
    return errors
