"""pw.demo — synthetic demo streams
(reference: python/pathway/demo/__init__.py:28-164)."""

from __future__ import annotations

import csv as _csv
import time
from typing import Any, Callable, Mapping

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.schema import schema_from_types
from pathway_tpu.internals.table import Table
from pathway_tpu.io.python import ConnectorSubject, read as python_read


def generate_custom_stream(
    value_generators: Mapping[str, Callable[[int], Any]],
    *,
    schema: Any,
    nb_rows: int | None = None,
    autocommit_duration_ms: int = 1000,
    input_rate: float = 1.0,
    persistent_id: str | None = None,
    name: str | None = None,
) -> Table:
    class StreamSubject(ConnectorSubject):
        def run(self) -> None:
            i = 0
            while nb_rows is None or i < nb_rows:
                values = {
                    name: gen(i) for name, gen in value_generators.items()
                }
                self.next(**values)
                i += 1
                if input_rate > 0:
                    time.sleep(1.0 / input_rate)

    return python_read(StreamSubject(), schema=schema, name=name)


def noisy_linear_stream(nb_rows: int = 10, input_rate: float = 1.0) -> Table:
    import random

    return generate_custom_stream(
        {
            "x": lambda i: float(i),
            "y": lambda i: float(i) + random.uniform(-1, 1),
        },
        schema=schema_from_types(x=float, y=float),
        nb_rows=nb_rows,
        input_rate=input_rate,
    )


def range_stream(
    nb_rows: int = 30,
    offset: int = 0,
    input_rate: float = 1.0,
    autocommit_duration_ms: int = 1000,
) -> Table:
    return generate_custom_stream(
        {"value": lambda i: float(i + offset)},
        schema=schema_from_types(value=float),
        nb_rows=nb_rows,
        input_rate=input_rate,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def replay_csv(
    path: str,
    *,
    schema: Any,
    input_rate: float = 1.0,
) -> Table:
    class ReplaySubject(ConnectorSubject):
        def run(self) -> None:
            with open(path, newline="") as f:
                for row in _csv.DictReader(f):
                    coerced = {}
                    for name, d in schema.dtypes().items():
                        v = row.get(name)
                        sd = d.strip_optional()
                        if sd == dt.INT:
                            coerced[name] = int(v)
                        elif sd == dt.FLOAT:
                            coerced[name] = float(v)
                        elif sd == dt.BOOL:
                            coerced[name] = str(v).lower() in ("true", "1")
                        else:
                            coerced[name] = v
                    self.next(**coerced)
                    if input_rate > 0:
                        time.sleep(1.0 / input_rate)

    return python_read(ReplaySubject(), schema=schema)


def replay_csv_with_time(path: str, *, schema: Any, time_column: str, unit: str = "s", **kw) -> Table:
    return replay_csv(path, schema=schema)
