"""pw.udfs — UDF helpers: caching, retries, executors
(reference: python/pathway/udfs.py)."""

from pathway_tpu.internals.udfs import (
    UDF,
    AsyncRetryStrategy,
    CacheStrategy,
    DefaultCache,
    DiskCache,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    InMemoryCache,
    NoRetryStrategy,
    async_executor,
    async_options,
    auto_executor,
    coerce_async,
    fully_async_executor,
    sync_executor,
    udf,
    with_cache_strategy,
    with_retry_strategy,
)

__all__ = [
    "UDF",
    "udf",
    "CacheStrategy",
    "DiskCache",
    "InMemoryCache",
    "DefaultCache",
    "AsyncRetryStrategy",
    "ExponentialBackoffRetryStrategy",
    "FixedDelayRetryStrategy",
    "NoRetryStrategy",
    "auto_executor",
    "sync_executor",
    "async_executor",
    "fully_async_executor",
    "async_options",
    "coerce_async",
    "with_cache_strategy",
    "with_retry_strategy",
]
