"""pw.debug — static fixtures & capture-based output
(reference: python/pathway/debug/__init__.py:207-709). The main unit-test
harness: markdown tables in, captured diff streams out."""

from __future__ import annotations

import re
from typing import Any, Iterable, Mapping, Sequence

import numpy as np
import pandas as _pd

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode, OutputNode
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.api import Pointer, ref_scalar, sequential_key
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


# debug fixtures with IDENTICAL key sets share one Universe object, so
# row-aligned cross-fixture expressions (select(num=t_num.num)) build,
# while differing key sets stay unrelated and raise (reference: the test
# utils' universe deduction over trusted fixture ids)
_FIXTURE_UNIVERSES: dict[frozenset, Universe] = {}


def _fixture_universe(keys: "Iterable[int]") -> Universe:
    key = frozenset(keys)
    u = _FIXTURE_UNIVERSES.get(key)
    if u is None:
        u = Universe()
        _FIXTURE_UNIVERSES[key] = u
    return u


def _fixture_universe_from_events(events: dict) -> Universe:
    """Universe keyed by the fixture's NET key set: retracted rows do not
    count, so only fixtures ending with identical keys unify."""
    net: dict[int, int] = {}
    for _t, rows in sorted(events.items()):
        for k, d, _v in rows:
            net[k] = net.get(k, 0) + d
    return _fixture_universe(k for k, c in net.items() if c > 0)


class _RowsSource(StaticSource):
    # debug fixtures are not persistable connectors: re-read fresh on every
    # run instead of being offset-suppressed/logged (reference: persistence
    # applies to sources with persistent ids only)
    transient = True

    def __init__(self, column_names, events):
        super().__init__(column_names)
        # columnarize at declare time — ingestion-to-columnar conversion is
        # I/O-layer work and must not be re-paid on every run of the graph
        self._events = [
            (t, DiffBatch.from_rows(rows, column_names)) for t, rows in events
        ]

    def events(self):
        yield from self._events


def _parse_value(s: str) -> Any:
    s = s.strip()
    if s == "":
        return None  # empty markdown cell = None (reference semantics)
    if s in ("None", "null"):
        return None
    if s == "True" or s == "true":
        return True
    if s == "False" or s == "false":
        return False
    if (s.startswith('"') and s.endswith('"')) or (
        s.startswith("'") and s.endswith("'")
    ):
        return s[1:-1]
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    if s.startswith("(") or s.startswith("["):
        import ast

        try:
            v = ast.literal_eval(s)
            if isinstance(v, list):
                return tuple(v)
            return v
        except (ValueError, SyntaxError):
            pass
    return s


def _dtype_for(values: list[Any]) -> dt.DType:
    non_null = [v for v in values if v is not None]
    if not non_null:
        return dt.ANY
    types = {type(v) for v in non_null}
    if types <= {bool}:
        out: dt.DType = dt.BOOL
    elif types <= {int, bool}:
        out = dt.INT
    elif types <= {int, float, bool}:
        out = dt.FLOAT
    elif types <= {str}:
        out = dt.STR
    elif types <= {tuple}:
        out = dt.ANY_TUPLE
    else:
        out = dt.ANY
    if len(non_null) != len(values):
        out = dt.Optional_(out)
    return out


def _split_markdown(table_def: str, require_pipes: bool = False):
    """Shared markdown tokenizer: (header, data_rows, raw_ids|None) —
    separator-row filtering, escaped-pipe splitting, edge-cell stripping
    and leading-id-column detection used by table_from_markdown and
    StreamGenerator.table_from_markdown. ``require_pipes`` rejects
    whitespace-split fallback (split_on_whitespace=False semantics)."""
    lines = [l for l in table_def.strip().splitlines() if l.strip()]
    if not lines:
        raise ValueError("table_from_markdown: empty table definition")
    # separator rows (|---|:--|) need a dash: a dashless all-empty row
    # like "   |   " is DATA — a row of Nones (reference semantics)
    lines = [
        l
        for l in lines
        if not (re.fullmatch(r"[\s|:+-]+", l) and "-" in l)
    ]
    if "|" in lines[0]:
        split = [
            [c.strip() for c in re.split(r"(?<!\\)\|", l)] for l in lines
        ]
        # "| a | b |" style: every row starts/ends with an empty cell
        if all(r and r[0] == "" for r in split):
            split = [r[1:] for r in split]
        if all(r and r[-1] == "" for r in split):
            split = [r[:-1] for r in split]
        header = split[0]
        data = split[1:]
        has_id_col = header[0] in ("", "id")
    else:
        if require_pipes:
            # single-column table: each line IS one cell (the reference's
            # split_on_whitespace=False semantics — a one-column table has
            # nothing to delimit, so full lines are the values)
            header = [lines[0].strip()]
            data = [[l] for l in lines[1:]]
            return header, data, None
        header = lines[0].split()
        if len(header) == 1:
            # single unnamed column: whole line is the value (strings with
            # spaces need no pipes)
            data = [[l.strip()] for l in lines[1:]]
        else:
            data = [l.split() for l in lines[1:]]
        has_id_col = header[0] == "id"
    ids = None
    if (
        not has_id_col
        and data
        and all(len(r) == len(header) + 1 for r in data)
    ):
        # header without a leading pipe but data rows carrying one extra
        # leading cell: that cell is the row id (reference T() accepts
        # "col | on" headers over "1 | a | 11" rows)
        has_id_col = True
    if has_id_col:
        # leading unnamed column = explicit row ids (reference style)
        if header and header[0] in ("", "id"):
            header = header[1:]
        ids = [r[0] for r in data]
        data = [r[1:] for r in data]
    return header, data, ids


def table_from_markdown(
    table_def: str,
    id_from: Sequence[str] | None = None,
    unsafe_trusted_ids: bool = False,
    schema: Any = None,
    split_on_whitespace: bool | None = None,
    _stream: bool = False,
) -> Table:
    """Parse a markdown / whitespace table. Special columns: ``__time__``
    (logical time), ``__diff__`` (+1/-1). ``split_on_whitespace=False``
    requires pipe delimiters (cells may contain spaces); the default
    auto-detects."""
    header, data, ids = _split_markdown(
        table_def, require_pipes=split_on_whitespace is False
    )
    col_names = [h for h in header if h not in ("__time__", "__diff__")]
    time_idx = header.index("__time__") if "__time__" in header else None
    diff_idx = header.index("__diff__") if "__diff__" in header else None
    if id_from is None and schema is not None:
        id_from = schema.primary_key_columns()

    events: dict[int, list] = {}
    counter = 0
    value_cols_idx = [
        i for i, h in enumerate(header) if h not in ("__time__", "__diff__")
    ]
    col_values: dict[str, list] = {n: [] for n in col_names}
    for ri, row in enumerate(data):
        parsed = [_parse_value(c) for c in row]
        t = int(parsed[time_idx]) if time_idx is not None else 0
        d = int(parsed[diff_idx]) if diff_idx is not None else 1
        vals = tuple(parsed[i] for i in value_cols_idx)
        if ids is not None:
            if unsafe_trusted_ids:
                key = int(_parse_value(ids[ri]))
            else:
                # hash the PARSED label ("1" -> int 1) so explicit markdown
                # ids match pointer_from(<value>) — the reference's id
                # derivation
                key = int(ref_scalar(_parse_value(ids[ri])))
        elif id_from:
            key = int(
                ref_scalar(*[vals[col_names.index(c)] for c in id_from])
            )
        elif unsafe_trusted_ids:
            # trusted ids: the raw row number IS the key (reference:
            # unsafe_make_pointer, ids_from_pandas:117-118)
            key = counter
        else:
            # reference derivation: unkeyed debug rows key by row number
            # through the SAME pointer hash as pointer_from(i)
            # (ids_from_pandas, reference internals/api.py:116-120)
            key = int(ref_scalar(counter))
        counter += 1
        for n, v in zip(col_names, vals):
            col_values[n].append(v)
        events.setdefault(t, []).append((key, d, vals))

    if schema is not None:
        dtypes = {n: schema.dtypes()[n] for n in col_names}
    else:
        dtypes = {n: _dtype_for(col_values[n]) for n in col_names}
    source = _RowsSource(col_names, sorted(events.items()))
    node = InputNode(source, col_names)
    return Table._from_node(
        node, dtypes, _fixture_universe_from_events(events)
    )


# reference test harness name
def T(table_def: str, **kwargs) -> Table:
    return table_from_markdown(table_def, **kwargs)


def table_from_rows(
    schema: Any,
    rows: Iterable[tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    col_names = list(schema.column_names())
    coercers = _schema_coercers(schema, col_names)
    events: dict[int, list] = {}
    for i, row in enumerate(rows):
        if is_stream:
            *vals, t, d = row
        else:
            vals, t, d = list(row), 0, 1
        vals = [c(v) for c, v in zip(coercers, vals)]
        pk = schema.primary_key_columns()
        if pk:
            key = int(ref_scalar(*[vals[col_names.index(c)] for c in pk]))
        else:
            key = int(ref_scalar(i))
        events.setdefault(int(t), []).append((key, int(d), tuple(vals)))
    source = _RowsSource(col_names, sorted(events.items()))
    node = InputNode(source, col_names)
    return Table._from_node(
        node, dict(schema.dtypes()), _fixture_universe_from_events(events)
    )


def table_from_pandas(
    df: Any,
    id_from: Sequence[str] | None = None,
    unsafe_trusted_ids: bool = False,
    schema: Any = None,
) -> Table:
    col_names = [c for c in df.columns if c not in ("__time__", "__diff__")]
    if id_from is None and schema is not None:
        # schema primary keys drive row identity, as in table_from_rows
        pk = schema.primary_key_columns()
        if pk:
            id_from = list(pk)
    coercers = (
        _schema_coercers(schema, col_names) if schema is not None else None
    )
    events: dict[int, list] = {}
    for i, (idx, row) in enumerate(df.iterrows()):
        t = int(row["__time__"]) if "__time__" in df.columns else 0
        d = int(row["__diff__"]) if "__diff__" in df.columns else 1
        vals = tuple(_np_unbox(row[c]) for c in col_names)
        if coercers is not None:
            vals = tuple(c(v) for c, v in zip(coercers, vals))
        if id_from:
            key = int(ref_scalar(*[vals[col_names.index(c)] for c in id_from]))
        else:
            # reference: keys come from the dataframe INDEX via ref_scalar
            key = int(ref_scalar(_np_unbox(idx)))
        events.setdefault(t, []).append((key, d, vals))
    if schema is not None:
        dtypes = {n: schema.dtypes()[n] for n in col_names}
    else:
        dtypes = {
            n: _dtype_for([e[2][i] for evs in events.values() for e in evs])
            for i, n in enumerate(col_names)
        }
    source = _RowsSource(col_names, sorted(events.items()))
    node = InputNode(source, col_names)
    return Table._from_node(node, dtypes, Universe())


def _np_unbox(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, _pd.Timestamp) and v.tzinfo is not None:
        # aware values are stored normalized to UTC (reference: DateTimeUtc
        # is chrono Utc; offsets survive only in formatting)
        return v.tz_convert("UTC")
    return v


def _schema_coercers(schema: Any, col_names: Sequence[str]) -> list:
    """Per-column input coercion to the declared dtype: raw dicts/lists
    (and any datetimes inside them) become normalized Json, ints promote
    to float — the engine-boundary conversions the reference performs in
    value extraction (python_api.rs extract_value)."""
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals.json import Json, normalize_json

    def _denan(v, sd):
        # pandas upcasts int columns with missing values to float-NaN;
        # undo that per the declared dtype
        if isinstance(v, float) and v != v and sd != dt.FLOAT:
            return None
        return v

    def for_dtype(d):
        sd = d.strip_optional()
        if sd == dt.JSON:
            return lambda v: v if v is None else normalize_json(v)
        if sd == dt.FLOAT:
            opt = d.is_optional()

            def to_float(v):
                if opt and isinstance(v, float) and v != v:
                    return None  # NaN marks a missing optional value
                if isinstance(v, int) and not isinstance(v, bool):
                    return float(v)
                return v

            return to_float
        if sd == dt.INT:
            def to_int(v):
                v = _denan(v, sd)
                if (
                    isinstance(v, float)
                    and v == v
                    and float(v).is_integer()
                ):
                    return int(v)
                return v

            return to_int
        return lambda v: _denan(v, sd)

    dtypes = schema.dtypes()
    return [for_dtype(dtypes[n]) for n in col_names]


# ---------------------------------------------------------------------------
# capture / output


class _Capture:
    """Captured output of one table. Batches are stored as-is; the row/
    update views are built lazily on first access — the bulk-join path
    emits hundreds of thousands of rows, and eagerly zipping them into
    per-row tuples doubled the join bench's wall time when the consumer
    (table_to_dicts) only ever wanted columns."""

    def __init__(self, table: Table):
        self.table = table
        self._batches: list[tuple[int, DiffBatch]] = []
        self._rows: dict[int, tuple] | None = None
        self._updates: list[tuple[int, int, int, tuple]] | None = None

    def on_batch(self, t: int, batch: DiffBatch) -> None:
        self._batches.append((t, batch))
        self._rows = None
        self._updates = None

    @property
    def rows(self) -> dict[int, tuple]:
        if self._rows is None:
            rows: dict[int, tuple] = {}
            for t, batch in self._batches:
                if len(batch) > 512 and bool((batch.diffs > 0).all()):
                    keys = batch.keys.tolist()
                    cols = [c.tolist() for c in batch.columns.values()]
                    vals = list(zip(*cols)) if cols else [()] * len(keys)
                    rows.update(zip(keys, vals))
                    continue
                for k, d, vals in batch.iter_rows():
                    if d > 0:
                        rows[k] = vals
                    else:
                        rows.pop(k, None)
            self._rows = rows
        return self._rows

    @property
    def updates(self) -> list[tuple[int, int, int, tuple]]:
        if self._updates is None:
            import itertools

            updates: list[tuple[int, int, int, tuple]] = []
            for t, batch in self._batches:
                if len(batch) > 512:
                    keys = batch.keys.tolist()
                    cols = [c.tolist() for c in batch.columns.values()]
                    vals = list(zip(*cols)) if cols else [()] * len(keys)
                    updates.extend(
                        zip(
                            itertools.repeat(t),
                            keys,
                            batch.diffs.tolist(),
                            vals,
                        )
                    )
                    continue
                for k, d, vals in batch.iter_rows():
                    updates.append((t, k, d, vals))
            self._updates = updates
        return self._updates

    def column_dicts(self) -> tuple[list[int], dict[str, dict[int, Any]]]:
        """Current rows as per-column dicts, built columnar — no per-row
        tuples. Key order matches the `rows` dict (insertion order)."""
        keys_live: dict[int, None] = {}
        cols: dict[str, dict[int, Any]] = {}
        for t, batch in self._batches:
            names = list(batch.columns)
            for nm in names:
                if nm not in cols:
                    cols[nm] = {}
            if len(batch) > 512 and bool((batch.diffs > 0).all()):
                keys = batch.keys.tolist()
                keys_live.update(dict.fromkeys(keys))
                for nm, c in batch.columns.items():
                    cols[nm].update(zip(keys, c.tolist()))
                continue
            for i, (k, d, vals) in enumerate(batch.iter_rows()):
                if d > 0:
                    keys_live[k] = None
                    for nm, v in zip(names, vals):
                        cols[nm][k] = v
                else:
                    keys_live.pop(k, None)
                    for nm in names:
                        cols[nm].pop(k, None)
        return list(keys_live.keys()), cols


def _run_capture(
    tables: Sequence[Table], persistence_config: Any = None
) -> list[_Capture]:
    captures = []
    outputs = []
    for tbl in tables:
        cap = _Capture(tbl)
        captures.append(cap)
        outputs.append(OutputNode(tbl._node, cap.on_batch))
    rt = Runtime(outputs)
    if persistence_config is not None:
        from pathway_tpu.persistence._runtime_glue import attach_persistence

        attach_persistence(rt, persistence_config)
    from pathway_tpu.internals import parse_graph

    parse_graph.G.last_runtime = rt
    rt.run()
    return captures


def table_to_dicts(table: Table, persistence_config: Any = None):
    cap = _run_capture([table], persistence_config=persistence_config)[0]
    col_names = table.column_names()
    keys, cols = cap.column_dicts()
    columns = {n: cols.get(n, {}) for n in col_names}
    return keys, columns


def table_from_parquet(
    path: Any,
    id_from: Sequence[str] | None = None,
    unsafe_trusted_ids: bool = False,
    **kwargs: Any,
) -> Table:
    """Parquet file -> table via pandas (reference: debug/__init__.py:458)."""
    import pandas as pd

    return table_from_pandas(
        pd.read_parquet(path),
        id_from=id_from,
        unsafe_trusted_ids=unsafe_trusted_ids,
    )


def table_to_parquet(table: Table, filename: Any) -> None:
    """Run the graph, write the table to a Parquet file (reference:
    debug/__init__.py:475)."""
    df = table_to_pandas(table, include_id=False)
    df.to_parquet(filename)


class StreamGenerator:
    """Explicitly-timestamped test streams (reference: debug/__init__.py:
    490). The reference routes events through persistence replay; the
    microbatch engine's sources take timestamped events directly, so
    persistence_config() returns None and the tables stream on pw.run."""

    def _table_from_dict(self, batches: dict, schema: Any) -> Table:
        """batches: {time: {worker: [(diff, key, [values...]), ...]}} —
        worker ids collapse onto the single logical worker."""
        col_names = list(schema.column_names())
        return self._from_batches(batches, col_names, dict(schema.dtypes()))

    @staticmethod
    def _from_batches(batches: dict, col_names: list, dtypes: dict) -> Table:
        # reference semantics (debug/__init__.py:536-541): if ANY
        # timestamp is odd, ALL are doubled, preserving relative order
        if any(int(t) % 2 == 1 for t in batches):
            import warnings

            warnings.warn(
                "timestamps are required to be even; all timestamps will "
                "be doubled"
            )
            batches = {2 * int(t): v for t, v in batches.items()}
        events: dict[int, list] = {}
        for t, by_worker in batches.items():
            for _worker, changes in by_worker.items():
                for diff, key, values in changes:
                    events.setdefault(int(t), []).append(
                        (int(key), int(diff), tuple(values))
                    )
        source = _RowsSource(col_names, sorted(events.items()))
        node = InputNode(source, col_names)
        return Table._from_node(node, dtypes, Universe())

    def table_from_list_of_batches_by_workers(
        self, batches: list[dict[int, list[dict]]], schema: Any, **kw: Any
    ) -> Table:
        counter = iter(range(10**9))
        as_dict: dict[int, dict[int, list]] = {}
        for i, batch in enumerate(batches):
            t = 2 * (i + 1)
            as_dict[t] = {
                w: [
                    (
                        1,
                        int(ref_scalar(next(counter))),
                        [row[n] for n in schema.column_names()],
                    )
                    for row in rows
                ]
                for w, rows in batch.items()
            }
        return self._table_from_dict(as_dict, schema)

    def table_from_list_of_batches(
        self, batches: list[list[dict]], schema: Any, **kw: Any
    ) -> Table:
        return self.table_from_list_of_batches_by_workers(
            [{0: batch} for batch in batches], schema
        )

    def table_from_pandas(
        self,
        df: Any,
        id_from: list[str] | None = None,
        unsafe_trusted_ids: bool = False,
        schema: Any = None,
        **kw: Any,
    ) -> Table:
        """`_time` / `_worker` / `_diff` columns control batching, exactly
        as in the reference. A non-default DataFrame index provides the
        row ids (hash of the index value), letting retractions target
        earlier insertions."""
        import pandas as pd

        df = df.copy()
        for col, default in (("_time", 2), ("_worker", 0), ("_diff", 1)):
            if col not in df:
                df[col] = [default] * len(df)
        value_cols = [
            c for c in df.columns if c not in ("_time", "_worker", "_diff")
        ]
        explicit_ids = not isinstance(df.index, pd.RangeIndex)
        if id_from is None and schema is not None and not explicit_ids:
            # schema primary keys fill in only when the index carries no
            # explicit ids (explicit ids win, like table_from_markdown)
            id_from = schema.primary_key_columns()
        if schema is None:
            dtypes = {
                n: _dtype_for([_np_unbox(v) for v in df[n]])
                for n in value_cols
            }
        else:
            dtypes = {n: schema.dtypes()[n] for n in value_cols}
        batches: dict[int, dict[int, list]] = {}
        for i in range(len(df)):
            row = df.iloc[i]
            vals = [_np_unbox(row[c]) for c in value_cols]
            if id_from:
                key = int(
                    ref_scalar(*[vals[value_cols.index(c)] for c in id_from])
                )
            elif explicit_ids:
                key = int(ref_scalar(_np_unbox(df.index[i])))
            else:
                key = int(ref_scalar(i))
            t = int(row["_time"])
            batches.setdefault(t, {}).setdefault(int(row["_worker"]), []).append(
                (int(row["_diff"]), key, vals)
            )
        return self._from_batches(batches, value_cols, dtypes)

    def table_from_markdown(
        self,
        table: str,
        id_from: list[str] | None = None,
        unsafe_trusted_ids: bool = False,
        schema: Any = None,
        **kw: Any,
    ) -> Table:
        # parse into a DataFrame and route through table_from_pandas so
        # _time/_worker/_diff handling, odd-timestamp doubling and
        # explicit-id semantics match the reference's single code path
        import pandas as pd

        header, data, raw_ids = _split_markdown(table)
        ids = (
            [_parse_value(x) for x in raw_ids] if raw_ids is not None else None
        )
        parsed = [[_parse_value(c) for c in row] for row in data]
        df = pd.DataFrame(parsed, columns=header, dtype=object)
        if ids is not None:
            df.index = ids
        return self.table_from_pandas(
            df, id_from, unsafe_trusted_ids, schema
        )

    def persistence_config(self):
        """The microbatch engine feeds StreamGenerator tables directly —
        no persistence replay needed; safe to pass to pw.run."""
        return None


def table_to_pandas(table: Table, include_id: bool = True):
    import pandas as pd

    cap = _run_capture([table])[0]
    col_names = table.column_names()
    data = {n: [] for n in col_names}
    index = []
    for k, vals in cap.rows.items():
        index.append(Pointer(k))
        for n, v in zip(col_names, vals):
            data[n].append(v)
    if include_id:
        return pd.DataFrame(data, index=index)
    return pd.DataFrame(data)


def _fmt_value(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, str):
        return v
    if isinstance(v, np.generic):
        v = v.item()
    return repr(v) if not isinstance(v, (int, float, bool, Pointer)) else str(v)


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    squash_updates: bool = True,
    terminate_on_error: bool = True,
) -> None:
    cap = _run_capture([table])[0]
    col_names = table.column_names()
    # reference display order: rows sorted by VALUES then key (debug/
    # __init__.py _compute_and_print_single); unsortable values keep
    # capture order
    rows = list(cap.rows.items())
    try:
        rows.sort(key=lambda kv: tuple(
            (v is not None, v) for v in kv[1]
        ) + (kv[0],))
    except (ValueError, TypeError):
        rows.sort(key=lambda kv: kv[0])
    if n_rows is not None:
        rows = rows[:n_rows]
    header = ([""] if include_id else []) + col_names
    out_rows = []
    for k, vals in rows:
        key_s = str(Pointer(k))
        if short_pointers:
            key_s = key_s[:12] + "..."
        out_rows.append(
            ([key_s] if include_id else []) + [_fmt_value(v) for v in vals]
        )
    widths = [
        max(len(header[i]), *(len(r[i]) for r in out_rows)) if out_rows else len(header[i])
        for i in range(len(header))
    ]
    print(
        " | ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip()
    )
    for r in out_rows:
        print(" | ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())


def compute_and_print_update_stream(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    **kwargs,
) -> None:
    cap = _run_capture([table])[0]
    col_names = table.column_names()
    header = ([""] if include_id else []) + col_names + ["__time__", "__diff__"]
    updates = list(cap.updates)
    # reference stream display order: (time, diff) first, then values,
    # then key; unsortable values keep CAPTURE order (sorted() leaves the
    # original untouched when a comparison raises)
    try:
        updates = sorted(
            updates,
            key=lambda u: (u[0], u[2])
            + tuple((v is not None, v) for v in u[3])
            + (u[1],),
        )
    except (ValueError, TypeError):
        pass
    if n_rows is not None:
        updates = updates[:n_rows]
    out_rows = []
    for t, k, d, vals in updates:
        key_s = str(Pointer(k))
        if short_pointers:
            key_s = key_s[:12] + "..."
        out_rows.append(
            ([key_s] if include_id else [])
            + [_fmt_value(v) for v in vals]
            + [str(t), str(d)]
        )
    widths = [
        max(len(header[i]), *(len(r[i]) for r in out_rows))
        if out_rows
        else len(header[i])
        for i in range(len(header))
    ]
    print(" | ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip())
    for r in out_rows:
        print(" | ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())


# ---------------------------------------------------------------------------
# equality assertions (harness used by our test-suite, modeled on the
# reference tests/utils.py assert_table_equality)


def _canon(vals: tuple) -> tuple:
    out = []
    for v in vals:
        if isinstance(v, np.ndarray):
            out.append(("__ndarray__", v.tobytes(), str(v.dtype), v.shape))
        elif isinstance(v, float) and float(v).is_integer():
            out.append(v)
        elif isinstance(v, np.generic):
            out.append(v.item())
        else:
            out.append(v)
    return tuple(out)


def assert_table_equality(t1: Table, t2: Table, **kwargs) -> None:
    caps = _run_capture([t1, t2])
    rows1 = {Pointer(k): _canon(v) for k, v in caps[0].rows.items()}
    rows2 = {Pointer(k): _canon(v) for k, v in caps[1].rows.items()}
    c1, c2 = t1.column_names(), t2.column_names()
    assert c1 == c2, f"column mismatch: {c1} vs {c2}"
    assert rows1 == rows2, (
        f"tables differ:\n  left:  {_show(rows1)}\n  right: {_show(rows2)}"
    )


def assert_table_equality_wo_index(t1: Table, t2: Table, **kwargs) -> None:
    caps = _run_capture([t1, t2])
    rows1 = sorted(
        (_canon(v) for v in caps[0].rows.values()), key=repr
    )
    rows2 = sorted(
        (_canon(v) for v in caps[1].rows.values()), key=repr
    )
    c1, c2 = t1.column_names(), t2.column_names()
    assert c1 == c2, f"column mismatch: {c1} vs {c2}"
    assert rows1 == rows2, (
        f"tables differ (wo index):\n  left:  {rows1}\n  right: {rows2}"
    )


assert_table_equality_wo_types = assert_table_equality
assert_table_equality_wo_index_types = assert_table_equality_wo_index


def _show(rows: Mapping) -> str:
    items = sorted(rows.items(), key=lambda kv: str(kv[0]))
    return "{" + ", ".join(f"{k}: {v}" for k, v in items[:20]) + (
        ", ..." if len(items) > 20 else ""
    ) + "}"


def _compute_tables(*tables: Table):
    """Run the graph and return the captured contents of `tables`
    (reference: debug._compute_tables with terminate_on_error=True —
    an error recorded during execution raises instead of poisoning)."""
    from pathway_tpu.internals.errors import clear_errors, peek_errors

    clear_errors()
    captures = _run_capture(list(tables))
    errors = peek_errors()
    if errors:
        first = errors[0]
        raise ValueError(
            f"error during computation: {first.get('message', first)!r}"
        )
    return captures


def trace(seconds: float | None = None, path: Any = None) -> dict:
    """Notebook entry point for the Trace Weaver
    (pathway_tpu/observability/tracing.py): return the recorded span ring
    as a Chrome trace-event document — the same body the monitoring
    server serves at ``/debug/trace``. Pass ``path`` to also write it to
    a file you can drag into Perfetto (ui.perfetto.dev)."""
    import json as _json

    from pathway_tpu.observability.tracing import get_tracer

    doc = get_tracer().chrome_trace(seconds=seconds)
    if path is not None:
        with open(path, "w") as f:
            _json.dump(doc, f)
    return doc


def trace_tree(
    trace_id: str | None = None, seconds: float | None = None
) -> str:
    """Human-readable parent/child breakdown of one trace (default: the
    most recently finished root span's trace). Prints and returns it."""
    from pathway_tpu.observability.tracing import get_tracer

    tracer = get_tracer()
    if trace_id is None:
        recs = tracer.spans(seconds)
        span_ids = {r.span_id for r in recs}
        # local roots: no parent, OR a parent that lives outside this
        # ring (a request that joined a caller's trace via traceparent)
        roots = [
            r
            for r in recs
            if r.parent_id is None or r.parent_id not in span_ids
        ]
        if not roots:
            out = "(no root spans recorded)"
            print(out)
            return out
        trace_id = roots[-1].trace_id
    out = tracer.format_tree(trace_id, seconds)
    print(out)
    return out


def diagnose(*tables: Table, min_severity: str = "info"):
    """Notebook entry point for the Graph Doctor (pathway_tpu.analysis):
    print and return the static-analysis report for the pipeline feeding
    the given table(s) — or the whole declared graph when called with no
    arguments. Nothing executes; the pass walks the declared nodes only."""
    from pathway_tpu.analysis import run_doctor
    from pathway_tpu.analysis.diagnostics import Severity
    from pathway_tpu.engine.runtime import collect_nodes

    if tables:
        seeds = [t._node for t in tables]
        # scope to the upstream cone: a table under diagnosis counts as
        # consumed, and unrelated parts of the graph stay out of view
        report = run_doctor(outputs=seeds, all_nodes=collect_nodes(seeds))
    else:
        report = run_doctor()
    print(report.format(min_severity=Severity.parse(min_severity)))
    return report
