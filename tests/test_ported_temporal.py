"""Ported temporal-window tests (reference:
python/pathway/tests/temporal/test_windows.py) — exact expected outputs
for session-with-predicate and sliding windows with instances."""

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import assert_table_equality_wo_index


def test_session_simple():
    t = T(
        """
            | instance |  t |  v
        1   | 0        |  1 |  10
        2   | 0        |  2 |  1
        3   | 0        |  4 |  3
        4   | 0        |  8 |  2
        5   | 0        |  9 |  4
        6   | 0        |  10|  8
        7   | 1        |  1 |  9
        8   | 1        |  2 |  16
    """
    )

    def should_merge(a, b):
        return abs(a - b) <= 1

    gb = t.windowby(
        t.t,
        window=pw.temporal.session(predicate=should_merge),
        instance=t.instance,
    )
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_v=pw.reducers.max(pw.this.v),
        count=pw.reducers.count(),
    )
    res = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_v | count
        0            | 1                | 2              | 1     | 10    | 2
        0            | 4                | 4              | 4     | 3     | 1
        0            | 8                | 10             | 8     | 8     | 3
        1            | 1                | 2              | 1     | 16    | 2
    """
    )
    assert_table_equality_wo_index(result, res)


def test_sliding():
    t = T(
        """
            | instance | t
        1   | 0        |  12
        2   | 0        |  13
        3   | 0        |  14
        4   | 0        |  15
        5   | 0        |  16
        6   | 0        |  17
        7   | 1        |  10
        8   | 1        |  11
    """
    )
    gb = t.windowby(
        t.t,
        window=pw.temporal.sliding(duration=10, hop=3),
        instance=t.instance,
    )
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    res = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
            0        |     3            |     13         | 12    | 12    | 1
            0        |     6            |     16         | 12    | 15    | 4
            0        |     9            |     19         | 12    | 17    | 6
            0        |     12           |     22         | 12    | 17    | 6
            0        |     15           |     25         | 15    | 17    | 3
            1        |     3            |     13         | 10    | 11    | 2
            1        |     6            |     16         | 10    | 11    | 2
            1        |     9            |     19         | 10    | 11    | 2
            """
    )
    assert_table_equality_wo_index(result, res)


def test_session_max_gap():
    t = T(
        """
            | t
        1   | 1
        2   | 2
        3   | 10
        4   | 11
        5   | 30
    """
    )
    gb = t.windowby(t.t, window=pw.temporal.session(max_gap=5))
    result = gb.reduce(
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        count=pw.reducers.count(),
    )
    res = T(
        """
        _pw_window_start | _pw_window_end | count
        1                | 2              | 2
        10               | 11             | 2
        30               | 30             | 1
    """
    )
    assert_table_equality_wo_index(result, res)


def test_tumbling_with_origin():
    t = T(
        """
            | t
        1   | 1
        2   | 5
        3   | 6
        4   | 11
    """
    )
    gb = t.windowby(
        t.t, window=pw.temporal.tumbling(duration=5, origin=1)
    )
    result = gb.reduce(
        pw.this._pw_window_start,
        count=pw.reducers.count(),
    )
    res = T(
        """
        _pw_window_start | count
        1                | 2
        6                | 1
        11               | 1
    """
    )
    assert_table_equality_wo_index(result, res)
