"""Tenant Weave (pathway_tpu/serving/tenancy.py) tests: weight-class
parsing, bounded-cardinality labeling, fair-share buckets, WFQ
ordering, queue-full eviction charged to the hot tenant, the flood=
fault directive, the tenant-fairness doctor rule, and the total
PATHWAY_TENANT_QOS=0 escape hatch."""

import time

import pytest

import pathway_tpu as pw
from pathway_tpu.serving import (
    QoSConfig,
    ShedError,
    TenancyConfig,
    TenantLabeler,
    TenantLedger,
    parse_weight_classes,
    tenancy_enabled_via_env,
)
from pathway_tpu.serving.tenancy import ledger_for
from pathway_tpu.testing import faults


def _config(**kw) -> TenancyConfig:
    kw.setdefault("weights", {"default": 1.0})
    kw.setdefault("metric_topn", 32)
    kw.setdefault("state_cap", 1024)
    kw.setdefault("burst", 4.0)
    return TenancyConfig(**kw)


# --- weight classes --------------------------------------------------------


def test_weight_classes_parse():
    w = parse_weight_classes("premium:4,default:1,batch:0.25")
    assert w == {"premium": 4.0, "default": 1.0, "batch": 0.25}
    # default class added when absent; empty spec is just the default
    assert parse_weight_classes("premium:2") == {
        "premium": 2.0,
        "default": 1.0,
    }
    assert parse_weight_classes("") == {"default": 1.0}


def test_weight_classes_validation():
    with pytest.raises(ValueError):
        parse_weight_classes("premium")  # no weight
    with pytest.raises(ValueError):
        parse_weight_classes("premium:fast")  # not a number
    with pytest.raises(ValueError):
        parse_weight_classes("premium:0")  # must be > 0
    with pytest.raises(ValueError):
        parse_weight_classes(":3")  # no class name


def test_weight_of_unknown_class_falls_back_to_default():
    cfg = _config(weights={"premium": 4.0, "default": 1.0})
    assert cfg.weight_of("premium") == 4.0
    assert cfg.weight_of("bronze") == 1.0
    assert cfg.weight_of(None) == 1.0


# --- escape hatch ----------------------------------------------------------


def test_escape_hatch_builds_no_ledger(monkeypatch):
    monkeypatch.delenv("PATHWAY_TENANT_QOS", raising=False)
    assert not tenancy_enabled_via_env()
    assert ledger_for(QoSConfig()) is None
    # the gate path stays byte-identical: no ledger, plain-EDF batcher
    from pathway_tpu.serving.gate import SurgeGate

    class _Sess:
        def insert_batch(self, rows):
            pass

    gate = SurgeGate(QoSConfig(), _Sess(), route="/plain")
    try:
        assert gate.ledger is None
        r = _req(1, time.monotonic() + 5)
        assert gate.batcher._order(r) == r.deadline
    finally:
        gate.close()


def _req(key, deadline, tenant=None, tenant_class=None):
    from pathway_tpu.serving.gate import PendingRequest

    return PendingRequest(
        key, (key,), deadline, tenant=tenant, tenant_class=tenant_class
    )


# --- bounded-cardinality labeling ------------------------------------------


def test_labeler_topn_fold_and_sticky():
    lab = TenantLabeler(topn=2)
    assert lab.label("a") == "a"
    assert lab.label("b") == "b"
    # slots are full: everyone else folds, labels stay sticky
    assert lab.label("c") == "__other__"
    for _ in range(100):
        assert lab.label("c") == "__other__"
    assert lab.label("a") == "a"
    assert lab.labeled() == {"a", "b"}


def test_labeler_summary_stays_bounded():
    lab = TenantLabeler(topn=4)
    for i in range(10_000):
        lab.label(f"t{i}")
    assert len(lab._counts) <= 8 * 4
    assert len(lab.labeled()) == 4


# --- fair-share admission --------------------------------------------------


def test_ledger_work_conserving_without_pressure():
    led = TenantLedger(_config(), route="/t", capacity_rps=10.0)
    now = time.monotonic()
    # a lone hot tenant on an idle endpoint keeps its full throughput:
    # way past its fair share, but pressure=False never sheds
    for i in range(100):
        led.admit("hot", None, now + i * 0.001, pressure=False)


def test_ledger_sheds_hot_tenant_under_pressure():
    led = TenantLedger(
        _config(burst=2.0), route="/t", capacity_rps=10.0
    )
    now = time.monotonic()
    with pytest.raises(ShedError) as ei:
        for i in range(50):
            led.admit("hot", None, now + i * 1e-4, pressure=True)
    assert ei.value.status == 429
    assert ei.value.reason == "tenant_rate"
    # the tail tenant is untouched: its own bucket is full
    led.admit("tail", None, now + 0.01, pressure=True)


def test_fair_share_splits_by_active_weight():
    cfg = _config(weights={"premium": 3.0, "default": 1.0})
    led = TenantLedger(cfg, route="/t", capacity_rps=8.0)
    now = time.monotonic()
    led.admit("p", "premium", now, pressure=False)
    led.admit("d", None, now, pressure=False)
    # W_active = 4.0: premium gets 3/4 of capacity, default 1/4
    assert led.fair_rate(3.0) == pytest.approx(6.0)
    assert led.fair_rate(1.0) == pytest.approx(2.0)


def test_explicit_tenant_rps_beats_derived_share():
    cfg = _config(tenant_rps=5.0)
    led = TenantLedger(cfg, route="/t", capacity_rps=1000.0)
    assert led.fair_rate(1.0) == pytest.approx(5.0)
    assert led.fair_rate(2.0) == pytest.approx(10.0)


def test_state_cap_bounds_tracked_tenants():
    led = TenantLedger(
        _config(state_cap=8), route="/t", capacity_rps=None
    )
    now = time.monotonic()
    for i in range(1000):
        led.admit(f"t{i}", None, now + i * 1e-6, pressure=False)
    assert led.tracked_tenants <= 8


def test_active_weight_decays_idle_tenants():
    import math

    from pathway_tpu.serving import tenancy

    led = TenantLedger(_config(), route="/t", capacity_rps=10.0)
    now = time.monotonic()
    led.admit("a", None, now, pressure=False)
    led.admit("b", None, now, pressure=False)
    assert led.active_weight(now) == pytest.approx(2.0)
    # b goes idle: its contribution decays exponentially — at τ+2s it
    # still counts e^(-1.2), and by 5τ it is effectively gone
    t1 = now + tenancy.ACTIVE_TAU_S + 2.0
    led.admit("a", None, t1, pressure=False)
    assert led.active_weight(t1) == pytest.approx(
        1.0 + math.exp(-(tenancy.ACTIVE_TAU_S + 2.0) / tenancy.ACTIVE_TAU_S),
        rel=1e-6,
    )
    t2 = now + 5.0 * tenancy.ACTIVE_TAU_S
    led.admit("a", None, t2, pressure=False)
    assert led.active_weight(t2) == pytest.approx(1.0, abs=0.01)


def test_no_fair_share_cliff_at_idle_boundaries():
    """Regression (ROADMAP tenant (a)): the fixed 10 s ACTIVE window
    made W_active — and so every tenant's fair share — JUMP the instant
    an idle neighbor crossed the expiry boundary.  The decayed estimate
    must be continuous: W(t) sampled just before and just after the old
    boundary (and at every other instant) differs only by the decay of
    an epsilon of wall time."""
    from pathway_tpu.serving import tenancy

    led = TenantLedger(_config(), route="/t", capacity_rps=12.0)
    now = time.monotonic()
    led.admit("a", None, now, pressure=False)
    led.admit("b", None, now, pressure=False)
    eps = 1e-3
    for boundary in (
        tenancy.ACTIVE_TAU_S,  # the old window expiry — the cliff
        tenancy.ACTIVE_TAU_S / 2.0,
        2.0 * tenancy.ACTIVE_TAU_S,
    ):
        before = led.active_weight(now + boundary - eps)
        after = led.active_weight(now + boundary + eps)
        # pre-fix: before=2.0, after=1.0 at the 10 s boundary (a 2x
        # fair-share jump).  post-fix: continuous to ~eps/τ.
        assert abs(before - after) < 1e-3, (boundary, before, after)
    # and the share a still-active tenant derives from it is monotone
    # (B only ever fades): no re-doubling sawtooth across the day
    samples = [
        led.active_weight(now + t) for t in (1.0, 5.0, 10.0, 20.0, 40.0)
    ]
    assert all(a >= b - 1e-9 for a, b in zip(samples, samples[1:]))


# --- WFQ ordering ----------------------------------------------------------


def test_wfq_tags_order_hot_backlog_behind_fresh_tail():
    led = TenantLedger(_config(), route="/t", capacity_rps=None)
    now = time.monotonic()
    hot_tags = [
        led.admit("hot", None, now, pressure=False) for _ in range(5)
    ]
    tail_tag = led.admit("tail", None, now, pressure=False)
    # the hot tenant's 5th request finishes (virtually) after the
    # tail's 1st: the batcher's (tag, deadline) heap drains tail first
    assert hot_tags == sorted(hot_tags)
    assert tail_tag < hot_tags[-1]


def test_wfq_weight_scales_virtual_cost():
    cfg = _config(weights={"premium": 4.0, "default": 1.0})
    led = TenantLedger(cfg, route="/t", capacity_rps=None)
    now = time.monotonic()
    p = [led.admit("p", "premium", now, pressure=False) for _ in range(4)]
    d = [led.admit("d", None, now, pressure=False) for _ in range(1)]
    # 4 premium requests cost the same virtual time as 1 default one
    assert p[-1] == pytest.approx(d[-1], rel=1e-9)


def test_batcher_orders_by_wfq_tag_not_deadline():
    from pathway_tpu.serving.batcher import MicroBatcher

    cfg = QoSConfig(max_batch_size=2, max_wait_ms=10_000.0)
    dispatched: list = []
    b = MicroBatcher(
        cfg,
        dispatch=lambda reqs: dispatched.append([r.key for r in reqs]),
        reject=lambda r, e: None,
        order=lambda r: r.order,
    )
    try:
        now = time.monotonic()
        # hot request has the EARLIER deadline but the LATER vfinish:
        # weighted fairness must beat EDF
        hot = _req(1, now + 1.0, tenant="hot")
        hot.order = (5.0, hot.deadline)
        tail = _req(2, now + 9.0, tenant="tail")
        tail.order = (1.0, tail.deadline)
        b.put(hot)
        b.put(tail)
        deadline = time.monotonic() + 5
        while not dispatched and time.monotonic() < deadline:
            time.sleep(0.01)
        assert dispatched and dispatched[0] == [2, 1]
    finally:
        b.close()


def test_batcher_expiry_reads_deadline_not_order_tag():
    from pathway_tpu.serving import DeadlineExceeded
    from pathway_tpu.serving.batcher import MicroBatcher

    cfg = QoSConfig(max_batch_size=64, max_wait_ms=5.0)
    rejected: list = []
    b = MicroBatcher(
        cfg,
        dispatch=lambda reqs: None,
        reject=lambda r, e: rejected.append((r.key, type(e).__name__)),
        order=lambda r: r.order,
    )
    try:
        expired = _req(1, time.monotonic() - 0.01)
        # a huge order tag must not shield the expired request
        expired.order = (1e9, expired.deadline)
        b.put(expired)
        deadline = time.monotonic() + 5
        while not rejected and time.monotonic() < deadline:
            time.sleep(0.01)
        assert rejected == [(1, DeadlineExceeded.__name__)]
    finally:
        b.close()


# --- queue-full eviction ----------------------------------------------------


def test_pick_victim_selects_most_over_share():
    led = TenantLedger(_config(), route="/t")
    reqs = [_req(i, time.monotonic() + 5) for i in range(3)]
    reqs[0].order = (2.0, reqs[0].deadline)
    reqs[1].order = (9.0, reqs[1].deadline)
    reqs[2].order = (4.0, reqs[2].deadline)
    assert led.pick_victim(reqs, arriving_tag=3.0) is reqs[1]
    # the arrival itself is the hottest: no victim, normal shed applies
    assert led.pick_victim(reqs, arriving_tag=99.0) is None


def test_gate_queue_full_evicts_hot_tenant_not_tail(monkeypatch):
    monkeypatch.setenv("PATHWAY_TENANT_QOS", "1")
    from pathway_tpu.serving.gate import SurgeGate

    class _Sess:
        def insert_batch(self, rows):
            pass

    # max_wait huge + window 1 so nothing flushes while we fill the
    # queue; max_queue tiny so the eviction path triggers
    cfg = QoSConfig(
        max_queue=3,
        max_batch_size=64,
        max_wait_ms=60_000.0,
        max_dispatched=1,
    )
    gate = SurgeGate(cfg, _Sess(), route="/evict")
    try:
        assert gate.ledger is not None
        rejected: list = []
        from pathway_tpu.serving.gate import PendingRequest

        class _Recording(PendingRequest):
            def reject(self, exc):
                rejected.append((self.key, exc))

        now = time.monotonic()
        for i in range(3):
            gate.submit(_Recording(i, (i,), now + 30.0, tenant="hot"))
        assert gate.queue_depth == 3
        tail = _Recording(99, (99,), now + 30.0, tenant="tail")
        gate.submit(tail)  # must NOT raise: the hot victim pays
        assert rejected, "no hot-tenant request was evicted"
        key, exc = rejected[0]
        assert key in (0, 1, 2)
        assert isinstance(exc, ShedError)
        assert exc.status == 429 and exc.reason == "tenant_evict"
        assert gate.queue_depth == 3  # tail took the victim's slot
        with gate.batcher._cond:
            queued_keys = {r.key for _t, _s, r in gate.batcher._heap}
        assert 99 in queued_keys and key not in queued_keys
    finally:
        gate.close()


def test_admission_under_pressure_signal():
    from pathway_tpu.serving.admission import AdmissionController

    ctl = AdmissionController(QoSConfig(max_queue=4), route="/p")
    assert not ctl.under_pressure()
    ctl.queued = 2  # half full
    assert ctl.under_pressure()
    ctl.queued = 0
    rps = AdmissionController(
        QoSConfig(max_queue=100, rate_limit_rps=5.0, rate_limit_burst=2.0),
        route="/p2",
    )
    now = time.monotonic()
    assert not rps.under_pressure(now)
    rps._bucket.tokens = 0.5
    rps._bucket._last = now
    assert rps.under_pressure(now)


def test_replica_admission_sheds_tenant_rate():
    from pathway_tpu.serving.admission import AdmissionController

    led = TenantLedger(
        _config(burst=1.0), route="/r", capacity_rps=1.0
    )
    ctl = AdmissionController(
        QoSConfig(max_queue=2, rate_limit_rps=1.0, rate_limit_burst=1.0),
        route="/r",
        ledger=led,
    )
    now = time.monotonic()
    ctl.admit(now, tenant="hot")
    # bucket drained (shared AND tenant): the next hot admit sheds as
    # tenant_rate BEFORE consuming anything shared
    with pytest.raises(ShedError) as ei:
        ctl.admit(now + 1e-4, tenant="hot")
    assert ei.value.reason == "tenant_rate"


def test_shared_path_shed_refunds_tenant_charge():
    from pathway_tpu.serving.admission import AdmissionController

    led = TenantLedger(_config(burst=2.0), route="/rf", capacity_rps=10.0)
    ctl = AdmissionController(
        QoSConfig(max_queue=1), route="/rf", ledger=led
    )
    now = time.monotonic()
    ctl.admit(now, tenant="hot")  # tokens 2 -> 1, queued 1
    # queue full: the shared-path queue_full shed must REFUND the
    # tenant charge — the request never entered the queue
    with pytest.raises(ShedError) as ei:
        ctl.admit(now + 1e-4, tenant="hot")
    assert ei.value.reason == "queue_full"
    # after the queue drains, the refunded token admits the next
    # request even under sticky pressure (without the refund the
    # bucket would be empty and this would shed tenant_rate)
    ctl.on_flushed(1)
    ctl.admit(now + 2e-4, tenant="hot")
    # only the two REAL admissions were counted as admitted
    assert led._m_admitted.labels("/rf", "hot").value == 2


def test_refund_restores_token_and_wfq_clock():
    led = TenantLedger(_config(burst=2.0), route="/t", capacity_rps=10.0)
    now = time.monotonic()
    tag1 = led.admit("t", None, now, pressure=False)
    tag2 = led.admit("t", None, now, pressure=False)
    assert led._tenants["t"].tokens == pytest.approx(0.0, abs=1e-6)
    led.refund("t", None, tag2)
    assert led._tenants["t"].tokens == pytest.approx(1.0, abs=1e-6)
    assert led._tenants["t"].vfinish == pytest.approx(tag1)
    # later admits moved the clock past the refunded tag: no rollback
    led.admit("t", None, now, pressure=False)
    tag4 = led.admit("t", None, now, pressure=False)
    assert tag4 > tag2
    led.refund("t", None, tag2)
    assert led._tenants["t"].vfinish == pytest.approx(tag4)


# --- Fault Forge flood= -----------------------------------------------------


def test_flood_spec_parses_and_validates():
    p = faults.FaultPlan("flood=tenant:hot,rps:5,ticks:3", 0, 0)
    assert p.flood_charges(1) == [("hot", None, 5)]
    assert p.flood_charges(3) == [("hot", None, 5)]
    assert p.flood_charges(4) == []  # past the ticks bound
    p2 = faults.FaultPlan("flood=tenant:hot,rps:2,class:batch", 0, 0)
    assert p2.flood_charges(100) == [("hot", "batch", 2)]


def test_flood_spec_rejections():
    with pytest.raises(faults.FaultSpecError):
        faults.FaultPlan("flood=rps:5", 0, 0)  # needs tenant
    with pytest.raises(faults.FaultSpecError):
        faults.FaultPlan("flood=tenant:t", 0, 0)  # needs rps
    with pytest.raises(faults.FaultSpecError):
        # admissions have no head/tail
        faults.FaultPlan("flood=tenant:t,rps:5,at:head", 0, 0)


def test_flood_is_incarnation_gated():
    p = faults.FaultPlan("flood=tenant:hot,rps:5", 0, 1)
    assert p.flood_charges(1) == []  # directive defaults to inc 0
    p2 = faults.FaultPlan("flood=tenant:hot,rps:5,inc:1", 0, 1)
    assert p2.flood_charges(1) == [("hot", None, 5)]


def test_flood_charges_ledger_without_wall_clock(monkeypatch):
    monkeypatch.setenv("PATHWAY_FAULTS", "flood=tenant:hot,rps:50")
    faults.reset()
    try:
        led = TenantLedger(
            _config(burst=2.0), route="/f", capacity_rps=10.0
        )
        now = time.monotonic()
        # ONE real tail admission; the directive charges 50 synthetic
        # hot requests against the same instant — the hot tenant's
        # bucket drains deterministically, no load generator involved
        led.admit("tail", None, now, pressure=True)
        with pytest.raises(ShedError) as ei:
            led.admit("hot", None, now + 1e-4, pressure=True)
        assert ei.value.reason == "tenant_rate"
        # synthetic charges never advance the REAL admission counter
        # (the flood would otherwise feed itself)
        assert led._admissions == 2
    finally:
        monkeypatch.delenv("PATHWAY_FAULTS")
        faults.reset()


# --- Graph Doctor -----------------------------------------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _gated_graph():
    from pathway_tpu.io.http import rest_connector

    class QuerySchema(pw.Schema):
        text: str

    gated, writer = rest_connector(
        host="127.0.0.1",
        port=_free_port(),
        schema=QuerySchema,
        route="/gated",
        qos=QoSConfig(),
    )
    writer(gated.select(query_id=gated.id, result=gated.text))


def test_doctor_tenant_fairness_warns_on_tenant_blind_plane(monkeypatch):
    from pathway_tpu.analysis import run_doctor

    monkeypatch.setenv(
        "PATHWAY_SERVING_REPLICAS", "http://127.0.0.1:1,http://127.0.0.1:2"
    )
    monkeypatch.delenv("PATHWAY_TENANT_QOS", raising=False)
    _gated_graph()
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    hits = report.by_rule("tenant-fairness")
    assert len(hits) == 1
    assert hits[0].severity.name == "WARNING"
    # arming tenancy clears the finding
    monkeypatch.setenv("PATHWAY_TENANT_QOS", "1")
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    assert not report.by_rule("tenant-fairness")


def test_doctor_tenant_fairness_info_on_ttl_only_cache(monkeypatch):
    from pathway_tpu.analysis import run_doctor

    monkeypatch.delenv("PATHWAY_SERVING_REPLICAS", raising=False)
    monkeypatch.setenv("PATHWAY_TENANT_QOS", "1")
    monkeypatch.setenv("PATHWAY_ROUTER_CACHE", "1")
    monkeypatch.delenv("PATHWAY_ROUTER_CACHE_WRITER", raising=False)
    _gated_graph()
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    hits = report.by_rule("tenant-fairness")
    assert len(hits) == 1
    assert hits[0].severity.name == "INFO"
    # naming the writer's delta endpoint clears it
    monkeypatch.setenv("PATHWAY_ROUTER_CACHE_WRITER", "127.0.0.1:9999")
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    assert not report.by_rule("tenant-fairness")


# --- router WFQ dispatch window --------------------------------------------


def test_router_wfq_dispatch_orders_by_virtual_finish():
    """With the window full, a cold tenant's first request releases
    ahead of the hot tenant's queued backlog (WFQ tag order, not FIFO)."""
    import asyncio

    from pathway_tpu.serving.router import _WfqDispatch

    async def scenario():
        ledger = TenantLedger(_config(), route="router")
        disp = _WfqDispatch(ledger, width=1)
        order: list[str] = []

        # occupy the single slot
        await disp.acquire("hot", None)

        async def routed(tenant):
            await disp.acquire(tenant, None)
            order.append(tenant)
            disp.release()

        # hot tenant queues three more, THEN a cold tenant arrives
        tasks = [asyncio.ensure_future(routed("hot")) for _ in range(3)]
        await asyncio.sleep(0)  # let the hot backlog enqueue first
        tasks.append(asyncio.ensure_future(routed("cold")))
        await asyncio.sleep(0)
        assert disp.queued == 4
        disp.release()  # free the occupied slot
        await asyncio.gather(*tasks)
        return order

    order = asyncio.run(scenario())
    # cold's first virtual-finish tag ties hot's SECOND (seq breaks the
    # tie) and sorts strictly below hot's third and fourth: FIFO would
    # have released [hot, hot, hot, cold]
    assert order == ["hot", "cold", "hot", "hot"]


def test_router_wfq_dispatch_width_bounds_inflight():
    import asyncio

    from pathway_tpu.serving.router import _WfqDispatch

    async def scenario():
        ledger = TenantLedger(_config(), route="router")
        disp = _WfqDispatch(ledger, width=2)
        t1, w1 = await disp.acquire("a", None)
        t2, w2 = await disp.acquire("b", None)
        assert not w1 and not w2
        third = asyncio.ensure_future(disp.acquire("c", None))
        await asyncio.sleep(0)
        assert not third.done() and disp.queued == 1
        disp.release()
        _t3, w3 = await third
        assert w3
        return True

    assert asyncio.run(scenario())
