"""Port of the reference test_asof_joins_stream.py (reference:
python/pathway/tests/temporal/test_asof_joins_stream.py). Mechanical port: package and
imports adapted, fixtures and assertions kept identical."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.temporal.temporal_behavior import common_behavior
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import assert_stream_equality_wo_index


def get_tables() -> tuple[pw.Table, pw.Table]:
    queries = T(
        """
    a | t | __time__
    1 | 2 |    2
    2 | 3 |    2
    3 | 3 |    4
    4 | 5 |    4
    6 | 2 |    6
    7 | 6 |    8
    """
    )

    data = T(
        """
    b | t | __time__
    1 | 1 |    4
    2 | 4 |    6
    3 | 2 |    8
    """
    )

    return (queries, data)


def test_without_behavior():
    queries, data = get_tables()
    result = queries.asof_join_left(data, pw.left.t, pw.right.t).select(
        a=pw.left.a, tl=pw.left.t, b=pw.right.b, tr=pw.right.t
    )

    expected = T(
        """
      | a | tl | b | tr | __time__ | __diff__
    1 | 1 |  2 |   |    |    2     |    1
    2 | 2 |  3 |   |    |    2     |    1
    1 | 1 |  2 |   |    |    4     |   -1
    2 | 2 |  3 |   |    |    4     |   -1
    1 | 1 |  2 | 1 |  1 |    4     |    1
    2 | 2 |  3 | 1 |  1 |    4     |    1
    3 | 3 |  3 | 1 |  1 |    4     |    1
    4 | 4 |  5 | 1 |  1 |    4     |    1
    5 | 6 |  2 | 1 |  1 |    6     |    1
    4 | 4 |  5 | 1 |  1 |    6     |   -1
    4 | 4 |  5 | 2 |  4 |    6     |    1
    6 | 7 |  6 | 2 |  4 |    8     |    1
    1 | 1 |  2 | 1 |  1 |    8     |   -1
    2 | 2 |  3 | 1 |  1 |    8     |   -1
    3 | 3 |  3 | 1 |  1 |    8     |   -1
    5 | 6 |  2 | 1 |  1 |    8     |   -1
    1 | 1 |  2 | 3 |  2 |    8     |    1
    2 | 2 |  3 | 3 |  2 |    8     |    1
    3 | 3 |  3 | 3 |  2 |    8     |    1
    5 | 6 |  2 | 3 |  2 |    8     |    1
    """
    )

    assert_stream_equality_wo_index(result, expected)


@pytest.mark.parametrize("keep_results", [True, False])
def test_cutoff(keep_results: bool):
    queries, data = get_tables()
    behavior = common_behavior(cutoff=2, keep_results=keep_results)
    result = queries.asof_join_left(
        data, pw.left.t, pw.right.t, behavior=behavior
    ).select(a=pw.left.a, tl=pw.left.t, b=pw.right.b, tr=pw.right.t)

    if keep_results:
        expected = T(
            """
          | a | tl | b | tr | __time__ | __diff__
        1 | 1 |  2 |   |    |    2     |    1
        2 | 2 |  3 |   |    |    2     |    1
        1 | 1 |  2 |   |    |    4     |   -1
        2 | 2 |  3 |   |    |    4     |   -1
        1 | 1 |  2 | 1 |  1 |    4     |    1
        2 | 2 |  3 | 1 |  1 |    4     |    1
        3 | 3 |  3 | 1 |  1 |    4     |    1
        4 | 4 |  5 | 1 |  1 |    4     |    1
        4 | 4 |  5 | 1 |  1 |    6     |   -1
        4 | 4 |  5 | 2 |  4 |    6     |    1
        6 | 7 |  6 | 2 |  4 |    8     |    1
        """
        )
    else:
        expected = T(
            """
          | a | tl | b | tr | __time__ | __diff__
        1 | 1 |  2 |   |    |    2     |    1
        2 | 2 |  3 |   |    |    2     |    1
        1 | 1 |  2 |   |    |    4     |   -1
        2 | 2 |  3 |   |    |    4     |   -1
        1 | 1 |  2 | 1 |  1 |    4     |    1
        2 | 2 |  3 | 1 |  1 |    4     |    1
        3 | 3 |  3 | 1 |  1 |    4     |    1
        4 | 4 |  5 | 1 |  1 |    4     |    1
        4 | 4 |  5 | 1 |  1 |    6     |   -1
        4 | 4 |  5 | 2 |  4 |    6     |    1
        1 | 1 |  2 | 1 |  1 |    8     |   -1
        2 | 2 |  3 | 1 |  1 |    8     |   -1
        3 | 3 |  3 | 1 |  1 |    8     |   -1
        6 | 7 |  6 | 2 |  4 |    8     |    1
        """
        )

    assert_stream_equality_wo_index(result, expected)


def test_delay():
    queries, data = get_tables()
    behavior = common_behavior(delay=2)
    result = queries.asof_join_left(
        data, pw.left.t, pw.right.t, behavior=behavior
    ).select(a=pw.left.a, tl=pw.left.t, b=pw.right.b, tr=pw.right.t)

    expected = T(
        """
      | a | tl | b | tr | __time__ | __diff__
    1 | 1 |  2 |   |    |    4     |    1
    2 | 2 |  3 |   |    |    4     |    1
    3 | 3 |  3 |   |    |    4     |    1
    1 | 1 |  2 |   |    |    6     |   -1
    2 | 2 |  3 |   |    |    6     |   -1
    3 | 3 |  3 |   |    |    6     |   -1
    1 | 1 |  2 | 1 |  1 |    6     |    1
    2 | 2 |  3 | 1 |  1 |    6     |    1
    3 | 3 |  3 | 1 |  1 |    6     |    1
    5 | 6 |  2 | 1 |  1 |    6     |    1
    1 | 1 |  2 | 1 |  1 |    8     |   -1
    2 | 2 |  3 | 1 |  1 |    8     |   -1
    3 | 3 |  3 | 1 |  1 |    8     |   -1
    5 | 6 |  2 | 1 |  1 |    8     |   -1
    1 | 1 |  2 | 3 |  2 |    8     |    1
    2 | 2 |  3 | 3 |  2 |    8     |    1
    3 | 3 |  3 | 3 |  2 |    8     |    1
    5 | 6 |  2 | 3 |  2 |    8     |    1
    6 | 4 | 5  | 2 |  4 | 18446744073709551614 | 1
    7 | 7 | 6  | 2 |  4 | 18446744073709551614 | 1
    """
    )

    assert_stream_equality_wo_index(result, expected)
