"""Columnar arrangement engine + delta-join path (engine/arrangement.py,
engine/nodes.py JoinExec): differential-oracle property tests (the
vectorized path must emit the same diffs as the rowwise dict oracle for
random insert/retract sequences across every mode/id_from combination,
null keys, duplicate-id poisoning, multi-batch ticks), arrangement state
semantics vs a dict replay, compaction/merge behavior, and the
regression that a delta tick after a bulk backfill stays columnar (the
PR-5 `_materialize()` cliff fix)."""

import os

import numpy as np
import pytest

import pathway_tpu as pw  # noqa: F401  (conftest clears its graph)
from pathway_tpu.engine.arrangement import (
    Arrangement,
    consolidate_entries,
    mix_keys,
)
from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import InputNode, JoinNode, OutputNode
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.internals.api import (
    Pointer,
    _value_bytes,
    match_keys,
    ref_scalar,
)

L_COLS = ["k", "a"]
R_COLS = ["k", "b"]


def _run_join(mode, id_from, ticks, rowwise):
    """Drive a JoinNode tick by tick; ticks is a list of
    (left_batches, right_batches), each a list of row lists
    [(key, diff, (jk_val, payload)), ...].  Returns the canonicalized
    per-tick outputs: sorted (key, diff, value-bytes) triplets."""
    if rowwise:
        os.environ["PATHWAY_JOIN_ROWWISE"] = "1"
    try:
        inp_l = InputNode(StaticSource(L_COLS), L_COLS)
        inp_r = InputNode(StaticSource(R_COLS), R_COLS)
        join = JoinNode(inp_l, inp_r, ["k"], ["k"], mode, id_from)
        emitted: dict[int, list] = {}

        def on_batch(t, b):
            rows = emitted.setdefault(t, [])
            for k, d, vals in b.iter_rows():
                rows.append((k, d, _value_bytes(vals)))

        out = OutputNode(join, on_batch)
        rt = Runtime([out], worker_threads=False)
        for i, (l_batches, r_batches) in enumerate(ticks):
            injected = {}
            if any(l_batches):
                injected[inp_l.id] = [
                    DiffBatch.from_rows(rows, L_COLS) for rows in l_batches
                ]
            if any(r_batches):
                injected[inp_r.id] = [
                    DiffBatch.from_rows(rows, R_COLS) for rows in r_batches
                ]
            rt.tick(2 * i, injected)
        ex = rt.execs[join.id]
        assert ex._rowwise == rowwise, "unexpected fallback/oracle state"
        return {t: sorted(rows) for t, rows in emitted.items()}
    finally:
        os.environ.pop("PATHWAY_JOIN_ROWWISE", None)


def _random_ticks(seed, n_ticks=8, jk_pool=6, with_nulls=True):
    """Random insert/retract tick sequences for both sides.  Retracted
    row keys are never reused (dict insertion order and arrangement age
    order then agree, which the duplicate-id winner choice depends on);
    live keys may be re-inserted (multiplicity / value updates)."""
    rng = np.random.default_rng(seed)
    next_key = [1]
    live = [{}, {}]  # side -> key -> vals tuple

    def jk_val():
        v = int(rng.integers(0, jk_pool))
        if with_nulls and rng.random() < 0.15:
            return None
        return v

    ticks = []
    for _ in range(n_ticks):
        per_side = []
        for side in (0, 1):
            rows = []
            for _ in range(int(rng.integers(0, 12))):
                op = rng.random()
                if op < 0.30 and live[side]:
                    # retract an existing row (exact values), retire key
                    k = list(live[side])[
                        int(rng.integers(0, len(live[side])))
                    ]
                    rows.append((k, -1, live[side].pop(k)))
                elif op < 0.42 and live[side]:
                    # value update: re-insert the same key, new payload
                    k = list(live[side])[
                        int(rng.integers(0, len(live[side])))
                    ]
                    vals = (live[side][k][0], int(rng.integers(0, 100)))
                    live[side][k] = vals
                    rows.append((k, 1, vals))
                else:
                    k = next_key[0]
                    next_key[0] += 1
                    vals = (jk_val(), int(rng.integers(0, 100)))
                    live[side][k] = vals
                    rows.append((k, 1, vals))
            # multi-batch ticks: occasionally split the rows
            if len(rows) > 2 and rng.random() < 0.4:
                cut = int(rng.integers(1, len(rows)))
                per_side.append([rows[:cut], rows[cut:]])
            else:
                per_side.append([rows] if rows else [])
        ticks.append((per_side[0], per_side[1]))
    return ticks


@pytest.mark.parametrize("mode", ["inner", "left", "right", "outer"])
@pytest.mark.parametrize("id_from", [None, "left", "right"])
def test_columnar_matches_rowwise_oracle(mode, id_from):
    """The arrangement path must emit the same per-tick diffs as the
    rowwise dict oracle for random insert/retract/update sequences with
    null keys and multi-batch ticks, in every mode/id_from combination
    (incl. duplicate-id poisoning for id_from with non-unique matches)."""
    for seed in (3, 17, 92):
        ticks = _random_ticks(seed)
        got = _run_join(mode, id_from, ticks, rowwise=False)
        want = _run_join(mode, id_from, ticks, rowwise=True)
        assert got == want, f"divergence mode={mode} id_from={id_from} seed={seed}"


@pytest.mark.parametrize("mode", ["inner", "left", "right", "outer"])
@pytest.mark.parametrize("id_from", [None, "left"])
def test_columnar_matches_oracle_with_key_reuse(mode, id_from):
    """Retract-then-reinsert of the SAME row key: the dict deletes and
    re-creates the entry (fresh value memory + a fresh insertion
    position); the arrangement's zero-crossing reset rule must agree."""
    rng = np.random.default_rng(23)
    ticks = []
    live = [{}, {}]
    for _ in range(10):
        per_side = []
        for side in (0, 1):
            rows = []
            for _ in range(int(rng.integers(0, 8))):
                k = int(rng.integers(1, 8))  # tiny key pool: heavy reuse
                if k in live[side] and rng.random() < 0.5:
                    rows.append((k, -1, live[side].pop(k)))
                else:
                    vals = (int(rng.integers(0, 3)), int(rng.integers(0, 50)))
                    live[side][k] = vals
                    rows.append((k, 1, vals))
            per_side.append([rows] if rows else [])
        ticks.append((per_side[0], per_side[1]))
    got = _run_join(mode, id_from, ticks, rowwise=False)
    want = _run_join(mode, id_from, ticks, rowwise=True)
    assert got == want


def test_columnar_matches_oracle_heavy_churn():
    """Retraction-heavy single-jk hot spot (every row shares one join
    key) — exercises cross products, negative counts, and compaction."""
    rng = np.random.default_rng(5)
    live: dict[int, tuple] = {}
    ticks = []
    nk = 1
    for _ in range(10):
        rows = []
        for _ in range(8):
            if live and rng.random() < 0.45:
                k = list(live)[int(rng.integers(0, len(live)))]
                rows.append((k, -1, live.pop(k)))
            else:
                vals = (1, int(rng.integers(0, 50)))
                live[nk] = vals
                rows.append((nk, 1, vals))
                nk += 1
        ticks.append(([rows], [[(10_000 + nk, 1, (1, nk))]]))
    got = _run_join("outer", None, ticks, rowwise=False)
    want = _run_join("outer", None, ticks, rowwise=True)
    assert got == want


def test_retraction_before_insert_matches_oracle():
    """A retraction arriving before its insert leaves a negative-count
    entry; the old dict path emits the pair once both sides' counts have
    the same sign (lc*rc>0) — the arrangement path must agree."""
    ticks = [
        ([[(1, -1, (7, 10))]], [[(2, -1, (7, 20))]]),  # both negative
        ([[(1, 1, (7, 10))]], []),  # left back to 0
        ([[(1, 1, (7, 10))]], [[(2, 1, (7, 20))]]),  # both at 0/positive
        ([], [[(2, 1, (7, 20))]]),
    ]
    for mode in ("inner", "outer"):
        got = _run_join(mode, None, ticks, rowwise=False)
        want = _run_join(mode, None, ticks, rowwise=True)
        assert got == want


# --- arrangement state semantics ------------------------------------------


def _dict_replay(entries):
    """Reference semantics: _SideState.apply replayed on a plain dict."""
    state: dict[tuple, list] = {}
    for jk, k, d, val in entries:
        e = state.get((jk, k))
        if e is None:
            if d != 0:
                state[(jk, k)] = [val, d]
        else:
            e[1] += d
            if d > 0:
                e[0] = val
            if e[1] == 0:
                del state[(jk, k)]
    return {kk: (v[0], v[1]) for kk, v in state.items()}


def test_arrangement_matches_dict_replay():
    rng = np.random.default_rng(11)
    arr = Arrangement(1)
    entries = []
    for _tick in range(30):
        n = int(rng.integers(1, 20))
        jks = rng.integers(0, 5, size=n).astype(np.uint64)
        keys = rng.integers(0, 12, size=n).astype(np.uint64)
        diffs = rng.choice([-1, 1, 2], size=n).astype(np.int64)
        vals = rng.integers(0, 1000, size=n)
        arr.append(jks, keys, diffs, [vals])
        entries.extend(
            (int(j), int(k), int(d), int(v))
            for j, k, d, v in zip(jks, keys, diffs, vals)
        )
        if rng.random() < 0.3:
            rows = arr.entries()  # forces seal + consolidation
            got = {
                (int(j), int(k)): (int(val), int(c))
                for j, k, c, val in zip(
                    rows.jk, rows.key, rows.count, rows.cols[0]
                )
            }
            assert got == _dict_replay(entries)
    rows = arr.entries()
    got = {
        (int(j), int(k)): (int(val), int(c))
        for j, k, c, val in zip(rows.jk, rows.key, rows.count, rows.cols[0])
    }
    assert got == _dict_replay(entries)


def test_probe_returns_only_requested_jks():
    arr = Arrangement(1)
    jks = np.array([1, 2, 3, 2, 1], dtype=np.uint64)
    keys = np.arange(5, dtype=np.uint64)
    arr.append(jks, keys, np.ones(5, np.int64), [np.arange(5)])
    rows = arr.probe(np.array([2], dtype=np.uint64))
    assert sorted(rows.key.tolist()) == [1, 3]
    assert (rows.jk == 2).all()


def test_compaction_cancels_dead_entries():
    arr = Arrangement(1, compact_ratio=0.2)
    n = 1000
    jks = np.arange(n, dtype=np.uint64)
    keys = np.arange(n, dtype=np.uint64)
    vals = np.arange(n)
    arr.append(jks, keys, np.ones(n, np.int64), [vals])
    # retract 40% — crosses the 20% retraction-density threshold
    m = 400
    arr.append(jks[:m], keys[:m], -np.ones(m, np.int64), [vals[:m]])
    rows = arr.entries()
    assert arr.compactions >= 1
    assert len(rows) == n - m
    assert len(arr) == n - m  # dead insert+retract pairs are gone
    assert sorted(rows.key.tolist()) == list(range(m, n))


def test_seal_survives_midway_exception_without_double_count():
    """A seal that raises halfway (e.g. allocation failure during a
    merge) must not re-seal already-committed batches on retry — sealed
    entries would double their net weights."""
    arr = Arrangement(1)
    for start in (0, 10):
        keys = np.arange(start, start + 5, dtype=np.uint64)
        arr.append(keys, keys, np.ones(5, np.int64), [keys.astype(np.int64)])
    orig = arr._merge_last_two
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise MemoryError("boom")

    arr._merge_last_two = boom
    with pytest.raises(MemoryError):
        arr.entries()
    arr._merge_last_two = orig
    rows = arr.entries()  # retry after the failure
    assert len(rows) == 10
    assert rows.count.tolist() == [1] * 10  # nothing sealed twice


def test_merge_keeps_dtype_and_values():
    arr = Arrangement(1, max_segments=2)
    a = np.array([5, 7], dtype=np.int64)
    b = np.empty(2, dtype=object)
    b[:] = ["x", "y"]
    arr.append(np.array([1, 2], np.uint64), np.array([1, 2], np.uint64),
               np.ones(2, np.int64), [a])
    arr.append(np.array([3, 4], np.uint64), np.array([3, 4], np.uint64),
               np.ones(2, np.int64), [b])
    rows = arr.entries()
    got = {int(k): v for k, v in zip(rows.key, rows.cols[0])}
    assert got == {1: 5, 2: 7, 3: "x", 4: "y"}
    assert type(got[1]) in (int, np.int64)


def test_consolidate_last_positive_value_wins():
    # +v1, +v2, -retract: count 1, value stays v2 (dict parity)
    jks = np.zeros(3, np.uint64)
    keys = np.zeros(3, np.uint64)
    diffs = np.array([1, 1, -1], np.int64)
    vals = np.array(["v1", "v2", "v2"], dtype=object)
    rows = consolidate_entries(
        jks, keys, diffs, np.arange(3, dtype=np.int64), [vals]
    )
    assert len(rows) == 1
    assert rows.count[0] == 1 and rows.cols[0][0] == "v2"


def test_match_keys_fallback_matches_native():
    rng = np.random.default_rng(2)
    left = rng.integers(0, 50, size=200).astype(np.uint64)
    right = rng.integers(0, 50, size=150).astype(np.uint64)
    li, ri = match_keys(left, right)
    # brute-force reference, in (left order, right order)
    want = [
        (i, j)
        for i in range(len(left))
        for j in range(len(right))
        if left[i] == right[j]
    ]
    assert list(zip(li.tolist(), ri.tolist())) == want


# --- the _materialize() cliff fix ------------------------------------------


def _counter_value(counter, *labels):
    child = counter.labels(*labels) if labels else counter._unlabeled()
    return child.value


def test_delta_tick_after_bulk_backfill_stays_columnar():
    """Regression for the PR-5 cliff: the first incremental delta after a
    100k-row bulk backfill must NOT convert the operator state into
    Python dicts — the arrangement stays columnar and the tick is served
    by the delta path (bulk-hits counter, zero new fallbacks)."""
    n = 100_000
    inp_l = InputNode(StaticSource(L_COLS), L_COLS)
    inp_r = InputNode(StaticSource(R_COLS), R_COLS)
    join = JoinNode(inp_l, inp_r, ["k"], ["k"], "inner", None)
    out_rows = {"n": 0}
    out = OutputNode(join, lambda t, b: out_rows.__setitem__(
        "n", out_rows["n"] + int(b.diffs.sum())
    ))
    rt = Runtime([out], worker_threads=False)
    ex = rt.execs[join.id]
    hits0 = _counter_value(ex._m_hits)
    fb0 = sum(
        child.value for child in ex._m_fallbacks._children.values()
    )
    rk = np.arange(n, dtype=np.int64)
    bulk = DiffBatch(
        np.arange(n, dtype=np.uint64) + 1,
        np.ones(n, np.int64),
        {"k": rk, "b": rk},
    )
    rt.tick(0, {inp_r.id: [bulk]})
    # incremental delta tick probing the arranged side
    lk = np.array([5, 17, 99_999], dtype=np.int64)
    delta = DiffBatch(
        np.array([900_001, 900_002, 900_003], np.uint64),
        np.ones(3, np.int64),
        {"k": lk, "a": lk * 10},
    )
    rt.tick(2, {inp_l.id: [delta]})
    assert out_rows["n"] == 3
    assert ex._rowwise is False
    assert ex.left is None and ex.right is None  # dicts never built
    assert len(ex.arr_r) == n  # state stayed in the arrangement
    assert _counter_value(ex._m_hits) == hits0 + 2  # both ticks columnar
    fb1 = sum(
        child.value for child in ex._m_fallbacks._children.values()
    )
    assert fb1 == fb0  # no fallback fired


def test_env_forced_rowwise_counts_fallback(monkeypatch):
    monkeypatch.setenv("PATHWAY_JOIN_ROWWISE", "1")
    inp_l = InputNode(StaticSource(L_COLS), L_COLS)
    inp_r = InputNode(StaticSource(R_COLS), R_COLS)
    join = JoinNode(inp_l, inp_r, ["k"], ["k"], "inner", None)
    out = OutputNode(join, lambda t, b: None)
    rt = Runtime([out], worker_threads=False)
    ex = rt.execs[join.id]
    assert ex._rowwise and ex._fallback_reason == "env"
    env0 = _counter_value(ex._m_fallbacks, "env")
    rt.tick(
        0,
        {
            inp_l.id: [
                DiffBatch.from_rows([(1, 1, (7, 1))], L_COLS)
            ],
            inp_r.id: [
                DiffBatch.from_rows([(2, 1, (7, 2))], R_COLS)
            ],
        },
    )
    assert _counter_value(ex._m_fallbacks, "env") == env0 + 1


def test_exception_fallback_materializes_and_survives(monkeypatch):
    """If the columnar path blows up mid-tick, the exec logs, converts
    the (pre-tick) arrangements to dict state, finishes the tick rowwise,
    and keeps producing correct outputs."""
    inp_l = InputNode(StaticSource(L_COLS), L_COLS)
    inp_r = InputNode(StaticSource(R_COLS), R_COLS)
    join = JoinNode(inp_l, inp_r, ["k"], ["k"], "inner", None)
    emitted = []

    def on_batch(t, b):
        for k, d, vals in b.iter_rows():
            emitted.append((d, vals[0], vals[2]))

    out = OutputNode(join, on_batch)
    rt = Runtime([out], worker_threads=False)
    ex = rt.execs[join.id]
    rt.tick(0, {inp_r.id: [DiffBatch.from_rows([(2, 1, (7, 2))], R_COLS)]})
    monkeypatch.setattr(
        ex, "_delta_tick",
        lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    rt.tick(2, {inp_l.id: [DiffBatch.from_rows([(1, 1, (7, 1))], L_COLS)]})
    assert ex._rowwise and ex._fallback_reason == "exception"
    assert ex.left is not None and len(ex.right.by_jk) == 1
    assert sorted(emitted) == [(1, 7, 7)]  # the join still happened


def test_join_exec_state_dict_roundtrips():
    """Operator snapshots: arrangements pickle (registry handles are
    excluded) and a restored exec keeps answering deltas."""
    import pickle

    inp_l = InputNode(StaticSource(L_COLS), L_COLS)
    inp_r = InputNode(StaticSource(R_COLS), R_COLS)
    join = JoinNode(inp_l, inp_r, ["k"], ["k"], "inner", None)
    out = OutputNode(join, lambda t, b: None)
    rt = Runtime([out], worker_threads=False)
    ex = rt.execs[join.id]
    rt.tick(0, {
        inp_r.id: [DiffBatch.from_rows([(2, 1, (7, 2))], R_COLS)],
    })
    blob = pickle.dumps(ex.state_dict())
    ex2 = join.make_exec()
    ex2.load_state(pickle.loads(blob))
    out2 = ex2.process(
        2,
        [[DiffBatch.from_rows([(1, 1, (7, 1))], L_COLS)], []],
    )
    assert sum(len(b) for b in out2) == 1


# --- vectorized null-key private hashing -----------------------------------


def test_batch_jks_null_rows_byte_identical():
    """The batched null-key path must produce the same private keys as
    the per-row ref_scalar loop it replaced."""
    inp_l = InputNode(StaticSource(L_COLS), L_COLS)
    inp_r = InputNode(StaticSource(R_COLS), R_COLS)
    join = JoinNode(inp_l, inp_r, ["k"], ["k"], "inner", None)
    ex = join.make_exec()
    rows = [(10, 1, (None, 1)), (11, 1, (3, 2)), (12, 1, (None, 3))]
    b = DiffBatch.from_rows(rows, L_COLS)
    jks = ex._batch_jks(b, ex.l_on_idx, "l")
    for i, (k, _d, vals) in enumerate(rows):
        if vals[0] is None:
            want = int(ref_scalar("__pw_null", "l", Pointer(k)))
            assert int(jks[i]) == want & 0xFFFFFFFFFFFFFFFF
        else:
            assert int(jks[i]) == int(ref_scalar(3))


def test_sharded_join_null_key_routing_matches_single_shard():
    """ShardedJoinExec routes by the inner exec's _batch_jks contract
    (null on-columns get per-row private keys): output must equal the
    single-shard exec, including outer padding for null-keyed rows."""
    import jax
    from jax.sharding import Mesh

    from pathway_tpu.engine.sharded import ShardedJoinExec

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("data",))
    inp_l = InputNode(StaticSource(L_COLS), L_COLS)
    inp_r = InputNode(StaticSource(R_COLS), R_COLS)
    jn = JoinNode(inp_l, inp_r, ["k"], ["k"], "outer", None)
    sharded = ShardedJoinExec(jn, mesh, "data")
    single = JoinNode(inp_l, inp_r, ["k"], ["k"], "outer", None).make_exec()
    l_rows = [(1, 1, (7, 10)), (2, 1, (None, 11)), (3, 1, (None, 12)),
              (4, 1, (8, 13))]
    r_rows = [(5, 1, (7, 20)), (6, 1, (None, 21)), (7, 1, (8, 22))]
    lb = [DiffBatch.from_rows(l_rows, L_COLS)]
    rb = [DiffBatch.from_rows(r_rows, R_COLS)]

    def canon(batches):
        return sorted(
            (k, d, _value_bytes(vals))
            for b in batches
            for k, d, vals in b.iter_rows()
        )

    assert canon(sharded.process(0, [lb, rb])) == canon(
        single.process(0, [lb, rb])
    )


def test_mix_keys_no_false_negatives():
    jks = np.array([1, 2, 3], np.uint64)
    keys = np.array([7, 8, 9], np.uint64)
    assert (mix_keys(jks, keys) == mix_keys(jks, keys)).all()
    assert len(set(mix_keys(jks, keys).tolist())) == 3
