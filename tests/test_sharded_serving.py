"""Shard Harbor tests — replica×shard scatter-gather serving and the
standby-writer takeover path.

Covers the acceptance bars in-process and fast (tier-1):

* property: sharded scatter-gather merged top-k equals the unsharded
  top-k over random corpora — ties, deletions mid-stream, and
  per-shard staleness skew included;
* torn shard assignment maps rejected at BOOT (router map validation +
  replica shard bounds + stream-level shard-count fencing);
* 2-shard scatter-gather through the real writer→replica→router path,
  partial-shard outage naming the missing shards;
* writer-kill → standby takeover handoff with incarnation fencing of a
  zombie primary.

The heavy multi-process legs live in ``bench.py serve_chaos`` (shard ×
replica sweep + SIGKILL takeover, SERVE_r11.json).
"""

import json
import threading
import time

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _repl_env(monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "shard-harbor-test-secret")
    monkeypatch.delenv("PATHWAY_SERVING_SHARDS", raising=False)
    monkeypatch.delenv("PATHWAY_SERVING_SHARD_MAP", raising=False)
    monkeypatch.delenv("PATHWAY_MESH_INCARNATION", raising=False)
    from pathway_tpu.parallel import replicate

    yield
    replicate.reset_publisher()


def _wait(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class ToyIndex:
    """Dict-backed index for non-vector payloads (takeover smoke)."""

    def __init__(self):
        self.d: dict[int, tuple] = {}

    def keys(self):
        return list(self.d.keys())

    def upsert(self, key, data, meta):
        self.d[int(key)] = (data, meta)

    def remove(self, key):
        self.d.pop(int(key), None)

    def search(self, triples):
        return [
            tuple((key, 1.0) for key in sorted(self.d)[: int(k)])
            for _q, k, _f in triples
        ]


class ToyVecIndex:
    """Brute-force vector index with the DETERMINISTIC (score desc,
    key asc) tie-break — the same rule merge_topk applies, so sharded
    and unsharded answers are bit-comparable."""

    def __init__(self):
        self.d: dict[int, np.ndarray] = {}

    def keys(self):
        return list(self.d.keys())

    def upsert(self, key, data, meta):
        self.d[int(key)] = np.asarray(data, dtype=np.float32)

    def remove(self, key):
        self.d.pop(int(key), None)

    def search(self, triples):
        out = []
        for q, k, _f in triples:
            qv = np.asarray(q, dtype=np.float32)
            scored = [
                (key, float(qv @ vec)) for key, vec in self.d.items()
            ]
            scored.sort(key=lambda m: (-m[1], m[0]))
            out.append(tuple(scored[: int(k)]))
        return out


def _batch(rows):
    from pathway_tpu.engine.batch import DiffBatch

    return DiffBatch.from_rows(rows, ("_data", "_meta"))


# ---------------------------------------------------------------------------
# merge + map validation (pure units)


def test_merge_topk_equals_brute_force_property():
    from pathway_tpu.serving.router import merge_topk

    rng = np.random.default_rng(7)
    for _trial in range(50):
        n_shards = int(rng.integers(1, 5))
        k = int(rng.integers(1, 8))
        # duplicate scores on purpose: ties must break by key
        pool = [
            [int(key), float(score)]
            for key, score in zip(
                rng.choice(10_000, size=40, replace=False),
                rng.choice([0.1, 0.5, 0.5, 0.9], size=40),
            )
        ]
        shards = [pool[s::n_shards] for s in range(n_shards)]
        per_shard_topk = [
            sorted(s, key=lambda m: (-m[1], m[0]))[:k] for s in shards
        ]
        expect = sorted(pool, key=lambda m: (-m[1], m[0]))[:k]
        assert merge_topk(per_shard_topk, k) == expect


def test_shard_map_validation_rejects_torn_maps(monkeypatch):
    from pathway_tpu.serving.router import (
        FailoverRouter,
        shard_map_from_env,
        validate_shard_map,
    )

    with pytest.raises(ValueError, match="no members"):
        validate_shard_map([["http://a"], []])
    with pytest.raises(ValueError, match="listed in shard"):
        validate_shard_map([["http://a"], ["http://a"]])
    with pytest.raises(ValueError, match="empty"):
        validate_shard_map([])
    # the same rejection through the constructor and the env
    with pytest.raises(ValueError, match="listed in shard"):
        FailoverRouter(shards=[["http://a"], ["http://b", "http://a"]])
    monkeypatch.setenv(
        "PATHWAY_SERVING_SHARD_MAP", "http://a|http://b|"
    )
    with pytest.raises(ValueError, match="no members"):
        shard_map_from_env()


def test_replica_rejects_torn_shard_assignment_at_boot():
    from pathway_tpu.serving.replica import ReplicaServer

    with pytest.raises(ValueError, match="torn shard"):
        ReplicaServer(
            replica_id=0, index_factory=ToyVecIndex, shard=5, n_shards=3
        )
    with pytest.raises(ValueError, match="torn shard"):
        # sharded plane with NO shard assignment
        ReplicaServer(
            replica_id=0, index_factory=ToyVecIndex, shard=-1, n_shards=3
        )


# ---------------------------------------------------------------------------
# property: sharded == unsharded over random corpora


def _apply_ops(index, ops):
    for key, diff, vec in ops:
        if diff > 0:
            index.upsert(key, vec, None)
        else:
            index.remove(key)


def test_scatter_gather_property_random_corpora():
    """Random insert/delete streams with forced score ties: per-shard
    top-k merged with merge_topk is bit-equal to the unsharded index's
    top-k — including per-shard STALENESS SKEW (one shard applied only
    a prefix of its stream; the reference is the union of exactly what
    each shard applied, well-defined because shards own disjoint
    keys)."""
    from pathway_tpu.parallel.replicate import corpus_shard_of
    from pathway_tpu.serving.router import merge_topk

    rng = np.random.default_rng(42)
    DIM = 6
    for trial in range(8):
        n_shards = int(rng.integers(2, 5))
        # a small vector vocabulary FORCES exact-score ties
        vocab = rng.standard_normal((4, DIM)).astype(np.float32)
        live: set[int] = set()
        ops: list[tuple[int, int, np.ndarray | None]] = []
        for _ in range(200):
            if live and rng.random() < 0.3:
                key = int(rng.choice(list(live)))
                live.discard(key)
                ops.append((key, -1, None))
            else:
                key = int(rng.integers(0, 500))
                live.add(key)
                ops.append((key, 1, vocab[int(rng.integers(0, 4))]))
        # per-shard streams (the writer's split), then a skew point per
        # shard: shard s applies only its first skew[s] ops
        shard_ops: list[list] = [[] for _ in range(n_shards)]
        for op in ops:
            s = int(corpus_shard_of([op[0]], n_shards)[0])
            shard_ops[s].append(op)
        skew = [
            int(rng.integers(len(so) // 2, len(so) + 1)) if so else 0
            for so in shard_ops
        ]
        shard_indexes = [ToyVecIndex() for _ in range(n_shards)]
        reference = ToyVecIndex()
        for s in range(n_shards):
            applied = shard_ops[s][: skew[s]]
            _apply_ops(shard_indexes[s], applied)
            _apply_ops(reference, applied)
        for qi in range(5):
            q = vocab[qi % 4] + (
                0 if qi < 4 else rng.standard_normal(DIM).astype(np.float32)
            )
            k = int(rng.integers(1, 9))
            per_shard = [
                [[key, score] for key, score in idx.search([(q, k, None)])[0]]
                for idx in shard_indexes
            ]
            merged = merge_topk(per_shard, k)
            expect = [
                [key, score]
                for key, score in reference.search([(q, k, None)])[0]
            ]
            assert merged == expect, (trial, qi, merged, expect)


# ---------------------------------------------------------------------------
# sharded delta-stream fan-out


def test_sharded_fanout_delivers_only_owned_keys():
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
        corpus_shard_of,
    )

    srv = DeltaStreamServer(0, n_shards=2)
    seen: dict[int, list] = {0: [], 1: [], -1: []}
    ticks: dict[int, list] = {0: [], 1: [], -1: []}
    clients = []
    for shard in (0, 1, -1):
        cl = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            # full-corpus subscriptions to a sharded writer are an
            # OBSERVER/standby privilege (negative id) — a replica
            # subscribing unsharded would be fenced as torn
            shard if shard >= 0 else -7,
            from_tick=-1,
            on_deltas=lambda t, bs, shard=shard: (
                ticks[shard].append(t),
                seen[shard].extend(
                    k for b in bs for k, _d, _v in b.iter_rows()
                ),
            ),
            shard=shard,
            expect_shards=2 if shard >= 0 else 0,
        )
        cl.start()
        clients.append(cl)
    try:
        keys = list(range(40))
        srv.publish(0, [_batch([(k, 1, (f"d{k}", None)) for k in keys])])
        srv.publish(1, [])  # idle marker reaches every shard
        assert _wait(
            lambda: all(t and t[-1] == 1 for t in ticks.values())
        ), ticks
        dest = corpus_shard_of(keys, 2)
        for shard in (0, 1):
            expect = {k for k, s in zip(keys, dest) if int(s) == shard}
            assert set(seen[shard]) == expect
        assert set(seen[-1]) == set(keys)  # full-corpus subscriber
        # every subscriber tracks freshness tick-by-tick
        for cl in clients:
            assert cl.applied_tick == 1
    finally:
        for cl in clients:
            cl.close()
        srv.close()


def test_stream_fences_torn_shard_count():
    """A replica expecting S shards against a writer splitting into a
    different count never applies a frame (the torn-map guard at the
    stream level)."""
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )

    srv = DeltaStreamServer(0, n_shards=2)
    applied: list[int] = []
    cl = DeltaStreamClient(
        "127.0.0.1",
        srv.port,
        0,
        from_tick=-1,
        on_deltas=lambda t, bs: applied.append(t),
        shard=0,
        expect_shards=3,  # torn: writer says 2
    )
    cl.start()
    try:
        srv.publish(0, [_batch([(1, 1, ("a", None))])])
        assert _wait(lambda: cl.config_error is not None, timeout=10)
        assert "torn shard assignment" in cl.config_error
        time.sleep(0.3)
        assert applied == []
        # an UNSHARDED replica (positive id, no expectation) against a
        # sharded writer is torn too — it would hold the full corpus
        # behind a router that thinks it owns one shard
        cl2 = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            1,
            from_tick=-1,
            on_deltas=lambda t, bs: applied.append(t),
        )
        cl2.start()
        try:
            assert _wait(lambda: cl2.config_error is not None, timeout=10)
            assert applied == []
        finally:
            cl2.close()
    finally:
        cl.close()
        srv.close()


# ---------------------------------------------------------------------------
# scatter-gather end-to-end: writer -> sharded replicas -> router


def _vec_responder(server, values):
    q = np.asarray(values["vec"], dtype=np.float32)
    res = server.search([(q, int(values.get("k", 3)), None)])[0]
    return {"matches": [[int(k), float(s)] for k, s in res]}


def _start_sharded_plane(n_shards=2, members_per_shard=2):
    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer
    from pathway_tpu.serving.router import FailoverRouter

    srv = DeltaStreamServer(0, n_shards=n_shards)
    reps: list[list] = []
    for shard in range(n_shards):
        members = []
        for i in range(members_per_shard):
            members.append(
                ReplicaServer(
                    replica_id=shard * members_per_shard + i,
                    index_factory=ToyVecIndex,
                    writer_port=srv.port,
                    responder=_vec_responder,
                    shard=shard,
                    n_shards=n_shards,
                ).start()
            )
        reps.append(members)
    router = FailoverRouter(
        shards=[
            [f"http://127.0.0.1:{m.http_port}" for m in members]
            for members in reps
        ],
        health_interval_ms=100,
    ).start()
    return srv, reps, router


def test_router_two_shard_scatter_gather_smoke():
    """Tier-1 scatter-gather smoke (<60 s): a 2-shard × 2-member plane
    answers merged global top-k equal to the unsharded reference; a
    member death inside one shard is retried on the shard sibling; a
    WHOLE shard going dark sheds 503 naming the missing shard for
    bounded reads — never silent truncation."""
    import requests

    from pathway_tpu.parallel.replicate import corpus_shard_of

    srv, reps, router = _start_sharded_plane(2, 2)
    try:
        rng = np.random.default_rng(3)
        vecs = {k: rng.standard_normal(4).astype(np.float32) for k in range(30)}
        srv.publish(
            0, [_batch([(k, 1, (v, None)) for k, v in vecs.items()])]
        )
        # a mid-stream deletion crosses the wire too
        srv.publish(1, [_batch([(5, -1, (None, None))])])
        del vecs[5]
        assert _wait(
            lambda: all(m.ready for ms in reps for m in ms), timeout=20
        )
        assert _wait(
            lambda: all(ep.ready for ep in router.endpoints), timeout=10
        )
        # every member holds ONLY its shard's keys (1/S ownership)
        for shard, members in enumerate(reps):
            for m in members:
                owned = set(m.index.keys())
                assert owned, "shard member hydrated nothing"
                assert all(
                    int(corpus_shard_of([k], 2)[0]) == shard for k in owned
                )
        reference = ToyVecIndex()
        for k, v in vecs.items():
            reference.upsert(k, v, None)
        url = f"http://127.0.0.1:{router.port}/query"
        q = rng.standard_normal(4).astype(np.float32)
        r = requests.post(
            url, json={"vec": [float(x) for x in q], "k": 6}, timeout=10
        )
        assert r.status_code == 200, r.text
        assert r.headers["x-pathway-shards"] == "2"
        expect = [
            [k, pytest.approx(s)]
            for k, s in reference.search([(q, 6, None)])[0]
        ]
        assert r.json()["matches"] == expect
        # a CLIENT error surfaces as itself — it must not burn every
        # member and masquerade as a shard outage (404: unknown route)
        r = requests.post(
            f"http://127.0.0.1:{router.port}/nope", json={}, timeout=15
        )
        assert r.status_code == 404
        # member death inside shard 0: the shard sibling answers
        reps[0][0]._http.stop()
        r = requests.post(
            url, json={"vec": [float(x) for x in q], "k": 6}, timeout=15
        )
        assert r.status_code == 200, r.text
        assert r.json()["matches"] == expect
        # WHOLE shard 0 dark: bounded reads shed naming the shard
        reps[0][1]._http.stop()
        assert _wait(
            lambda: all(
                ep.ejected for ep in router.endpoints if ep.shard == 0
            ),
            timeout=15,
        )
        r = requests.post(
            url,
            json={"vec": [float(x) for x in q], "k": 6},
            headers={"x-pathway-max-staleness-ms": "60000"},
            timeout=15,
        )
        assert r.status_code == 503
        assert "Retry-After" in r.headers
        assert r.headers.get("x-pathway-missing-shards") == "0"
        assert "shard" in r.json()["error"]
    finally:
        router.stop()
        for members in reps:
            for m in members:
                m.stop()
        srv.close()


# ---------------------------------------------------------------------------
# standby takeover + zombie fencing


class _InProcWriter:
    """A 'writer role' the in-process takeover respawns: a
    DeltaStreamServer on a FIXED port plus the corpus it republishes
    (the stand-in for the real writer's restore+replay+publish boot)."""

    def __init__(self, port, corpus, incarnation):
        from pathway_tpu.parallel.replicate import DeltaStreamServer

        self.srv = DeltaStreamServer(
            port, incarnation=incarnation, ring_ticks=64
        )
        self.corpus = corpus
        self.tick = 100 * incarnation  # distinct tick ranges per life
        self.srv.set_floor(-1 if incarnation == 0 else self.tick - 1)
        self.publish_corpus()

    def publish_corpus(self):
        rows = [(k, 1, (v, None)) for k, v in sorted(self.corpus.items())]
        self.srv.publish(self.tick, [_batch(rows)] if rows else [])
        self.tick += 1

    def publish(self, rows):
        for k, d, v in rows:
            if d > 0:
                self.corpus[k] = v[0]
            else:
                self.corpus.pop(k, None)
        self.srv.publish(self.tick, [_batch(rows)])
        self.tick += 1


def test_writer_kill_standby_takeover_smoke():
    """Tier-1 takeover smoke (<60 s): the primary dies mid-stream, the
    standby notices within its grace window, bumps the incarnation and
    resumes publishing on the writer endpoint; the replica reconnects,
    re-converges (idempotent re-applies, zero duplicate rows in the
    folded corpus) and keeps serving with error_served == 0."""
    import requests

    from pathway_tpu.parallel.standby import StandbyWriter
    from pathway_tpu.serving.replica import ReplicaServer
    from pathway_tpu.testing.chaos import free_dcn_port

    port = free_dcn_port(1)
    corpus = {k: f"v{k}" for k in range(6)}
    primary = _InProcWriter(port, dict(corpus), incarnation=0)
    rep = ReplicaServer(
        replica_id=0,
        index_factory=ToyIndex,
        writer_port=port,
        responder=lambda s, v: {
            "corpus": {str(k): str(val[0]) for k, val in _toy_items(s)}
        },
    ).start()

    takeovers: list = []

    def on_takeover(standby):
        new = _InProcWriter(
            port, dict(primary.corpus), standby.next_incarnation()
        )
        takeovers.append(new)

    standby = StandbyWriter(
        "127.0.0.1",
        port,
        on_takeover=on_takeover,
        grace_s=0.6,
        poll_s=0.05,
    ).start()
    try:
        assert _wait(lambda: rep.ready, timeout=15)
        assert _wait(lambda: standby.applied_tick >= 0, timeout=15)
        primary.publish([(6, 1, ("v6", None))])
        assert _wait(lambda: 6 in _toy_keys(rep), timeout=10)
        # primary dies mid-stream
        primary.srv.close()
        assert standby.wait_takeover(timeout=20), standby.events
        assert takeovers, "takeover callback never ran"
        new_writer = takeovers[0]
        assert new_writer.srv.incarnation == 1
        # the replica reconnects to the SAME endpoint, now served by
        # the takeover writer, and re-converges on the full corpus
        assert _wait(
            lambda: rep.health()["writer_incarnation"] == 1, timeout=20
        ), rep.health()
        new_writer.publish([(7, 1, ("v7", None))])
        assert _wait(lambda: 7 in _toy_keys(rep), timeout=15)
        # zero replayed-duplicate rows: the folded corpus matches the
        # writer's exactly (re-applied boundary ticks are idempotent)
        assert _toy_dict(rep) == {
            k: (f"v{k}", None) for k in list(range(8))
        }
        # reads keep answering across the handoff window's tail
        r = requests.post(
            f"http://127.0.0.1:{rep.http_port}/query", json={}, timeout=10
        )
        assert r.status_code == 200
        assert r.json()["corpus"]["7"] == "v7"
        assert rep.health()["fenced_writers"] == 0
    finally:
        standby.stop()
        rep.stop()
        primary.srv.close()
        for w in takeovers:
            w.srv.close()


def _toy_items(server):
    return list(server.index.d.items())


def _toy_keys(rep):
    return set(rep.index.d.keys())


def _toy_dict(rep):
    return dict(rep.index.d)


def test_zombie_primary_is_fenced():
    """After a takeover bumped the incarnation, a zombie primary coming
    back on the old endpoint is rejected at suback time: none of its
    frames ever apply."""
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )
    from pathway_tpu.testing.chaos import free_dcn_port

    p1, p2 = free_dcn_port(1), free_dcn_port(1)
    applied: list[tuple[int, list]] = []
    cl = DeltaStreamClient(
        "127.0.0.1",
        p1,
        0,
        from_tick=-1,
        on_deltas=lambda t, bs: applied.append(
            (t, [k for b in bs for k, _d, _v in b.iter_rows()])
        ),
        endpoints=[("127.0.0.1", p1), ("127.0.0.1", p2)],
    )
    # the post-takeover writer lives on p2 with incarnation 1
    new_writer = DeltaStreamServer(p2, incarnation=1)
    cl.start()
    zombie = None
    try:
        new_writer.publish(0, [_batch([(1, 1, ("legit", None))])])
        assert _wait(lambda: cl.writer_incarnation == 1, timeout=15)
        assert _wait(lambda: applied and applied[-1][0] == 0, timeout=10)
        # the takeover writer dies too; a ZOMBIE incarnation-0 primary
        # resurfaces on the old endpoint and keeps publishing
        new_writer.close()
        zombie = DeltaStreamServer(p1, incarnation=0)
        zombie.publish(50, [_batch([(666, 1, ("zombie", None))])])
        assert _wait(lambda: cl.fenced_count >= 1, timeout=15)
        time.sleep(0.5)
        assert all(666 not in keys for _t, keys in applied), applied
        assert cl.applied_tick == 0  # nothing from the zombie applied
    finally:
        cl.close()
        new_writer.close()
        if zombie is not None:
            zombie.close()


def test_unsharded_router_refuses_shard_owning_member():
    """The inverse misconfig: a member owning 1/S of the corpus behind
    a PLAIN replicas-list router would serve partial answers with
    healthy 200s — the health loop ejects it on the reported shard
    count instead."""
    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer
    from pathway_tpu.serving.router import FailoverRouter

    srv = DeltaStreamServer(0, n_shards=2)
    member = ReplicaServer(
        replica_id=0,
        index_factory=ToyVecIndex,
        writer_port=srv.port,
        responder=_vec_responder,
        shard=0,
        n_shards=2,
    ).start()
    router = FailoverRouter(
        [f"http://127.0.0.1:{member.http_port}"],
        health_interval_ms=100,
    ).start()
    try:
        srv.publish(0, [_batch([(1, 1, (np.ones(4, np.float32), None))])])
        assert _wait(lambda: member.ready, timeout=15)
        ep = router.endpoints[0]
        assert _wait(lambda: ep.ejected, timeout=10)
        assert "shard-mismatch" in ep.eject_reason
        assert not ep.ready  # never routed to
    finally:
        router.stop()
        member.stop()
        srv.close()


def test_restarted_replica_probes_endpoints_and_shuns_zombie():
    """A FRESH client (restarted replica: empty in-memory fencing
    high-water) facing a live zombie (incarnation 0) on the first
    endpoint AND the legitimate takeover writer (incarnation 1) on the
    second must probe both, subscribe to the highest incarnation, and
    never apply a zombie frame — dialing order must not decide."""
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )
    from pathway_tpu.testing.chaos import free_dcn_port

    p1, p2 = free_dcn_port(1), free_dcn_port(1)
    zombie = DeltaStreamServer(p1, incarnation=0)
    legit = DeltaStreamServer(p2, incarnation=1)
    zombie.publish(50, [_batch([(666, 1, ("zombie", None))])])
    legit.publish(0, [_batch([(1, 1, ("legit", None))])])
    applied: list[tuple[int, list]] = []
    cl = DeltaStreamClient(
        "127.0.0.1",
        p1,
        0,
        from_tick=-1,
        on_deltas=lambda t, bs: applied.append(
            (t, [k for b in bs for k, _d, _v in b.iter_rows()])
        ),
        endpoints=[("127.0.0.1", p1), ("127.0.0.1", p2)],
    )
    cl.start()
    try:
        assert _wait(lambda: cl.writer_incarnation == 1, timeout=15)
        assert _wait(lambda: applied and applied[-1][0] == 0, timeout=10)
        time.sleep(0.3)
        assert all(666 not in keys for _t, keys in applied), applied
    finally:
        cl.close()
        zombie.close()
        legit.close()


def test_standby_never_usurps_before_first_contact():
    """A standby booted before (or alongside) its primary must NOT
    take over when the primary is merely slow to open its port — the
    bumped incarnation would fence the legitimate writer forever.  The
    grace clock starts at the first successful contact."""
    from pathway_tpu.parallel.standby import StandbyWriter
    from pathway_tpu.testing.chaos import free_dcn_port

    port = free_dcn_port(1)  # nothing listens here yet
    standby = StandbyWriter(
        "127.0.0.1",
        port,
        on_takeover=lambda s: None,
        grace_s=0.2,
        poll_s=0.05,
    ).start()
    try:
        assert not standby.wait_takeover(timeout=1.5)
        assert not standby.took_over
        # an explicit failure notification still takes over immediately
        standby.notify_failure("test", "operator says dead")
        assert standby.wait_takeover(timeout=10)
    finally:
        standby.stop()


def test_standby_persists_position(tmp_path):
    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.parallel.standby import StandbyWriter

    srv = DeltaStreamServer(0)
    pos_file = str(tmp_path / "standby.json")
    standby = StandbyWriter(
        "127.0.0.1",
        srv.port,
        position_path=pos_file,
        grace_s=60.0,
        on_takeover=lambda s: None,
    ).start()
    try:
        def persisted_tick():
            try:
                return json.loads(open(pos_file).read())["applied_tick"]
            except (OSError, ValueError, KeyError):
                return -1

        srv.publish(0, [_batch([(1, 1, ("a", None))])])
        assert _wait(lambda: standby.applied_tick == 0, timeout=15)
        time.sleep(0.6)  # clear the position-write throttle window
        srv.publish(1, [_batch([(2, 1, ("b", None))])])
        # wait on the FILE: applied_tick is assigned before the atomic
        # position write lands
        assert _wait(lambda: persisted_tick() == 1, timeout=15)
    finally:
        standby.stop()
        srv.close()
    # a restarted standby resumes from the persisted position
    restarted = StandbyWriter(
        "127.0.0.1",
        1,  # nothing listens; only the restored position matters
        position_path=pos_file,
        grace_s=3600.0,
        on_takeover=lambda s: None,
    )
    assert restarted.applied_tick == 1
    assert restarted.next_incarnation() >= 1


def test_resume_point_reads_store(tmp_path):
    import pickle

    from pathway_tpu.persistence._runtime_glue import resume_point
    from pathway_tpu.persistence.backends import FilesystemStore

    store = FilesystemStore(str(tmp_path / "pstorage"))
    assert resume_point(store) == {
        "state_time": -1,
        "group_commit_time": -1,
        "last_time": -1,
    }
    store.put(
        "metadata.json",
        json.dumps(
            {"last_time": 42, "chunks": {}, "state": {"gen": 3, "time": 40}}
        ).encode(),
    )
    store.put("group_commit.json", json.dumps({"time": 38}).encode())
    del pickle
    assert resume_point(store) == {
        "state_time": 40,
        "group_commit_time": 38,
        "last_time": 42,
    }


# ---------------------------------------------------------------------------
# shard-filtered hydration + index compaction


def test_tpu_index_filter_keys_compacts():
    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    idx = TpuDenseKnnIndex(dimensions=8)
    rng = np.random.default_rng(0)
    vecs = {k: rng.standard_normal(8).astype(np.float32) for k in range(3000)}
    for k, v in vecs.items():
        idx.upsert(k, v, {"k": k})
    full_bytes = idx.resident_bytes()
    assert sorted(idx.keys()) == sorted(vecs)
    idx.filter_keys(lambda k: k < 900)
    assert sorted(idx.keys()) == list(range(900))
    assert idx.metadata == {k: {"k": k} for k in range(900)}
    # the backing buffers actually shrank (the ~1/S memory claim)
    assert idx.resident_bytes() < full_bytes / 2
    # and the survivors still answer exactly
    res = idx.search([(vecs[5], 1, None)])[0]
    assert res[0][0] == 5


def test_replica_hydration_filters_to_shard(tmp_path):
    import pickle

    from pathway_tpu.parallel.replicate import corpus_shard_of
    from pathway_tpu.persistence.backends import FilesystemStore
    from pathway_tpu.serving.replica import ReplicaServer

    src = ToyVecIndex()
    for k in range(50):
        src.upsert(k, np.ones(4, dtype=np.float32) * k, None)
    store = FilesystemStore(str(tmp_path / "pstorage"))
    state = {
        "live_queries": {},
        "emitted": {},
        "index_state": ("pickle", src),
    }
    store.put("states/gen-000001/00003.pkl", pickle.dumps(state))
    store.put(
        "metadata.json",
        json.dumps(
            {
                "last_time": 9,
                "chunks": {},
                "state": {
                    "gen": 1,
                    "time": 9,
                    "nodes": {"3": "ExternalIndexNode"},
                },
            }
        ).encode(),
    )
    rep = ReplicaServer(
        replica_id=0,
        index_factory=ToyVecIndex,
        store_root=str(tmp_path / "pstorage"),
        shard=1,
        n_shards=3,
    )
    rep.hydrate()
    owned = set(rep.index.keys())
    assert owned
    dest = corpus_shard_of(list(range(50)), 3)
    assert owned == {k for k in range(50) if int(dest[k]) == 1}
