"""Core Table op tests — modeled on the reference test strategy
(markdown fixtures + captured-output equality, reference
python/pathway/tests/test_common.py)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
)


def test_select_arithmetic():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    res = t.select(s=t.a + t.b, d=t.b - t.a, p=t.a * t.b, q=t.b / t.a)
    expected = T(
        """
        s | d | p | q
        3 | 1 | 2 | 2.0
        7 | 1 | 12| 1.3333333333333333
        """
    )
    assert_table_equality(res, expected)


def test_select_this():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    res = t.select(pw.this.a, c=pw.this.a + pw.this.b)
    expected = T(
        """
        a | c
        1 | 3
        """
    )
    assert_table_equality(res, expected)


def test_filter():
    t = T(
        """
        v
        1
        2
        3
        4
        """
    )
    res = t.filter(t.v % 2 == 0)
    assert_table_equality_wo_index(
        res,
        T(
            """
            v
            2
            4
            """
        ),
    )


def test_filter_keeps_ids():
    t = T(
        """
        v
        1
        2
        """
    )
    res = t.filter(t.v > 1).select(w=pw.this.v * 10)
    rows = pw.debug.table_to_dicts(res)[1]["w"]
    assert list(rows.values()) == [20]


def test_groupby_sum_count():
    t = T(
        """
        owner | age
        Alice | 10
        Bob   | 9
        Alice | 8
        """
    )
    res = t.groupby(t.owner).reduce(
        t.owner,
        total=pw.reducers.sum(t.age),
        cnt=pw.reducers.count(),
        mean=pw.reducers.avg(t.age),
    )
    expected = T(
        """
        owner | total | cnt | mean
        Alice | 18    | 2   | 9.0
        Bob   | 9     | 1   | 9.0
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_groupby_min_max_argmin_any():
    t = T(
        """
        g | v
        x | 3
        x | 1
        y | 7
        """
    )
    res = t.groupby(t.g).reduce(
        t.g,
        lo=pw.reducers.min(t.v),
        hi=pw.reducers.max(t.v),
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            g | lo | hi
            x | 1  | 3
            y | 7  | 7
            """
        ),
    )


def test_groupby_tuple_reducers():
    t = T(
        """
        g | v
        x | 3
        x | 1
        """
    )
    res = t.groupby(t.g).reduce(
        t.g,
        st=pw.reducers.sorted_tuple(t.v),
    )
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["st"].values()) == [(1, 3)]


def test_reduce_expression_arithmetic():
    t = T(
        """
        g | v
        x | 3
        x | 1
        y | 7
        """
    )
    res = t.groupby(t.g).reduce(
        t.g, twice=pw.reducers.sum(t.v) * 2 + pw.reducers.count()
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            g | twice
            x | 10
            y | 15
            """
        ),
    )


def test_global_reduce():
    t = T(
        """
        v
        1
        2
        3
        """
    )
    res = t.reduce(s=pw.reducers.sum(t.v))
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["s"].values()) == [6]


def test_join_inner():
    t1 = T(
        """
        owner | pet
        Alice | dog
        Bob   | cat
        Carol | fish
        """
    )
    t2 = T(
        """
        name  | age
        Alice | 30
        Bob   | 25
        """
    )
    res = t1.join(t2, t1.owner == t2.name).select(
        t1.owner, t1.pet, t2.age
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            owner | pet | age
            Alice | dog | 30
            Bob   | cat | 25
            """
        ),
    )


def test_join_left_outer():
    t1 = T(
        """
        k | a
        1 | x
        2 | y
        """
    )
    t2 = T(
        """
        k | b
        2 | p
        3 | q
        """
    )
    res_left = t1.join_left(t2, t1.k == t2.k).select(
        t1.k, a=t1.a, b=t2.b
    )
    assert_table_equality_wo_index(
        res_left,
        T(
            """
            k | a | b
            1 | x | None
            2 | y | p
            """
        ),
    )
    res_outer = t1.join_outer(t2, t1.k == t2.k).select(
        k=pw.coalesce(t1.k, t2.k), a=t1.a, b=t2.b
    )
    assert_table_equality_wo_index(
        res_outer,
        T(
            """
            k | a    | b
            1 | x    | None
            2 | y    | p
            3 | None | q
            """
        ),
    )


def test_concat():
    t1 = T(
        """
        v
        1
        """
    )
    t2 = T(
        """
        v
        2
        """
    )
    res = t1.concat_reindex(t2)
    assert_table_equality_wo_index(
        res,
        T(
            """
            v
            1
            2
            """
        ),
    )


def test_update_cells():
    t1 = T(
        """
        id | a | b
        1  | 1 | x
        2  | 2 | y
        """
    )
    t2 = T(
        """
        id | b
        1  | z
        """
    )
    res = t1.update_cells(t2)
    assert_table_equality_wo_index(
        res,
        T(
            """
            a | b
            1 | z
            2 | y
            """
        ),
    )


def test_update_rows():
    t1 = T(
        """
        id | a
        1  | 10
        2  | 20
        """
    )
    t2 = T(
        """
        id | a
        2  | 99
        3  | 30
        """
    )
    res = t1.update_rows(t2)
    assert_table_equality_wo_index(
        res,
        T(
            """
            a
            10
            99
            30
            """
        ),
    )


def test_flatten():
    t = T(
        """
        g
        x
        """
    ).select(g=pw.this.g, tup=pw.make_tuple(1, 2, 3))
    res = t.flatten(t.tup)
    assert_table_equality_wo_index(
        res.select(res.g, res.tup),
        T(
            """
            g | tup
            x | 1
            x | 2
            x | 3
            """
        ),
    )


def test_ix():
    target = T(
        """
        id | v
        a  | 1
        b  | 2
        """
    )
    source = T(
        """
        ptr
        a
        b
        a
        """
    )
    ptrs = source.select(p=target.pointer_from(source.ptr))
    res = target.ix(ptrs.p)
    assert_table_equality_wo_index(
        res,
        T(
            """
            v
            1
            2
            1
            """
        ),
    )


def test_with_id_from_and_ix_ref():
    t = T(
        """
        name  | v
        Alice | 1
        Bob   | 2
        """
    ).with_id_from(pw.this.name)
    res = t.ix_ref("Alice", context=t).select(other_v=pw.this.v)
    _keys, cols = pw.debug.table_to_dicts(res)
    assert set(cols["other_v"].values()) == {1}


def test_difference_intersect():
    t1 = T(
        """
        id | v
        1  | a
        2  | b
        3  | c
        """
    )
    t2 = T(
        """
        id | w
        2  | x
        3  | y
        """
    )
    assert_table_equality_wo_index(
        t1.difference(t2),
        T(
            """
            v
            a
            """
        ),
    )
    assert_table_equality_wo_index(
        t1.intersect(t2),
        T(
            """
            v
            b
            c
            """
        ),
    )


def test_rename_without():
    t = T(
        """
        a | b | c
        1 | 2 | 3
        """
    )
    res = t.without("c").rename_columns(x=pw.this.a)
    assert res.column_names() == ["x", "b"]


def test_streaming_diffs_groupby():
    t = T(
        """
          | v | __time__ | __diff__
        1 | 5 | 2        | 1
        2 | 3 | 2        | 1
        1 | 5 | 4        | -1
        """
    )
    res = t.reduce(s=pw.reducers.sum(pw.this.v))
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["s"].values()) == [3]


def test_apply_and_udf():
    t = T(
        """
        v
        1
        2
        """
    )

    @pw.udf
    def double(x: int) -> int:
        return 2 * x

    res = t.select(
        d=double(t.v), a=pw.apply_with_type(lambda x: x + 10, int, t.v)
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            d | a
            2 | 11
            4 | 12
            """
        ),
    )


def test_if_else_division_guard():
    t = T(
        """
        a | b
        6 | 2
        5 | 0
        """
    )
    res = t.select(q=pw.if_else(t.b != 0, t.a // t.b, -1))
    assert_table_equality_wo_index(
        res,
        T(
            """
            q
            3
            -1
            """
        ),
    )


def test_sort():
    t = T(
        """
        v
        30
        10
        20
        """
    )
    res = t.sort(key=t.v)
    _keys, cols = pw.debug.table_to_dicts(res)
    prevs = [v for v in cols["prev"].values()]
    nexts = [v for v in cols["next"].values()]
    assert sum(1 for p in prevs if p is None) == 1
    assert sum(1 for n in nexts if n is None) == 1


def test_deduplicate():
    t = T(
        """
          | v | __time__
        1 | 1 | 2
        2 | 2 | 4
        3 | 3 | 6
        """
    )
    res = t.deduplicate(
        value=pw.this.v, acceptor=lambda new, old: old is None or new >= old + 2
    )
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["v"].values()) == [3]


def test_string_namespace():
    t = T(
        """
        s
        Hello
        World
        """
    )
    res = t.select(
        up=t.s.str.upper(), n=t.s.str.len(), sw=t.s.str.startswith("He")
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            up    | n | sw
            HELLO | 5 | True
            WORLD | 5 | False
            """
        ),
    )


def test_num_namespace():
    t = T(
        """
        x
        -1.5
        2.25
        """
    )
    res = t.select(a=t.x.num.abs(), r=t.x.num.round(1))
    assert_table_equality_wo_index(
        res,
        T(
            """
            a   | r
            1.5 | -1.5
            2.25| 2.2
            """
        ),
    )


def test_json_access():
    import json

    t = T(
        """
        raw
        x
        """
    )
    res = t.select(
        j=pw.apply_with_type(
            lambda _: pw.Json({"a": {"b": 5}, "l": [1, 2]}), pw.Json, t.raw
        )
    ).select(
        b=pw.this.j["a"]["b"].as_int(),
        l0=pw.this.j["l"][0].as_int(),
        missing=pw.this.j.get("zzz"),
    )
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["b"].values()) == [5]
    assert list(cols["l0"].values()) == [1]
    assert list(cols["missing"].values()) == [None]


def test_error_poison_fill_error():
    t = T(
        """
        a | b
        6 | 2
        5 | 0
        """
    )
    res = t.select(q=pw.fill_error(t.a // t.b, -1))
    assert_table_equality_wo_index(
        res,
        T(
            """
            q
            3
            -1
            """
        ),
    )


def test_bulk_groupby_matches_per_row():
    """The columnar groupby path (>=256-row batches: factorize + hash-on-
    uniques + bincount/bulk-multiset, engine/nodes.py _try_bulk) must agree
    exactly with the per-row path on every bulk-eligible reducer, including
    retraction batches."""
    import numpy as np

    rng = np.random.default_rng(7)
    n = 2000
    groups = [f"g{int(i)}" for i in rng.integers(0, 7, size=n)]
    vals = [int(v) for v in rng.integers(-50, 50, size=n)]

    class S(pw.Schema):
        g: str
        v: int
        i: int

    # t=0: bulk insert of 2000 rows; t=2: bulk retraction of 600 of them
    rows = [(groups[i], vals[i], i, 0, 1) for i in range(n)]
    rows += [(groups[i], vals[i], i, 2, -1) for i in range(0, 1200, 2)]
    t = pw.debug.table_from_rows(S, rows, is_stream=True)
    res = t.groupby(t.g).reduce(
        t.g,
        cnt=pw.reducers.count(),
        s=pw.reducers.sum(t.v),
        av=pw.reducers.avg(t.v),
        lo=pw.reducers.min(t.v),
        hi=pw.reducers.max(t.v),
        am=pw.reducers.argmin(t.v, t.i),
        ax=pw.reducers.argmax(t.v, t.i),
        anyv=pw.reducers.any(t.v),
    )
    _keys, cols = pw.debug.table_to_dicts(res)

    live = [i for i in range(n) if not (i < 1200 and i % 2 == 0)]
    expected: dict[str, list[int]] = {}
    for i in live:
        expected.setdefault(groups[i], []).append(i)
    got = {}
    for k in cols["g"]:
        got[cols["g"][k]] = (
            cols["cnt"][k], cols["s"][k], cols["av"][k],
            cols["lo"][k], cols["hi"][k],
        )
    assert set(got) == set(expected)
    for g, idxs in expected.items():
        vs = [vals[i] for i in idxs]
        cnt, s, av, lo, hi = got[g]
        assert cnt == len(vs)
        assert s == sum(vs)
        assert abs(av - sum(vs) / len(vs)) < 1e-9
        assert lo == min(vs)
        assert hi == max(vs)
    # argmin returns an arg whose value attains the group min
    for k in cols["g"]:
        g = cols["g"][k]
        vs = [vals[i] for i in expected[g]]
        assert vals[cols["am"][k]] == min(vs)
        assert vals[cols["ax"][k]] == max(vs)
        assert cols["anyv"][k] in vs


def test_bulk_join_matches_per_row():
    """The columnar hash-join fast path (>=1024-row insert-only inner-join
    batches, engine/nodes.py JoinExec._try_bulk) must produce the same
    output as the per-row path, and the state it writes must support later
    incremental ticks (retraction of a bulk-loaded row)."""
    import numpy as np

    rng = np.random.default_rng(11)
    n_l, n_r = 1500, 700
    lk = [int(x) for x in rng.integers(0, 400, size=n_l)]
    rk = [int(x) for x in rng.integers(0, 400, size=n_r)]

    class L(pw.Schema):
        k: int
        a: int = pw.column_definition(primary_key=True)

    class R(pw.Schema):
        k: int
        b: int

    # t=0 bulk load (fast path), t=2 retract one left row (per-row path)
    l_rows = [(lk[i], i, 0, 1) for i in range(n_l)] + [(lk[0], 0, 2, -1)]
    r_rows = [(rk[i], 1000 + i, 0, 1) for i in range(n_r)]
    lt = pw.debug.table_from_rows(L, l_rows, is_stream=True)
    rt = pw.debug.table_from_rows(R, r_rows, is_stream=True)
    j = lt.join(rt, lt.k == rt.k).select(lt.a, rt.b)
    _keys, cols = pw.debug.table_to_dicts(j)
    got = sorted(zip(cols["a"].values(), cols["b"].values()))

    expected = []
    for i in range(1, n_l):  # row 0 retracted
        for jr in range(n_r):
            if lk[i] == rk[jr]:
                expected.append((i, 1000 + jr))
    assert got == sorted(expected)
