"""Core Table op tests — modeled on the reference test strategy
(markdown fixtures + captured-output equality, reference
python/pathway/tests/test_common.py)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
)


def test_select_arithmetic():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    res = t.select(s=t.a + t.b, d=t.b - t.a, p=t.a * t.b, q=t.b / t.a)
    expected = T(
        """
        s | d | p | q
        3 | 1 | 2 | 2.0
        7 | 1 | 12| 1.3333333333333333
        """
    )
    assert_table_equality(res, expected)


def test_select_this():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    res = t.select(pw.this.a, c=pw.this.a + pw.this.b)
    expected = T(
        """
        a | c
        1 | 3
        """
    )
    assert_table_equality(res, expected)


def test_filter():
    t = T(
        """
        v
        1
        2
        3
        4
        """
    )
    res = t.filter(t.v % 2 == 0)
    assert_table_equality_wo_index(
        res,
        T(
            """
            v
            2
            4
            """
        ),
    )


def test_filter_keeps_ids():
    t = T(
        """
        v
        1
        2
        """
    )
    res = t.filter(t.v > 1).select(w=pw.this.v * 10)
    rows = pw.debug.table_to_dicts(res)[1]["w"]
    assert list(rows.values()) == [20]


def test_groupby_sum_count():
    t = T(
        """
        owner | age
        Alice | 10
        Bob   | 9
        Alice | 8
        """
    )
    res = t.groupby(t.owner).reduce(
        t.owner,
        total=pw.reducers.sum(t.age),
        cnt=pw.reducers.count(),
        mean=pw.reducers.avg(t.age),
    )
    expected = T(
        """
        owner | total | cnt | mean
        Alice | 18    | 2   | 9.0
        Bob   | 9     | 1   | 9.0
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_groupby_min_max_argmin_any():
    t = T(
        """
        g | v
        x | 3
        x | 1
        y | 7
        """
    )
    res = t.groupby(t.g).reduce(
        t.g,
        lo=pw.reducers.min(t.v),
        hi=pw.reducers.max(t.v),
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            g | lo | hi
            x | 1  | 3
            y | 7  | 7
            """
        ),
    )


def test_groupby_tuple_reducers():
    t = T(
        """
        g | v
        x | 3
        x | 1
        """
    )
    res = t.groupby(t.g).reduce(
        t.g,
        st=pw.reducers.sorted_tuple(t.v),
    )
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["st"].values()) == [(1, 3)]


def test_reduce_expression_arithmetic():
    t = T(
        """
        g | v
        x | 3
        x | 1
        y | 7
        """
    )
    res = t.groupby(t.g).reduce(
        t.g, twice=pw.reducers.sum(t.v) * 2 + pw.reducers.count()
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            g | twice
            x | 10
            y | 15
            """
        ),
    )


def test_global_reduce():
    t = T(
        """
        v
        1
        2
        3
        """
    )
    res = t.reduce(s=pw.reducers.sum(t.v))
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["s"].values()) == [6]


def test_join_inner():
    t1 = T(
        """
        owner | pet
        Alice | dog
        Bob   | cat
        Carol | fish
        """
    )
    t2 = T(
        """
        name  | age
        Alice | 30
        Bob   | 25
        """
    )
    res = t1.join(t2, t1.owner == t2.name).select(
        t1.owner, t1.pet, t2.age
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            owner | pet | age
            Alice | dog | 30
            Bob   | cat | 25
            """
        ),
    )


def test_join_left_outer():
    t1 = T(
        """
        k | a
        1 | x
        2 | y
        """
    )
    t2 = T(
        """
        k | b
        2 | p
        3 | q
        """
    )
    res_left = t1.join_left(t2, t1.k == t2.k).select(
        t1.k, a=t1.a, b=t2.b
    )
    assert_table_equality_wo_index(
        res_left,
        T(
            """
            k | a | b
            1 | x | None
            2 | y | p
            """
        ),
    )
    res_outer = t1.join_outer(t2, t1.k == t2.k).select(
        k=pw.coalesce(t1.k, t2.k), a=t1.a, b=t2.b
    )
    assert_table_equality_wo_index(
        res_outer,
        T(
            """
            k | a    | b
            1 | x    | None
            2 | y    | p
            3 | None | q
            """
        ),
    )


def test_concat():
    t1 = T(
        """
        v
        1
        """
    )
    t2 = T(
        """
        v
        2
        """
    )
    res = t1.concat_reindex(t2)
    assert_table_equality_wo_index(
        res,
        T(
            """
            v
            1
            2
            """
        ),
    )


def test_update_cells():
    t1 = T(
        """
        id | a | b
        1  | 1 | x
        2  | 2 | y
        """
    )
    t2 = T(
        """
        id | b
        1  | z
        """
    )
    pw.universes.promise_is_subset_of(t2, t1)
    res = t1.update_cells(t2)
    assert_table_equality_wo_index(
        res,
        T(
            """
            a | b
            1 | z
            2 | y
            """
        ),
    )


def test_update_rows():
    t1 = T(
        """
        id | a
        1  | 10
        2  | 20
        """
    )
    t2 = T(
        """
        id | a
        2  | 99
        3  | 30
        """
    )
    res = t1.update_rows(t2)
    assert_table_equality_wo_index(
        res,
        T(
            """
            a
            10
            99
            30
            """
        ),
    )


def test_flatten():
    t = T(
        """
        g
        x
        """
    ).select(g=pw.this.g, tup=pw.make_tuple(1, 2, 3))
    res = t.flatten(t.tup)
    assert_table_equality_wo_index(
        res.select(res.g, res.tup),
        T(
            """
            g | tup
            x | 1
            x | 2
            x | 3
            """
        ),
    )


def test_ix():
    target = T(
        """
        id | v
        a  | 1
        b  | 2
        """
    )
    source = T(
        """
        ptr
        a
        b
        a
        """
    )
    ptrs = source.select(p=target.pointer_from(source.ptr))
    res = target.ix(ptrs.p)
    assert_table_equality_wo_index(
        res,
        T(
            """
            v
            1
            2
            1
            """
        ),
    )


def test_with_id_from_and_ix_ref():
    t = T(
        """
        name  | v
        Alice | 1
        Bob   | 2
        """
    ).with_id_from(pw.this.name)
    res = t.ix_ref("Alice", context=t).select(other_v=pw.this.v)
    _keys, cols = pw.debug.table_to_dicts(res)
    assert set(cols["other_v"].values()) == {1}


def test_difference_intersect():
    t1 = T(
        """
        id | v
        1  | a
        2  | b
        3  | c
        """
    )
    t2 = T(
        """
        id | w
        2  | x
        3  | y
        """
    )
    assert_table_equality_wo_index(
        t1.difference(t2),
        T(
            """
            v
            a
            """
        ),
    )
    assert_table_equality_wo_index(
        t1.intersect(t2),
        T(
            """
            v
            b
            c
            """
        ),
    )


def test_rename_without():
    t = T(
        """
        a | b | c
        1 | 2 | 3
        """
    )
    res = t.without("c").rename_columns(x=pw.this.a)
    # reference order: untouched columns first, renamed appended
    assert res.column_names() == ["b", "x"]


def test_streaming_diffs_groupby():
    t = T(
        """
          | v | __time__ | __diff__
        1 | 5 | 2        | 1
        2 | 3 | 2        | 1
        1 | 5 | 4        | -1
        """
    )
    res = t.reduce(s=pw.reducers.sum(pw.this.v))
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["s"].values()) == [3]


def test_apply_and_udf():
    t = T(
        """
        v
        1
        2
        """
    )

    @pw.udf
    def double(x: int) -> int:
        return 2 * x

    res = t.select(
        d=double(t.v), a=pw.apply_with_type(lambda x: x + 10, int, t.v)
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            d | a
            2 | 11
            4 | 12
            """
        ),
    )


def test_if_else_division_guard():
    t = T(
        """
        a | b
        6 | 2
        5 | 0
        """
    )
    res = t.select(q=pw.if_else(t.b != 0, t.a // t.b, -1))
    assert_table_equality_wo_index(
        res,
        T(
            """
            q
            3
            -1
            """
        ),
    )


def test_sort():
    t = T(
        """
        v
        30
        10
        20
        """
    )
    res = t.sort(key=t.v)
    _keys, cols = pw.debug.table_to_dicts(res)
    prevs = [v for v in cols["prev"].values()]
    nexts = [v for v in cols["next"].values()]
    assert sum(1 for p in prevs if p is None) == 1
    assert sum(1 for n in nexts if n is None) == 1


def test_deduplicate():
    t = T(
        """
          | v | __time__
        1 | 1 | 2
        2 | 2 | 4
        3 | 3 | 6
        """
    )
    res = t.deduplicate(
        value=pw.this.v, acceptor=lambda new, old: old is None or new >= old + 2
    )
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["v"].values()) == [3]


def test_string_namespace():
    t = T(
        """
        s
        Hello
        World
        """
    )
    res = t.select(
        up=t.s.str.upper(), n=t.s.str.len(), sw=t.s.str.startswith("He")
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            up    | n | sw
            HELLO | 5 | True
            WORLD | 5 | False
            """
        ),
    )


def test_num_namespace():
    t = T(
        """
        x
        -1.5
        2.25
        """
    )
    res = t.select(a=t.x.num.abs(), r=t.x.num.round(1))
    assert_table_equality_wo_index(
        res,
        T(
            """
            a   | r
            1.5 | -1.5
            2.25| 2.2
            """
        ),
    )


def test_json_access():
    import json

    t = T(
        """
        raw
        x
        """
    )
    res = t.select(
        j=pw.apply_with_type(
            lambda _: pw.Json({"a": {"b": 5}, "l": [1, 2]}), pw.Json, t.raw
        )
    ).select(
        b=pw.this.j["a"]["b"].as_int(),
        l0=pw.this.j["l"][0].as_int(),
        missing=pw.this.j.get("zzz"),
    )
    _keys, cols = pw.debug.table_to_dicts(res)
    assert list(cols["b"].values()) == [5]
    assert list(cols["l0"].values()) == [1]
    assert list(cols["missing"].values()) == [None]


def test_error_poison_fill_error():
    t = T(
        """
        a | b
        6 | 2
        5 | 0
        """
    )
    res = t.select(q=pw.fill_error(t.a // t.b, -1))
    assert_table_equality_wo_index(
        res,
        T(
            """
            q
            3
            -1
            """
        ),
    )


def test_bulk_groupby_matches_per_row():
    """The columnar groupby path (>=256-row batches: factorize + hash-on-
    uniques + bincount/bulk-multiset, engine/nodes.py _try_bulk) must agree
    exactly with the per-row path on every bulk-eligible reducer, including
    retraction batches."""
    import numpy as np

    rng = np.random.default_rng(7)
    n = 2000
    groups = [f"g{int(i)}" for i in rng.integers(0, 7, size=n)]
    vals = [int(v) for v in rng.integers(-50, 50, size=n)]

    class S(pw.Schema):
        g: str
        v: int
        i: int

    # t=0: bulk insert of 2000 rows; t=2: bulk retraction of 600 of them
    rows = [(groups[i], vals[i], i, 0, 1) for i in range(n)]
    rows += [(groups[i], vals[i], i, 2, -1) for i in range(0, 1200, 2)]
    t = pw.debug.table_from_rows(S, rows, is_stream=True)
    res = t.groupby(t.g).reduce(
        t.g,
        cnt=pw.reducers.count(),
        s=pw.reducers.sum(t.v),
        av=pw.reducers.avg(t.v),
        lo=pw.reducers.min(t.v),
        hi=pw.reducers.max(t.v),
        am=pw.reducers.argmin(t.v, t.i),
        ax=pw.reducers.argmax(t.v, t.i),
        anyv=pw.reducers.any(t.v),
    )
    _keys, cols = pw.debug.table_to_dicts(res)

    live = [i for i in range(n) if not (i < 1200 and i % 2 == 0)]
    expected: dict[str, list[int]] = {}
    for i in live:
        expected.setdefault(groups[i], []).append(i)
    got = {}
    for k in cols["g"]:
        got[cols["g"][k]] = (
            cols["cnt"][k], cols["s"][k], cols["av"][k],
            cols["lo"][k], cols["hi"][k],
        )
    assert set(got) == set(expected)
    for g, idxs in expected.items():
        vs = [vals[i] for i in idxs]
        cnt, s, av, lo, hi = got[g]
        assert cnt == len(vs)
        assert s == sum(vs)
        assert abs(av - sum(vs) / len(vs)) < 1e-9
        assert lo == min(vs)
        assert hi == max(vs)
    # argmin returns an arg whose value attains the group min
    for k in cols["g"]:
        g = cols["g"][k]
        vs = [vals[i] for i in expected[g]]
        assert vals[cols["am"][k]] == min(vs)
        assert vals[cols["ax"][k]] == max(vs)
        assert cols["anyv"][k] in vs


def test_bulk_join_matches_per_row():
    """The columnar delta-join path (engine/nodes.py JoinExec._delta_tick
    over the arrangement state) must produce the same output as the
    rowwise oracle on a bulk load, and the state it writes must support
    later incremental ticks (retraction of a bulk-loaded row)."""
    import numpy as np

    rng = np.random.default_rng(11)
    n_l, n_r = 1500, 700
    lk = [int(x) for x in rng.integers(0, 400, size=n_l)]
    rk = [int(x) for x in rng.integers(0, 400, size=n_r)]

    class L(pw.Schema):
        k: int
        a: int = pw.column_definition(primary_key=True)

    class R(pw.Schema):
        k: int
        b: int

    # t=0 bulk load (fast path), t=2 retract one left row (per-row path)
    l_rows = [(lk[i], i, 0, 1) for i in range(n_l)] + [(lk[0], 0, 2, -1)]
    r_rows = [(rk[i], 1000 + i, 0, 1) for i in range(n_r)]
    lt = pw.debug.table_from_rows(L, l_rows, is_stream=True)
    rt = pw.debug.table_from_rows(R, r_rows, is_stream=True)
    j = lt.join(rt, lt.k == rt.k).select(lt.a, rt.b)
    _keys, cols = pw.debug.table_to_dicts(j)
    got = sorted(zip(cols["a"].values(), cols["b"].values()))

    expected = []
    for i in range(1, n_l):  # row 0 retracted
        for jr in range(n_r):
            if lk[i] == rk[jr]:
                expected.append((i, 1000 + jr))
    assert got == sorted(expected)


def test_sort_incremental_o_changes():
    """SortExec maintains prev/next incrementally: after a 100k-row bulk
    load, a tick updating 100 rows must be orders of magnitude cheaper
    than the load tick and emit only the touched pointer pairs
    (reference: prev_next.rs pointer maintenance in O(changes))."""
    import time as _time

    import numpy as np

    from pathway_tpu.engine.nodes import InputNode, SortNode
    from pathway_tpu.engine.batch import DiffBatch
    from pathway_tpu.engine.runtime import StaticSource

    n = 100_000
    rng = np.random.default_rng(5)
    vals = rng.permutation(n)

    node_in = InputNode(StaticSource(["v"]), ["v"])
    sort_node = SortNode(node_in, "v", None)
    ex = sort_node.make_exec()

    load = DiffBatch.from_rows(
        [(k + 1, 1, (int(vals[k]),)) for k in range(n)], ["v"]
    )
    # gen-2 GC passes over other tests' garbage otherwise fire inside the
    # tiny update tick and get charged to this thread's CPU time
    import gc

    gc.disable()
    try:
        t0 = _time.thread_time()
        out0 = ex.process(0, [[load]])
        t_load = _time.thread_time() - t0
        assert sum(len(b) for b in out0) == n

        # 100 value updates (retract + reinsert with new sortval)
        upd_rows = []
        for i in range(100):
            k = i * 997 + 1
            upd_rows.append((k, -1, (int(vals[k - 1]),)))
            upd_rows.append((k, 1, (int(vals[k - 1]) + n,)))
        upd = DiffBatch.from_rows(upd_rows, ["v"])
        t0 = _time.thread_time()
        out1 = ex.process(2, [[upd]])
        t_upd = _time.thread_time() - t0
    finally:
        gc.enable()

    n_changed = sum(len(b) for b in out1)
    # each moved row touches itself + up to 2 old and 2 new neighbors,
    # each emitting a retraction+insertion — far below n
    assert 0 < n_changed < 100 * 12
    # O(changes): the update tick must be dramatically cheaper than the
    # bulk tick. Per-thread CPU time — wall time flaked under suite load,
    # and process_time would still count other tests' threads
    assert t_upd < t_load / 20, (t_load, t_upd)


def test_sort_incremental_matches_rebuild():
    """Pointer output after incremental updates equals a from-scratch sort."""
    import numpy as np

    rng = np.random.default_rng(6)

    class S(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        v: int

    n = 600
    vals = [int(x) for x in rng.integers(0, 10_000, size=n)]
    rows = [(i, vals[i], 0, 1) for i in range(n)]
    # move 40 rows to new positions at t=2 (small tick -> incremental path)
    for i in range(0, 80, 2):
        rows.append((i, vals[i], 2, -1))
        rows.append((i, vals[i] + 20_000, 2, 1))
    t = pw.debug.table_from_rows(S, rows, is_stream=True)
    res = t.sort(key=t.v)
    _keys, cols = pw.debug.table_to_dicts(res)

    final = {i: (vals[i] + 20_000 if i < 80 and i % 2 == 0 else vals[i])
             for i in range(n)}
    # source values per engine row key, from the same deterministic graph
    _k2, src_cols = pw.debug.table_to_dicts(t)
    vmap = src_cols["v"]
    prevs = cols["prev"]
    nexts = cols["next"]
    assert len(prevs) == n
    heads = [k for k, p in prevs.items() if p is None]
    assert len(heads) == 1
    # walk the chain: every row exactly once, values non-decreasing, and
    # the visited value sequence equals the expected full re-sort
    walked = []
    cur = heads[0]
    while cur is not None:
        walked.append(vmap[cur])
        nxt = nexts[cur]
        cur = int(nxt) if nxt is not None else None
    assert len(walked) == n
    assert walked == sorted(final.values())


def test_gradual_broadcast_static():
    """apx_value splits rows ~proportionally to (value-lower)/(upper-lower)
    (reference: python/pathway/tests/test_gradual_broadcast.py;
    operator: src/engine/dataflow/operators/gradual_broadcast.rs)."""
    class S(pw.Schema):
        val: int

    class Thr(pw.Schema):
        lower: float
        value: float
        upper: float

    n = 500
    tab = pw.debug.table_from_rows(S, [(i,) for i in range(n)])
    thr = pw.debug.table_from_rows(Thr, [(20.5, 29.5, 30.5)])
    ext = tab._gradual_broadcast(thr, thr.lower, thr.value, thr.upper)
    assert ext.column_names() == ["val", "apx_value"]
    _keys, cols = pw.debug.table_to_dicts(ext)
    vals = list(cols["apx_value"].values())
    assert len(vals) == n
    assert set(vals) <= {20.5, 30.5}
    hi = sum(1 for v in vals if v == 30.5)
    # fraction = 0.9; key hashes are uniform, allow generous slack
    assert 0.8 * n < hi < n


def test_gradual_broadcast_sweep_no_mass_retraction():
    """As `value` sweeps lower->upper each row flips from lower to upper
    exactly once — a 5-step sweep must NOT retract everything per step."""
    from pathway_tpu.debug import _run_capture

    class S(pw.Schema):
        val: int

    class Thr(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        lower: float
        value: float
        upper: float

    n = 400
    tab = pw.debug.table_from_rows(S, [(i,) for i in range(n)])
    # single logical threshold row upserted over 6 times
    thr_rows = []
    for step in range(6):
        t = 2 * step
        if step > 0:
            thr_rows.append((0, 0.0, float(step - 1), 5.0, t, -1))
        thr_rows.append((0, 0.0, float(step), 5.0, t, 1))
    thr = pw.debug.table_from_rows(Thr, thr_rows, is_stream=True)
    ext = tab._gradual_broadcast(thr, thr.lower, thr.value, thr.upper)
    cap = _run_capture([ext])[0]
    vals = [v[1] for v in cap.rows.values()]
    assert len(vals) == n
    # value=5.0 == upper at the end -> every row reads upper
    assert all(v == 5.0 for v in vals)
    # each row: 1 initial insert + <=2 events per single flip (retract +
    # re-insert), plus slack; a mass-retraction implementation would emit
    # ~6 * 2n events
    assert len(cap.updates) < 4.5 * n, len(cap.updates)


def test_sort_incremental_upsert_and_duplicate():
    """Incremental-path regression: an upsert without prior retraction and
    a repeated +1 for the same key must not leave ghost entries in the
    maintained order or emit self-pointing rows."""
    from pathway_tpu.engine.nodes import InputNode, SortNode
    from pathway_tpu.engine.batch import DiffBatch
    from pathway_tpu.engine.runtime import StaticSource

    node_in = InputNode(StaticSource(["v"]), ["v"])
    ex = SortNode(node_in, "v", None).make_exec()

    load = DiffBatch.from_rows(
        [(k, 1, (k * 10,)) for k in range(1, 101)], ["v"]
    )
    ex.process(0, [[load]])
    assert len(ex.orders[None]) == 100

    # upsert key 5 to a new position WITHOUT a retraction (small tick ->
    # incremental path), plus a duplicate +1 for key 7 at its same value
    upd = DiffBatch.from_rows([(5, 1, (2000,)), (7, 1, (70,))], ["v"])
    out = ex.process(2, [[upd]])
    assert len(ex.orders[None]) == 100  # no ghosts
    assert ex.instances[None][5] == 2000
    for b in out:
        for k, d, vals in b.iter_rows():
            if d > 0:
                prev_k, next_k = vals
                assert prev_k is None or int(prev_k) != k
                assert next_k is None or int(next_k) != k
    # key 5 is now last: its next is None and its prev is key 100
    emitted5 = ex.emitted[None][5]
    assert emitted5[1] is None and int(emitted5[0]) == 100

    # retract the upserted row: order shrinks cleanly
    out2 = ex.process(4, [[DiffBatch.from_rows([(5, -1, (2000,))], ["v"])]])
    assert len(ex.orders[None]) == 99
    assert 5 not in ex.instances[None]
    # key 100 becomes the tail again
    assert ex.emitted[None][100][1] is None


def test_udf_executors():
    """Executor objects (reference: internals/udfs/executors.py): async
    executor lifts a sync fn, bounds concurrency, and honors timeout."""
    import asyncio
    import time as _time

    import pytest

    class S(pw.Schema):
        v: int

    t = pw.debug.table_from_rows(S, [(i,) for i in range(6)])

    running = {"now": 0, "peak": 0}

    @pw.udf(executor=pw.udfs.async_executor(capacity=2))
    async def slow_double(v: int) -> int:
        running["now"] += 1
        running["peak"] = max(running["peak"], running["now"])
        await asyncio.sleep(0.02)
        running["now"] -= 1
        return v * 2

    res = t.select(d=slow_double(t.v))
    _k, cols = pw.debug.table_to_dicts(res)
    assert sorted(cols["d"].values()) == [0, 2, 4, 6, 8, 10]
    assert running["peak"] <= 2  # capacity bound held
    # second independent run = second event loop; the capacity wrapper
    # must not carry semaphore state across loops
    pw.internals.parse_graph.G.clear()
    t_b = pw.debug.table_from_rows(S, [(9,), (10,)])
    _kb, cb = pw.debug.table_to_dicts(t_b.select(d=slow_double(t_b.v)))
    assert sorted(cb["d"].values()) == [18, 20]

    # async executor lifts a plain BLOCKING function into the thread
    # pool — rows must overlap, not serialize behind each block
    @pw.udf(executor=pw.udfs.async_executor(capacity=8))
    def plain(v: int) -> int:
        _time.sleep(0.05)
        return v + 100

    pw.internals.parse_graph.G.clear()
    t2 = pw.debug.table_from_rows(S, [(i,) for i in range(6)])
    t0 = _time.perf_counter()
    _k2, c2 = pw.debug.table_to_dicts(t2.select(p=plain(t2.v)))
    elapsed = _time.perf_counter() - t0
    assert sorted(c2["p"].values()) == [100, 101, 102, 103, 104, 105]
    assert elapsed < 0.2, elapsed  # serial would be >= 0.3s

    # sync executor rejects coroutines at definition time
    with pytest.raises(TypeError, match="sync_executor"):
        @pw.udf(executor=pw.udfs.sync_executor())
        async def nope(v: int) -> int:  # pragma: no cover
            return v

    # timeout from the executor applies
    @pw.udf(executor=pw.udfs.async_executor(timeout=0.01))
    async def too_slow(v: int) -> int:
        await asyncio.sleep(1.0)
        return v

    pw.internals.parse_graph.G.clear()
    t3 = pw.debug.table_from_rows(S, [(1,)])
    _k3, c3 = pw.debug.table_to_dicts(t3.select(x=too_slow(t3.v)))
    from pathway_tpu.internals.api import ERROR
    assert list(c3["x"].values())[0] is ERROR  # timed out -> error poison
