"""Tick Scope tests — per-operator flight recorder, memory ledger,
roofline attribution (observability/tickscope.py + engine hooks).

Tier-1 coverage of the PR-18 acceptance bars:

* critical-path property test: random DAGs checked against a
  brute-force path enumeration (node weights + edge weights), cycle
  detection, and the cross-rank ``stitch_ranks`` composition;
* memory-ledger conservation: the runtime provider's parts equal the
  per-exec ``exec_memory_ledger`` sums, ``deep=True`` adds monolith
  pickle bytes and never shrinks the total;
* frozen-wall-clock regression (the PR-18 clock audit): with
  ``time.time`` pinned, tracer span durations, signal sampling and
  tick records all stay correct — every duration is a monotonic delta,
  wall is display-only;
* recorder on/off contract: ``PATHWAY_TICKSCOPE=0`` means
  ``begin_tick`` returns None and nothing is recorded; default-on
  records per-operator entries that reconcile with the tick wall;
* sub-millisecond buckets for the per-operator tick histogram
  (compiled ticks finish in 10-100 us — the old 0.1 ms floor flattened
  them into one bucket);
* roofline MFU math against a pinned PATHWAY_PEAK_FLOPS + XLA cost
  analysis on a real jitted program;
* the ``tickscope-coverage`` plane-doctor rule (INFO and WARNING);
* the ``/debug/tick`` surface (anatomy, deep ledger, Chrome trace) and
  ``federate_ticks`` fleet stitching over fake members.
"""

import json
import socket
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import pathway_tpu as pw  # noqa: F401 — parse-graph fixture parity
from pathway_tpu.observability import tickscope


@pytest.fixture(autouse=True)
def _tickscope_env(monkeypatch):
    for var in (
        "PATHWAY_TICKSCOPE",
        "PATHWAY_TICKSCOPE_RING",
        "PATHWAY_PEAK_FLOPS",
        "PATHWAY_COMPILED_MIN_ROWS",
    ):
        monkeypatch.delenv(var, raising=False)
    tickscope.reset()
    yield
    tickscope.reset()


# --- pipeline fixture ------------------------------------------------------


def _ref(name):
    from pathway_tpu.engine.expression_eval import InternalColRef

    return InternalColRef(0, name)


def _obj_col(values):
    out = np.empty(len(values), dtype=object)
    out[:] = values
    return out


def _ticks(n, per, cols):
    from pathway_tpu.engine.batch import DiffBatch

    out = []
    for lo in range(0, n, per):
        hi = min(n, lo + per)
        out.append(
            DiffBatch(
                np.arange(lo, hi, dtype=np.uint64),
                np.ones(hi - lo, np.int64),
                {c: _obj_col(vals[lo:hi]) for c, vals in cols.items()},
            )
        )
    return out


def _chain_runtime(n=512, per=128, worker_threads=False):
    """input -> rowwise -> filter -> groupby -> output over n rows."""
    from pathway_tpu.engine.nodes import (
        FilterNode,
        GroupByNode,
        InputNode,
        OutputNode,
        RowwiseNode,
    )
    from pathway_tpu.engine.reducers import ReducerSpec
    from pathway_tpu.engine.runtime import Runtime, StaticSource

    class _Src(StaticSource):
        def __init__(self, names, ticks):
            super().__init__(names)
            self._ticks = ticks

        def events(self):
            for i, b in enumerate(self._ticks):
                yield i, b

    rng = np.random.default_rng(7)
    a = [int(v) for v in rng.integers(-100, 100, n)]
    rows = [0]

    def sink(t, b):
        rows[0] += len(b)

    inp = InputNode(_Src(["a"], _ticks(n, per, {"a": a})), ["a"])
    m = RowwiseNode([inp], {"g": _ref("a") & 7, "v": _ref("a") * 2})
    f = FilterNode(m, _ref("v") > -195)
    gb = GroupByNode(f, ["g"], {"cnt": ReducerSpec(kind="count")})
    rt = Runtime(
        [OutputNode(gb, sink)], worker_threads=worker_threads
    )
    return rt, rows


# --- critical path (satellite 4: property test) ----------------------------


def _brute_force_longest(durations, edges, edge_weights):
    """Independent oracle: enumerate every path (small DAGs only)."""
    succs = {}
    for s, d in edges:
        succs.setdefault(s, []).append(d)
    nodes = set(durations) | {x for e in edges for x in e}
    best = 0.0
    if nodes:
        best = max(durations.get(n, 0.0) for n in nodes)

    def walk(n, total):
        nonlocal best
        best = max(best, total)
        for d in succs.get(n, ()):
            walk(
                d,
                total
                + edge_weights.get((n, d), 0.0)
                + durations.get(d, 0.0),
            )

    for n in nodes:
        walk(n, durations.get(n, 0.0))
    return best


def test_critical_path_random_dags_match_brute_force():
    for seed in range(25):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(2, 9))
        durations = {
            i: float(rng.uniform(0.0, 10.0)) for i in range(n)
        }
        # i < j only: acyclic by construction
        edges = [
            (i, j)
            for i in range(n)
            for j in range(i + 1, n)
            if rng.uniform() < 0.4
        ]
        weights = (
            {e: float(rng.uniform(0.0, 3.0)) for e in edges}
            if seed % 2
            else {}
        )
        total, path = tickscope.critical_path(
            durations, edges, weights or None
        )
        expect = _brute_force_longest(durations, edges, weights)
        assert total == pytest.approx(expect), (seed, edges)
        # the returned path re-sums to the total
        resum = durations.get(path[0], 0.0) if path else 0.0
        for s, d in zip(path, path[1:]):
            assert (s, d) in edges
            resum += weights.get((s, d), 0.0) + durations.get(d, 0.0)
        assert resum == pytest.approx(total)


def test_critical_path_cycle_raises():
    with pytest.raises(ValueError, match="cycle"):
        tickscope.critical_path({0: 1.0, 1: 1.0}, [(0, 1), (1, 0)])


def test_critical_path_empty():
    assert tickscope.critical_path({}, []) == (0.0, [])


def test_stitch_ranks_cross_rank_edge():
    total, path = tickscope.stitch_ranks(
        {0: {"a": 1.0, "b": 2.0}, 1: {"c": 0.5, "d": 0.25}},
        {0: [("a", "b")], 1: [("c", "d")]},
        [((0, "b"), (1, "c"), 0.3)],
    )
    assert total == pytest.approx(1.0 + 2.0 + 0.3 + 0.5 + 0.25)
    assert path == [(0, "a"), (0, "b"), (1, "c"), (1, "d")]


def test_stitch_ranks_disjoint_is_slowest_member():
    # no channel edges: the fleet answer is the slowest rank's chain —
    # exactly right for a lockstep tick with unmeasured channel waits
    total, path = tickscope.stitch_ranks(
        {0: {"a": 1.0}, 1: {"c": 5.0}}, {0: [], 1: []}
    )
    assert total == pytest.approx(5.0)
    assert path == [(1, "c")]


# --- flight recorder on/off ------------------------------------------------


def test_recorder_disabled_is_none_and_records_nothing(monkeypatch):
    monkeypatch.setenv("PATHWAY_TICKSCOPE", "0")
    rt, rows = _chain_runtime()
    assert rt._tickscope.enabled is False
    assert rt._tickscope.begin_tick(0) is None
    rt.run()
    assert rows[0] > 0
    assert rt._tickscope.ticks_recorded == 0
    assert rt._tickscope.records() == []


def test_recorder_records_per_operator_entries():
    rt, rows = _chain_runtime(n=512, per=128)
    rt.run()
    scope = rt._tickscope
    assert scope.enabled
    assert scope.ticks_recorded >= 4
    rec = scope.records()[0]
    names = {scope._names[e[0]] for e in rec.entries}
    assert any(n.startswith("InputNode") for n in names)
    assert any(n.startswith("GroupByNode") for n in names)
    for nid, t0, t1, rin, rout, compiled in rec.entries:
        assert t1 >= t0
        assert rin >= 0 and rout >= 0
    # stage sum can never exceed the single-threaded tick wall, and
    # after the ingest-attribution fix it accounts for nearly all of it
    stage_ns = sum(e[2] - e[1] for e in rec.entries)
    assert stage_ns <= rec.tick_ns
    total, path = scope.record_critical_path(rec)
    assert 0 < total <= rec.tick_ns / 1e9 + 1e-9
    assert path  # the chain orders input before output
    rollup = scope.operator_rollup()
    assert sum(d["rows_in"] for d in rollup.values()) > 0
    snap = scope.snapshot(ticks=4)
    assert snap["last"]["critical_path"]["coverage"] > 0
    assert snap["last"]["edges"]  # name-pair DAG for fleet stitching
    assert "rollup" in snap


def test_ring_bound(monkeypatch):
    monkeypatch.setenv("PATHWAY_TICKSCOPE_RING", "2")
    rt, _ = _chain_runtime(n=512, per=64)
    rt.run()
    scope = rt._tickscope
    assert scope.ticks_recorded >= 8
    assert len(scope.records()) == 2


def test_chrome_trace_one_track_per_exec():
    from pathway_tpu.observability.tracing import validate_chrome_trace

    rt, _ = _chain_runtime()
    rt.run()
    doc = rt._tickscope.chrome_trace()
    assert validate_chrome_trace(doc) == []
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert slices
    # one thread_name metadata event per distinct exec track
    assert len(meta) == len({e["tid"] for e in slices})


# --- memory ledger (satellite 4: conservation) -----------------------------


def test_memory_ledger_conservation():
    rt, _ = _chain_runtime(n=512, per=128)
    gb_execs = [
        ex
        for ex in rt.execs.values()
        if type(ex).__name__ == "GroupByExec"
    ]
    assert gb_execs
    gb_execs[0].enable_state_ledger()
    rt.run()
    snap = tickscope.memory_snapshot()
    parts = snap["owners"]["runtime"]
    # conservation: the provider's parts are exactly the per-exec
    # ledgers, re-derived independently here
    expect = {}
    for nid, ex in rt.execs.items():
        for part, nbytes in tickscope.exec_memory_ledger(ex).items():
            if nbytes:
                expect[f"{rt._tickscope._names[nid]}/{part}"] = nbytes
    assert parts == expect
    assert snap["total_bytes"] == sum(parts.values())
    assert any("ledger_blobs" in k for k in parts)
    # top list is sorted descending and drawn from the parts
    tops = [b for _, b in snap["top"]]
    assert tops == sorted(tops, reverse=True)


def test_memory_ledger_deep_adds_monolith_pickle():
    rt, _ = _chain_runtime(n=256, per=64)
    rt.run()  # GroupBy ledger NOT enabled: monolithic state
    shallow = tickscope.memory_snapshot(deep=False)
    deep = tickscope.memory_snapshot(deep=True)
    deep_parts = deep["owners"]["runtime"]
    assert any(k.endswith("/monolith_pickle") for k in deep_parts)
    assert not any(
        k.endswith("/monolith_pickle")
        for k in shallow["owners"].get("runtime", {})
    )
    assert deep["total_bytes"] >= shallow["total_bytes"]


def test_memory_provider_registry_and_errors():
    tickscope.register_memory_provider("good", lambda: {"x": 10})
    tickscope.register_memory_provider(
        "bad", lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    snap = tickscope.memory_snapshot()
    assert snap["owners"]["good"] == {"x": 10}
    assert "bad" not in snap["owners"]  # exceptions swallowed
    tickscope.unregister_memory_provider("good")
    assert "good" not in tickscope.memory_snapshot()["owners"]


def test_kv_ledger_resident_bytes():
    from pathway_tpu.generate.kv_cache import KvLedger

    kv = KvLedger()
    page = np.zeros((1, 4, 2, 8), np.float32)
    kv.put_page(0, 0, page, page)
    kv.put_seq(0, {"seq_id": 0})
    parts = kv.resident_bytes()
    assert parts["host_mirror"] >= 2 * page.nbytes
    assert parts["pages_arrangement"] > 0
    assert parts["seqs_arrangement"] > 0


def test_arrangement_resident_bytes_lower_bound():
    from pathway_tpu.engine.arrangement import Arrangement

    arr = Arrangement(n_cols=1)
    n = 64
    arr.append(
        np.arange(n, dtype=np.uint64),
        np.arange(n, dtype=np.uint64),
        np.ones(n, np.int64),
        [_obj_col([float(i) for i in range(n)])],
    )
    # at least the three u64/i64 index arrays' raw bytes
    assert arr.resident_bytes() >= 3 * n * 8


# --- clock audit (satellite 2: frozen wall clock) --------------------------


def test_frozen_wall_clock_durations_unaffected(monkeypatch):
    """Pin time.time: spans, signals and tick records must keep
    working — every duration is a perf_counter delta (the PR-18 clock
    audit contract in tracing.py / signals.py)."""
    from pathway_tpu.observability.signals import SignalSampler
    from pathway_tpu.observability.tracing import Tracer

    frozen = 1_700_000_000.0
    monkeypatch.setattr(time, "time", lambda: frozen)

    tr = Tracer(capacity=16, enabled=True)
    with tr.span("frozen-op"):
        time.sleep(0.02)
    rec = tr.spans()[-1]
    assert rec.duration_ns >= 15_000_000  # ~20 ms slept

    sampler = SignalSampler(interval_s=0.05)
    sampler.sample_once()
    time.sleep(0.01)
    sampler.sample_once()  # mono deltas: no div-by-zero, no negatives
    snap = sampler.snapshot()
    assert snap["samples"] >= 2

    rt, _ = _chain_runtime(n=128, per=64)
    rt.run()
    rec = rt._tickscope.last()
    assert rec is not None
    assert rec.tick_ns > 0
    assert all(e[2] >= e[1] for e in rec.entries)


# --- sub-millisecond buckets (satellite 3) ---------------------------------


def test_operator_tick_histogram_has_sub_ms_buckets():
    from pathway_tpu.observability.registry import REGISTRY

    rt, _ = _chain_runtime()  # construction registers the family
    fam = REGISTRY._metrics["pathway_operator_tick_seconds"]
    assert fam.bounds[0] <= 2e-6
    # enough resolution below the old 1e-4 floor to separate 10 us
    # compiled ticks from 100 us ones
    assert sum(1 for b in fam.bounds if b < 1e-4) >= 8
    del rt


def test_kernel_seconds_histogram_sub_ms():
    r = tickscope.Roofline()
    r.observe("compiled_tick", "k", 5e-5)  # drives the histogram too
    from pathway_tpu.observability.registry import REGISTRY

    fam = REGISTRY._metrics["pathway_tickscope_kernel_seconds"]
    assert fam.bounds[0] <= 2e-6


# --- roofline --------------------------------------------------------------


def test_roofline_mfu_math(monkeypatch):
    monkeypatch.setenv("PATHWAY_PEAK_FLOPS", "1e9")
    r = tickscope.Roofline()
    r.register("fam", "k1", flops=1e6, bytes_accessed=4e6)
    r.observe("fam", "k1", 1e-3)
    r.observe("fam", "k1", 1e-3)
    snap = r.snapshot()["fam"]
    assert snap["calls"] == 2
    assert snap["flops_total"] == pytest.approx(2e6)
    assert snap["achieved_flops_s"] == pytest.approx(1e9, rel=1e-6)
    assert snap["mfu"] == pytest.approx(1.0, rel=1e-6)
    assert r.known("fam", "k1") and not r.known("fam", "nope")
    assert r.samples("fam") == 2


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("PATHWAY_PEAK_FLOPS", "123.5e12")
    assert tickscope.peak_flops() == pytest.approx(123.5e12)
    monkeypatch.delenv("PATHWAY_PEAK_FLOPS")
    assert tickscope.peak_flops() > 0  # CPU table fallback


def test_estimate_program_cost_real_program():
    import jax

    fn = jax.jit(lambda x: x @ x)
    flops, nbytes = tickscope.estimate_program_cost(
        fn, jax.ShapeDtypeStruct((16, 16), np.float32)
    )
    # 16^3 multiply-adds = 8192 flops at minimum
    assert flops >= 4096
    assert nbytes >= 0


def test_compiled_tick_roofline_hook(monkeypatch):
    """The engine/compile.py hook registers + observes compiled_tick
    programs when segments actually run jitted."""
    monkeypatch.setenv("PATHWAY_COMPILED_MIN_ROWS", "1")
    rt, _ = _chain_runtime(n=512, per=128)
    rt.run()
    assert rt.compiled_plan is not None and rt.compiled_plan.segments
    assert tickscope.roofline().samples("compiled_tick") > 0
    snap = tickscope.roofline().snapshot()["compiled_tick"]
    assert snap["flops_total"] > 0
    assert snap["wall_s"] > 0
    # and the recorder tagged at least one entry compiled
    assert rt._tickscope.compiled_entries > 0


# --- wire taps -------------------------------------------------------------


def test_wire_tap_accounting():
    tickscope.wire_tap("exch:0", 100, raw_bytes=240, rows=5)
    tickscope.wire_tap("exch:0", 50, raw_bytes=120, rows=2)
    snap = tickscope.wire_snapshot()["exch:0"]
    assert snap == {
        "wire_bytes": 150,
        "raw_bytes": 360,
        "rows": 7,
        "frames": 2,
    }


def test_tap_frame_best_effort():
    from pathway_tpu.parallel import wire

    wire.tap_frame("ch9", 64, {"raw_bytes": 128, "rows": 3})
    assert tickscope.wire_snapshot()["ch9"]["frames"] == 1
    wire.tap_frame("ch9", 32, None)  # stats-less frame: still counted
    assert tickscope.wire_snapshot()["ch9"]["wire_bytes"] == 96


# --- plane-doctor rule (satellite 5) ---------------------------------------


def _coverage_diags():
    from pathway_tpu.analysis import run_plane_doctor

    t = pw.debug.table_from_markdown(
        """
        k | v
        a | 1
        """
    )
    pw.io.null.write(t)
    return run_plane_doctor().by_rule("tickscope-coverage")


def test_coverage_rule_info_when_serving_blind(monkeypatch):
    from pathway_tpu.analysis import Severity

    monkeypatch.setenv("PATHWAY_TICKSCOPE", "0")
    tickscope.mark_serving(True)
    diags = [
        d for d in _coverage_diags() if d.severity == Severity.INFO
    ]
    assert diags
    assert "PATHWAY_TICKSCOPE" in diags[0].message


def test_coverage_rule_quiet_when_recording(monkeypatch):
    from pathway_tpu.analysis import Severity

    tickscope.mark_serving(True)  # serving AND recording: no INFO
    assert not [
        d for d in _coverage_diags() if d.severity == Severity.INFO
    ]


def test_coverage_rule_warns_on_zero_roofline_samples(monkeypatch):
    from pathway_tpu.analysis import Severity

    monkeypatch.setenv("PATHWAY_COMPILED_MIN_ROWS", "1")
    rt, _ = _chain_runtime(n=256, per=64)
    rt.run()
    assert tickscope.coverage_status()["compiled_ticks"] > 0
    # samples exist -> quiet
    assert not [
        d
        for d in _coverage_diags()
        if d.severity == Severity.WARNING
    ]
    # wipe the roofline (reset) while the compiled runtime lives on:
    # compiled ticks with zero samples = silently-broken hook
    tickscope.reset()
    diags = [
        d
        for d in _coverage_diags()
        if d.severity == Severity.WARNING
    ]
    assert diags
    assert "compiled_tick" in diags[0].message
    del rt


# --- /debug/tick + fleet federation ----------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode())


def test_debug_tick_endpoint():
    from pathway_tpu.internals.monitoring_server import start_http_server

    rt, _ = _chain_runtime(n=512, per=128)
    rt.run()
    port = _free_port()
    server = start_http_server(rt, port=port)
    try:
        base = f"http://127.0.0.1:{port}"
        doc = _get_json(f"{base}/debug/tick?ticks=4&deep=1")
        assert doc["enabled"] is True
        assert doc["ticks_recorded"] >= 4
        ops = doc["last"]["operators"]
        assert ops and all("wall_ms" in o for o in ops)
        assert doc["last"]["critical_path"]["stages"]
        assert "rollup" in doc
        assert any(
            k.endswith("/monolith_pickle")
            for k in doc["memory"]["owners"].get("runtime", {})
        )
        trace = _get_json(f"{base}/debug/tick?trace=1")
        assert trace["traceEvents"]
        assert _get_json(f"{base}/debug/tick")["ring"] >= 1
    finally:
        server.shutdown()


class _TickMember(BaseHTTPRequestHandler):
    doc: dict = {}

    def do_GET(self):  # noqa: N802
        body = json.dumps(type(self).doc).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


def _member(doc):
    handler = type("_H", (_TickMember,), {"doc": doc})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _tick_doc(ops, edges, wall_ms):
    return {
        "enabled": True,
        "last": {
            "t": 3,
            "wall_ms": wall_ms,
            "operators": [
                {"node": n, "wall_ms": ms} for n, ms in ops
            ],
            "edges": edges,
            "critical_path": {
                "total_ms": sum(ms for _, ms in ops),
                "stages": [n for n, _ in ops],
            },
        },
    }


def test_federate_ticks_stitches_fleet_critical_path():
    from pathway_tpu.observability.fleet import federate_ticks

    srv_a, url_a = _member(
        _tick_doc(
            [("In_1", 2.0), ("Out_2", 1.0)], [["In_1", "Out_2"]], 3.5
        )
    )
    srv_b, url_b = _member(
        _tick_doc(
            [("In_1", 4.0), ("Out_2", 0.5)], [["In_1", "Out_2"]], 5.0
        )
    )
    try:
        res = federate_ticks({"a": url_a, "b": url_b})
        assert res["errors"] == {}
        assert set(res["members"]) == {"a", "b"}
        # disjoint DAGs: the slowest member's chain wins (4.5 ms on b)
        assert res["critical_path"]["total_ms"] == pytest.approx(4.5)
        assert res["critical_path"]["stages"] == [
            "b:In_1",
            "b:Out_2",
        ]
        # a channel hop from a's output into b's input stitches one
        # cross-rank path: 2.0 + 1.0 + wait 1.0 + 4.0 + 0.5 = 8.5
        res2 = federate_ticks(
            {"a": url_a, "b": url_b},
            channel_edges=[(("a", "Out_2"), ("b", "In_1"), 1e-3)],
        )
        assert res2["critical_path"]["total_ms"] == pytest.approx(8.5)
        assert res2["critical_path"]["stages"][0] == "a:In_1"
        # dead member: reported, not fatal
        res3 = federate_ticks(
            {"a": url_a, "dead": "http://127.0.0.1:9"}, timeout=0.5
        )
        assert "dead" in res3["errors"]
        assert "a" in res3["members"]
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_coverage_status_names_serving_providers():
    tickscope.register_memory_provider("replica:7", lambda: {"x": 1})
    assert tickscope.coverage_status()["serving_active"] is True
    tickscope.unregister_memory_provider("replica:7")
