"""Graph algorithms (reference: stdlib/graphs tests — pagerank,
bellman_ford, louvain)."""

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts


def test_pagerank_star():
    edges = T(
        """
        u | v
        a | hub
        b | hub
        c | hub
        hub | a
        """
    )
    res = pw.graphs.pagerank(edges, steps=60)
    _keys, cols = table_to_dicts(res)
    ranks = {cols["v"][k]: cols["rank"][k] for k in cols["v"]}
    # closed form: hub = 0.405 + 0.85*a, a = 0.15 + 0.85*hub
    assert abs(ranks["hub"] - 1.9189) < 1e-2
    assert abs(ranks["a"] - 1.7811) < 1e-2
    assert abs(ranks["b"] - 0.15) < 1e-9 and abs(ranks["c"] - 0.15) < 1e-9
    assert ranks["hub"] == max(ranks.values())


def test_louvain_two_cliques():
    # two triangles joined by one weak edge -> two communities
    edges = T(
        """
        u | v
        a | b
        b | c
        a | c
        x | y
        y | z
        x | z
        c | x
        """
    )
    vertices = T(
        """
        v
        a
        b
        c
        x
        y
        z
        """
    )
    res = pw.graphs.louvain_communities(vertices, edges, iteration_limit=8)
    _keys, cols = table_to_dicts(res)
    comm = {cols["v"][k]: cols["c"][k] for k in cols["v"]}
    assert comm["a"] == comm["b"] == comm["c"]
    assert comm["x"] == comm["y"] == comm["z"]
    assert comm["a"] != comm["x"]


def test_modularity_of_perfect_split():
    edges = T(
        """
        u | v | weight
        a | b | 1.0
        x | y | 1.0
        """
    )
    communities = T(
        """
        v | c
        a | 1
        b | 1
        x | 2
        y | 2
        """
    )
    res = pw.graphs.modularity(edges, communities)
    _keys, cols = table_to_dicts(res)
    (q,) = cols["modularity"].values()
    assert abs(q - 0.5) < 1e-9
