"""Smart fuzzy join spec — modeled on the reference's
python/pathway/tests/test_fuzzy_join.py."""

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.stdlib.ml.smart_table_ops import (
    FuzzyJoinFeatureGeneration,
    FuzzyJoinNormalization,
    fuzzy_match,
    fuzzy_match_tables,
    smart_fuzzy_match,
)


def _pairs(res):
    _k, cols = pw.debug.table_to_dicts(res)
    return sorted(
        (int(l), int(r), round(w, 6))
        for l, r, w in zip(
            cols["left"].values(), cols["right"].values(),
            cols["weight"].values(),
        )
    )


def test_fuzzy_match_edge_level():
    """Reference test_fuzzy_match_simple: three disjoint features, WEIGHT
    normalization -> each pair scores 1/2^ceil(log2 2) = 0.5."""
    nodes = T(
        """
        name
        a
        b
        c
        AA
        BB
        CC
        """,
        id_from=["name"],
    )
    features = T(
        """
      | weight | normalization_type
    1 | 1.0    | 1
    2 | 1.0    | 1
    3 | 1.0    | 1
    """
    )
    nf_left = T(
        """
     node | feature | weight
        a |       1 |    1.0
        b |       2 |    1.0
        c |       3 |    1.0
    """
    ).with_columns(
        node=nodes.pointer_from(pw.this.node),
        feature=features.pointer_from(pw.this.feature),
    )
    nf_right = T(
        """
     node | feature | weight
       AA |       1 |    1.0
       BB |       2 |    1.0
       CC |       3 |    1.0
    """
    ).with_columns(
        node=nodes.pointer_from(pw.this.node),
        feature=features.pointer_from(pw.this.feature),
    )
    res = fuzzy_match(nf_left, nf_right, features)
    got = _pairs(res)
    exp = sorted(
        (
            int(pw.internals.api.ref_scalar(l)),
            int(pw.internals.api.ref_scalar(r)),
            0.5,
        )
        for l, r in (("a", "AA"), ("b", "BB"), ("c", "CC"))
    )
    assert got == exp


def test_fuzzy_match_tables_names():
    """Same-name rows with rare shared tokens match; ubiquitous tokens are
    down-weighted (reference test_fuzzy_match_tables behavior)."""
    left = T(
        """
        name
        john smith
        anne brown
        david li
        """
    )
    right = T(
        """
        surname
        smith john
        brown anne
        li david
        """
    )
    res = fuzzy_match_tables(left, right)
    _k, cols = pw.debug.table_to_dicts(res)
    # every left row finds exactly its permuted twin
    assert len(cols["left"]) == 3
    assert all(w > 0 for w in cols["weight"].values())
    # verify the pairing is the permutation by checking sources
    _kl, lcols = pw.debug.table_to_dicts(left)
    _kr, rcols = pw.debug.table_to_dicts(right)
    lmap = {k: v for k, v in lcols["name"].items()}
    rmap = {k: v for k, v in rcols["surname"].items()}
    for l, r in zip(cols["left"].values(), cols["right"].values()):
        assert sorted(lmap[int(l)].split()) == sorted(rmap[int(r)].split())


def test_mutual_best_selection():
    """A right row shared by two left rows goes to the stronger match."""
    left = T(
        """
        name
        alpha beta gamma
        alpha
        """
    )
    right = T(
        """
        name
        alpha beta gamma
        """
    )
    res = fuzzy_match_tables(left, right)
    _k, cols = pw.debug.table_to_dicts(res)
    assert len(cols["left"]) == 1
    _kl, lcols = pw.debug.table_to_dicts(left)
    winner = lcols["name"][int(next(iter(cols["left"].values())))]
    assert winner == "alpha beta gamma"


def test_letters_feature_generation():
    left = T(
        """
        name
        qwxz
        """
    )
    right = T(
        """
        name
        q-w-x-z
        """
    )
    res = fuzzy_match_tables(
        left, right, feature_generation=FuzzyJoinFeatureGeneration.LETTERS
    )
    _k, cols = pw.debug.table_to_dicts(res)
    assert len(cols["left"]) == 1  # shares all letters despite no tokens


def test_by_hand_match_override():
    left = T(
        """
        name
        aaa bbb
        ccc ddd
        """
    )
    right = T(
        """
        name
        aaa bbb
        ccc ddd
        """
    )
    # pin the CROSS pairing by hand; automatic matching must not override
    _kl, lcols = pw.debug.table_to_dicts(left)
    _kr, rcols = pw.debug.table_to_dicts(right)
    l_ids = {v: k for k, v in lcols["name"].items()}
    r_ids = {v: k for k, v in rcols["name"].items()}

    class Hand(pw.Schema):
        left: pw.Pointer
        right: pw.Pointer
        weight: float

    hand = pw.debug.table_from_rows(
        Hand,
        [(pw.internals.api.Pointer(l_ids["aaa bbb"]),
          pw.internals.api.Pointer(r_ids["ccc ddd"]), 99.0)],
    )
    res = fuzzy_match_tables(left, right, by_hand_match=hand)
    _k, cols = pw.debug.table_to_dicts(res)
    pairs = {
        (int(l), int(r)): w
        for l, r, w in zip(
            cols["left"].values(), cols["right"].values(),
            cols["weight"].values(),
        )
    }
    assert (l_ids["aaa bbb"], r_ids["ccc ddd"]) in pairs
    assert pairs[(l_ids["aaa bbb"], r_ids["ccc ddd"])] == 99.0
    # the pinned left row must not also auto-match
    assert (l_ids["aaa bbb"], r_ids["aaa bbb"]) not in pairs


def test_self_match_symmetric():
    t = T(
        """
        name
        hello world
        world hello
        unrelated thing
        """
    )
    res = smart_fuzzy_match(t.name, t.name)
    _k, cols = pw.debug.table_to_dicts(res)
    assert len(cols["left"]) == 1
    (l,), (r,) = cols["left"].values(), cols["right"].values()
    assert int(l) < int(r)


def test_symmetric_by_hand_excludes_right_node():
    t = T(
        """
        name
        xx yy
        xx yy zz
        yy zz
        """
    )
    _kt, tcols = pw.debug.table_to_dicts(t)
    ids = {v: k for k, v in tcols["name"].items()}

    class Hand(pw.Schema):
        left: pw.Pointer
        right: pw.Pointer
        weight: float

    hand = pw.debug.table_from_rows(
        Hand,
        [(pw.internals.api.Pointer(ids["xx yy"]),
          pw.internals.api.Pointer(ids["xx yy zz"]), 7.0)],
    )
    res = smart_fuzzy_match(t.name, t.name, by_hand_match=hand)
    _k, cols = pw.debug.table_to_dicts(res)
    auto_nodes = set()
    for l, r, w in zip(
        cols["left"].values(), cols["right"].values(),
        cols["weight"].values(),
    ):
        if w != 7.0:
            auto_nodes |= {int(l), int(r)}
    # BOTH pinned nodes are out of automatic matching
    assert ids["xx yy"] not in auto_nodes
    assert ids["xx yy zz"] not in auto_nodes


def test_smart_fuzzy_join_compat_case_insensitive():
    from pathway_tpu.stdlib.ml.smart_table_ops import smart_fuzzy_join

    left = T(
        """
        name
        John Smith
        """
    )
    right = T(
        """
        name
        john smith
        """
    )
    res = smart_fuzzy_join(left, right)
    _k, cols = pw.debug.table_to_dicts(res)
    assert len(cols["left_id"]) == 1
