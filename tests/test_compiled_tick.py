"""Tick Forge differential suite: compiled segments (engine/compile.py)
must produce diff-batch streams EQUAL to the interpreter — exact for
int/bool/key/diff columns, allclose for floats — over randomized
insert/retract/update sequences, including graphs whose chains are cut
by fallback boundaries (UDFs, object columns), plus the escape hatch
(PATHWAY_COMPILED_TICK=0 restores the byte-identical interpreter), the
shape-bucketed compilation cache, and the compile-boundary doctor rule.
Oracle pattern as in PR 5/7 (tests/test_state_ledger.py)."""

from __future__ import annotations

import math
import os

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.expression_eval import InternalColRef
from pathway_tpu.engine.nodes import (
    ConcatNode,
    FilterNode,
    GroupByNode,
    InputNode,
    OutputNode,
    ReindexNode,
    RowwiseNode,
)
from pathway_tpu.engine.reducers import ReducerSpec
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr

# ---------------------------------------------------------------------------
# harness


class _Src(StaticSource):
    def __init__(self, names, ticks):
        super().__init__(names)
        self.ticks = ticks

    def events(self):
        for i, b in enumerate(self.ticks):
            yield i, b


def _ref(name: str) -> InternalColRef:
    return InternalColRef(0, name)


def _run(build, compiled: bool, min_rows: str = "1"):
    """Build a fresh graph via `build(capture)` and run it under the
    requested path; returns (per-tick rows, runtime)."""
    old_tick = os.environ.get("PATHWAY_COMPILED_TICK")
    old_min = os.environ.get("PATHWAY_COMPILED_MIN_ROWS")
    os.environ["PATHWAY_COMPILED_TICK"] = "1" if compiled else "0"
    os.environ["PATHWAY_COMPILED_MIN_ROWS"] = min_rows
    try:
        captured: dict[int, list] = {}

        def capture(t, b):
            rows = captured.setdefault(t, [])
            for k, d, vals in b.iter_rows():
                rows.append((int(k), int(d), tuple(vals)))

        out = build(capture)
        rt = Runtime([out] if not isinstance(out, list) else out)
        rt.run()
        return captured, rt
    finally:
        for k, v in (
            ("PATHWAY_COMPILED_TICK", old_tick),
            ("PATHWAY_COMPILED_MIN_ROWS", old_min),
        ):
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _vals_close(a, b) -> bool:
    if isinstance(a, float) or isinstance(b, float) or isinstance(
        a, np.floating
    ) or isinstance(b, np.floating):
        fa, fb = float(a), float(b)
        if math.isnan(fa) and math.isnan(fb):
            return True
        return math.isclose(fa, fb, rel_tol=1e-9, abs_tol=1e-12)
    if isinstance(a, (bool, np.bool_)) or isinstance(b, (bool, np.bool_)):
        return bool(a) == bool(b)
    if isinstance(a, (int, np.integer)) and isinstance(b, (int, np.integer)):
        return int(a) == int(b)
    return a == b


def _assert_streams_equal(got, want):
    """Per-tick equality of the emitted diff streams.  Both paths are
    order-deterministic (maps/filters/concat preserve input order, the
    bulk groupby factorizes by first occurrence), so rows compare
    pairwise; values compare by numeric identity, not representation —
    the compiled path legally returns np scalars where the interpreter
    boxes Python ones."""
    assert set(got) == set(want), (sorted(got), sorted(want))
    for t in sorted(want):
        g, w = got[t], want[t]
        assert len(g) == len(w), f"tick {t}: {len(g)} rows vs {len(w)}"
        for (gk, gd, gv), (wk, wd, wv) in zip(g, w):
            assert gk == wk and gd == wd, f"tick {t}: {gk, gd} vs {wk, wd}"
            assert len(gv) == len(wv)
            for x, y in zip(gv, wv):
                assert _vals_close(x, y), f"tick {t} key {gk}: {x!r} != {y!r}"


def _random_ticks(
    rng, n_ticks=6, rows_per_tick=40, with_floats=True, with_str=False
):
    """Randomized insert/retract/update sequence over int/float/bool
    (and optionally object/string) columns.  Retractions replay an
    earlier row with diff=-1; updates are retract+insert under one key."""
    names = ["a", "b", "flag"] + (["tag"] if with_str else [])
    live: list[tuple[int, tuple]] = []
    ticks = []
    next_key = 0
    for _ in range(n_ticks):
        keys, diffs, rows = [], [], []
        for _ in range(rows_per_tick):
            ins = not live or rng.random() < 0.7
            if ins:
                k = next_key
                next_key += 1
                vals = (
                    int(rng.integers(-1000, 1000)),
                    float(rng.normal()) if with_floats else float(0),
                    bool(rng.integers(0, 2)),
                ) + ((f"tag{int(rng.integers(0, 7))}",) if with_str else ())
                live.append((k, vals))
                keys.append(k)
                diffs.append(1)
                rows.append(vals)
            else:
                i = int(rng.integers(0, len(live)))
                k, vals = live.pop(i)
                keys.append(k)
                diffs.append(-1)
                rows.append(vals)
                if rng.random() < 0.5:  # update: re-insert changed values
                    nv = (vals[0] + 1, vals[1] * 2.0, not vals[2]) + vals[3:]
                    live.append((k, nv))
                    keys.append(k)
                    diffs.append(1)
                    rows.append(nv)
        cols = {}
        for ci, name in enumerate(names):
            vals = [r[ci] for r in rows]
            if name == "a":
                cols[name] = np.array(vals, dtype=np.int64)
            elif name == "b":
                cols[name] = np.array(vals, dtype=np.float64)
            elif name == "flag":
                cols[name] = np.array(vals, dtype=bool)
            else:
                col = np.empty(len(vals), dtype=object)
                col[:] = vals
                cols[name] = col
        ticks.append(
            DiffBatch(
                np.array(keys, dtype=np.uint64),
                np.array(diffs, dtype=np.int64),
                cols,
            )
        )
    return names, ticks


def _segments(rt):
    assert rt.compiled_plan is not None, "expected a compiled plan"
    return rt.compiled_plan.segments


def _compiled_ticks(rt) -> int:
    return sum(s.compiled_ticks for s in _segments(rt))


# ---------------------------------------------------------------------------
# differential: map / filter / reindex / concat chains


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_map_filter_map_chain_differential(seed):
    rng0 = np.random.default_rng(seed)
    names, ticks = _random_ticks(rng0)

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m1 = RowwiseNode(
            [inp],
            {
                "x": _ref("a") * 2 + 1,
                "y": _ref("b") - _ref("a"),
                "flag": _ref("flag"),
            },
        )
        f = FilterNode(
            m1, (_ref("x") > 0) & _ref("flag") | (_ref("y") <= 0.0)
        )
        m2 = RowwiseNode(
            [f],
            {
                "z": expr.IfElseExpression(
                    _ref("flag"), _ref("x"), -_ref("x")
                ),
                "w": expr.CastExpression(dt.FLOAT, _ref("x")) * _ref("y"),
            },
        )
        return OutputNode(m2, capture)

    want, rt0 = _run(build, compiled=False)
    assert rt0.compiled_plan is None  # escape hatch: no planning at all
    got, rt1 = _run(build, compiled=True)
    assert _compiled_ticks(rt1) > 0, "compiled path never dispatched"
    assert all(not s.broken for s in _segments(rt1))
    _assert_streams_equal(got, want)


def test_bare_column_predicate_and_keys_compile():
    """Filter predicates and reindex keys that are BARE column refs
    (no expression on top) must still register the column as a device
    input — the untraced entry used to KeyError on first dispatch and
    permanently break the segment (or, with nothing else to lower,
    refuse to compile at all as 'constant-only')."""
    rng = np.random.default_rng(11)
    names = ["a", "flag"]
    ticks = []
    for t in range(4):
        n = 32
        ticks.append(
            DiffBatch(
                np.arange(t * n, (t + 1) * n, dtype=np.uint64),
                np.ones(n, dtype=np.int64),
                {
                    # non-negative: reindex keys go through uint64
                    "a": rng.integers(0, 1000, size=n).astype(np.int64),
                    "flag": rng.integers(0, 2, size=n).astype(bool),
                },
            )
        )

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m = RowwiseNode(
            [inp],
            {"x": _ref("a") * 2, "flag": _ref("flag"), "k": _ref("a")},
        )
        f = FilterNode(m, _ref("flag"))  # bare bool column predicate
        r = ReindexNode(f, _ref("k"))    # bare int64 column keys
        return OutputNode(r, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    assert _compiled_ticks(rt) > 0, "bare-ref chain never compiled"
    assert all(not s.broken for s in _segments(rt))
    _assert_streams_equal(got, want)

    # the pure-passthrough variant: a LONE bare-ref filter is the whole
    # chain — nothing else registers device inputs
    def build_lone(capture):
        inp = InputNode(_Src(names, ticks), names)
        f = FilterNode(inp, _ref("flag"))
        return OutputNode(f, capture)

    want2, _ = _run(build_lone, compiled=False)
    got2, rt2 = _run(build_lone, compiled=True)
    assert _compiled_ticks(rt2) > 0, "lone bare-ref filter never compiled"
    assert all(not s.broken for s in _segments(rt2))
    _assert_streams_equal(got2, want2)


@pytest.mark.parametrize("seed", [3, 4])
def test_reindex_chain_differential(seed):
    rng0 = np.random.default_rng(seed)
    names, ticks = _random_ticks(rng0, with_floats=False)

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m1 = RowwiseNode(
            [inp], {"a": _ref("a"), "k2": abs(_ref("a")) * 11 + 5}
        )
        ri = ReindexNode(m1, _ref("k2"))
        m2 = RowwiseNode([ri], {"v": _ref("a") + _ref("k2")})
        return OutputNode(m2, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    assert _compiled_ticks(rt) > 0
    _assert_streams_equal(got, want)


def test_concat_fanin_differential():
    rng0 = np.random.default_rng(7)
    names, ticks_a = _random_ticks(rng0, n_ticks=4)
    _, ticks_b = _random_ticks(rng0, n_ticks=4)
    # disjoint key spaces: shift input B's keys
    ticks_b = [
        DiffBatch(b.keys + np.uint64(1 << 32), b.diffs, b.columns)
        for b in ticks_b
    ]

    def build(capture):
        ia = InputNode(_Src(names, ticks_a), names)
        ib = InputNode(_Src(names, ticks_b), names)
        cc = ConcatNode([ia, ib])
        m = RowwiseNode(
            [cc], {"s": _ref("a") + 1, "b": _ref("b"), "flag": _ref("flag")}
        )
        f = FilterNode(m, _ref("s") >= 0)
        return OutputNode(f, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    assert _compiled_ticks(rt) > 0
    _assert_streams_equal(got, want)


def test_object_column_passes_through_host_side():
    """String columns never cross the device but must ride compiled
    segments untouched (host passthrough with the filter mask applied)."""
    rng0 = np.random.default_rng(11)
    names, ticks = _random_ticks(rng0, with_str=True)

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m = RowwiseNode(
            [inp], {"x": _ref("a") * 3, "tag": _ref("tag")}
        )
        f = FilterNode(m, _ref("x") > -600)
        return OutputNode(f, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    assert _compiled_ticks(rt) > 0
    _assert_streams_equal(got, want)


# ---------------------------------------------------------------------------
# differential: fallback boundaries


def test_udf_boundary_splits_chain_differential():
    """A pw.apply node in the middle of a chain is NOT lowerable: the
    planner must cut there, the UDF runs interpreted, and the fused
    prefix/suffix still agree with the full interpreter."""
    rng0 = np.random.default_rng(13)
    names, ticks = _random_ticks(rng0)

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m1 = RowwiseNode(
            [inp], {"x": _ref("a") + 7, "b": _ref("b")}
        )
        udf = RowwiseNode(
            [m1],
            {
                "x": _ref("x"),
                "u": expr.ApplyExpression(
                    lambda x: x % 97, dt.INT, False, True, (_ref("x"),), {}
                ),
            },
        )
        m2 = RowwiseNode([udf], {"y": _ref("u") * 2 - _ref("x")})
        f = FilterNode(m2, _ref("y") != 0)
        return OutputNode(f, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    plan = rt.compiled_plan
    assert plan is not None
    # the UDF node itself is in no segment
    udf_nodes = [
        n
        for n in rt.order
        if isinstance(n, RowwiseNode)
        and any(
            isinstance(e, expr.ApplyExpression) for e in n.exprs.values()
        )
    ]
    assert udf_nodes and all(
        plan.segment_of(n.id) is None for n in udf_nodes
    )
    assert _compiled_ticks(rt) > 0
    _assert_streams_equal(got, want)


def test_error_poison_operator_falls_back():
    """Division has interpreter-only poison semantics (record_error +
    per-row Error on zero divisors) — chains containing it must run
    interpreted and still match."""
    names = ["a", "d"]
    ticks = [
        DiffBatch(
            np.arange(4, dtype=np.uint64),
            np.ones(4, dtype=np.int64),
            {
                "a": np.array([10, 20, 30, 40], dtype=np.int64),
                "d": np.array([2, 0, 5, 0], dtype=np.int64),
            },
        )
    ]

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m = RowwiseNode([inp], {"q": _ref("a") // _ref("d")})
        return OutputNode(m, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    # the whole chain is uncompilable -> no segments at all
    assert rt.compiled_plan is None or all(
        s.compiled_ticks == 0 for s in rt.compiled_plan.segments
    )
    _assert_streams_equal(got, want)
    # the poison contract itself: zero divisors yield ERROR rows, the
    # clean rows the exact quotient
    by_key = {k: v for k, d, v in next(iter(got.values()))}
    from pathway_tpu.internals.api import ERROR

    assert by_key[0] == (5,) and by_key[2] == (6,)
    assert by_key[1] == (ERROR,) and by_key[3] == (ERROR,)


def test_runtime_dtype_fallback_is_negative_cached():
    """Object-dtype values in a structurally compilable chain fall back
    per tick (NotCompilable at lowering) and the (bucket, dtype) key is
    negative-cached so later ticks skip re-tracing."""
    names = ["a"]
    col = np.empty(8, dtype=object)
    col[:] = [1, 2, None, 4, 5, 6, 7, 8]  # None keeps the column object
    tick = DiffBatch(
        np.arange(8, dtype=np.uint64), np.ones(8, dtype=np.int64), {"a": col}
    )
    ticks = [tick, tick, tick]

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m = RowwiseNode([inp], {"x": _ref("a") * 2})
        f = FilterNode(m, _ref("x") != 4)
        return OutputNode(f, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    segs = _segments(rt)
    assert len(segs) == 1
    assert segs[0].compiled_ticks == 0
    assert segs[0].fallback_ticks == 3
    assert segs[0]._FALLBACK in segs[0]._cache.values()
    _assert_streams_equal(got, want)


def test_min_rows_keeps_tiny_ticks_on_the_interpreter():
    names = ["a"]
    ticks = [
        DiffBatch(
            np.array([i], dtype=np.uint64),
            np.ones(1, dtype=np.int64),
            {"a": np.array([i], dtype=np.int64)},
        )
        for i in range(3)
    ]

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m = RowwiseNode([inp], {"x": _ref("a") + 1})
        f = FilterNode(m, _ref("x") > 0)
        return OutputNode(f, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True, min_rows="64")
    segs = _segments(rt)
    assert segs[0].compiled_ticks == 0 and segs[0].fallback_ticks == 3
    _assert_streams_equal(got, want)


# ---------------------------------------------------------------------------
# shape-bucketed cache


def test_shape_bucket_cache_reuses_programs():
    """Ticks on the same (bucket, dtype) signature compile once; a new
    row-count bucket adds exactly one cache entry; every dispatch after
    warmup is a hit (the steady-state serving contract)."""
    names = ["a", "b"]

    def tick(n, base):
        return DiffBatch(
            np.arange(base, base + n, dtype=np.uint64),
            np.ones(n, dtype=np.int64),
            {
                "a": np.arange(n, dtype=np.int64),
                "b": np.linspace(0.0, 1.0, n),
            },
        )

    # 6 ticks in the 64-bucket (33..64 rows), then 2 in the 128-bucket
    ticks = [tick(40 + i, 1000 * i) for i in range(6)] + [
        tick(100 + i, 100_000 + 1000 * i) for i in range(2)
    ]

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m = RowwiseNode([inp], {"x": _ref("a") * 2 + 1, "y": _ref("b") * 0.5})
        f = FilterNode(m, _ref("x") >= 0)
        return OutputNode(f, capture)

    want, _ = _run(build, compiled=False)
    got, rt = _run(build, compiled=True)
    segs = _segments(rt)
    assert len(segs) == 1
    seg = segs[0]
    assert seg.compiled_ticks == 8 and seg.fallback_ticks == 0
    buckets = {k[0] for k in seg._cache}
    assert buckets == {64, 128}
    assert len(seg._cache) == 2  # one program per bucket, none negative
    _assert_streams_equal(got, want)


def test_escape_hatch_env_zero_means_no_planning():
    os.environ["PATHWAY_COMPILED_TICK"] = "0"
    try:
        from pathway_tpu.engine.compile import (
            compiled_tick_enabled,
            plan_segments,
        )

        assert not compiled_tick_enabled()
        assert plan_segments([], {}) is None
    finally:
        os.environ.pop("PATHWAY_COMPILED_TICK", None)


# ---------------------------------------------------------------------------
# groupby semigroup partials (device twin, forced on for the test)


@pytest.mark.parametrize("force_device", ["0", "1"])
def test_groupby_semigroup_partials_differential(force_device):
    rng0 = np.random.default_rng(17)
    names, ticks = _random_ticks(rng0, n_ticks=5, rows_per_tick=120)

    def build(capture):
        inp = InputNode(_Src(names, ticks), names)
        m = RowwiseNode(
            [inp],
            {"g": _ref("a") & 15, "v": _ref("a"), "b": _ref("b")},
        )
        gb = GroupByNode(
            m,
            ["g"],
            {
                "cnt": ReducerSpec(kind="count"),
                "tot": ReducerSpec(kind="sum", arg_cols=("v",)),
                "mean": ReducerSpec(kind="avg", arg_cols=("b",)),
            },
        )
        return OutputNode(gb, capture)

    old = os.environ.get("PATHWAY_COMPILED_GROUPBY")
    os.environ["PATHWAY_COMPILED_GROUPBY"] = force_device
    try:
        want, _ = _run(build, compiled=False)
        got, _rt = _run(build, compiled=True)
    finally:
        if old is None:
            os.environ.pop("PATHWAY_COMPILED_GROUPBY", None)
        else:
            os.environ["PATHWAY_COMPILED_GROUPBY"] = old
    _assert_streams_equal(got, want)


# ---------------------------------------------------------------------------
# public API end-to-end


class _NumSchema(pw.Schema):
    a: int
    b: float


def _public_rows(n=200, seed=23):
    rng = np.random.default_rng(seed)
    return [
        (int(rng.integers(-500, 500)), float(rng.normal()))
        for _ in range(n)
    ]


def _public_build_and_collect():
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_rows(_NumSchema, _public_rows())
    r = t.select(x=t.a * 2 + 1, y=t.b - t.a).filter(
        pw.this.x > 0
    ).select(z=pw.this.x + 1, y=pw.this.y)
    keys, cols = pw.debug.table_to_dicts(r)
    rt = pw.internals.parse_graph.G.last_runtime
    return keys, cols, rt


def test_public_api_differential():
    os.environ["PATHWAY_COMPILED_TICK"] = "0"
    os.environ["PATHWAY_COMPILED_MIN_ROWS"] = "1"
    try:
        _, cols_i, rt_i = _public_build_and_collect()
        assert rt_i.compiled_plan is None
        os.environ["PATHWAY_COMPILED_TICK"] = "1"
        _, cols_c, rt_c = _public_build_and_collect()
    finally:
        os.environ.pop("PATHWAY_COMPILED_TICK", None)
        os.environ.pop("PATHWAY_COMPILED_MIN_ROWS", None)
    assert rt_c.compiled_plan is not None
    assert sum(s.compiled_ticks for s in rt_c.compiled_plan.segments) > 0
    assert set(cols_i["z"]) == set(cols_c["z"])
    for k in cols_i["z"]:
        assert int(cols_i["z"][k]) == int(cols_c["z"][k])
        assert math.isclose(
            float(cols_i["y"][k]), float(cols_c["y"][k]), rel_tol=1e-9
        )


def test_debug_graph_reports_segments():
    os.environ["PATHWAY_COMPILED_TICK"] = "1"
    os.environ["PATHWAY_COMPILED_MIN_ROWS"] = "1"
    try:
        _, _, rt = _public_build_and_collect()
    finally:
        os.environ.pop("PATHWAY_COMPILED_TICK", None)
        os.environ.pop("PATHWAY_COMPILED_MIN_ROWS", None)
    from pathway_tpu.observability.debug import graph_table

    rows = graph_table(rt)
    tails = [r for r in rows if r.get("segment_tail")]
    assert tails, "no segment tail rows in /debug/graph"
    assert any(r["compiled_ticks"] > 0 for r in tails)
    assert all("compiled" in r for r in rows)


# ---------------------------------------------------------------------------
# Graph Doctor: compile-boundary rule


def test_doctor_compile_boundary_names_udf():
    from pathway_tpu.analysis import run_doctor

    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_rows(_NumSchema, [(1, 1.0), (2, 2.0)])
    m = t.select(x=t.a * 2)
    u = m.select(
        u=pw.apply(lambda x: x + 1, pw.this.x), x=pw.this.x
    )
    pw.io.null.write(u.select(y=pw.this.u + pw.this.x))
    report = run_doctor()
    diags = report.by_rule("compile-boundary")
    assert diags, "expected a compile-boundary diagnostic for the UDF"
    assert any("UDF" in d.message or "udf" in d.message for d in diags)


def test_doctor_compile_boundary_negative_pure_chain():
    from pathway_tpu.analysis import run_doctor

    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_rows(_NumSchema, [(1, 1.0), (2, 2.0)])
    pw.io.null.write(t.select(x=t.a * 2).filter(pw.this.x > 0))
    report = run_doctor()
    assert not report.by_rule("compile-boundary")
