"""Ported join-semantics tests (reference:
python/pathway/tests/test_joins.py) — left/right/outer behavior with
duplicates, missing sides, require-guards, set-id joins, and pw.left /
pw.right desugaring."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import assert_table_equality_wo_index


def _t1():
    return T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )


def test_left_join_01():
    t1 = _t1()
    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )
    expected = T(
        """
        a   | t2_a  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        14  | 14    | 328
        """
    )
    res = t1.join_left(t2, t1.a == t2.a).select(
        t1.a,
        t2_a=t2.a,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_empty_duplicates():
    t1 = _t1()
    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 13 | 212
          3 | 13 | 213
          4 | 13 | 214
        """
    )
    expected = T(
        """
        t2_c2  | s
        121    | 322
        169    | 325
        169    | 326
        169    | 327
               |
               |
        """
    )
    res = t1.join_left(t2, t1.a == t2.c).select(
        t2_c2=pw.require(t2.c * t2.c, t2.id),
        s=pw.require(t1.b + t2.d, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_right_join_duplicates():
    t1 = _t1()
    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 13 | 212
          3 | 13 | 213
          4 | 15 | 214
        """
    )
    res = t1.join_right(t2, t1.a == t2.c).select(
        b=pw.require(t1.b, t1.id),
        d=t2.d,
    )
    expected = T(
        """
        b    | d
        111  | 211
        113  | 212
        113  | 213
             | 214
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join():
    t1 = T(
        """
        a  | b
        11 | 111
        12 | 112
        """
    )
    t2 = T(
        """
        c  | d
        12 | 212
        13 | 213
        """
    )
    res = t1.join_outer(t2, t1.a == t2.c).select(
        a=pw.require(t1.a, t1.id),
        c=pw.require(t2.c, t2.id),
    )
    expected = T(
        """
        a  | c
        11 |
        12 | 12
           | 13
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_this_desugaring():
    t1 = _t1()
    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )
    res = t1.join_left(t2, pw.left.a == pw.right.a).select(
        pw.left.b, d=pw.right.d
    )
    expected = T(
        """
        b   | d
        111 | 211
        112 | 212
        113 | 213
        114 | 214
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_set_id():
    """id=pw.left.id: output universe reuses the left row ids."""
    t1 = _t1()
    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
        """
    )
    res = t1.join_left(t2, t1.a == t2.a, id=t1.id).select(
        t1.b, d=pw.require(t2.d, t2.id)
    )
    _k, cols = pw.debug.table_to_dicts(res)
    _k1, cols1 = pw.debug.table_to_dicts(_t1())
    assert set(_k) == set(_k1)  # left universe preserved


def test_join_inner_chained_conditions():
    t1 = T(
        """
        a | b | v
        1 | x | 10
        1 | y | 20
        2 | x | 30
        """
    )
    t2 = T(
        """
        a | b | w
        1 | x | 7
        2 | x | 8
        2 | y | 9
        """
    )
    res = t1.join(t2, t1.a == t2.a, t1.b == t2.b).select(
        t1.v, w=t2.w
    )
    expected = T(
        """
        v  | w
        10 | 7
        30 | 8
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_join_cross_no_condition():
    """Join with no conditions = cross product (reference join semantics)."""
    t1 = T(
        """
        a
        1
        2
        """
    )
    t2 = T(
        """
        b
        x
        y
        """
    )
    res = t1.join(t2).select(t1.a, t2.b)
    _k, cols = pw.debug.table_to_dicts(res)
    got = sorted(zip(cols["a"].values(), cols["b"].values()))
    assert got == [(1, "x"), (1, "y"), (2, "x"), (2, "y")]


def test_join_select_star_left_right():
    t1 = T(
        """
        a | b
        1 | 10
        """
    )
    t2 = T(
        """
        c | d
        1 | 20
        """
    )
    res = t1.join(t2, t1.a == t2.c).select(*pw.left, *pw.right)
    assert sorted(res.column_names()) == ["a", "b", "c", "d"]
    _k, cols = pw.debug.table_to_dicts(res)
    assert list(cols["d"].values()) == [20]
