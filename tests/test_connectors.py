"""Connector tests — sqlite, debezium, deltalake, iceberg, elasticsearch
(REST bulk against a local capture server), s3-over-fsspec, null.
(reference test analogs: tests/integration/test_dsv.rs, test_debezium.rs,
python/pathway/tests/test_io.py)."""

import json
import sqlite3
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T


def _run_streaming_until(predicate, timeout=15.0):
    t = threading.Thread(
        target=lambda: pw.run(autocommit_duration_ms=20), daemon=True
    )
    t.start()
    deadline = time.time() + timeout
    ok = False
    while time.time() < deadline:
        if predicate():
            ok = True
            break
        time.sleep(0.05)
    rt = pw.internals.parse_graph.G.runtime
    if rt is not None:
        rt.stop()
    t.join(timeout=10)
    assert ok


class KV(pw.Schema):
    k: str = pw.column_definition(primary_key=True)
    v: int


# --- sqlite ----------------------------------------------------------------


def test_sqlite_static_read(tmp_path):
    db = tmp_path / "t.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE kv (k TEXT, v INTEGER)")
    conn.executemany("INSERT INTO kv VALUES (?, ?)", [("a", 1), ("b", 2)])
    conn.commit()
    conn.close()

    t = pw.io.sqlite.read(str(db), "kv", KV, mode="static")
    keys, cols = pw.debug.table_to_dicts(t)
    assert sorted(cols["v"].values()) == [1, 2]


def test_sqlite_write_roundtrip(tmp_path):
    db = tmp_path / "out.db"
    t = T(
        """
        k | v
        a | 1
        b | 2
        """
    )
    pw.io.sqlite.write(t, str(db), "out")
    pw.run()
    conn = sqlite3.connect(db)
    rows = sorted(conn.execute("SELECT k, v FROM out").fetchall())
    conn.close()
    assert rows == [("a", 1), ("b", 2)]


def test_sqlite_streaming_picks_up_changes(tmp_path):
    db = tmp_path / "s.db"
    conn = sqlite3.connect(db, check_same_thread=False)
    conn.execute("CREATE TABLE kv (k TEXT, v INTEGER)")
    conn.execute("INSERT INTO kv VALUES ('a', 1)")
    conn.commit()

    t = pw.io.sqlite.read(str(db), "kv", KV, mode="streaming")
    out = tmp_path / "out.jsonl"
    pw.io.jsonlines.write(t, str(out))

    def late_insert():
        time.sleep(0.6)
        conn.execute("INSERT INTO kv VALUES ('b', 5)")
        conn.commit()

    threading.Thread(target=late_insert, daemon=True).start()

    def got_both():
        try:
            lines = [json.loads(x) for x in open(out) if x.strip()]
        except OSError:
            return False
        vs = {o["k"]: o["v"] for o in lines if o["diff"] > 0}
        return vs.get("a") == 1 and vs.get("b") == 5

    _run_streaming_until(got_both)
    conn.close()


# --- debezium ---------------------------------------------------------------


def test_debezium_dir_cdc(tmp_path):
    msgs = tmp_path / "msgs"
    msgs.mkdir()
    events = [
        {"payload": {"op": "c", "after": {"k": "a", "v": 1}, "before": None}},
        {"payload": {"op": "c", "after": {"k": "b", "v": 2}, "before": None}},
        {
            "payload": {
                "op": "u",
                "before": {"k": "a", "v": 1},
                "after": {"k": "a", "v": 10},
            }
        },
        {"payload": {"op": "d", "before": {"k": "b", "v": 2}, "after": None}},
    ]
    with open(msgs / "m.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    t = pw.io.debezium.read(input_dir=str(msgs), schema=KV)
    out = tmp_path / "out.jsonl"
    pw.io.jsonlines.write(t, str(out))

    def settled():
        try:
            lines = [json.loads(x) for x in open(out) if x.strip()]
        except OSError:
            return False
        state = {}
        for o in lines:
            if o["diff"] > 0:
                state[o["k"]] = o["v"]
            elif state.get(o["k"]) == o["v"]:
                del state[o["k"]]
        return state == {"a": 10}

    _run_streaming_until(settled)


def test_debezium_mongodb_dialect():
    from pathway_tpu.io.debezium import parse_debezium_message

    msg = {
        "payload": {
            "op": "u",
            "before": None,
            "after": json.dumps({"k": "x", "v": 3}),
        }
    }
    ev = parse_debezium_message(msg, ["k", "v"], None, db_type="mongodb")
    assert ev == [(1, ("x", 3))]
    dmsg = {"payload": {"op": "d", "filter": json.dumps({"k": "x", "v": 3})}}
    ev = parse_debezium_message(dmsg, ["k", "v"], None, db_type="mongodb")
    assert ev == [(-1, ("x", 3))]


# --- delta lake -------------------------------------------------------------


def test_deltalake_write_then_static_read(tmp_path):
    lake = tmp_path / "lake"
    t = T(
        """
        k | v
        a | 1
        b | 2
        """
    )
    pw.io.deltalake.write(t, str(lake))
    pw.run()
    assert (lake / "_delta_log").is_dir()

    pw.internals.parse_graph.G.clear()

    class KVD(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int
        diff: int

    t2 = pw.io.deltalake.read(str(lake), schema=KVD, mode="static")
    keys, cols = pw.debug.table_to_dicts(t2)
    assert sorted(cols["v"].values()) == [1, 2]


def test_deltalake_streaming_tails_new_commits(tmp_path):
    lake = tmp_path / "lake"
    from pathway_tpu.engine.batch import DiffBatch
    from pathway_tpu.io.deltalake import _DeltaWriter, _Store

    w = _DeltaWriter(_Store(str(lake)), ["k", "v"])
    w.write_batch(0, DiffBatch.from_rows([(1, 1, ("a", 1))], ["k", "v"]))

    class KVD(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int

    t = pw.io.deltalake.read(str(lake), schema=KVD, mode="streaming")
    out = tmp_path / "out.jsonl"
    pw.io.jsonlines.write(t, str(out))

    def late_commit():
        time.sleep(0.6)
        w.write_batch(2, DiffBatch.from_rows([(2, 1, ("b", 7))], ["k", "v"]))

    threading.Thread(target=late_commit, daemon=True).start()

    def got_both():
        try:
            lines = [json.loads(x) for x in open(out) if x.strip()]
        except OSError:
            return False
        vs = {o["k"]: o["v"] for o in lines if o["diff"] > 0}
        return vs.get("a") == 1 and vs.get("b") == 7

    _run_streaming_until(got_both)


# --- iceberg ----------------------------------------------------------------


def test_iceberg_write_then_read(tmp_path):
    root = tmp_path / "warehouse"
    t = T(
        """
        k | v
        a | 1
        b | 2
        """
    )
    pw.io.iceberg.write(
        t, str(root), namespace=["app"], table_name="kv"
    )
    pw.run()

    pw.internals.parse_graph.G.clear()

    class KVD(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int

    t2 = pw.io.iceberg.read(
        str(root), namespace=["app"], table_name="kv", schema=KVD,
        mode="static",
    )
    keys, cols = pw.debug.table_to_dicts(t2)
    assert sorted(cols["v"].values()) == [1, 2]


# --- elasticsearch (REST bulk against local capture server) ----------------


def test_elasticsearch_bulk_writer(tmp_path):
    import socket
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    captured: list[str] = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            captured.append(self.rfile.read(n).decode())
            body = b'{"errors": false, "items": []}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        t = T(
            """
            k | v
            a | 1
            """
        )
        pw.io.elasticsearch.write(
            t,
            f"http://127.0.0.1:{port}",
            auth=pw.io.elasticsearch.ElasticSearchAuth.basic("u", "p"),
            index_name="idx",
        )
        pw.run()
    finally:
        server.shutdown()
    assert captured, "no bulk request received"
    lines = [json.loads(x) for x in captured[0].strip().splitlines()]
    assert lines[0]["index"]["_index"] == "idx"
    assert lines[1] == {"k": "a", "v": 1}


# --- s3 via fsspec ----------------------------------------------------------


def test_s3_scanner_over_memory_fs(tmp_path):
    fsspec = pytest.importorskip("fsspec")
    fs = fsspec.filesystem("memory")
    with fs.open("/bucket/data/part1.jsonl", "w") as f:
        f.write(json.dumps({"k": "a", "v": 1}) + "\n")
        f.write(json.dumps({"k": "b", "v": 2}) + "\n")
    try:
        t = pw.io.s3.read(
            "memory://bucket/data", format="json", schema=KV, mode="static"
        )
        keys, cols = pw.debug.table_to_dicts(t)
        assert sorted(cols["v"].values()) == [1, 2]
    finally:
        fs.rm("/bucket", recursive=True)


# --- null -------------------------------------------------------------------


def test_null_writer_consumes():
    t = T(
        """
        v
        1
        """
    )
    pw.io.null.write(t)
    pw.run()
