"""Port of the reference time-utils suite (reference:
python/pathway/tests/temporal/test_time_utils.py). Mechanical port:
package and imports adapted, fixtures and assertions kept identical."""

from __future__ import annotations

import datetime
from unittest.mock import patch

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import assert_stream_equality_wo_index


@patch("pathway_tpu.stdlib.temporal.time_utils.utc_now")
def test_inactivity_detection_instance(utc_now_mock):
    now = datetime.datetime.now(datetime.timezone.utc)
    now_ms = int((int(now.timestamp() * 1000) // 1000) * 1000) + 100000
    events = T(
        f"""
            | t             | instance | __time__
        1   | {now_ms}      |        A | {now_ms}
        2   | {now_ms+50}   |        A | {now_ms+50}
        3   | {now_ms+150}  |        A | {now_ms+150}
        4   | {now_ms+200}  |        A | {now_ms+200}
        5   | {now_ms+900}  |        A | {now_ms+900}
        6   | {now_ms+1000} |        A | {now_ms+1000}
        7   | {now_ms}      |        B | {now_ms}
        8   | {now_ms+200}  |        B | {now_ms+200}
        9   | {now_ms+400}  |        B | {now_ms+400}
       10   | {now_ms+1000} |        B | {now_ms+1000}



    """
    ).with_columns(t=pw.this.t.dt.utc_from_timestamp(unit="ms"))

    utc_now_mock.side_effect = lambda refresh_rate: pw.debug.table_from_rows(
        pw.schema_from_types(t=int),
        [
            (time_ms, time_ms, 1)
            for time_ms in range(
                now_ms, now_ms + 1400, int(refresh_rate.total_seconds() * 1000)
            )
        ],
        is_stream=True,
    ).select(timestamp_utc=pw.this.t.dt.utc_from_timestamp(unit="ms"))

    inactivities, resumed_activities = pw.temporal.inactivity_detection(
        events.t,
        pw.Duration(milliseconds=300),
        refresh_rate=pw.Duration(milliseconds=50),
        instance=pw.this.instance,
    )

    expected_inactivities = T(
        f"""
             instance | inactive_t    | __time__      | __diff__
                    A | {now_ms+200}  | {now_ms+550}  | 1
                    A | {now_ms+1000} | {now_ms+1350} | 1
                    B | {now_ms+400}  | {now_ms+750}  | 1
                    B | {now_ms+1000} | {now_ms+1350} | 1
        """
    )
    expected_resumes = T(
        f"""
             instance | resumed_t     | __time__      | __diff__
                    A | {now_ms+900}  | {now_ms+900}  | 1
                    B | {now_ms+1000} | {now_ms+1000} | 1
        """
    )
    assert_stream_equality_wo_index(
        (
            inactivities.with_columns(
                inactive_t=pw.cast(int, pw.this.inactive_t.dt.timestamp(unit="ms"))
            ),
            resumed_activities.with_columns(
                resumed_t=pw.cast(int, pw.this.resumed_t.dt.timestamp(unit="ms"))
            ),
        ),
        (expected_inactivities, expected_resumes),
    )


@patch("pathway_tpu.stdlib.temporal.time_utils.utc_now")
def test_inactivity_detection(utc_now_mock):
    now = datetime.datetime.now(datetime.timezone.utc)
    now_ms = int((int(now.timestamp() * 1000) // 1000) * 1000) + 100000
    events = T(
        f"""
            | t             | __time__
        1   | {now_ms}      | {now_ms}
        2   | {now_ms+50}   | {now_ms+50}
        3   | {now_ms+150}  | {now_ms+150}
        4   | {now_ms+200}  | {now_ms+200}
        5   | {now_ms+900}  | {now_ms+900}
        6   | {now_ms+1000} | {now_ms+1000}


    """
    ).with_columns(t=pw.this.t.dt.utc_from_timestamp(unit="ms"))

    utc_now_mock.side_effect = lambda refresh_rate: pw.debug.table_from_rows(
        pw.schema_from_types(t=int),
        [
            (time_ms, time_ms, 1)
            for time_ms in range(
                now_ms, now_ms + 1400, int(refresh_rate.total_seconds() * 1000)
            )
        ],
        is_stream=True,
    ).select(timestamp_utc=pw.this.t.dt.utc_from_timestamp(unit="ms"))

    inactivities, resumed_activities = pw.temporal.inactivity_detection(
        events.t,
        pw.Duration(milliseconds=300),
        refresh_rate=pw.Duration(milliseconds=50),
    )

    expected_inactivities = T(
        f"""
            inactive_t    | __time__      | __diff__
            {now_ms+200}  | {now_ms+550}  | 1
            {now_ms+1000} | {now_ms+1350} | 1
        """
    )
    expected_resumes = T(
        f"""
            resumed_t     | __time__      | __diff__
            {now_ms+900}  | {now_ms+900}  | 1
        """
    )
    assert_stream_equality_wo_index(
        (
            inactivities.with_columns(
                inactive_t=pw.cast(int, pw.this.inactive_t.dt.timestamp(unit="ms"))
            ),
            resumed_activities.with_columns(
                resumed_t=pw.cast(int, pw.this.resumed_t.dt.timestamp(unit="ms"))
            ),
        ),
        (expected_inactivities, expected_resumes),
    )
