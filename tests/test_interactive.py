"""LiveTable interactive mode (reference: internals/interactive.py:130 —
background graph runner + export/import round trip)."""

import time

import pathway_tpu as pw
from pathway_tpu.internals.interactive import live


class S(pw.Schema):
    v: int


def test_live_snapshot_and_frontier():
    t = pw.debug.table_from_rows(S, [(1,), (2,), (3,)])
    res = t.reduce(s=pw.reducers.sum(t.v))
    lt = live(res)
    assert lt.wait(10)
    frontier, rows = lt.snapshot()
    assert len(rows) == 1
    assert next(iter(rows.values()))[0] == 6
    assert lt.done
    from pathway_tpu.engine.batch import END_OF_TIME

    assert lt.frontier() == END_OF_TIME
    assert len(lt) == 1
    df = lt.to_pandas()
    assert list(df["s"]) == [6]
    lt.stop()


def test_live_subscribe_replays_state():
    t = pw.debug.table_from_rows(S, [(5,), (7,)])
    lt = live(t)
    assert lt.wait(10)
    seen = []
    lt.subscribe(lambda k, row, t_, add: seen.append((row["v"], add)))
    assert sorted(seen) == [(5, True), (7, True)]
    lt.stop()


def test_live_table_reimport_composes():
    """The import half: a LiveTable feeds a NEW graph as a source."""
    t = pw.debug.table_from_rows(S, [(1,), (2,), (3,), (4,)])
    lt = live(t)
    assert lt.wait(10)
    pw.internals.parse_graph.G.clear()
    t2 = lt.table()
    res = t2.filter(t2.v >= 3).reduce(s=pw.reducers.sum(t2.v))
    _k, cols = pw.debug.table_to_dicts(res)
    assert list(cols["s"].values()) == [7]
    lt.stop()


def test_live_failure_is_observable():
    t = pw.debug.table_from_rows(S, [(1,)])

    @pw.udf
    def boom(v: int) -> int:
        raise RuntimeError("kaput")

    # force a hard failure in the background run via a sink-side error
    from pathway_tpu.engine.nodes import OutputNode
    lt = live(t)
    lt.wait(10)
    lt._done.clear()
    lt.error = RuntimeError("injected")
    lt._done.set()
    import pytest
    with pytest.raises(RuntimeError, match="injected"):
        lt.wait(1)
    assert lt.failed
    lt.stop()
