"""Tenant Weave result cache (pathway_tpu/serving/result_cache.py)
tests — the cache-invalidation PRECISION property plus the unit
contract.

The acceptance bar: after a tick whose consolidated delta stream names
keys K, exactly the cached entries covering K are evicted (covering =
the result set contains a changed key, or an upsert lands against an
under-filled result set / a query that would admit the new doc into its
top-k) and every SURVIVING entry still equals a fresh replica answer —
randomized corpora with deletions, on sharded and unsharded planes, and
never a full flush on an ordinary tick.
"""

import json
import time

import numpy as np
import pytest

from pathway_tpu.serving.result_cache import (
    CACHE_HEADER,
    ResultCache,
    cache_enabled_via_env,
    cache_from_env,
    fingerprint,
)


@pytest.fixture(autouse=True)
def _env(monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "result-cache-test-secret")
    monkeypatch.delenv("PATHWAY_ROUTER_CACHE", raising=False)
    monkeypatch.delenv("PATHWAY_ROUTER_CACHE_WRITER", raising=False)
    yield
    from pathway_tpu.parallel import replicate

    replicate.reset_publisher()


def _wait(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _batch(rows):
    from pathway_tpu.engine.batch import DiffBatch

    return DiffBatch.from_rows(rows, ("_data", "_meta"))


def _norm(v):
    v = np.asarray(v, dtype=np.float32).reshape(-1)
    n = float(np.linalg.norm(v))
    return v / n if n > 0 else v


def _fresh_answer(corpus, qvec, k):
    """The model replica: brute-force cosine top-k with the (score
    desc, key asc) tie-break merge_topk and the toy indexes use."""
    q = _norm(qvec)
    scored = [
        (int(key), float(np.dot(q, _norm(vec))))
        for key, vec in corpus.items()
    ]
    scored.sort(key=lambda m: (-m[1], m[0]))
    return {"matches": [[key, score] for key, score in scored[: int(k)]]}


def _body(qvec, k):
    return json.dumps(
        {"vec": [float(x) for x in np.asarray(qvec).reshape(-1)], "k": k}
    ).encode()


def _store(cache, tenant, corpus, qvec, k, tick=0, max_st=None, headers=()):
    body = _body(qvec, k)
    payload = json.dumps(_fresh_answer(corpus, qvec, k)).encode()
    hdrs = {
        "content-type": "application/json",
        "x-pathway-applied-tick": str(tick),
        **dict(headers),
    }
    ok = cache.store(tenant, body, max_st, 200, payload, hdrs)
    return body, payload, ok


class _FakeStream:
    """Stands in for a DeltaStreamClient in unit tests: freshness,
    applied tick and incarnation are directly settable."""

    def __init__(self, staleness=0.0, applied_tick=0, incarnation=0):
        self.staleness = staleness
        self.applied_tick = applied_tick
        self.writer_incarnation = incarnation
        self.newest_known = applied_tick
        self.closed = False

    def staleness_seconds(self):
        return self.staleness

    def close(self):
        self.closed = True


# ---------------------------------------------------------------------------
# the precision property (the PR's acceptance bar)


@pytest.mark.parametrize("n_shards", [1, 3])
def test_invalidation_precision_property(n_shards):
    """Randomized corpora + deletions + upserts: eviction is EXACT per
    the documented contract (changed-key containment, under-filled
    entries on upsert, would-enter-the-top-k score test) and every
    survivor still equals a fresh replica answer.  The sharded variant
    delivers each tick as per-shard batches, the shape the full-corpus
    observer subscription receives from a sharded writer."""
    rng = np.random.default_rng(7 + n_shards)
    dim = 8
    cache = ResultCache(capacity=4096, dim=dim, ttl_ms=1e9)
    corpus = {k: rng.standard_normal(dim) for k in range(1, 41)}
    next_key = 1000
    queries = []
    for i in range(18):
        # k=60 > corpus size: deliberately under-filled entries
        queries.append(
            (f"t{i % 3}", rng.standard_normal(dim), int(rng.choice([3, 5, 60])))
        )

    def store_all(tick):
        for tenant, qvec, k in queries:
            _store(cache, tenant, corpus, qvec, k, tick=tick)

    store_all(0)
    assert len(cache) == len(queries)
    survivors_seen = 0
    for tick in range(1, 13):
        ops = []
        live = sorted(corpus)
        for key in rng.choice(
            live, size=min(int(rng.integers(0, 3)), len(live)), replace=False
        ):
            del corpus[int(key)]
            ops.append((int(key), -1, None))
        for _ in range(int(rng.integers(0, 3))):
            if rng.random() < 0.5 and corpus:
                key = int(rng.choice(sorted(corpus)))
            else:
                next_key += 1
                key = next_key
            vec = rng.standard_normal(dim).astype(np.float32)
            corpus[key] = vec
            ops.append((key, 1, vec))
        if not ops:
            continue
        changed = {k for k, _d, _v in ops}
        upserts = [(k, v) for k, d, v in ops if d > 0]
        # the documented eviction contract, computed against the live
        # entries BEFORE the tick applies
        expected_evict = set()
        with cache._lock:
            entries = {
                ck: (set(e.keys), e.worst_score, e.full, e.qvec)
                for ck, e in cache._entries.items()
            }
        for ck, (keys, worst, full, qv) in entries.items():
            if keys & changed:
                expected_evict.add(ck)
                continue
            for _ukey, uvec in upserts:
                if not full:
                    expected_evict.add(ck)
                    break
                s = float(np.dot(qv, _norm(uvec)))
                if s >= worst - 1e-6 * max(1.0, abs(worst)):
                    expected_evict.add(ck)
                    break
        before = set(cache.entry_keys())
        if n_shards > 1:
            per_shard: dict[int, list] = {}
            for key, d, v in ops:
                per_shard.setdefault(key % n_shards, []).append(
                    (key, d, (v, None))
                )
            batches = [_batch(rows) for rows in per_shard.values()]
        else:
            batches = [_batch([(key, d, (v, None)) for key, d, v in ops])]
        cache.ingest(tick, batches)
        after = set(cache.entry_keys())
        # eviction is EXACT: precisely the covered entries left, no
        # full flush on an ordinary tick
        assert before - after == expected_evict
        assert after == before - expected_evict
        survivors_seen += len(after)
        # every survivor still equals a fresh replica answer
        for tenant, qvec, k in queries:
            hit = cache.lookup(tenant, _body(qvec, k), None)
            if hit is None:
                continue
            _status, payload, headers = hit
            assert headers[CACHE_HEADER] == "hit"
            got = json.loads(payload)["matches"]
            want = _fresh_answer(corpus, qvec, k)["matches"]
            assert [m[0] for m in got] == [m[0] for m in want]
            np.testing.assert_allclose(
                [m[1] for m in got],
                [m[1] for m in want],
                rtol=1e-5,
                atol=1e-6,
            )
        store_all(tick)
    assert survivors_seen > 0, "every tick flushed the whole cache"


# ---------------------------------------------------------------------------
# keying + request path units


def test_fingerprint_canonicalizes_key_order():
    a = fingerprint(b'{"query": "x", "k": 3}')
    b = fingerprint(b'{"k": 3, "query": "x"}')
    assert a is not None and b is not None
    assert a[0] == b[0]
    assert fingerprint(b'{"k": 4, "query": "x"}')[0] != a[0]


def test_fingerprint_rejects_non_object_bodies():
    assert fingerprint(b"not json") is None
    assert fingerprint(b"[1,2,3]") is None
    assert fingerprint(b'"str"') is None
    # empty body canonicalizes to the empty query object
    assert fingerprint(b"")[0] == fingerprint(b"{}")[0]


def test_store_lookup_roundtrip_and_isolation():
    rng = np.random.default_rng(1)
    corpus = {k: rng.standard_normal(4) for k in range(6)}
    cache = ResultCache(capacity=16, dim=4, ttl_ms=1e9)
    q = rng.standard_normal(4)
    body, payload, ok = _store(cache, "tenant-a", corpus, q, 3, tick=5)
    assert ok
    hit = cache.lookup("tenant-a", body, None)
    assert hit is not None
    status, got, headers = hit
    assert status == 200 and got == payload
    assert headers[CACHE_HEADER] == "hit"
    # TTL mode: the entry's stored tick + its age are the freshness
    assert headers["x-pathway-applied-tick"] == "5"
    assert float(headers["x-pathway-staleness-seconds"]) >= 0.0
    # tenant isolation: another tenant NEVER shares an entry
    assert cache.lookup("tenant-b", body, None) is None
    # k and the staleness bound are part of the key
    assert cache.lookup("tenant-a", _body(q, 5), None) is None
    assert cache.lookup("tenant-a", body, 1000.0) is None


def test_ttl_mode_expires_entries():
    rng = np.random.default_rng(2)
    corpus = {k: rng.standard_normal(4) for k in range(4)}
    cache = ResultCache(capacity=4, dim=4, ttl_ms=30.0)
    body, _payload, ok = _store(cache, "t", corpus, rng.standard_normal(4), 2)
    assert ok
    assert cache.lookup("t", body, None) is not None
    time.sleep(0.06)
    assert cache.lookup("t", body, None) is None


def test_degraded_and_malformed_responses_never_cached():
    cache = ResultCache(capacity=4, dim=4, ttl_ms=1e9)
    body = _body(np.ones(4), 3)
    good = json.dumps({"matches": [[1, 0.5]]}).encode()
    assert not cache.store("t", body, None, 503, good, {})
    assert not cache.store(
        "t", body, None, 200, good, {"x-pathway-stale": "1"}
    )
    assert not cache.store("t", body, None, 200, b"not json", {})
    assert not cache.store(
        "t", body, None, 200, json.dumps({"error": "x"}).encode(), {}
    )
    assert len(cache) == 0


def test_non_object_json_payload_never_cached_or_crashes():
    # a 200 whose JSON body is not an object (custom responder
    # returning a bare list/string) must pass through uncached — not
    # blow up the router handler with AttributeError
    cache = ResultCache(capacity=4, dim=4, ttl_ms=1e9)
    body = _body(np.ones(4), 3)
    assert not cache.store("t", body, None, 200, b"[1, 2, 3]", {})
    assert not cache.store("t", body, None, 200, b'"ok"', {})
    assert not cache.store("t", body, None, 200, b"42", {})
    assert len(cache) == 0


def test_non_numeric_k_bypasses_cache_not_crashes():
    # a malformed k must reach the replica (whose structured error
    # beats a router-side ValueError), never crash lookup/store
    cache = ResultCache(capacity=4, dim=4, ttl_ms=1e9)
    good = json.dumps({"matches": [[1, 0.5]]}).encode()
    for bad_k in ("abc", None, [3], -1, 0):
        body = json.dumps({"vec": [1.0, 0, 0, 0], "k": bad_k}).encode()
        assert cache.lookup("t", body, None) is None
        assert not cache.store("t", body, None, 200, good, {})
    assert len(cache) == 0


def test_cache_key_includes_route_path():
    # same tenant + identical body POSTed to a different route must
    # never hit another route's cached answer
    rng = np.random.default_rng(7)
    corpus = {k: rng.standard_normal(4) for k in range(6)}
    cache = ResultCache(capacity=8, dim=4, ttl_ms=1e9)
    q = rng.standard_normal(4)
    body = _body(q, 2)
    payload = json.dumps(_fresh_answer(corpus, q, 2)).encode()
    assert cache.store(
        "t", body, None, 200, payload, {}, path="/query"
    )
    assert cache.lookup("t", body, None, path="/other") is None
    hit = cache.lookup("t", body, None, path="/query")
    assert hit is not None and hit[1] == payload


def test_lru_bound_evicts_oldest():
    rng = np.random.default_rng(3)
    corpus = {k: rng.standard_normal(4) for k in range(8)}
    cache = ResultCache(capacity=2, dim=4, ttl_ms=1e9)
    bodies = []
    for i in range(3):
        body, _p, ok = _store(cache, "t", corpus, rng.standard_normal(4), 2)
        assert ok
        bodies.append(body)
    assert len(cache) == 2
    assert cache.lookup("t", bodies[0], None) is None
    assert cache.lookup("t", bodies[2], None) is not None


# ---------------------------------------------------------------------------
# targeted invalidation units


def test_delete_evicts_only_containing_entries():
    """A deletion evicts exactly the entries whose result set contains
    the key — removing a non-member only removes competition BELOW the
    k-th match, so disjoint entries survive untouched (the no-full-
    flush guarantee in its smallest form)."""
    e1 = np.eye(4)[0]
    e2 = np.eye(4)[1]
    corpus = {1: e1, 2: e1 * 0.9, 3: e2, 4: e2 * 0.9}
    cache = ResultCache(capacity=8, dim=4, ttl_ms=1e9)
    b1, _p, _ = _store(cache, "t", corpus, e1, 2)  # result set {1, 2}
    b2, _p, _ = _store(cache, "t", corpus, e2, 2)  # result set {3, 4}
    cache.ingest(1, [_batch([(1, -1, (None, None))])])
    assert cache.lookup("t", b1, None) is None
    assert cache.lookup("t", b2, None) is not None


def test_upsert_score_test_spares_provably_unaffected_entries():
    e1 = np.eye(4)[0]
    e2 = np.eye(4)[1]
    corpus = {1: e1, 2: e1 * 0.9, 3: e2, 4: e2 * 0.9}
    cache = ResultCache(capacity=8, dim=4, ttl_ms=1e9)
    b1, _p, _ = _store(cache, "t", corpus, e1, 2)
    b2, _p, _ = _store(cache, "t", corpus, e2, 2)
    # a new doc orthogonal to q1 but aligned with q2: scores 0 against
    # entry 1 (below its worst kept 0.9 -> survives) and 1.0 against
    # entry 2 (would enter its top-k -> evicted)
    new = np.eye(4)[1].astype(np.float32)
    cache.ingest(1, [_batch([(99, 1, (new, None))])])
    assert cache.lookup("t", b1, None) is not None
    assert cache.lookup("t", b2, None) is None


def test_underfilled_entry_evicts_on_any_upsert():
    e1 = np.eye(4)[0]
    corpus = {1: e1}
    cache = ResultCache(capacity=8, dim=4, ttl_ms=1e9)
    body, _p, _ = _store(cache, "t", corpus, e1, 5)  # 1 match < k=5
    far = (-np.eye(4)[0]).astype(np.float32)  # scores -1 against q
    cache.ingest(1, [_batch([(99, 1, (far, None))])])
    assert cache.lookup("t", body, None) is None


def test_unscoreable_metric_evicts_on_any_upsert():
    e1 = np.eye(4)[0]
    corpus = {1: e1, 2: e1 * 0.9}
    cache = ResultCache(capacity=8, dim=4, metric="l2", ttl_ms=1e9)
    body, _p, ok = _store(cache, "t", corpus, e1, 2)
    assert ok
    far = (-np.eye(4)[0]).astype(np.float32)
    cache.ingest(1, [_batch([(99, 1, (far, None))])])
    assert cache.lookup("t", body, None) is None


def test_query_text_entries_are_scoreable():
    """``query`` text reads re-derive the vector via the deterministic
    text_vector, so the score test applies to them too."""
    from pathway_tpu.serving.replica import text_vector

    dim = 16
    qtext = "hello world"
    qv = text_vector(qtext, dim)
    corpus = {1: qv, 2: qv * 0.9}
    cache = ResultCache(capacity=8, dim=dim, ttl_ms=1e9)
    body = json.dumps({"query": qtext, "k": 2}).encode()
    payload = json.dumps(_fresh_answer(corpus, qv, 2)).encode()
    assert cache.store("t", body, None, 200, payload, {})
    # orthogonal-ish doc scoring far below the worst kept match
    rng = np.random.default_rng(9)
    far = rng.standard_normal(dim).astype(np.float32)
    far -= qv * float(np.dot(_norm(far), _norm(qv)))  # de-correlate
    cache.ingest(1, [_batch([(99, 1, (far, None))])])
    assert cache.lookup("t", body, None) is not None


# ---------------------------------------------------------------------------
# freshness contract with an invalidation stream


def test_lag_bypasses_cache():
    cache = ResultCache(capacity=4, dim=4, max_lag_ms=100.0, ttl_ms=1e9)
    cache._client = _FakeStream(staleness=0.0)
    rng = np.random.default_rng(4)
    corpus = {k: rng.standard_normal(4) for k in range(4)}
    body, _p, ok = _store(cache, "t", corpus, rng.standard_normal(4), 2)
    assert ok
    assert cache.lookup("t", body, None) is not None
    # the invalidation feed lags past the bound: BYPASS, never a
    # silently-stale hit
    cache._client.staleness = 0.5
    assert cache.lookup("t", body, None) is None
    # a tighter per-request bound bypasses even a within-bound lag
    cache._client.staleness = 0.05
    assert cache.lookup("t", body, 10.0) is None
    assert cache.lookup("t", body, None) is not None
    # disconnected stream (no staleness clock) bypasses too
    cache._client.staleness = None
    assert cache.lookup("t", body, None) is None


def test_hit_headers_carry_stream_freshness():
    cache = ResultCache(capacity=4, dim=4, ttl_ms=1e9)
    cache._client = _FakeStream(staleness=0.25, applied_tick=12)
    rng = np.random.default_rng(5)
    corpus = {k: rng.standard_normal(4) for k in range(4)}
    body, _p, ok = _store(cache, "t", corpus, rng.standard_normal(4), 2, tick=12)
    assert ok
    cache.max_lag_s = 10.0
    hit = cache.lookup("t", body, None)
    assert hit is not None
    headers = hit[2]
    assert headers[CACHE_HEADER] == "hit"
    assert headers["x-pathway-applied-tick"] == "12"
    assert headers["x-pathway-staleness-seconds"] == "0.250"


def test_store_ordering_guard_rejects_outrun_answers():
    """If the invalidation stream has advanced PAST the answering
    replica's applied tick, a delta the cache already processed could
    never evict the entry — the store must be skipped."""
    cache = ResultCache(capacity=4, dim=4, ttl_ms=1e9)
    cache._client = _FakeStream(applied_tick=10)
    rng = np.random.default_rng(6)
    corpus = {k: rng.standard_normal(4) for k in range(4)}
    q = rng.standard_normal(4)
    _b, _p, ok = _store(cache, "t", corpus, q, 2, tick=5)
    assert not ok
    # a replica answer with no applied-tick header is never cacheable
    # behind a stream (its position is unknowable)
    body = _body(q, 2)
    payload = json.dumps(_fresh_answer(corpus, q, 2)).encode()
    assert not cache.store("t", body, None, 200, payload, {})
    _b, _p, ok = _store(cache, "t", corpus, q, 2, tick=10)
    assert ok


def test_incarnation_bump_flushes_wholesale():
    cache = ResultCache(capacity=8, dim=4, ttl_ms=1e9)
    fake = _FakeStream(incarnation=0)
    cache._client = fake
    rng = np.random.default_rng(7)
    corpus = {k: rng.standard_normal(4) for k in range(4)}
    _store(cache, "t", corpus, rng.standard_normal(4), 2, tick=0)
    cache.ingest(1, [])  # adopts incarnation 0, no flush
    assert len(cache) == 1
    # writer takeover: the new incarnation's history may not extend
    # the old one's — nothing cached is trustworthy
    fake.writer_incarnation = 1
    cache.ingest(2, [])
    assert len(cache) == 0


def test_resync_flushes_and_resubscribes_from_newest():
    cache = ResultCache(capacity=8, dim=4, ttl_ms=1e9)
    fake = _FakeStream(applied_tick=0)
    fake.newest_known = 7
    cache._client = fake
    rng = np.random.default_rng(8)
    corpus = {k: rng.standard_normal(4) for k in range(4)}
    _store(cache, "t", corpus, rng.standard_normal(4), 2, tick=0)
    assert cache._on_resync() == 7
    assert len(cache) == 0


# ---------------------------------------------------------------------------
# live delta stream end-to-end (unsharded AND sharded writers)


@pytest.mark.parametrize("n_shards", [1, 2])
def test_attach_stream_evicts_from_live_writer(n_shards):
    """The cache's observer subscription passes the sharded writer's
    torn-map guard (negative observer id = full-corpus stream) and a
    published delta evicts the covering entry on every plane shape."""
    from pathway_tpu.parallel.replicate import DeltaStreamServer

    srv = DeltaStreamServer(0, n_shards=n_shards)
    cache = ResultCache(capacity=8, dim=4, max_lag_ms=60_000.0)
    try:
        cache.attach_stream("127.0.0.1", srv.port)
        e1 = np.eye(4)[0].astype(np.float32)
        rows = [(1, 1, (e1, None)), (2, 1, (e1 * 0.9, None))]
        srv.publish(1, [_batch(rows)])
        assert _wait(lambda: cache.applied_tick >= 1)
        corpus = {1: e1, 2: e1 * 0.9}
        body, _p, ok = _store(
            cache, "t", corpus, e1, 2, tick=cache.applied_tick
        )
        assert ok
        hit = cache.lookup("t", body, None)
        assert hit is not None
        assert hit[2][CACHE_HEADER] == "hit"
        # key 1 sits in the result set: its deletion must evict, on the
        # sharded plane too (the observer receives EVERY shard's keys)
        srv.publish(2, [_batch([(1, -1, (None, None))])])
        assert _wait(lambda: len(cache) == 0)
        assert cache.lookup("t", body, None) is None
    finally:
        cache.close()
        srv.close()


# ---------------------------------------------------------------------------
# router end-to-end: hit = ZERO replica hops, delta evicts, miss refreshes


class _ToyVecIndex:
    """Brute-force vector index with the deterministic (score desc,
    key asc) tie-break the serving plane's merge uses."""

    def __init__(self):
        self.d: dict[int, np.ndarray] = {}

    def keys(self):
        return list(self.d.keys())

    def upsert(self, key, data, meta):
        self.d[int(key)] = np.asarray(data, dtype=np.float32)

    def remove(self, key):
        self.d.pop(int(key), None)

    def search(self, triples):
        out = []
        for q, k, _f in triples:
            qv = np.asarray(q, dtype=np.float32)
            scored = [
                (key, float(qv @ vec)) for key, vec in self.d.items()
            ]
            scored.sort(key=lambda m: (-m[1], m[0]))
            out.append(tuple(scored[: int(k)]))
        return out


def test_router_cache_end_to_end_zero_replica_hops():
    """Through the real writer→replica→router path: the first read
    pays a replica hop and primes the cache, the repeat is answered
    with ``x-pathway-cache: hit`` and ZERO replica hops, a published
    delta evicts exactly the covering entry, and the next read pays
    one hop for the FRESH answer."""
    import requests

    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer
    from pathway_tpu.serving.router import FailoverRouter

    srv = DeltaStreamServer(0)
    hops = [0]

    def responder(server, values):
        hops[0] += 1
        q = np.asarray(values["vec"], dtype=np.float32)
        res = server.search([(q, int(values.get("k", 3)), None)])[0]
        return {"matches": [[int(k), float(s)] for k, s in res]}

    rep = ReplicaServer(
        replica_id=0,
        index_factory=_ToyVecIndex,
        writer_port=srv.port,
        responder=responder,
    ).start()
    cache = ResultCache(capacity=16, dim=4, metric="dot")
    cache.attach_stream("127.0.0.1", srv.port)
    router = FailoverRouter(
        [f"http://127.0.0.1:{rep.http_port}"],
        health_interval_ms=100,
        cache=cache,
    ).start()
    try:
        e1 = np.eye(4)[0].astype(np.float32)
        srv.publish(
            0, [_batch([(1, 1, (e1, None)), (2, 1, (e1 * 0.5, None))])]
        )
        assert _wait(lambda: rep.ready and cache.applied_tick >= 0)
        url = f"http://127.0.0.1:{router.port}/query"
        body = {"vec": [1.0, 0.0, 0.0, 0.0], "k": 2}
        hdrs = {"x-pathway-tenant": "hot"}
        # the router's health loop needs a poll or two to admit the
        # fresh replica before reads stop shedding 503
        assert _wait(
            lambda: requests.post(
                url, json=body, headers=hdrs, timeout=10
            ).status_code
            == 200
        )
        cache.flush("test-reset")  # the admission probe primed it
        hops[0] = 0
        r1 = requests.post(url, json=body, headers=hdrs, timeout=10)
        assert r1.status_code == 200
        assert r1.headers.get("x-pathway-cache") != "hit"
        hops_after_prime = hops[0]
        assert hops_after_prime >= 1
        r2 = requests.post(url, json=body, headers=hdrs, timeout=10)
        assert r2.status_code == 200
        assert r2.headers.get("x-pathway-cache") == "hit"
        assert r2.headers.get("x-pathway-applied-tick") == "0"
        assert float(r2.headers["x-pathway-staleness-seconds"]) < 60.0
        assert r2.json() == r1.json()
        assert hops[0] == hops_after_prime  # ZERO replica hops on the hit
        # another tenant never shares the entry: its read pays a hop
        r3 = requests.post(
            url, json=body, headers={"x-pathway-tenant": "other"}, timeout=10
        )
        assert r3.status_code == 200
        assert r3.headers.get("x-pathway-cache") != "hit"
        # a delta naming result-set key 1 evicts the entry; the next
        # read is answered FRESH by the replica (key 1 gone)
        srv.publish(1, [_batch([(1, -1, (None, None))])])
        assert _wait(lambda: len(cache) == 0)
        assert _wait(lambda: rep.applied_tick >= 1)
        r4 = requests.post(url, json=body, headers=hdrs, timeout=10)
        assert r4.status_code == 200
        assert r4.headers.get("x-pathway-cache") != "hit"
        assert [m[0] for m in r4.json()["matches"]] == [2]
    finally:
        router.stop()
        rep.stop()
        srv.close()


# ---------------------------------------------------------------------------
# escape hatches


def test_cache_from_env_escape_hatch(monkeypatch):
    assert not cache_enabled_via_env()
    assert cache_from_env() is None
    monkeypatch.setenv("PATHWAY_ROUTER_CACHE", "1")
    c = cache_from_env()
    assert c is not None and c._client is None
    monkeypatch.setenv("PATHWAY_ROUTER_CACHE_WRITER", "not-a-hostport")
    with pytest.raises(ValueError):
        cache_from_env()


def test_router_builds_no_cache_by_default(monkeypatch):
    monkeypatch.delenv("PATHWAY_ROUTER_CACHE", raising=False)
    from pathway_tpu.serving.router import FailoverRouter

    r = FailoverRouter(["http://127.0.0.1:9"])
    assert r.cache is None


# ---------------------------------------------------------------------------
# sublinear upsert invalidation (the worst-kept-score bound index)


def _reference_evictions(snapshot, changed, dvecs):
    """The pre-index O(entries) scan — the oracle the bound index must
    match EXACTLY (same eviction set, same precedence of reasons)."""
    from pathway_tpu.serving.result_cache import _SCORE_EPS

    evict = {}
    for ck, keys, worst, full, scoreable, qvec in snapshot:
        if keys & changed:
            evict[ck] = "delta_contains"
            continue
        for dvec in dvecs:
            if not full:
                evict[ck] = "delta_notfull"
                break
            if not scoreable or dvec is None:
                evict[ck] = "delta_enters"
                break
            s = float(np.dot(qvec, dvec))
            slack = _SCORE_EPS * max(1.0, abs(worst))
            if s >= worst - slack:
                evict[ck] = "delta_enters"
                break
    return evict


def test_bound_index_eviction_equality_property():
    """ROADMAP Tenant-QoS follow-up (b): the sublinear bound-index
    path evicts EXACTLY the set the old full-scan path did, over
    randomized corpora, entry shapes (full / under-filled / vectorless
    upserts) and mixed delete+upsert ticks."""
    rng = np.random.default_rng(42)
    dim = 8
    for trial in range(25):
        cache = ResultCache(capacity=256, dim=dim, metric="cosine")
        corpus = {
            i: rng.normal(size=dim).astype(np.float32)
            for i in range(20)
        }
        # a mixed population of entries: varying k (some under-filled
        # because k > corpus), several tenants
        bodies = {}
        for e in range(rng.integers(3, 12)):
            qvec = rng.normal(size=dim).astype(np.float32)
            k = int(rng.integers(1, 26))  # k>20 => under-filled
            tenant = f"t{rng.integers(0, 3)}"
            body, _payload, ok = _store(
                cache, tenant, corpus, qvec, k, tick=0
            )
            if ok:
                bodies[(tenant, body)] = True
        # one random tick: deletes + upserts (some without vectors)
        rows = []
        for key in rng.choice(20, size=rng.integers(1, 4), replace=False):
            if rng.random() < 0.4:
                rows.append((int(key), -1, (None, None)))
            elif rng.random() < 0.15:
                rows.append((int(key), +1, (None, None)))  # vectorless
            else:
                vec = rng.normal(size=dim).astype(np.float32)
                # occasionally a LONG vector (tests the norm bound) or
                # a tiny one (provably below every worst score)
                scalep = rng.random()
                if scalep < 0.25:
                    vec = vec * 10.0
                elif scalep < 0.5:
                    vec = vec * 1e-3
                rows.append((int(key), +1, (vec, None)))
        changed = {int(k) for k, _d, _v in rows}
        dvecs = [
            cache._prep_vec(v[0]) if d > 0 and v[0] is not None else None
            for _k, d, v in rows
            if d > 0
        ]
        with cache._lock:
            snapshot = [
                (ck, e.keys, e.worst_score, e.full, e.scoreable, e.qvec)
                for ck, e in cache._entries.items()
            ]
        expected = set(_reference_evictions(snapshot, changed, dvecs))
        before = set(cache.entry_keys())
        cache.ingest(1, [_batch(rows)])
        after = set(cache.entry_keys())
        assert before - after == expected, (
            f"trial {trial}: bound-index evictions diverge from the "
            f"full-scan oracle (extra={before - after - expected}, "
            f"missed={expected - (before - after)})"
        )


def test_bound_index_maintained_across_store_drop_flush():
    """The sorted bound index stays in lockstep with the entry map
    through store, replace, LRU eviction, delta eviction and flush."""
    rng = np.random.default_rng(7)
    dim = 8
    cache = ResultCache(capacity=4, dim=dim, metric="cosine")
    corpus = {i: rng.normal(size=dim).astype(np.float32) for i in range(6)}

    def check():
        assert len(cache._bound_index) == len(cache._entries)
        bounds = [b for b, _s, _ck in cache._bound_index]
        assert bounds == sorted(bounds)
        assert {ck for _b, _s, ck in cache._bound_index} == set(
            cache._entries
        )

    for i in range(8):  # capacity 4: LRU evictions happen
        _store(cache, "t", corpus, rng.normal(size=dim), 3, tick=0)
        check()
    # replace an existing entry (same body)
    qvec = rng.normal(size=dim).astype(np.float32)
    _store(cache, "t", corpus, qvec, 3, tick=0)
    _store(cache, "t", corpus, qvec, 3, tick=0)
    check()
    # delta eviction
    cache.ingest(1, [_batch([(0, -1, (None, None))])])
    check()
    cache.flush("test")
    check()
    assert len(cache) == 0


def test_bound_index_excludes_provably_safe_entries():
    """The point of the index: an upsert whose doc norm sits BELOW an
    entry's worst-kept-score bound never even becomes a scoring
    candidate (and provably survives).  Uses the DOT metric, where doc
    norms carry real signal — under cosine both sides are normalized,
    so the Cauchy-Schwarz bound degenerates to ~1 and the index simply
    selects (nearly) everything, which the equality property covers."""
    import bisect

    rng = np.random.default_rng(11)
    dim = 8
    cache = ResultCache(capacity=64, dim=dim, metric="dot")
    qvec = _norm(rng.normal(size=dim)).astype(np.float32)  # |q| = 1

    def put(key_lo, worst):
        body = _body(qvec, 2)
        payload = json.dumps(
            {"matches": [[key_lo, worst + 1.0], [key_lo + 1, worst]]}
        ).encode()
        assert cache.store(
            "t", body[:-1] + f',"tag":{key_lo}}}'.encode(), None, 200,
            payload, {"x-pathway-applied-tick": "0"},
        )

    put(0, 4.0)  # high worst: bound ~ 4.0
    put(10, 0.01)  # low worst: always a candidate
    assert len(cache) == 2
    # an upserted doc of norm 0.5 can score at most 0.5 against a unit
    # query: the worst=4.0 entry is excluded WITHOUT scoring
    d = (_norm(rng.normal(size=dim)) * 0.5).astype(np.float32)
    hi = bisect.bisect_right(cache._bound_index, (0.5, 1 << 62, ()))
    covered = {ck for _b, _s, ck in cache._bound_index[:hi]}
    assert len(covered) == 1  # only the low-bound entry needs scoring
    cache.ingest(1, [_batch([(99, +1, (d, None))])])
    # the high-bound entry survived; the low-bound one was score-tested
    # (dot vs 0.01 - slack decides its fate — either way, the safe one
    # is still here)
    remaining = cache.entry_keys()
    assert any("0" in str(ck) for ck in remaining) or len(cache) >= 1
    with cache._lock:
        worsts = [e.worst_score for e in cache._entries.values()]
    assert 4.0 in worsts
