"""Port of the reference window-join suite (reference:
python/pathway/tests/temporal/test_window_joins.py - 14 functions).
Mechanical port: package/imports adapted, fixtures and assertions kept
identical so outputs are checked against the reference's expected data."""

import datetime

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.dtype import DATE_TIME_NAIVE, DATE_TIME_UTC
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
)


@pytest.mark.parametrize(
    "join_type",
    [pw.JoinMode.INNER, pw.JoinMode.LEFT, pw.JoinMode.RIGHT, pw.JoinMode.OUTER],
)
@pytest.mark.parametrize(
    "w",
    [
        pw.temporal.tumbling(1),
        pw.temporal.tumbling(2),
        pw.temporal.sliding(1, 2),
        pw.temporal.sliding(2, 1),
    ],
)
def test_window_join_time_only(join_type: pw.JoinMode, w: pw.temporal.Window) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -2
    1 | 2 | 1
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    5 | 6 | 13
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    3 | 4 | 7
    4 | 5 | 14
    """
    )

    if w == pw.temporal.tumbling(1):
        expected = T(
            """
          | a | b
        0 | 3 | 1
        1 | 5 | 4
          """
        )
        left = T(
            """
          | a | b
        3 | 1 |
        4 | 2 |
        5 | 4 |
        6 | 6 |
            """
        )
        right = T(
            """
          | a | b
        7 |   | 2
        8 |   | 3
        9 |   | 5
            """
        )
    elif w == pw.temporal.tumbling(2):
        expected = T(
            """
          | a | b
        0 | 3 | 1
        1 | 4 | 1
        2 | 5 | 3
        3 | 5 | 4
        """
        )
        left = T(
            """
          | a | b
        4 | 1 |
        5 | 2 |
        6 | 6 |
            """
        )
        right = T(
            """
          | a | b
        7 |   | 2
        8 |   | 5
            """
        )
    elif w == pw.temporal.sliding(1, 2):
        expected = T(
            """
           | a | b
        0  | 2 | 1
        1  | 3 | 1
        2  | 3 | 1
        3  | 4 | 1
        4  | 5 | 3
        5  | 5 | 4
        6  | 5 | 4
        7  | 6 | 5
        """
        )
        left = T(
            """
          | a | b
        6 | 1 |
        7 | 1 |
        8 | 2 |
        9 | 4 |
       10 | 6 |
            """
        )
        right = T(
            """
          | a | b
        0 |   | 2
        1 |   | 2
        2 |   | 3
        3 |   | 5
            """
        )
    elif w == pw.temporal.sliding(2, 1):
        expected = T(
            """
          | a | b
        0 | 3 | 1
          """
        )
        left = T(
            """
          | a | b
        3 | 1 |
            """
        )
        right = T(
            """
          | a | b
        9 |   | 3
       11 |   | 5
            """
        )
    else:
        raise ValueError("Inappropriate window provided")

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    res = {
        pw.JoinMode.INNER: t1.window_join_inner,
        pw.JoinMode.LEFT: t1.window_join_left,
        pw.JoinMode.RIGHT: t1.window_join_right,
        pw.JoinMode.OUTER: t1.window_join_outer,
    }[join_type](t2, t1.t, t2.t, w).select(t1.a, t2.b)

    assert_table_equality_wo_index(res, expected)

    res2 = t1.window_join(t2, t1.t, t2.t, w, how=join_type).select(t1.a, t2.b)
    assert_table_equality(res, res2)


@pytest.mark.parametrize(
    "join_type",
    [pw.JoinMode.INNER, pw.JoinMode.LEFT, pw.JoinMode.RIGHT, pw.JoinMode.OUTER],
)
def test_window_join_sharded_with_smart_cols(join_type: pw.JoinMode) -> None:
    t1 = T(
        """
      | a | t  | k
    0 | 1 | -2 | 1
    1 | 2 | 1  | 1
    2 | 3 | 2  | 1
    3 | 4 | 3  | 1
    4 | 5 | 7  | 1
    5 | 6 | 13 | 1
    6 | 1 | 2  | 2
    7 | 4 | 4  | 3
    """
    )

    t2 = T(
        """
      | b | t  | k
    0 | 1 | 2  | 1
    1 | 2 | 5  | 1
    2 | 3 | 6  | 1
    3 | 4 | 7  | 1
    4 | 5 | 14 | 1
    5 | 1 | 3  | 2
    6 | 3 | 3  | 4
    """
    )

    expected = T(
        """
      | a | b | k
    0 | 3 | 1 | 1
    1 | 4 | 1 | 1
    2 | 5 | 3 | 1
    3 | 5 | 4 | 1
    4 | 1 | 1 | 2
    """
    )
    left = T(
        """
      | a | b | k
    4 | 1 |   | 1
    5 | 2 |   | 1
    6 | 6 |   | 1
    7 | 4 |   | 3
        """
    )
    right = T(
        """
      | a | b | k
    7 |   | 2 | 1
    8 |   | 5 | 1
    9 |   | 3 | 4
        """
    )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    w = pw.temporal.tumbling(2)

    join_function = {
        pw.JoinMode.INNER: t1.window_join_inner,
        pw.JoinMode.LEFT: t1.window_join_left,
        pw.JoinMode.RIGHT: t1.window_join_right,
        pw.JoinMode.OUTER: t1.window_join_outer,
    }[join_type]
    res = (
        join_function(t2, pw.left.t, pw.right.t, w, t1.k == pw.right.k)
        .select(pw.left.a, pw.right.b, pw.this.k)
        .update_types(k=int)
    )

    assert_table_equality_wo_index(res, expected)


def test_window_join_sharded_by_multiple_cols() -> None:
    t1 = T(
        """
      | a | t  | k1 | k2
    0 | 1 | -2 |  1 |  1
    1 | 2 | 1  |  1 |  1
    2 | 3 | 2  |  1 |  1
    3 | 4 | 3  |  1 |  1
    4 | 5 | 7  |  1 |  1
    5 | 6 | 13 |  1 |  1
    6 | 1 | 2  |  2 |  1
    7 | 4 | 4  |  1 |  2
    """
    )

    t2 = T(
        """
      | b | t  | k1 | k2
    0 | 1 | 2  |  1 |  1
    1 | 2 | 5  |  1 |  1
    2 | 3 | 6  |  1 |  1
    3 | 4 | 7  |  1 |  1
    4 | 5 | 14 |  1 |  1
    5 | 1 | 3  |  2 |  1
    6 | 3 | 3  |  2 |  2
    """
    )

    expected = T(
        """
       | a | b | k
     0 | 3 | 1 | 1
     1 | 4 | 1 | 1
     2 | 5 | 3 | 1
     3 | 5 | 4 | 1
     4 | 1 | 1 | 2
     5 | 1 |   | 1
     6 | 2 |   | 1
     7 | 6 |   | 1
     8 | 4 |   | 1
     9 |   | 2 | 1
    10 |   | 5 | 1
    11 |   | 3 | 2
        """
    )

    w = pw.temporal.tumbling(2)
    res = t1.window_join_outer(
        t2, pw.left.t, pw.right.t, w, t1.k1 == t2.k1, t1.k2 == t2.k2
    )
    res = res.select(pw.left.a, pw.right.b, k=pw.declare_type(int, pw.this.k1))

    assert_table_equality_wo_index(res, expected)


def test_window_join_with_time_expressions() -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -5
    1 | 2 | -2
    2 | 3 | -1
    3 | 4 | 0
    4 | 5 | 4
    5 | 6 | 10
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 3
    1 | 2 | 6
    2 | 3 | 7
    3 | 4 | 8
    4 | 5 | 15
    """
    )

    expected = T(
        """
      | a | b
    0 | 3 | 1
    1 | 4 | 1
    2 | 5 | 3
    3 | 5 | 4
    """
    )

    res = t1.window_join_inner(
        t2, 4 * (pw.left.t + 3) // 2, 6 * (pw.right.t - 1) // 3, pw.temporal.tumbling(4)
    )
    res = res.select(t1.a, t2.b)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.xfail(reason="Ix and joins do not mix.")
def test_window_left_join_ix() -> None:
    t1 = T(
        """
      | t
    0 | -2
    1 | 1
    2 | 2
    3 | 3
    4 | 7
    5 | 13
    """
    )

    t2 = T(
        """
      | t
    0 | 2
    1 | 5
    2 | 6
    3 | 7
    4 | 14
    5 | 20
    6 | 30
    """
    )
    expected = T(
        """
           | x  | y  | other
        1  | -2 |    | 2
        2  | -2 |    | 2
        3  | 1  |    | 5
        4  | 1  | 2  | 5
        5  | 2  | 2  | 6
        6  | 2  | 2  | 6
        7  | 3  |    | 7
        8  | 3  | 2  | 7
        9  | 7  | 6  | 14
        10 | 7  | 7  | 14
        11 | 7  | 7  | 14
        12 | 13 | 14 | 20
        13 | 13 |    | 20
            """
    )
    join_result = t1.window_join_left(t2, t1.t, t2.t, pw.temporal.sliding(1, 2))
    res = join_result.select(x=t1.t, y=t2.t, other=t2.ix(t1.id).t)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize(
    "join_type",
    [pw.JoinMode.INNER, pw.JoinMode.LEFT, pw.JoinMode.RIGHT, pw.JoinMode.OUTER],
)
@pytest.mark.parametrize("max_difference", [1, 2])
@pytest.mark.parametrize("use_predicate", [True, False])
def test_session_window_join_time_only(
    join_type: pw.JoinMode, max_difference: int, use_predicate: bool
):
    t1 = T(
        """
      | a | t
    0 | 1 | 0
    1 | 2 | 5
    2 | 3 | 10
    3 | 4 | 15
    4 | 5 | 17
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | -3
    1 | 2 | 2
    2 | 3 | 3
    3 | 4 | 6
    4 | 5 | 16
    """
    )
    if max_difference == 1:
        expected = T(
            """
          | a | b
        0 | 2 | 4
        1 | 4 | 5
        2 | 5 | 5
          """
        )
        left = T(
            """
          | a | b
        3 | 1 |
        4 | 3 |
            """
        )
        right = T(
            """
          | a | b
        7 |   | 1
        8 |   | 2
        9 |   | 3
            """
        )
    else:
        expected = T(
            """
          | a | b
        0 | 1 | 2
        1 | 1 | 3
        2 | 1 | 4
        3 | 2 | 2
        4 | 2 | 3
        5 | 2 | 4
        6 | 4 | 5
        7 | 5 | 5
          """
        )
        left = T(
            """
          | a | b
        8 | 3 |
            """
        )
        right = T(
            """
          | a | b
        9 |   | 1
            """
        )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    if use_predicate:
        w = pw.temporal.session(predicate=lambda a, b: abs(a - b) <= max_difference)
    else:
        w = pw.temporal.session(max_gap=max_difference + 1)

    res = {
        pw.JoinMode.INNER: t1.window_join_inner,
        pw.JoinMode.LEFT: t1.window_join_left,
        pw.JoinMode.RIGHT: t1.window_join_right,
        pw.JoinMode.OUTER: t1.window_join_outer,
    }[join_type](t2, t1.t, t2.t, w).select(t1.a, t2.b)

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize(
    "join_type",
    [pw.JoinMode.INNER, pw.JoinMode.LEFT, pw.JoinMode.RIGHT, pw.JoinMode.OUTER],
)
@pytest.mark.parametrize("max_difference", [1, 2])
def test_session_window_join_sharded_with_smart_cols(
    join_type: pw.JoinMode, max_difference: int
) -> None:
    t1 = T(
        """
      | k | t
    1 | 1 | 3
    2 | 1 | 4
    3 | 1 | 6
    4 | 1 | 11
    5 | 2 | 0
    6 | 2 | 5
    7 | 2 | 10
    8 | 2 | 15
    9 | 2 | 17
   10 | 3 | 4
    """
    )
    t2 = T(
        """
      | k | t
    1 | 1 | 0
    2 | 1 | 1
    3 | 1 | 5
    4 | 2 | -3
    5 | 2 | 2
    6 | 2 | 3
    7 | 2 | 6
    8 | 2 | 16
    9 | 4 | 3
    """
    )
    if max_difference == 1:
        expected = T(
            """
          | k | left_t | right_t
        0 | 1 |    3   |    5
        1 | 1 |    4   |    5
        2 | 1 |    6   |    5
        3 | 2 |    5   |    6
        4 | 2 |   15   |   16
        5 | 2 |   17   |   16
          """
        )
        left = T(
            """
          | k | left_t | right_t
        6 | 1 |   11   |
        7 | 2 |    0   |
        8 | 2 |   10   |
        9 | 3 |    4   |
            """
        )
        right = T(
            """
          | k | left_t | right_t
       10 | 1 |        |    0
       11 | 1 |        |    1
       12 | 2 |        |   -3
       13 | 2 |        |    2
       14 | 2 |        |    3
       15 | 4 |        |    3
            """
        )
    else:
        expected = T(
            """
          | k | left_t | right_t
        0 | 1 |    3   |    0
        1 | 1 |    3   |    1
        2 | 1 |    3   |    5
        3 | 1 |    4   |    0
        4 | 1 |    4   |    1
        5 | 1 |    4   |    5
        6 | 1 |    6   |    0
        7 | 1 |    6   |    1
        8 | 1 |    6   |    5
        9 | 2 |    0   |    2
       10 | 2 |    0   |    3
       11 | 2 |    0   |    6
       12 | 2 |    5   |    2
       13 | 2 |    5   |    3
       14 | 2 |    5   |    6
       15 | 2 |   15   |   16
       16 | 2 |   17   |   16
          """
        )
        left = T(
            """
          | k | left_t | right_t
       17 | 1 |   11   |
       18 | 2 |   10   |
       19 | 3 |    4   |
            """
        )
        right = T(
            """
          | k | left_t | right_t
       20 | 2 |        |   -3
       21 | 4 |        |    3
            """
        )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    w = pw.temporal.session(max_gap=max_difference + 1)

    res = {
        pw.JoinMode.INNER: t1.window_join_inner,
        pw.JoinMode.LEFT: t1.window_join_left,
        pw.JoinMode.RIGHT: t1.window_join_right,
        pw.JoinMode.OUTER: t1.window_join_outer,
    }[join_type](t2, t1.t, t2.t, w, pw.left.k == pw.right.k).select(
        k=pw.declare_type(int, pw.coalesce(t1.k, t2.k)),
        left_t=pw.left.t,
        right_t=pw.right.t,
    )

    assert_table_equality_wo_index(res, expected)


def test_session_window_join_sharded_by_multiple_cols() -> None:
    t1 = T(
        """
      | k1 | k2 | t
    1 | 1  |  1 | 3
    2 | 1  |  1 | 4
    3 | 1  |  1 | 6
    4 | 1  |  1 | 11
    5 | 2  |  1 | 0
    6 | 2  |  1 | 5
    7 | 2  |  1 | 10
    8 | 2  |  1 | 15
    9 | 2  |  1 | 17
   10 | 1  |  2 | 2
    """
    )
    t2 = T(
        """
      | k1 | k2 | t
    1 |  1 |  1 | 0
    2 |  1 |  1 | 1
    3 |  1 |  1 | 5
    4 |  2 |  1 | -3
    5 |  2 |  1 | 2
    6 |  2 |  1 | 3
    7 |  2 |  1 | 6
    8 |  2 |  1 | 16
    9 |  2 |  2 | 4
    """
    )
    expected = T(
        """
       | k | left_t | right_t
     0 | 1 |    3   |    5
     1 | 1 |    4   |    5
     2 | 1 |    6   |    5
     3 | 2 |    5   |    6
     4 | 2 |   15   |   16
     5 | 2 |   17   |   16
     6 | 1 |   11   |
     7 | 2 |    0   |
     8 | 2 |   10   |
     9 | 1 |    2   |
    10 | 1 |        |    0
    11 | 1 |        |    1
    12 | 2 |        |   -3
    13 | 2 |        |    2
    14 | 2 |        |    3
    15 | 2 |        |    4
        """
    )
    w = pw.temporal.session(max_gap=2)
    res = t1.window_join_outer(t2, t1.t, t2.t, w, t1.k1 == t2.k1, t1.k2 == t2.k2)
    res = res.select(
        k=pw.declare_type(int, pw.coalesce(t1.k1, t2.k1)), left_t=t1.t, right_t=t2.t
    )
    assert_table_equality_wo_index(res, expected)


def test_window_session_join_with_time_expressions() -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -3
    1 | 2 | 2
    2 | 3 | 7
    3 | 4 | 12
    4 | 5 | 14
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | -2
    1 | 2 | 3
    2 | 3 | 4
    3 | 4 | 7
    4 | 5 | 17
    """
    )

    expected = T(
        """
      | a | b
    0 | 1 | 2
    1 | 1 | 3
    2 | 1 | 4
    3 | 2 | 2
    4 | 2 | 3
    5 | 2 | 4
    6 | 4 | 5
    7 | 5 | 5
        """
    )

    res = t1.window_join_inner(
        t2,
        4 * (pw.left.t + 3) // 2,
        6 * (pw.right.t - 1) // 3,
        pw.temporal.session(max_gap=5),
    )
    res = res.select(t1.a, t2.b)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.xfail(reason="Duplicates not working in sorting")
@pytest.mark.parametrize(
    "join_type",
    [pw.JoinMode.INNER, pw.JoinMode.LEFT, pw.JoinMode.RIGHT, pw.JoinMode.OUTER],
)
def test_session_window_join_with_duplicates(join_type: pw.JoinMode):
    t1 = T(
        """
      | a | t
    0 | 1 | 3
    1 | 2 | 5
    2 | 3 | 10
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | -3
    1 | 2 | 2
    2 | 3 | 3
    3 | 4 | 6
    """
    )

    expected = T(
        """
      | a | b
    0 | 1 | 2
    1 | 1 | 3
    2 | 1 | 4
    3 | 2 | 2
    4 | 2 | 3
    5 | 2 | 4
        """
    )
    left = T(
        """
      | a | b
    6 | 3 |
        """
    )
    right = T(
        """
      | a | b
    7 |   | 1
        """
    )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    w = pw.temporal.session(max_gap=3)  # max_difference = 3-eps

    res = {
        pw.JoinMode.INNER: t1.window_join_inner,
        pw.JoinMode.LEFT: t1.window_join_left,
        pw.JoinMode.RIGHT: t1.window_join_right,
        pw.JoinMode.OUTER: t1.window_join_outer,
    }[join_type](t2, t1.t, t2.t, w).select(t1.a, t2.b)

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize(
    "w",
    [
        pw.temporal.tumbling(0.2),
        pw.temporal.tumbling(0.4),
        pw.temporal.sliding(0.1, 0.3),
        pw.temporal.session(max_gap=0.101),
    ],
)
def test_window_join_float(w: pw.temporal.Window) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -0.2
    1 | 2 | -0.05
    2 | 3 | 0.09
    3 | 4 | 0.61
    4 | 5 | 5.29
    5 | 6 | 5.31
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | -0.1
    1 | 2 | 0.19
    2 | 3 | 0.2
    3 | 4 | 0.3
    4 | 5 | 0.4
    5 | 6 | 5.0
    """
    )

    if w == pw.temporal.tumbling(0.2):
        expected = T(
            """
          | a | b
        0 | 1 | 1
        1 | 2 | 1
        2 | 3 | 2
          """
        )
    elif w == pw.temporal.tumbling(0.4):
        expected = T(
            """
          | a | b
        0 | 1 | 1
        1 | 2 | 1
        2 | 3 | 2
        3 | 3 | 3
        4 | 3 | 4
        5 | 4 | 5
        """
        )
    elif w == pw.temporal.sliding(0.1, 0.3):
        expected = T(
            """
           | a | b
         0 | 1 | 1
         1 | 1 | 1
         2 | 2 | 1
         3 | 2 | 1
         4 | 2 | 1
         5 | 2 | 2
         6 | 3 | 1
         7 | 3 | 1
         8 | 3 | 2
         9 | 3 | 2
        10 | 3 | 3
        11 | 4 | 5
        12 | 5 | 6
          """
        )
    elif w == pw.temporal.session(max_gap=0.101):
        expected = T(
            """
           | a | b
         0 | 1 | 1
         1 | 2 | 1
         2 | 3 | 2
         3 | 3 | 3
         4 | 3 | 4
         5 | 3 | 5
          """
        )
    else:
        raise ValueError("Inappropriate window provided")

    res = t1.window_join_inner(t2, t1.t, t2.t, w).select(t1.a, t2.b)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize(
    "join_mode",
    [pw.JoinMode.INNER, pw.JoinMode.LEFT, pw.JoinMode.RIGHT, pw.JoinMode.OUTER],
)
@pytest.mark.parametrize(
    "left_type,right_type,window,error_str",
    [
        (
            int,
            int,
            pw.temporal.tumbling(duration=datetime.timedelta(days=1)),
            ", window.hop",
        ),
        (
            int,
            DATE_TIME_NAIVE,
            pw.temporal.tumbling(duration=2, origin=1.1),
            ", window.hop, window.origin",
        ),
        (
            int,
            int,
            pw.temporal.sliding(
                hop=datetime.timedelta(days=1), duration=datetime.timedelta(days=2)
            ),
            ", window.hop, window.duration",
        ),
        (DATE_TIME_NAIVE, float, pw.temporal.tumbling(duration=1.2), ", window.hop"),
        (int, DATE_TIME_UTC, pw.temporal.tumbling(duration=1.2), ", window.hop"),
        (float, DATE_TIME_NAIVE, pw.temporal.session(max_gap=2), ", window.max_gap"),
        (DATE_TIME_UTC, int, pw.temporal.session(predicate=lambda a, b: False), ""),
        (
            DATE_TIME_NAIVE,
            DATE_TIME_NAIVE,
            pw.temporal.sliding(hop=2, duration=3.5),
            ", window.hop, window.duration",
        ),
    ],
)
def test_incorrect_args(join_mode, left_type, right_type, window, error_str):
    t1 = pw.Table.empty(a=int, t=left_type)

    t2 = pw.Table.empty(b=int, t=right_type)

    with pytest.raises(
        TypeError,
        match=rf"Arguments \(left_time_expression, right_time_expression{error_str}"
        + r"\) have to be of types .* but are of types .*",
    ):
        {
            pw.JoinMode.INNER: t1.window_join_inner,
            pw.JoinMode.LEFT: t1.window_join_left,
            pw.JoinMode.RIGHT: t1.window_join_right,
            pw.JoinMode.OUTER: t1.window_join_outer,
        }[join_mode](t2, t1.t, t2.t, window).select(t1.a, t2.b)


def test_complicated_windowjoin():
    clickstream_data = T(
        """
     user_id |   session_id  |   timestamp   |   page_url
    0x7f8b4c |   0x64a0c7    |   1686024012  | /home
    0x7f8b4c |   0x64a0c7    |   1686024098  | /products/0x40c391
    0x5eaf7f |   0x22e5b3    |   1686025112  | /products
    0x5eaf7f |   0xf508e6    |   1686025184  | /products/0x04g7d5
    0x6b9d6e |   0x13f6c4    |   1686025647  | /products/0x7a8c5d
    """
    )

    purchase_data = T(
        """
    purchase_id | user_id | timestamp    | product_url
    0x0a1b2c    | 0x7f8b4c| 1686024015   | /products/0x11b87b
    0x0b1a2d    | 0x32ad44| 1686024205   | /products/0x40c391
    0x0c1b3d    | 0x5eaf7f| 1686025115   | /products/0x31d4a2
    0x0d1e3f    | 0x5eaf7f| 1686025190   | /products/0x04g7d5
    0x0d1e3f    | 0x5eaf7f| 1686025240   | /products/0x04g7d5
    0x0e1f4g    | 0x6b9d6e| 1686025650   | /products/0x7a8c5d
    """
    )
    matched_data = (
        purchase_data.window_join_inner(
            clickstream_data,
            purchase_data.timestamp,
            clickstream_data.timestamp,
            pw.temporal.sliding(
                hop=50, duration=100
            ),  # Change to a sliding window with a hop of 5 and duration of 10
            (pw.left.user_id == pw.right.user_id),
            (pw.left.product_url == pw.right.page_url),
        )
        .select(pw.this._pw_window_start, pw.this._pw_window_end, pw.this._pw_window)
        .groupby(pw.this._pw_window)
        .reduce(
            window_start=pw.reducers.unique(pw.this._pw_window_start),
            window_end=pw.reducers.unique(pw.this._pw_window_end),
            count=pw.reducers.count(),
        )
    )
    expected = T(
        """
window_start | window_end | count
1686025100   | 1686025200 | 1
1686025150   | 1686025250 | 2
1686025600   | 1686025700 | 1
        """
    )
    assert_table_equality_wo_index(matched_data, expected)


def test_window_joins_typing_on():
    left_table = pw.Table.empty(timestamp=int, col=int)
    right_table = pw.Table.empty(timestamp=int, col=str)
    with pytest.raises(expected_exception=TypeError):
        left_table.window_join(
            right_table,
            left_table.timestamp,
            right_table.timestamp,
            pw.temporal.sliding(hop=50, duration=100),
            left_table.col == right_table.col,
        )
