"""Port of the reference test_interval_joins_stream.py (reference:
python/pathway/tests/temporal/test_interval_joins_stream.py). Mechanical port: package and
imports adapted, fixtures and assertions kept identical."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import assert_table_equality_wo_index


class TimeInputSchema(pw.Schema):
    t: int


@pytest.mark.parametrize("keep_results", [True, False])
@pytest.mark.parametrize(
    "interval", [pw.temporal.interval(0, 0), pw.temporal.interval(-0.1, 0.1)]
)
def test_forgetting(keep_results: bool, interval: pw.temporal.Interval):
    t1 = pw.debug.table_from_markdown(
        """
        t | __time__
        0 |     2
        1 |     4
        2 |     6
        3 |     8
        4 |    10
        0 |    12
        1 |    14
        2 |    16
        3 |    18
        4 |    20
        """
    )

    t2 = pw.debug.table_from_markdown(
        """
        t | __time__
        0 |     2
        1 |     4
        2 |     6
        3 |     8
        4 |    10
        0 |    12
        1 |    14
        2 |    16
        3 |    18
        4 |    20
        """
    )

    result = t1.interval_join(
        t2,
        t1.t,
        t2.t,
        interval,
        behavior=pw.temporal.common_behavior(0, 2, keep_results=keep_results),
    ).select(left_t=pw.left.t, right_t=pw.right.t)
    if keep_results:
        expected = T(
            """
            left_t | right_t
               0   |    0
               1   |    1
               2   |    2
               3   |    3
               3   |    3
               3   |    3
               3   |    3
               4   |    4
               4   |    4
               4   |    4
               4   |    4
            """
        )
    else:
        expected = T(
            """
            left_t | right_t
               3   |    3
               3   |    3
               3   |    3
               3   |    3
               4   |    4
               4   |    4
               4   |    4
               4   |    4
            """
        )
    assert_table_equality_wo_index(result, expected)


class TimeValueInputSchema(pw.Schema):
    t: int
    v: int


@pytest.mark.parametrize("keep_results", [True, False])
@pytest.mark.parametrize(
    "interval", [pw.temporal.interval(0, 0), pw.temporal.interval(-0.1, 0.1)]
)
def test_forgetting_with_instance(keep_results: bool, interval: pw.temporal.Interval):
    t1 = pw.debug.table_from_markdown(
        """
        t | v | __time__
        0 | 0 |     2
        0 | 1 |     2
        1 | 0 |     4
        1 | 1 |     4
        2 | 0 |     6
        2 | 1 |     6
        3 | 0 |     8
        3 | 1 |     8
        4 | 0 |    10
        4 | 1 |    10
        0 | 0 |    12
        0 | 1 |    12
        1 | 0 |    14
        1 | 1 |    14
        2 | 0 |    16
        2 | 1 |    16
        3 | 0 |    18
        3 | 1 |    18
        4 | 0 |    20
        4 | 1 |    20
        """
    )

    t2 = t1.copy()

    result = t1.interval_join(
        t2,
        t1.t,
        t2.t,
        interval,
        t1.v == t2.v,
        behavior=pw.temporal.common_behavior(0, 2, keep_results=keep_results),
    ).select(v=pw.this.v, left_t=pw.left.t, right_t=pw.right.t)
    if keep_results:
        expected = T(
            """
            v | left_t | right_t
            0 |   0    |    0
            0 |   1    |    1
            0 |   2    |    2
            0 |   3    |    3
            0 |   3    |    3
            0 |   3    |    3
            0 |   3    |    3
            0 |   4    |    4
            0 |   4    |    4
            0 |   4    |    4
            0 |   4    |    4
            1 |   0    |    0
            1 |   1    |    1
            1 |   2    |    2
            1 |   3    |    3
            1 |   3    |    3
            1 |   3    |    3
            1 |   3    |    3
            1 |   4    |    4
            1 |   4    |    4
            1 |   4    |    4
            1 |   4    |    4
            """
        )
    else:
        expected = T(
            """
            v | left_t | right_t
            0 |   3    |    3
            0 |   3    |    3
            0 |   3    |    3
            0 |   3    |    3
            0 |   4    |    4
            0 |   4    |    4
            0 |   4    |    4
            0 |   4    |    4
            1 |   3    |    3
            1 |   3    |    3
            1 |   3    |    3
            1 |   3    |    3
            1 |   4    |    4
            1 |   4    |    4
            1 |   4    |    4
            1 |   4    |    4
            """
        )
    assert_table_equality_wo_index(result, expected)
