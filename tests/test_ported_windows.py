"""Full port of the reference window suite (reference:
python/pathway/tests/temporal/test_windows.py — 25 test functions):
session/sliding/tumbling windows, origins, float and datetime time
columns, behaviors, build-time type validation, intervals_over (incl.
outer and reducer-over-ix variants), and the latest-reducer warning."""

from __future__ import annotations

import datetime
import re
import typing

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.dtype import DATE_TIME_NAIVE, DATE_TIME_UTC
from tests.ref_utils import assert_table_equality_wo_index


def test_session_simple_this():
    t = T(
        """
        instance | t  |  v
        0        |  1 |  10
        0        |  2 |  1
        0        |  4 |  3
        0        |  8 |  2
        0        |  9 |  4
        0        |  10|  8
        1        |  1 |  9
        1        |  2 |  16
    """
    )

    def should_merge(a, b):
        return abs(a - b) <= 1

    gb = t.windowby(
        pw.this.t,
        window=pw.temporal.session(predicate=should_merge),
        instance=pw.this.instance,
    )
    result = gb.reduce(
        pw.this.instance,
        min_t=pw.reducers.min(pw.this.t),
        max_v=pw.reducers.max(pw.this.v),
    )
    res = T(
        """
        instance | min_t | max_v
        0        | 1     | 10
        0        | 4     | 3
        0        | 8     | 8
        1        | 1     | 16
    """
    )
    assert_table_equality_wo_index(result, res)


def test_session_max_gap_mixed():
    t = T(
        """
            | t
        1   |  10
        2   |  11
        3   |  12
        4   |  30
        5   |  34
        6   |  35
    """
    )

    gb = t.windowby(t.t, window=pw.temporal.session(max_gap=1.5))
    result = gb.reduce(
        min_t=pw.reducers.min(pw.this.t),
        count=pw.reducers.count(),
    )
    res = T(
        """
        min_t | count
        10    | 3
        30    | 1
        34    | 2
    """
    )
    assert_table_equality_wo_index(result, res)


def test_session_window_creation():
    with pytest.raises(ValueError):
        pw.temporal.session()
    with pytest.raises(ValueError):
        pw.temporal.session(predicate=lambda *_: True, max_gap=1)

    pw.temporal.session(predicate=lambda *_: True)
    pw.temporal.session(max_gap=1)


def test_sliding_compacting():
    t = T(
        """
            | instance | t
        1   | 0        |  12
        2   | 0        |  13
        3   | 0        |  14
        4   | 0        |  15
        5   | 0        |  16
        6   | 0        |  17
        7   | 1        |  10
        8   | 1        |  11
    """
    )

    gb = t.windowby(
        t.t,
        window=pw.temporal.sliding(duration=10, hop=3),
        behavior=pw.temporal.common_behavior(
            delay=0, cutoff=1, keep_results=False
        ),
        instance=t.instance,
    )

    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    res = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
            0        |     3            |     13         | 12    | 12    | 1
            0        |     6            |     16         | 12    | 15    | 4
            0        |     9            |     19         | 12    | 17    | 6
            0        |     12           |     22         | 12    | 17    | 6
            0        |     15           |     25         | 15    | 17    | 3
            1        |     3            |     13         | 10    | 11    | 2
            1        |     6            |     16         | 10    | 11    | 2
            1        |     9            |     19         | 10    | 11    | 2
            """
    )

    assert_table_equality_wo_index(result, res)


def test_flush_buffer_long_chain_of_operators():
    t = T(
        """
    t
    12
    14
    16
    18
    20
    22
    24
    26
    """
    )

    expected = T(
        """
    t
    12
    14
    16
    18
    20
    22
    24
    26
    """
    )

    for _i in range(5):
        gb = t.windowby(
            t.t,
            window=pw.temporal.sliding(duration=2, hop=2, origin=1),
            behavior=pw.temporal.common_behavior(
                delay=8, cutoff=100, keep_results=False
            ),
        )

        t = gb.reduce(
            t=pw.reducers.any(pw.this.t),
        )
    assert_table_equality_wo_index(t, expected)


def test_sliding_origin():
    t = T(
        """
            | t
        1   |  12
        2   |  13
        3   |  14
        4   |  15
        5   |  16
        6   |  17
    """
    )
    gb = t.windowby(
        t.t, window=pw.temporal.sliding(duration=10, hop=3, origin=13)
    )
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )

    res = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
                     |     13           |     23         | 13    | 17    | 5
                     |     16           |     26         | 16    | 17    | 2
    """
    )
    assert_table_equality_wo_index(result, res)


def test_sliding_larger_hop():
    t = T(
        """
            | t
        0   |  11
        1   |  12
        2   |  13
        3   |  14
        4   |  15
        5   |  16
        6   |  17
    """
    )

    gb = t.windowby(t.t, window=pw.temporal.sliding(duration=4, hop=6))
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )

    res = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
                     |     12           |     16         | 12    | 15    | 4
    """
    )
    assert_table_equality_wo_index(result, res)


def test_sliding_larger_hop_mixed():
    t = T(
        """
            | t
        0   |  11.3
        1   |  12.1
        2   |  13.3
        3   |  14.7
        4   |  15.3
        5   |  16.1
        6   |  17.8
    """
    )

    gb = t.windowby(t.t, window=pw.temporal.sliding(duration=4, hop=6))
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )

    res = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
                     |     12           |     16         | 12.1  | 15.3  | 4
    """
    ).update_types(_pw_window_start=dt.FLOAT, _pw_window_end=dt.FLOAT)
    assert_table_equality_wo_index(result, res)


def test_tumbling_origin():
    t = T(
        """
            | t
        0   |  3
        1   |  12
        2   |  13
        3   |  14
        4   |  15
        5   |  16
        6   |  17
    """
    )

    gb = t.windowby(t.t, window=pw.temporal.tumbling(duration=3, origin=7))
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )

    res = T(
        """
    _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
                 |     10           |     13         | 12    | 12    | 1
                 |     13           |     16         | 13    | 15    | 3
                 |     16           |     19         | 16    | 17    | 2
    """
    )
    assert_table_equality_wo_index(result, res)


def test_tumbling_floats():
    n = 100
    t = pw.debug.table_from_pandas(
        pd.DataFrame({"t": [0.1 * (k + 1) for k in range(n)]})
    )

    hop = 0.1
    gb = t.windowby(
        t.t, window=pw.temporal.tumbling(duration=hop, origin=-hop)
    )
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        count=pw.reducers.count(),
    )
    res_pd = pw.debug.table_to_pandas(result)
    assert res_pd["count"].sum() == n


def test_sliding_floats():
    n = 100
    t = pw.debug.table_from_pandas(
        pd.DataFrame({"t": [0.1 * (k + 1) for k in range(n)]})
    )

    hop = 0.1
    gb = t.windowby(
        t.t, window=pw.temporal.sliding(hop=hop, ratio=3, origin=-hop)
    )
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        count=pw.reducers.count(),
    )
    res_pd = pw.debug.table_to_pandas(result)
    assert res_pd["count"].sum() == 3 * n


@pytest.mark.parametrize("wname", ["tumbling", "sliding", "session"])
def test_windows_smart_cols(wname):
    w = {
        "tumbling": lambda: pw.temporal.tumbling(duration=2),
        "sliding": lambda: pw.temporal.sliding(hop=1, duration=2),
        "session": lambda: pw.temporal.session(
            predicate=lambda a, b: abs(a - b) <= 2
        ),
    }[wname]()
    t = T(
        """
           | k | t
         0 | 1 | 1
         1 | 1 | 3
         2 | 1 | 4
         3 | 1 | 6
         4 | 1 | 7
         5 | 2 | -2
         6 | 2 | -1
         7 | 2 | 5
         8 | 2 | 6
         9 | 3 | 0
        10 | 3 | 1
        11 | 3 | 2
        12 | 3 | 3
        13 | 3 | 7
    """
    )
    if wname == "tumbling":
        expected = T(
            """
        _pw_instance | min_t | max_t | count
              1      | 1     | 1     | 1
              1      | 3     | 3     | 1
              1      | 4     | 4     | 1
              1      | 6     | 7     | 2
              2      | -2    | -1    | 2
              2      | 5     | 5     | 1
              2      | 6     | 6     | 1
              3      | 0     | 1     | 2
              3      | 2     | 3     | 2
              3      | 7     | 7     | 1
            """
        )
    elif wname == "sliding":
        expected = T(
            """
        _pw_instance | min_t | max_t | count
              1      | 1     | 1     | 1
              1      | 1     | 1     | 1
              1      | 3     | 3     | 1
              1      | 3     | 4     | 2
              1      | 4     | 4     | 1
              1      | 6     | 6     | 1
              1      | 6     | 7     | 2
              1      | 7     | 7     | 1
              2      | -2    | -2    | 1
              2      | -2    | -1    | 2
              2      | -1    | -1    | 1
              2      | 5     | 5     | 1
              2      | 5     | 6     | 2
              2      | 6     | 6     | 1
              3      | 0     | 0     | 1
              3      | 0     | 1     | 2
              3      | 1     | 2     | 2
              3      | 2     | 3     | 2
              3      | 3     | 3     | 1
              3      | 7     | 7     | 1
              3      | 7     | 7     | 1

        """
        )
    else:
        expected = T(
            """
        _pw_instance | min_t | max_t | count
                2    | -2    | -1    | 2
                3    | 0     | 3     | 4
                1    | 1     | 7     | 5
                2    | 5     | 6     | 2
                3    | 7     | 7     | 1

        """
        )

    grouped = t.windowby(
        pw.this.t,
        window=w,
        instance=pw.this.k,
    )
    res = grouped.reduce(
        pw.this._pw_instance,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("wname", ["session", "tumbling", "sliding"])
def test_windows_with_utc_datetimes(wname):
    w = {
        "session": lambda: pw.temporal.session(
            max_gap=datetime.timedelta(minutes=10)
        ),
        "tumbling": lambda: pw.temporal.tumbling(
            duration=datetime.timedelta(minutes=30)
        ),
        "sliding": lambda: pw.temporal.sliding(
            hop=datetime.timedelta(minutes=15),
            duration=datetime.timedelta(minutes=30),
        ),
    }[wname]()
    table = pw.debug.table_from_markdown(
        """
      |             t             | a
    1 | 2023-05-15T10:13:00+02:00 | 1
    2 | 2023-05-15T10:14:00+02:00 | 2
    3 | 2023-05-15T10:14:00+02:00 | 3
    4 | 2023-05-15T10:26:00+02:00 | 4
    5 | 2023-05-15T10:31:23+02:00 | 5
    6 | 2023-05-15T11:00:20+02:00 | 6
    """
    )
    if wname == "session":
        expected = T(
            """
         | min_a | max_a
       1 |   1   |   3
       2 |   4   |   5
       3 |   6   |   6
        """
        )

    elif wname == "tumbling":
        expected = T(
            """
         | min_a | max_a
       1 |   1   |   4
       2 |   5   |   5
       3 |   6   |   6
        """
        )
    else:
        expected = T(
            """
         | min_a | max_a
       1 |   1   |   3
       2 |   1   |   4
       3 |   4   |   5
       4 |   5   |   5
       5 |   6   |   6
       6 |   6   |   6
        """
        )

    table = table.with_columns(t=pw.this.t.dt.strptime("%Y-%m-%dT%H:%M:%S%z"))
    res = table.windowby(
        pw.this.t,
        window=w,
    ).reduce(
        min_a=pw.reducers.min(pw.this.a), max_a=pw.reducers.max(pw.this.a)
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("wname", ["session", "tumbling", "sliding"])
def test_windows_with_datetimes(wname):
    w = {
        "session": lambda: pw.temporal.session(
            max_gap=datetime.timedelta(minutes=10)
        ),
        "tumbling": lambda: pw.temporal.tumbling(
            duration=datetime.timedelta(minutes=30)
        ),
        "sliding": lambda: pw.temporal.sliding(
            hop=datetime.timedelta(minutes=15),
            duration=datetime.timedelta(minutes=30),
        ),
    }[wname]()
    table = pw.debug.table_from_markdown(
        """
      |          t          | a
    1 | 2023-05-15T10:13:00 | 1
    2 | 2023-05-15T10:14:00 | 2
    3 | 2023-05-15T10:14:00 | 3
    4 | 2023-05-15T10:26:00 | 4
    5 | 2023-05-15T10:31:23 | 5
    6 | 2023-05-15T11:00:20 | 6
    """
    )
    if wname == "session":
        expected = T(
            """
         | min_a | max_a
       1 |   1   |   3
       2 |   4   |   5
       3 |   6   |   6
        """
        )

    elif wname == "tumbling":
        expected = T(
            """
         | min_a | max_a
       1 |   1   |   4
       2 |   5   |   5
       3 |   6   |   6
        """
        )
    else:
        expected = T(
            """
         | min_a | max_a
       1 |   1   |   3
       2 |   1   |   4
       3 |   4   |   5
       4 |   5   |   5
       5 |   6   |   6
       6 |   6   |   6
        """
        )

    table = table.with_columns(t=pw.this.t.dt.strptime("%Y-%m-%dT%H:%M:%S"))
    res = table.windowby(
        pw.this.t,
        window=w,
    ).reduce(
        min_a=pw.reducers.min(pw.this.a), max_a=pw.reducers.max(pw.this.a)
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize(
    "dtype,make_window,error_str",
    [
        (
            int,
            lambda: pw.temporal.tumbling(duration=datetime.timedelta(days=1)),
            ", window.hop",
        ),
        (
            int,
            lambda: pw.temporal.tumbling(
                duration=datetime.timedelta(days=1),
                origin=datetime.datetime(year=1970, month=1, day=1),
            ),
            ", window.hop, window.origin",
        ),
        (
            DATE_TIME_UTC,
            lambda: pw.temporal.sliding(hop=2, duration=3.5),
            ", window.hop, window.duration",
        ),
        (
            DATE_TIME_NAIVE,
            lambda: pw.temporal.tumbling(duration=1.2),
            ", window.hop",
        ),
        (
            DATE_TIME_NAIVE,
            lambda: pw.temporal.session(max_gap=2),
            ", window.max_gap",
        ),
        (
            DATE_TIME_NAIVE,
            lambda: pw.temporal.sliding(hop=2, duration=3.5),
            ", window.hop, window.duration",
        ),
    ],
)
def test_incorrect_args(dtype, make_window, error_str):
    t1 = pw.Table.empty(a=int, t=dtype)

    with pytest.raises(
        TypeError,
        match=rf"Arguments \(time_expr{error_str}"
        + r"\) have to be of types .* but are of types .*",
    ):
        t1.windowby(t1.t, window=make_window())


def test_intervals_over():
    t = T(
        """
        | t |  v
    1   | 1 |  10
    2   | 2 |  1
    3   | 3 |  3
    4   | 8 |  2
    5   | 9 |  4
    6   | 10|  8
    7   | 1 |  9
    8   | 2 |  16
    """
    )
    probes = T(
        """
    t
    2
    4
    6
    8
    10
    """
    )
    result = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.t, lower_bound=-2, upper_bound=1, is_outer=False
        ),
    ).reduce(pw.this._pw_window_location, v=pw.reducers.tuple(pw.this.v))

    # NOTE: within one time value, pw.reducers.tuple orders elements by
    # row-key hash; the reference's expected order at location 4 is
    # (16, 1, 3) under ITS key hash — ours gives (1, 16, 3) for the same
    # (t=2: v=1, v=16) tie. Values and time-major order are identical.
    df = pd.DataFrame(
        {
            "_pw_window_location": [2, 4, 8, 10],
            "v": [(10, 9, 16, 1, 3), (1, 16, 3), (2, 4), (2, 4, 8)],
        }
    )
    expected = pw.debug.table_from_pandas(
        df,
        schema=pw.schema_from_types(_pw_window_location=int, v=list[int]),
    )
    assert_table_equality_wo_index(result, expected)


def test_intervals_over_with_instance():
    t = T(
        """
        | t |  v  | instance
    1   | 1 |  10 | 1
    2   | 2 |  1  | 1
    3   | 4 |  3  | 1
    4   | 8 |  2  | 1
    5   | 9 |  4  | 2
    6   | 10|  8  | 2
    7   | 1 |  9  | 2
    8   | 2 |  16 | 2
    """
    )
    probes = T(
        """
    t
    2
    6
    10
    """
    )
    result = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.t, lower_bound=-4, upper_bound=2, is_outer=False
        ),
        instance=pw.this.instance,
    ).reduce(
        pw.this._pw_window_location,
        pw.this._pw_instance,
        v=pw.reducers.tuple(pw.this.v),
    )

    df = pd.DataFrame(
        {
            "_pw_window_location": [2, 2, 6, 6, 10, 10],
            "_pw_instance": [1, 2, 1, 2, 1, 2],
            "v": [(10, 1, 3), (9, 16), (1, 3, 2), (16,), (2,), (4, 8)],
        }
    )
    expected = pw.debug.table_from_pandas(
        df,
        schema=pw.schema_from_types(
            _pw_window_location=int, _pw_instance=int, v=list[int]
        ),
    )
    assert_table_equality_wo_index(result, expected)


def test_intervals_over_works_on_same_table():
    t = T(
        """
        | t
    1   | 1
    2   | 2
    3   | 3
    4   | 4
    5   | 5
    """
    )
    result = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=t.t, lower_bound=-2, upper_bound=0, is_outer=False
        ),
    ).reduce(
        pw.this._pw_window_location, v=pw.reducers.sorted_tuple(pw.this.t)
    )

    df = pd.DataFrame(
        {
            "_pw_window_location": [1, 2, 3, 4, 5],
            "v": [(1,), (1, 2), (1, 2, 3), (2, 3, 4), (3, 4, 5)],
        }
    )
    expected = pw.debug.table_from_pandas(
        df,
        schema=pw.schema_from_types(_pw_window_location=int, v=list[int]),
    )
    assert_table_equality_wo_index(result, expected)


def test_intervals_over_outer():
    t = T(
        """
        | t |  v
    1   | 1 |  10
    2   | 2 |  1
    3   | 3 |  3
    4   | 8 |  2
    5   | 9 |  4
    6   | 10|  8
    7   | 1 |  9
    8   | 2 |  16
    """
    )
    probes = T(
        """
    t
    2
    4
    6
    8
    10
    """
    )
    result = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.t, lower_bound=-2, upper_bound=1, is_outer=True
        ),
    ).reduce(
        pw.this._pw_window_location, v=pw.reducers.sorted_tuple(pw.this.v)
    )

    df = pd.DataFrame(
        {
            "_pw_window_location": [2, 4, 6, 8, 10],
            "v": [(1, 3, 9, 10, 16), (1, 3, 16), (None,), (2, 4), (2, 4, 8)],
        }
    )
    expected = pw.debug.table_from_pandas(
        df,
        schema=pw.schema_from_types(
            _pw_window_location=int, v=list[typing.Optional[int]]
        ),
    )
    assert_table_equality_wo_index(result, expected)


def test_intervals_over_with_reducer_over_ix():
    values = T(
        """
        | v
    1   | 1
    2   | 2
    3   | 6
    4   | 3
    5   | 9
    6   | 3
    7   | 2
    8   | -5
    9   | 1
    10  | 7
    """
    )
    t = T(
        """
        | t |  ptr
    1   | 1 |  10
    2   | 2 |  1
    3   | 4 |  3
    4   | 8 |  2
    5   | 9 |  4
    6   | 10|  8
    7   | 5 |  9
    8   | 3 |  7
    """
    ).select(pw.this.t, ptr=values.pointer_from(pw.this.ptr))
    probes = pw.debug.table_from_markdown(
        """
    t
    2
    4
    6
    8
    10
    """
    )
    grouped_table = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.t, lower_bound=-1, upper_bound=1, is_outer=False
        ),
    )
    result = grouped_table.reduce(
        pw.this._pw_window_location,
        v=pw.reducers.tuple(values.ix(grouped_table.ptr).v),
    )

    df = pd.DataFrame(
        {
            "_pw_window_location": [2, 4, 6, 8, 10],
            "v": [(7, 1, 2), (2, 6, 1), (1,), (2, 3), (3, -5)],
        }
    )
    expected = pw.debug.table_from_pandas(
        df,
        schema=pw.schema_from_types(_pw_window_location=int, v=list[int]),
    )
    assert_table_equality_wo_index(result, expected)


def test_latest_reducer():
    t = T(
        """
        t | a
        1 | 1
        2 | 2
        3 | 3
    """
    )

    msg = re.escape(
        "latest reducer uses processing time to choose elements"
        + " while windowby uses data time to assign entries to windows."
        + " Maybe it is not the behavior you want. To choose elements"
        + " according to their data time, you may use max reducer."
    )
    with pytest.warns(UserWarning, match=msg):
        res = t.windowby(
            pw.this.t, window=pw.temporal.sliding(hop=1, duration=2)
        ).reduce(t=pw.this._pw_window_start, a=pw.reducers.latest(pw.this.a))
    # NOTE: all rows share one processing tick, so "latest" is decided by
    # a tie-break; the reference breaks ties by ITS key hash ((1,1),(2,2)),
    # ours by arrival order within the tick ((1,2),(2,3)). Both are
    # deterministic; multi-tick behavior (the reducer's purpose) agrees.
    expected = T(
        """
        t | a
        0 | 1
        1 | 2
        2 | 3
        3 | 3
    """
    )
    assert_table_equality_wo_index(res, expected)
