"""Temporal stdlib tests: windows, interval/window/asof/asof_now joins,
behaviors — modeled on the reference test strategy (markdown fixtures +
__time__/__diff__ simulated streams, reference
python/pathway/tests/temporal/)."""

import pytest

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from pathway_tpu.debug import T, assert_table_equality_wo_index


def rows_of(table):
    keys, cols = dbg.table_to_dicts(table)
    return [{n: cols[n][k] for n in cols} for k in keys]


def test_tumbling_window():
    t = T(
        """
        instance | t
        0        | 12
        0        | 13
        0        | 14
        0        | 15
        0        | 16
        0        | 17
        1        | 12
        1        | 13
        """
    )
    result = t.windowby(
        t.t, window=pw.temporal.tumbling(duration=5), instance=t.instance
    ).reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    expected = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
        0            | 10               | 15             | 12    | 14    | 3
        0            | 15               | 20             | 15    | 17    | 3
        1            | 10               | 15             | 12    | 13    | 2
        """
    )
    assert_table_equality_wo_index(result, expected)


def test_sliding_window():
    t = T(
        """
        t
        12
        13
        17
        """
    )
    result = t.windowby(
        t.t, window=pw.temporal.sliding(hop=5, duration=10)
    ).reduce(
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        count=pw.reducers.count(),
    )
    # t=12,13 in [5,15) and [10,20); t=17 in [10,20) and [15,25)
    expected = T(
        """
        _pw_window_start | _pw_window_end | count
        5                | 15             | 2
        10               | 20             | 3
        15               | 25             | 1
        """
    )
    assert_table_equality_wo_index(result, expected)


def test_session_window_predicate():
    t = T(
        """
        instance |  t |  v
        0        |  1 |  10
        0        |  2 |  1
        0        |  4 |  3
        0        |  8 |  2
        0        |  9 |  4
        0        |  10|  8
        1        |  1 |  9
        1        |  2 |  16
        """
    )
    result = t.windowby(
        t.t,
        window=pw.temporal.session(predicate=lambda a, b: abs(a - b) <= 1),
        instance=t.instance,
    ).reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_v=pw.reducers.max(pw.this.v),
        count=pw.reducers.count(),
    )
    expected = T(
        """
        _pw_instance | _pw_window_start | _pw_window_end | min_t | max_v | count
        0            | 1                | 2              | 1     | 10    | 2
        0            | 4                | 4              | 4     | 3     | 1
        0            | 8                | 10             | 8     | 8     | 3
        1            | 1                | 2              | 1     | 16    | 2
        """
    )
    assert_table_equality_wo_index(result, expected)


def test_session_window_max_gap_streaming_merge():
    # two separate sessions merge into one when a bridging row arrives later
    t = T(
        """
        t  | __time__
        1  | 2
        5  | 2
        3  | 4
        """
    )
    result = t.windowby(
        t.t, window=pw.temporal.session(max_gap=3)
    ).reduce(
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        count=pw.reducers.count(),
    )
    expected = T(
        """
        _pw_window_start | _pw_window_end | count
        1                | 5              | 3
        """
    )
    assert_table_equality_wo_index(result, expected)


def test_intervals_over():
    t = T(
        """
        t |  v
        1 |  10
        2 |  1
        4 |  3
        8 |  2
        9 |  4
        10|  8
        1 |  9
        2 |  16
        """
    )
    probes = T(
        """
        t
        2
        4
        6
        8
        10
        """
    )
    result = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.t, lower_bound=-2, upper_bound=1
        ),
    ).reduce(
        pw.this._pw_window_location,
        v=pw.reducers.sorted_tuple(pw.this.v),
    )
    rows = sorted(
        (r["_pw_window_location"], tuple(r["v"]) if r["v"] else None)
        for r in rows_of(result)
    )
    assert rows == [
        (2, (1, 9, 10, 16)),
        (4, (1, 3, 16)),
        (6, (3,)),
        (8, (2, 4)),
        (10, (2, 4, 8)),
    ]


def test_intervals_over_outer_empty_window():
    t = T(
        """
        t | v
        1 | 5
        """
    )
    probes = T(
        """
        p
        1
        9
        """
    )
    result = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.p, lower_bound=-1, upper_bound=1, is_outer=True
        ),
    ).reduce(
        pw.this._pw_window_location,
        s=pw.reducers.sum(pw.this.v),
    )
    rows = sorted(
        (r["_pw_window_location"], r["s"]) for r in rows_of(result)
    )
    assert rows == [(1, 5), (9, None)]


def test_interval_join_inner():
    t1 = T(
        """
        t | a
        3 | 1
        7 | 2
        13| 3
        """
    )
    t2 = T(
        """
        t | b
        2 | 10
        5 | 20
        6 | 30
        10| 40
        """
    )
    res = t1.interval_join(
        t2, t1.t, t2.t, pw.temporal.interval(-2, 1)
    ).select(a=t1.a, b=t2.b, lt=t1.t, rt=t2.t)
    expected = T(
        """
        a | b  | lt | rt
        1 | 10 | 3  | 2
        2 | 20 | 7  | 5
        2 | 30 | 7  | 6
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_interval_join_outer_with_on():
    t1 = T(
        """
        t | k | a
        1 | x | 1
        9 | x | 2
        1 | y | 3
        """
    )
    t2 = T(
        """
        t | k | b
        2 | x | 10
        2 | z | 30
        """
    )
    res = t1.interval_join_outer(
        t2, t1.t, t2.t, pw.temporal.interval(-1, 1), t1.k == t2.k
    ).select(a=t1.a, b=t2.b)
    expected = T(
        """
        a    | b
        1    | 10
        2    | None
        3    | None
        None | 30
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_window_join_tumbling():
    t1 = T(
        """
        t | a
        1 | 1
        2 | 2
        6 | 3
        """
    )
    t2 = T(
        """
        t | b
        2 | 10
        7 | 20
        11| 30
        """
    )
    res = t1.window_join(
        t2, t1.t, t2.t, pw.temporal.tumbling(duration=5)
    ).select(a=pw.left.a, b=pw.right.b)
    expected = T(
        """
        a | b
        1 | 10
        2 | 10
        3 | 20
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_window_join_session():
    t1 = T(
        """
        t | a
        1 | 1
        5 | 2
        """
    )
    t2 = T(
        """
        t | b
        2 | 10
        20| 20
        """
    )
    res = t1.window_join(
        t2, t1.t, t2.t, pw.temporal.session(max_gap=3)
    ).select(a=pw.left.a, b=pw.right.b)
    # merged times 1,2,5 form one session (gaps 1,3<? 3<3 false) ->
    # sessions over union: {1,2} (gap 1), {5}, {20}; pairs in shared window:
    # (a=1,b=10)
    expected = T(
        """
        a | b
        1 | 10
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_asof_join_backward():
    trades = T(
        """
        t  | sym | price
        3  | A   | 100
        7  | A   | 101
        5  | B   | 50
        """
    )
    quotes = T(
        """
        t  | sym | bid
        1  | A   | 99
        6  | A   | 100
        9  | B   | 49
        """
    )
    res = trades.asof_join(
        quotes, trades.t, quotes.t, trades.sym == quotes.sym
    ).select(sym=trades.sym, price=trades.price, bid=quotes.bid)
    expected = T(
        """
        sym | price | bid
        A   | 100   | 99
        A   | 101   | 100
        B   | 50    | None
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_asof_join_defaults_and_direction():
    t1 = T(
        """
        t | a
        5 | 1
        """
    )
    t2 = T(
        """
        t | val
        7 | 42
        """
    )
    res = t1.asof_join(
        t2,
        t1.t,
        t2.t,
        defaults={t2.val: -1},
    ).select(a=t1.a, val=t2.val)
    expected = T(
        """
        a | val
        1 | -1
        """
    )
    assert_table_equality_wo_index(res, expected)

    res_fwd = t1.asof_join(
        t2, t1.t, t2.t, direction=pw.temporal.Direction.FORWARD
    ).select(a=t1.a, val=t2.val)
    expected_fwd = T(
        """
        a | val
        1 | 42
        """
    )
    assert_table_equality_wo_index(res_fwd, expected_fwd)


def test_asof_now_join_no_revision():
    # queries at time 2 see only right rows present at time <= 2;
    # later right updates must NOT revise earlier results
    queries = T(
        """
        q | __time__
        1 | 2
        2 | 6
        """
    )
    state = T(
        """
        v | __time__
        10| 2
        20| 4
        """
    )
    res = queries.asof_now_join(state).select(q=queries.q, v=state.v)
    rows = sorted((r["q"], r["v"]) for r in rows_of(res))
    # q=1 joined with v=10 only (as of t=2); q=2 with both 10 and 20
    assert rows == [(1, 10), (2, 10), (2, 20)]


def test_windowby_exactly_once_behavior():
    t = T(
        """
        t | __time__
        1 | 2
        2 | 2
        11| 4
        3 | 6
        21| 8
        """
    )
    result = t.windowby(
        t.t,
        window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.exactly_once_behavior(),
    ).reduce(
        pw.this._pw_window_start,
        count=pw.reducers.count(),
    )
    # window [0,10) closes when t=11 arrives; the late row t=3 (arriving
    # at logical time 6) is dropped; window [10,20) closes at t=21
    rows = sorted(
        (r["_pw_window_start"], r["count"]) for r in rows_of(result)
    )
    # window [20,30) flushes at end-of-stream (time -> +inf), like the
    # reference's batch-mode close
    assert rows == [(0, 2), (10, 1), (20, 1)]


def test_windowby_common_behavior_cutoff_drops_late():
    t = T(
        """
        t  | __time__
        1  | 2
        12 | 4
        2  | 6
        """
    )
    result = t.windowby(
        t.t,
        window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.common_behavior(cutoff=0),
    ).reduce(
        pw.this._pw_window_start,
        count=pw.reducers.count(),
    )
    rows = sorted(
        (r["_pw_window_start"], r["count"]) for r in rows_of(result)
    )
    # the late row t=2 arrives after max_t=12 passed window [0,10) end
    assert rows == [(0, 1), (10, 1)]


def test_interval_join_streaming_retraction():
    t1 = T(
        """
          | t | a | __time__ | __diff__
        1 | 3 | 1 | 2        | 1
        1 | 3 | 1 | 6        | -1
        """
    )
    t2 = T(
        """
        t | b
        3 | 7
        """
    )
    res = t1.interval_join(t2, t1.t, t2.t, pw.temporal.interval(0, 0)).select(
        a=t1.a, b=t2.b
    )
    assert rows_of(res) == []
