import os

# Force a virtual 8-device CPU mesh so multi-chip sharding paths are
# exercised without TPU hardware (the driver's dryrun does the same).
# XLA_FLAGS must be set before the CPU backend initializes.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize pins JAX_PLATFORMS=axon (single tunneled TPU chip);
# unit tests must not depend on the tunnel — switch to host CPU.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: the full chaos/replication suites
    # (multi-process supervised kills, long closed-loop load) carry the
    # marker; fast smokes of the same machinery stay in tier-1
    config.addinivalue_line(
        "markers",
        "slow: heavy multi-process chaos/replication suites excluded "
        "from the tier-1 `-m 'not slow'` run",
    )


@pytest.fixture(autouse=True)
def _clear_parse_graph():
    from pathway_tpu.internals import parse_graph
    from pathway_tpu.internals.errors import clear_errors

    parse_graph.G.clear()
    clear_errors()
    yield
    parse_graph.G.clear()
    clear_errors()
