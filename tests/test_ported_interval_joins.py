"""Port of the reference interval-join suite (reference:
python/pathway/tests/temporal/test_interval_joins.py — 25 functions):
inner/left/right/outer interval joins over ints, floats, datetimes;
sharded and smart-column variants; randomized cross-checks against
join+filter; expression/coalesce/require select paths; build-time type
validation with reference-exact messages; freeze-based consolidation."""

from __future__ import annotations

import datetime
import re
from typing import Optional

import numpy as np
import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.internals.dtype import DATE_TIME_NAIVE, DATE_TIME_UTC, NONE
from tests.ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
)

ALL_MODES = [
    pw.JoinMode.INNER,
    pw.JoinMode.LEFT,
    pw.JoinMode.RIGHT,
    pw.JoinMode.OUTER,
]


@pytest.mark.parametrize("join_type", ALL_MODES)
@pytest.mark.parametrize("max_time_difference", [1, 2, 3])
def test_interval_join_time_only(join_type, max_time_difference) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    1 | 2 | 0
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    5 | 6 | 13
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    3 | 4 | 10
    4 | 5 | 15
    """
    )

    if max_time_difference == 1:
        expected = T(
            """
          | a | b
        0 | 3 | 1
        1 | 4 | 1
        2 | 5 | 3
          """
        )
        left = T(
            """
          | a | b
        3 | 1 |
        4 | 2 |
        5 | 6 |
            """
        )
        right = T(
            """
          | a | b
        6 |   | 2
        7 |   | 4
        8 |   | 5
            """
        )
    elif max_time_difference == 2:
        expected = T(
            """
          | a | b
        0 | 2 | 1
        1 | 3 | 1
        2 | 4 | 1
        3 | 4 | 2
        4 | 5 | 2
        5 | 5 | 3
        6 | 6 | 5
        """
        )
        left = T(
            """
          | a | b
        7 | 1 |
            """
        )
        right = T(
            """
          | a | b
        8 |   | 4
            """
        )
    else:
        expected = T(
            """
           | a | b
        0  | 1 | 1
        1  | 2 | 1
        2  | 3 | 1
        3  | 3 | 2
        4  | 4 | 1
        5  | 4 | 2
        6  | 4 | 3
        7  | 5 | 2
        8  | 5 | 3
        9  | 5 | 4
        10 | 6 | 4
        11 | 6 | 5
        """
        )
        left = pw.Table.empty(a=int, b=NONE)
        right = pw.Table.empty(a=NONE, b=int)

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    res = {
        pw.JoinMode.INNER: t1.interval_join_inner,
        pw.JoinMode.LEFT: t1.interval_join_left,
        pw.JoinMode.RIGHT: t1.interval_join_right,
        pw.JoinMode.OUTER: t1.interval_join_outer,
    }[join_type](
        t2,
        t1.t,
        t2.t,
        pw.temporal.interval(-max_time_difference, max_time_difference),
    ).select(
        t1.a, t2.b
    )
    assert_table_equality_wo_index(res, expected)
    res2 = t1.interval_join(
        t2,
        t1.t,
        t2.t,
        pw.temporal.interval(-max_time_difference, max_time_difference),
        how=join_type,
    ).select(t1.a, t2.b)
    assert_table_equality(res, res2)


@pytest.mark.parametrize("join_type", ALL_MODES)
def test_interval_join_time_only_empty_interval(join_type) -> None:
    t1 = T(
        """
    a | t
    1 | -1
    2 | 0
    3 | 2
    4 | 3
    5 | 4
    6 | 10
    """
    )

    t2 = T(
        """
    b | t
    1 | 0
    2 | 2
    3 | 3
    4 | 5
    5 | 11
    """
    )

    interval = pw.temporal.interval(0, 0)
    expected = T(
        """
    a | b
    2 | 1
    3 | 2
    4 | 3
    """
    )
    left = T(
        """
    a | b
    1 |
    5 |
    6 |
    """
    )
    right = T(
        """
    a | b
      | 4
      | 5
    """
    )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    res = t1.interval_join(
        t2,
        t1.t,
        t2.t,
        interval,
        how=join_type,
    ).select(t1.a, t2.b)

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("join_type", ALL_MODES)
def test_interval_join_time_only_empty_interval_shifted(join_type) -> None:
    t1 = T(
        """
    a | t
    1 | -1
    2 | 0
    3 | 2
    4 | 3
    5 | 4
    6 | 10
    """
    )

    t2 = T(
        """
    b | t
    1 | 0
    2 | 2
    3 | 3
    4 | 5
    5 | 11
    """
    )

    interval = pw.temporal.interval(1, 1)
    expected = T(
        """
    a | b
    1 | 1
    3 | 3
    5 | 4
    6 | 5
    """
    )
    left = T(
        """
    a | b
    2 |
    4 |
    """
    )
    right = T(
        """
    a | b
      | 2
    """
    )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    res = t1.interval_join(
        t2,
        t1.t,
        t2.t,
        interval,
        how=join_type,
    ).select(t1.a, t2.b)

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("join_type", ALL_MODES)
@pytest.mark.parametrize("bounds", [(1, 0), (15, -10)])
def test_interval_join_negative_time_errors(join_type, bounds) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    1 | 2 | 0
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    5 | 6 | 13
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    3 | 4 | 10
    4 | 5 | 15
    """
    )
    with pytest.raises(ValueError):
        {
            pw.JoinMode.INNER: t1.interval_join_inner,
            pw.JoinMode.LEFT: t1.interval_join_left,
            pw.JoinMode.RIGHT: t1.interval_join_right,
            pw.JoinMode.OUTER: t1.interval_join_outer,
        }[join_type](t2, t1.t, t2.t, pw.temporal.interval(bounds[0], bounds[1]))


@pytest.mark.parametrize(
    "bounds",
    [
        (-1, 0),
        (0, 1),
        (-2, 0),
        (0, 2),
        (-2, 1),
        (-1, 2),
        (-3, 0),
        (0, 3),
        (2, 3),
        (-3, -2),
    ],
)
def test_interval_join_non_symmetric(bounds: tuple[int, int]) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    1 | 2 | 0
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    5 | 6 | 13
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    3 | 4 | 10
    4 | 5 | 15
    """
    )

    expected = T(
        """
       | a | b | left_t | right_t
    0  | 1 | 1 |  -1    |    2
    1  | 2 | 1 |   0    |    2
    2  | 3 | 1 |   2    |    2
    3  | 3 | 2 |   2    |    5
    4  | 4 | 1 |   3    |    2
    5  | 4 | 2 |   3    |    5
    6  | 4 | 3 |   3    |    6
    7  | 5 | 2 |   7    |    5
    8  | 5 | 3 |   7    |    6
    9  | 5 | 4 |   7    |   10
    10 | 6 | 4 |  13    |   10
    11 | 6 | 5 |  13    |   15
    """
    )
    expected = expected.filter(
        (expected.left_t + bounds[0] <= expected.right_t)
        & (expected.right_t <= expected.left_t + bounds[1])
    ).select(pw.this.a, pw.this.b)

    res = t1.interval_join_inner(
        t2, t1.t, t2.t, pw.temporal.interval(bounds[0], bounds[1])
    ).select(t1.a, t2.b)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("join_type", ALL_MODES)
@pytest.mark.parametrize("max_time_difference", [1, 2])
def test_interval_join_sharded(join_type, max_time_difference) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -2
    1 | 1 | 1
    2 | 1 | 4
    3 | 1 | 7
    4 | 1 | 8
    5 | 2 | -4
    6 | 2 | -3
    7 | 2 | 1
    8 | 2 | 2
    9 | 2 | 4
   10 | 2 | 20
   11 | 3 | 1
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | -5
    1 | 1 | -4
    2 | 1 | -2
    3 | 1 | 0
    4 | 1 | 1
    5 | 1 | 7
    6 | 1 | 9
    7 | 2 | -5
    8 | 2 | -3
    9 | 2 | -1
   10 | 2 | 0
   11 | 2 | 5
   12 | 2 | 6
   13 | 2 | 7
   14 | 4 | 0
    """
    )

    if max_time_difference == 1:
        expected = T(
            """
          | a | b | left_t | right_t
        0 | 1 | 1 | -2     | -2
        1 | 1 | 1 | 1      | 0
        2 | 1 | 1 | 1      | 1
        3 | 1 | 1 | 7      | 7
        4 | 1 | 1 | 8      | 7
        5 | 1 | 1 | 8      | 9
        6 | 2 | 2 | -4     | -5
        7 | 2 | 2 | -4     | -3
        8 | 2 | 2 | -3     | -3
        9 | 2 | 2 | 1      | 0
       10 | 2 | 2 | 4      | 5
          """
        )
        left = T(
            """
          | a | b | left_t | right_t
       11 | 1 |   | 4      |
       12 | 2 |   | 2      |
       13 | 2 |   | 20     |
       14 | 3 |   | 1      |
          """
        )
        right = T(
            """
          | a | b | left_t | right_t
       15 |   | 1 |        | -5
       16 |   | 1 |        | -4
       17 |   | 2 |        | -1
       18 |   | 2 |        | 6
       19 |   | 2 |        | 7
       20 |   | 4 |        | 0
          """
        )
    else:
        expected = T(
            """
          | a | b | left_t | right_t
        0 | 1 | 1 | -2     | -4
        1 | 1 | 1 | -2     | -2
        2 | 1 | 1 | -2     | 0
        3 | 1 | 1 | 1      | 0
        4 | 1 | 1 | 1      | 1
        5 | 1 | 1 | 7      | 7
        6 | 1 | 1 | 7      | 9
        7 | 1 | 1 | 8      | 7
        8 | 1 | 1 | 8      | 9
        9 | 2 | 2 | -4     | -5
       10 | 2 | 2 | -4     | -3
       11 | 2 | 2 | -3     | -5
       12 | 2 | 2 | -3     | -3
       13 | 2 | 2 | -3     | -1
       14 | 2 | 2 | 1      | -1
       15 | 2 | 2 | 1      | 0
       16 | 2 | 2 | 2      | 0
       17 | 2 | 2 | 4      | 5
       18 | 2 | 2 | 4      | 6
        """
        )
        left = T(
            """
          | a | b | left_t | right_t
       19 | 1 |   | 4      |
       20 | 2 |   | 20     |
       21 | 3 |   | 1      |
          """
        )
        right = T(
            """
          | a | b | left_t | right_t
       22 |   | 1 |        | -5
       23 |   | 2 |        | 7
       24 |   | 4 |        | 0
          """
        )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    res = {
        pw.JoinMode.INNER: t1.interval_join_inner,
        pw.JoinMode.LEFT: t1.interval_join_left,
        pw.JoinMode.RIGHT: t1.interval_join_right,
        pw.JoinMode.OUTER: t1.interval_join_outer,
    }[join_type](
        t2,
        t1.t,
        t2.t,
        pw.temporal.interval(-max_time_difference, max_time_difference),
        t1.a == t2.b,
    ).select(
        t1.a, t2.b, left_t=t1.t, right_t=t2.t
    )
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("join_type", ALL_MODES)
def test_interval_join_smart_cols(join_type) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -2
    1 | 1 | 1
    2 | 1 | 4
    3 | 1 | 7
    4 | 1 | 8
    5 | 2 | -4
    6 | 2 | -3
    7 | 2 | 2
    8 | 2 | 4
    9 | 3 | 1
    """
    )

    t2 = T(
        """
      | a | t
    0 | 1 | -4
    1 | 1 | -2
    2 | 1 | 1
    3 | 1 | 7
    4 | 1 | 9
    5 | 2 | -3
    6 | 2 | 5
    7 | 2 | 6
    8 | 4 | 0
    """
    )

    expected = T(
        """
      | a | left_t | right_t
    0 | 1 | -2     | -2
    1 | 1 | 1      | 1
    2 | 1 | 7      | 7
    3 | 1 | 8      | 9
    4 | 2 | -4     | -3
    5 | 2 | -3     | -3
    6 | 2 | 4      | 5
        """
    )
    left = T(
        """
       | a | left_t | right_t
    7  | 1 | 4      |
    8  | 2 | 2      |
    9  | 3 | 1      |
        """
    )
    right = T(
        """
       | a | left_t | right_t
    10 | 1 |        | -4
    11 | 2 |        | 6
    12 | 4 |        | 0
        """
    )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    res = {
        pw.JoinMode.INNER: t1.interval_join_inner,
        pw.JoinMode.LEFT: t1.interval_join_left,
        pw.JoinMode.RIGHT: t1.interval_join_right,
        pw.JoinMode.OUTER: t1.interval_join_outer,
    }[join_type](
        t2,
        pw.left.t,
        pw.right.t,
        pw.temporal.interval(0, 1),
        pw.left.a == pw.right.a,
    ).select(
        pw.this.a, left_t=pw.left.t, right_t=pw.right.t
    )
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("max_time_difference", [0.1, 0.5])
def test_interval_join_float(max_time_difference: float) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -1.0
    1 | 2 | 0
    2 | 3 | 2.7
    3 | 4 | 5.0
    """
    )
    t2 = T(
        """
      | b  | t
    0 | 1  | -1.6
    1 | 2  | -1.4
    2 | 3  | -0.51
    3 | 4  | -0.5
    4 | 5  | -0.49
    5 | 6  | 2.1
    6 | 7  | 2.3
    7 | 8  | 3.4
    8 | 9  | 5.0
    9 | 10 | 5.09
   10 | 11 | 5.11
    """
    )
    if max_time_difference == 0.1:
        expected = T(
            """
         | a | b
       0 | 4 | 9
       1 | 4 | 10
        """
        )
    else:
        expected = T(
            """
         | a | b
       0 | 1 | 2
       1 | 1 | 3
       2 | 1 | 4
       3 | 2 | 4
       4 | 2 | 5
       5 | 3 | 7
       6 | 4 | 9
       7 | 4 | 10
       8 | 4 | 11
        """
        )
    res = t1.interval_join_inner(
        t2,
        t1.t,
        t2.t,
        pw.temporal.interval(-max_time_difference, max_time_difference),
    ).select(t1.a, t2.b)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("max_time_difference", [1, 1.5])
def test_interval_join_int_float(max_time_difference: float) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | 3
    1 | 2 | 7
    """
    )
    t2 = T(
        """
      | b  | t
    0 | 1  | 1.6
    1 | 2  | 2.1
    2 | 3  | 4.4
    3 | 4  | 6.1
    4 | 5  | 7.8
    5 | 6  | 0.0
    6 | 7  | 9.2
    7 | 8  | 8.3
    """
    )
    if max_time_difference == 1:
        expected = T(
            """
         | a | b
       0 | 1 | 2
       1 | 2 | 4
       2 | 2 | 5
        """
        )
    else:
        expected = T(
            """
         | a | b
       0 | 1 | 1
       1 | 1 | 2
       2 | 1 | 3
       3 | 2 | 4
       4 | 2 | 5
       5 | 2 | 8
        """
        )
    res = t1.interval_join_inner(
        t2,
        t1.t,
        t2.t,
        pw.temporal.interval(-max_time_difference, max_time_difference),
    ).select(t1.a, t2.b)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("join_type", ALL_MODES)
def test_non_overlapping_times(join_type) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | 0
    1 | 2 | 1
    2 | 3 | 2
    3 | 4 | 3
    """
    )
    t2 = T(
        """
      | b  | t
    0 | 1  | 9
    1 | 2  | 10
    2 | 3  | 11
    """
    )
    bounds = (-1, 2)
    if join_type == pw.JoinMode.INNER:
        expected = pw.Table.empty(a=int, b=int)
        res = t1.interval_join_inner(
            t2,
            t1.t,
            t2.t,
            pw.temporal.interval(bounds[0], bounds[1]),
            t1.a == t2.b,
        )
    elif join_type == pw.JoinMode.LEFT:
        expected = T(
            """
          | a | b
        0 | 1 |
        1 | 2 |
        2 | 3 |
        3 | 4 |
        """
        ).update_types(b=Optional[int])
        res = t1.interval_join_left(
            t2,
            t1.t,
            t2.t,
            pw.temporal.interval(bounds[0], bounds[1]),
            t1.a == t2.b,
        )
    elif join_type == pw.JoinMode.RIGHT:
        expected = T(
            """
          | a | b
        0 |   | 1
        1 |   | 2
        2 |   | 3
        """
        ).update_types(a=Optional[int])
        res = t1.interval_join_right(
            t2,
            t1.t,
            t2.t,
            pw.temporal.interval(bounds[0], bounds[1]),
            t1.a == t2.b,
        )
    else:
        expected = T(
            """
          | a | b
        0 | 1 |
        1 | 2 |
        2 | 3 |
        3 | 4 |
        4 |   | 1
        5 |   | 2
        6 |   | 3
        """
        )
        res = t1.interval_join_outer(
            t2,
            t1.t,
            t2.t,
            pw.temporal.interval(bounds[0], bounds[1]),
            t1.a == t2.b,
        )

    res = res.select(t1.a, t2.b)
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("seed", list(range(10)))
def test_interval_join_time_only_automatic(seed: int) -> None:
    n = 10
    time_min = -5
    time_max = 15
    np.random.seed(seed)

    df_a = pd.DataFrame({"a": np.random.randint(time_min, time_max, size=n)})
    t_a = pw.debug.table_from_pandas(df_a)
    df_b = pd.DataFrame({"b": np.random.randint(time_min, time_max, size=n)})
    t_b = pw.debug.table_from_pandas(df_b)

    lower_bound = np.random.randint(-10, 1)
    upper_bound = np.random.randint(1, 10)

    res = t_a.interval_join_inner(
        t_b, t_a.a, t_b.b, pw.temporal.interval(lower_bound, upper_bound)
    ).select(t_a.a, t_b.b)

    expected = (
        t_a.join(t_b)
        .filter(
            (t_a.a + lower_bound <= t_b.b) & (t_b.b <= t_a.a + upper_bound)
        )
        .select(t_a.a, t_b.b)
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("seed", list(range(10)))
def test_interval_join_sharded_automatic(seed: int) -> None:
    n = 20
    time_min = -5
    time_max = 15
    n_shards = 4

    np.random.seed(seed)

    df_a = pd.DataFrame(
        {
            "a": np.random.randint(time_min, time_max, size=n),
            "k1": np.random.randint(0, n_shards, size=n),
        }
    )
    t_a = pw.debug.table_from_pandas(df_a)
    df_b = pd.DataFrame(
        {
            "b": np.random.randint(time_min, time_max, size=n),
            "k2": np.random.randint(0, n_shards, size=n),
        }
    )
    t_b = pw.debug.table_from_pandas(df_b)

    lower_bound = np.random.randint(1, 5)
    upper_bound = np.random.randint(5, 10)

    res = t_a.interval_join_inner(
        t_b,
        t_a.a,
        t_b.b,
        pw.temporal.interval(lower_bound, upper_bound),
        t_a.k1 == t_b.k2,
    ).select(t_a.a, t_a.k1, t_b.b, t_b.k2)

    expected = (
        t_a.join(t_b, t_a.k1 == t_b.k2)
        .filter(
            (t_a.a + lower_bound <= t_b.b) & (t_b.b <= t_a.a + upper_bound)
        )
        .select(t_a.a, t_a.k1, t_b.b, t_b.k2)
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("seed", list(range(10)))
def test_interval_join_float_automatic(seed: int) -> None:
    n = 20
    time_min = -0.5
    time_max = 1.5

    np.random.seed(seed)

    df_a = pd.DataFrame(
        {"a": np.random.rand(n) * (time_max - time_min) + time_min}
    )
    t_a = pw.debug.table_from_pandas(df_a)
    df_b = pd.DataFrame(
        {"b": np.random.rand(n) * (time_max - time_min) + time_min}
    )
    t_b = pw.debug.table_from_pandas(df_b)

    lower_bound = np.random.rand() * 0.1 - 0.05
    upper_bound = np.random.rand() * 0.1 + 0.1

    res = t_a.interval_join_inner(
        t_b,
        pw.left.a,
        pw.right.b,
        pw.temporal.interval(lower_bound, upper_bound),
    ).select(a=pw.left.a, b=pw.right.b)

    expected = (
        t_a.join(t_b)
        .filter(
            (t_a.a + lower_bound <= t_b.b) & (t_b.b <= t_a.a + upper_bound)
        )
        .select(t_a.a, t_b.b)
    )

    assert_table_equality_wo_index(res, expected)


def test_interval_inner_join_expressions() -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    1 | 2 | 0
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    5 | 6 | 13
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    3 | 4 | 10
    4 | 5 | 15
    """
    )

    expected = T(
        """
      | a | b | t_diff | t_sum | sth
    0 | 3 | 1 |  0     |  4    | 0b
    1 | 4 | 1 |  1     |  5    | 3a
    2 | 5 | 3 |  1     | 13    | 2a
        """
    )

    res = t1.interval_join_inner(
        t2, t1.t, t2.t, pw.temporal.interval(-1, 1)
    ).select(
        t1.a,
        t2.b,
        t_diff=t1.t - t2.t,
        t_sum=t1.t + t2.t,
        sth=pw.if_else(
            t1.a + t2.b > 4,
            pw.cast(str, t1.t // t2.b) + "a",
            pw.cast(str, t2.t // t1.a) + "b",
        ),
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize(
    "join_type", [pw.JoinMode.LEFT, pw.JoinMode.RIGHT, pw.JoinMode.OUTER]
)
def test_interval_join_expressions(join_type) -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    1 | 2 | 0
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    5 | 6 | 13
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    3 | 4 | 10
    4 | 5 | 15
    """
    )

    expected = T(
        """
      | a | b | t_diff | t_sum | sth | only_left | only_right
    0 | 3 | 1 |  0     |  4    | 0y  |    5      |    3
    1 | 4 | 1 |  1     |  5    | 3x  |    7      |    3
    2 | 5 | 3 |  1     | 13    | 2x  |   12      |    9
        """
    )
    left = T(
        """
      | a | b | t_diff | t_sum | sth | only_left | only_right
    0 | 1 |   |        |       |     |    0      |
    1 | 2 |   |        |       |     |    2      |
    2 | 6 |   |        |       |     |   19      |
        """
    )
    right = T(
        """
      | a | b | t_diff | t_sum | sth | only_left | only_right
    0 |   | 2 |        |       |     |           |    7
    1 |   | 4 |        |       |     |           |   14
    2 |   | 5 |        |       |     |           |   20
        """
    )

    if join_type in [pw.JoinMode.LEFT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(left)
    if join_type in [pw.JoinMode.RIGHT, pw.JoinMode.OUTER]:
        expected = expected.concat_reindex(right)

    res = {
        pw.JoinMode.INNER: t1.interval_join_inner,
        pw.JoinMode.LEFT: t1.interval_join_left,
        pw.JoinMode.RIGHT: t1.interval_join_right,
        pw.JoinMode.OUTER: t1.interval_join_outer,
    }[join_type](t2, t1.t, t2.t, pw.temporal.interval(-1, 1)).select(
        t1.a,
        t2.b,
        t_diff=pw.require(t1.t - t2.t, t1.id, t2.id),
        t_sum=pw.require(t1.t + t2.t, t1.id, t2.id),
        sth=pw.require(
            pw.if_else(
                t1.a + t2.b > 4,
                pw.cast(str, t1.t // t2.b) + "x",
                pw.cast(str, t2.t // t1.a) + "y",
            ),
            t1.id,
            t2.id,
        ),
        only_left=(
            pw.require(t1.t + t1.a, t1.id)
            if join_type in (pw.JoinMode.RIGHT, pw.JoinMode.OUTER)
            else t1.t + t1.a
        ),
        only_right=(
            pw.require(t2.t + t2.b, t2.id)
            if join_type in (pw.JoinMode.LEFT, pw.JoinMode.OUTER)
            else t2.t + t2.b
        ),
    )
    res = res.update_types(
        t_diff=Optional[int],
        t_sum=Optional[int],
    )
    if join_type in (pw.JoinMode.RIGHT, pw.JoinMode.OUTER):
        res = res.update_types(only_left=Optional[int])
    if join_type in (pw.JoinMode.LEFT, pw.JoinMode.OUTER):
        res = res.update_types(only_right=Optional[int])

    assert_table_equality_wo_index(res, expected)


def test_interval_join_coalesce() -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    """
    )

    expected = T(
        """
      | a | b | coalesce
    0 | 3 | 1 |    3
    1 | 4 | 1 |    4
    2 | 5 | 3 |    5
    3 | 1 |   |    1
    4 |   | 2 |    2
        """
    )

    res = t1.interval_join_outer(
        t2, pw.left.t, pw.right.t, pw.temporal.interval(-1, 1)
    ).select(
        pw.left.a,
        pw.right.b,
        coalesce=pw.declare_type(int, pw.coalesce(pw.left.a, pw.right.b)),
    )

    assert_table_equality_wo_index(res, expected)


def test_interval_join_with_time_expressions() -> None:
    t1 = T(
        """
      | a | t
    0 | 1 | 9
    2 | 3 | 12
    3 | 4 | 13
    4 | 5 | 17
    """
    )

    t2 = T(
        """
      | b | t
    0 | 1 | 1
    1 | 2 | 4
    2 | 3 | 5
    """
    )

    expected = T(
        """
      | a | b
    0 | 3 | 1
    1 | 4 | 1
    2 | 5 | 3
    3 | 1 |
    4 |   | 2
        """
    )

    res = t1.interval_join_outer(
        t2,
        (4 * pw.left.t - 40) // 2,
        (6 * pw.right.t + 6) // 3,
        pw.temporal.interval(-2, 2),
    ).select(
        pw.left.a,
        pw.right.b,
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("with_timezone", [True, False])
def test_with_timestamps(with_timezone: bool) -> None:
    fmt = "%Y-%m-%dT%H:%M:%S"
    if with_timezone:
        fmt += "%z"
        tz = "+02:00"
    else:
        tz = ""

    t1 = T(
        """
      | a | t
    0 | 1 | 2023-05-22T09:59:00
    1 | 2 | 2023-05-22T10:00:00
    2 | 3 | 2023-05-22T10:02:00
    3 | 4 | 2023-05-22T10:03:00
    4 | 5 | 2023-05-22T10:07:00
    5 | 6 | 2023-05-22T10:13:00
    """
    ).with_columns(t=(pw.this.t + tz).dt.strptime(fmt))

    t2 = T(
        """
      | b | t
    0 | 1 | 2023-05-22T10:02:00
    1 | 2 | 2023-05-22T10:05:00
    2 | 3 | 2023-05-22T10:06:00
    3 | 4 | 2023-05-22T10:10:00
    4 | 5 | 2023-05-22T10:15:00
    """
    ).with_columns(t=(pw.this.t + tz).dt.strptime(fmt))

    res = t1.interval_join_outer(
        t2,
        t1.t,
        t2.t,
        pw.temporal.interval(
            datetime.timedelta(minutes=-2), datetime.timedelta(minutes=1)
        ),
    ).select(pw.left.a, pw.right.b)

    expected = T(
        """
       | a | b
     1 |   | 4
     2 |   | 5
     3 | 1 |
     4 | 2 |
     5 | 3 | 1
     6 | 4 | 1
     7 | 5 | 2
     8 | 5 | 3
     9 | 6 |
    """
    )

    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize("join_mode", ALL_MODES)
@pytest.mark.parametrize(
    "left_type,right_type,lower_bound,upper_bound",
    [
        (int, int, 1, datetime.timedelta(days=1)),
        (int, int, datetime.timedelta(days=-1), datetime.timedelta(days=1)),
        (int, int, datetime.timedelta(days=-1), 1),
        (int, DATE_TIME_NAIVE, 0, 1),
        (DATE_TIME_NAIVE, int, 0, 1),
        (float, DATE_TIME_NAIVE, 1, 0.2),
        (DATE_TIME_NAIVE, DATE_TIME_NAIVE, 1, 2),
        (DATE_TIME_NAIVE, DATE_TIME_NAIVE, datetime.timedelta(days=1), 2),
        (
            DATE_TIME_UTC,
            DATE_TIME_NAIVE,
            datetime.timedelta(days=1),
            datetime.timedelta(days=2),
        ),
        (int, int, datetime.timedelta(seconds=2), 10),
    ],
)
def test_incorrect_args(
    join_mode, left_type, right_type, lower_bound, upper_bound
):
    t1 = pw.Table.empty(a=int, t=left_type)

    t2 = pw.Table.empty(b=int, t=right_type)

    with pytest.raises(
        TypeError,
        match=r"Arguments \(self_time_expression, other_time_expression, "
        + r"lower_bound, upper_bound\) have to be of types .* but are of "
        + r"types .*",
    ):
        {
            pw.JoinMode.INNER: t1.interval_join_inner,
            pw.JoinMode.LEFT: t1.interval_join_left,
            pw.JoinMode.RIGHT: t1.interval_join_right,
            pw.JoinMode.OUTER: t1.interval_join_outer,
        }[join_mode](
            t2, t1.t, t2.t, pw.temporal.interval(lower_bound, upper_bound)
        ).select(
            t1.a, t2.b
        )


def test_incorrect_args_specific():
    t1 = pw.Table.empty(a=int, t=DATE_TIME_NAIVE)

    t2 = pw.Table.empty(b=int, t=int)

    with pytest.raises(
        TypeError,
        match=re.escape(
            "Arguments (self_time_expression, other_time_expression, "
            "lower_bound, upper_bound) "
            "have to be of types (INT, INT, INT, INT) or "
            "(FLOAT, FLOAT, FLOAT, FLOAT) or "
            "(DATE_TIME_NAIVE, DATE_TIME_NAIVE, DURATION, DURATION) or "
            "(DATE_TIME_UTC, DATE_TIME_UTC, DURATION, DURATION) but are of "
            "types (DATE_TIME_NAIVE, INT, INT, INT)."
        ),
    ):
        t1.interval_join(t2, t1.t, t2.t, pw.temporal.interval(-1, 2))


def test_interval_joins_typing_on():
    left_table = pw.Table.empty(timestamp=int, col=int)
    right_table = pw.Table.empty(timestamp=int, col=str)
    with pytest.raises(expected_exception=TypeError):
        left_table.interval_join(
            right_table,
            left_table.timestamp,
            right_table.timestamp,
            pw.temporal.interval(-1, 2),
            left_table.col == right_table.col,
        )


def test_errors_on_equal_tables():
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    """
    )

    with pytest.raises(
        ValueError,
        match=re.escape(
            "Cannot join table with itself. Use <table>.copy() as one of "
            "the arguments of the join."
        ),
    ):
        t1.interval_join(t1, t1.t, t1.t, pw.temporal.interval(-2, 0))


def test_consolidate_for_cutoff():
    t = T(
        """
    a | t  | __time__ | __diff__
    1 | 2  | 2        | 1
    2 | 2  | 2        | 1
    3 | 10 | 2        | 1
    4 | 2  | 2        | 1
    5 | 2  | 4        | 1
    6 | 2  | 4        | 1
    7 | 2  | 4        | 1
    8 | 2  | 4        | 1
    9 | 2  | 4        | 1
    10| 2  | 4        | 1
    11| 2  | 4        | 1
    12| 2  | 4        | 1
    """
    )
    t = t._freeze(threshold_column=pw.this.t + 1, time_column=pw.this.t)

    assert_table_equality_wo_index(
        t,
        T(
            """
            a | t
            1 | 2
            2 | 2
            3 | 10
            4 | 2
            """
        ),
    )


def test_no_columns_added():
    t1 = T(
        """
      | a | t
    0 | 1 | 2
    """
    )
    expected = T(
        """
        a | t | b | s
        1 | 2 | 1 | 2
    """
    )
    t2 = t1.rename({"a": "b", "t": "s"})
    res = t1.interval_join(
        t2, pw.left.t, pw.right.s, interval=pw.temporal.interval(-1, 1)
    ).select(*pw.left, *pw.right)

    assert_table_equality_wo_index(res, expected)
