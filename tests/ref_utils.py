"""Reference-test harness shims (reference: python/pathway/tests/utils.py):
assert_table_equality / _wo_index compare the captured final state of two
tables, with or without row-key identity."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw


def _capture(table) -> dict[int, tuple]:
    from pathway_tpu.debug import _run_capture

    return _run_capture([table])[0].rows


def _both(t1, t2):
    from pathway_tpu.debug import _run_capture

    c1, c2 = _run_capture([t1, t2])
    return c1.rows, c2.rows


def _norm(v: Any) -> Any:
    import numpy as np

    if isinstance(v, np.ndarray):
        # the reference hashes arrays by their DISPLAY string
        # (make_value_hashable, tests/utils.py:302) — display rounding is
        # part of the comparison semantics (12.2999999... == 12.3)
        return ("__ndarray__", str(v.dtype), v.shape, str(v))
    if isinstance(v, float) and v != v:
        return "__nan__"
    if isinstance(v, (list, tuple)):
        # lists and tuples compare alike (and hash) in captured rows
        return tuple(_norm(x) for x in v)
    return v


def assert_table_equality(t1, t2) -> None:
    """Same keys AND same values per key."""
    r1, r2 = _both(t1, t2)
    n1 = {k: tuple(_norm(x) for x in v) for k, v in r1.items()}
    n2 = {k: tuple(_norm(x) for x in v) for k, v in r2.items()}
    assert n1 == n2, (
        f"\nleft:  {sorted(n1.items(), key=str)}"
        f"\nright: {sorted(n2.items(), key=str)}"
    )


def assert_table_equality_wo_index(t1, t2) -> None:
    """Same multiset of rows, ignoring keys."""
    r1, r2 = _both(t1, t2)

    def multiset(rows):
        out: dict = {}
        for v in rows.values():
            key = tuple(_norm(x) for x in v)
            out[key] = out.get(key, 0) + 1
        return out

    m1, m2 = multiset(r1), multiset(r2)
    assert m1 == m2, (
        f"\nleft:  {sorted(m1, key=str)}\nright: {sorted(m2, key=str)}"
    )


assert_table_equality_wo_index_types = assert_table_equality_wo_index
assert_table_equality_wo_types = assert_table_equality


def run_all(**kwargs) -> None:
    pw.run_all(monitoring_level=pw.MonitoringLevel.NONE, **kwargs)


def _capture_streams(tables, **kwargs):
    """Capture each table's full update stream [(key, vals, time, diff)]
    by running the graph once with subscribers attached
    (reference: GraphRunner.run_tables + CapturedStream)."""
    streams: list[list] = [[] for _ in tables]

    for i, t in enumerate(tables):
        names = list(t.column_names())

        def on_change(key, row, time, is_addition, _acc=streams[i], _names=names):
            _acc.append(
                (
                    int(key),
                    tuple(row[n] for n in _names),
                    time,
                    1 if is_addition else -1,
                )
            )

        pw.io.subscribe(t, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE, **kwargs)
    return streams


def assert_stream_equality_wo_index(t1, t2, **kwargs) -> None:
    """Same multiset of (values, time, diff) updates, ignoring keys
    (reference: tests/utils.py assert_equal_streams_wo_index)."""
    from collections import Counter

    s1, s2 = _capture_streams([t1, t2], **kwargs)
    c1 = Counter((tuple(_norm(x) for x in v), t, d) for _k, v, t, d in s1)
    c2 = Counter((tuple(_norm(x) for x in v), t, d) for _k, v, t, d in s2)
    assert c1 == c2, f"\nleft:  {sorted(c1.items(), key=str)}\nright: {sorted(c2.items(), key=str)}"


def assert_stream_equality(t1, t2, **kwargs) -> None:
    """Same multiset of (key, values, time, diff) updates
    (reference: tests/utils.py assert_equal_streams)."""
    from collections import Counter

    s1, s2 = _capture_streams([t1, t2], **kwargs)
    c1 = Counter((k, tuple(_norm(x) for x in v), t, d) for k, v, t, d in s1)
    c2 = Counter((k, tuple(_norm(x) for x in v), t, d) for k, v, t, d in s2)
    assert c1 == c2, (
        f"\nleft:  {sorted(c1.items(), key=str)}"
        f"\nright: {sorted(c2.items(), key=str)}"
    )
