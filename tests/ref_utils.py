"""Reference-test harness shims (reference: python/pathway/tests/utils.py):
assert_table_equality / _wo_index compare the captured final state of two
tables, with or without row-key identity."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw


def _capture(table) -> dict[int, tuple]:
    from pathway_tpu.debug import _run_capture

    return _run_capture([table])[0].rows


def _both(t1, t2):
    return _pairs(t1, t2)[0]


def _norm(v: Any) -> Any:
    import numpy as np

    if isinstance(v, np.ndarray):
        # the reference hashes arrays by their DISPLAY string
        # (make_value_hashable, tests/utils.py:302) — display rounding is
        # part of the comparison semantics (12.2999999... == 12.3)
        return ("__ndarray__", str(v.dtype), v.shape, str(v))
    if isinstance(v, float) and v != v:
        return "__nan__"
    if isinstance(v, (list, tuple)):
        # lists and tuples compare alike (and hash) in captured rows
        return tuple(_norm(x) for x in v)
    return v


def _pairs(t1, t2):
    """Support the reference's tuple form: comparing N table pairs in ONE
    graph run (tests/utils.py passes e.g. (result, error_log) vs
    (expected, expected_errors))."""
    from pathway_tpu.debug import _run_capture

    lefts = list(t1) if isinstance(t1, (tuple, list)) else [t1]
    rights = list(t2) if isinstance(t2, (tuple, list)) else [t2]
    assert len(lefts) == len(rights)
    caps = _run_capture(lefts + rights)
    n = len(lefts)
    return [(caps[i].rows, caps[n + i].rows) for i in range(n)]


def assert_table_equality(t1, t2, **kwargs) -> None:
    """Same keys AND same values per key. Extra kwargs
    (terminate_on_error=...) are accepted for reference-test parity; the
    debug capture path never terminates on ERROR rows."""
    for r1, r2 in _pairs(t1, t2):
        n1 = {k: tuple(_norm(x) for x in v) for k, v in r1.items()}
        n2 = {k: tuple(_norm(x) for x in v) for k, v in r2.items()}
        assert n1 == n2, (
            f"\nleft:  {sorted(n1.items(), key=str)}"
            f"\nright: {sorted(n2.items(), key=str)}"
        )


def assert_table_equality_wo_index(t1, t2, **kwargs) -> None:
    """Same multiset of rows, ignoring keys."""
    for r1, r2 in _pairs(t1, t2):

        def multiset(rows):
            out: dict = {}
            for v in rows.values():
                key = tuple(_norm(x) for x in v)
                out[key] = out.get(key, 0) + 1
            return out

        m1, m2 = multiset(r1), multiset(r2)
        assert m1 == m2, (
            f"\nleft:  {sorted(m1, key=str)}\nright: {sorted(m2, key=str)}"
        )


assert_table_equality_wo_index_types = assert_table_equality_wo_index
assert_table_equality_wo_types = assert_table_equality


def run_all(**kwargs) -> None:
    pw.run_all(monitoring_level=pw.MonitoringLevel.NONE, **kwargs)


def _capture_streams(tables, **kwargs):
    """Capture each table's full update stream [(key, vals, time, diff)]
    by running the graph once with subscribers attached
    (reference: GraphRunner.run_tables + CapturedStream)."""
    streams: list[list] = [[] for _ in tables]

    for i, t in enumerate(tables):
        names = list(t.column_names())

        def on_change(key, row, time, is_addition, _acc=streams[i], _names=names):
            _acc.append(
                (
                    int(key),
                    tuple(row[n] for n in _names),
                    time,
                    1 if is_addition else -1,
                )
            )

        pw.io.subscribe(t, on_change)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE, **kwargs)
    return streams


def assert_stream_equality_wo_index(t1, t2, **kwargs) -> None:
    """Same multiset of (values, time, diff) updates, ignoring keys.
    Accepts tuples of tables, compared pairwise in ONE run (reference:
    tests/utils.py assert_equal_streams_wo_index over run_tables)."""
    from collections import Counter

    ts1 = t1 if isinstance(t1, tuple) else (t1,)
    ts2 = t2 if isinstance(t2, tuple) else (t2,)
    assert len(ts1) == len(ts2)
    streams = _capture_streams([*ts1, *ts2], **kwargs)
    for s1, s2 in zip(streams[: len(ts1)], streams[len(ts1) :]):
        c1 = Counter(
            (tuple(_norm(x) for x in v), t, d) for _k, v, t, d in s1
        )
        c2 = Counter(
            (tuple(_norm(x) for x in v), t, d) for _k, v, t, d in s2
        )
        assert c1 == c2, (
            f"\nleft:  {sorted(c1.items(), key=str)}"
            f"\nright: {sorted(c2.items(), key=str)}"
        )


def assert_stream_equality(t1, t2, **kwargs) -> None:
    """Same multiset of (key, values, time, diff) updates
    (reference: tests/utils.py assert_equal_streams)."""
    from collections import Counter

    s1, s2 = _capture_streams([t1, t2], **kwargs)
    c1 = Counter((k, tuple(_norm(x) for x in v), t, d) for k, v, t, d in s1)
    c2 = Counter((k, tuple(_norm(x) for x in v), t, d) for k, v, t, d in s2)
    assert c1 == c2, (
        f"\nleft:  {sorted(c1.items(), key=str)}"
        f"\nright: {sorted(c2.items(), key=str)}"
    )


# --- streaming test utilities (reference: tests/utils.py DiffEntry,
# CheckKeyConsistentInStreamCallback, assert_split_into_time_groups,
# CsvPathwayChecker) --------------------------------------------------------


class DiffEntry:
    """One expected stream update for a key, ordered by (order, insertion)
    (reference: tests/utils.py:166)."""

    def __init__(self, key, order: int, insertion: bool, row: dict):
        self.key = key
        self.order = order
        self.insertion = insertion
        self.row = row

    @staticmethod
    def create(pk_table, pk_columns: dict, order: int, insertion: bool, row: dict, instance=None):
        key = DiffEntry.create_id_from(pk_table, pk_columns, instance=instance)
        return DiffEntry(key, order, insertion, row)

    @staticmethod
    def create_id_from(pk_table, pk_columns: dict, instance=None):
        from pathway_tpu.internals import api

        values = list(pk_columns.values())
        if instance is None:
            return api.ref_scalar(*values)
        return api.ref_scalar_with_instance(*values, instance=instance)

    def final_cleanup_entry(self):
        return DiffEntry(self.key, self.order + 1, False, self.row)

    def _sort_key(self):
        return (int(self.key), self.order, self.insertion)

    def __repr__(self):
        return (
            f"DiffEntry(key={self.key}, order={self.order}, "
            f"insertion={self.insertion}, row={self.row})"
        )


class _CheckKeyConsistentCallback:
    """For each key: the observed update sequence must be a subsequence of
    the expected (order, insertion)-sorted sequence, and drain it fully
    (reference: CheckKeyConsistentInStreamCallback)."""

    def __init__(self, state_list):
        import collections

        self.state = collections.defaultdict(collections.deque)
        for entry in sorted(state_list, key=DiffEntry._sort_key):
            self.state[int(entry.key)].append(entry)

    def __call__(self, key, row, time, is_addition):
        q = self.state.get(int(key))
        assert q, (
            f"Got unexpected entry key={key} row={row} "
            f"is_addition={is_addition}, expected={dict(self.state)!r}"
        )
        while True:
            entry = q.popleft()
            if (is_addition, row) == (entry.insertion, entry.row):
                if not q:
                    self.state.pop(int(key))
                break
            else:
                assert q, (
                    "Skipping over entries emptied the expected set for "
                    f"key={key}, state={dict(self.state)!r}"
                )

    def on_end(self):
        assert not self.state, f"Non empty final state = {dict(self.state)!r}"


class _CheckStreamEntriesEqualityCallback(_CheckKeyConsistentCallback):
    """Strict variant: the observed per-key update sequence must EQUAL the
    expected sequence (reference: CheckStreamEntriesEqualityCallback)."""

    def __call__(self, key, row, time, is_addition):
        q = self.state.get(int(key))
        assert q, (
            f"Got unexpected entry key={key} row={row} "
            f"is_addition={is_addition}, expected={dict(self.state)!r}"
        )
        entry = q.popleft()
        assert (is_addition, row) == (entry.insertion, entry.row), (
            f"Got unexpected entry key={key} row={row} "
            f"is_addition={is_addition}, expected={entry!r}"
        )
        if not q:
            self.state.pop(int(key))


def assert_stream_equal(expected, table) -> None:
    cb = _CheckStreamEntriesEqualityCallback(expected)

    def on_change(key, row, time, is_addition):
        cb(key, row, time, is_addition)

    pw.io.subscribe(table, on_change, cb.on_end)


def assert_key_entries_in_stream_consistent(expected, table) -> None:
    cb = _CheckKeyConsistentCallback(expected)

    def on_change(key, row, time, is_addition):
        cb(key, row, time, is_addition)

    pw.io.subscribe(table, on_change, cb.on_end)


def _assert_split_into_time_groups(s0, s1, transform) -> None:
    import collections

    result = [transform(k, v, t, d) for k, v, t, d in s0]
    expected = [transform(k, v, t, d) for k, v, t, d in s1]
    assert len(result) == len(expected), (len(result), len(expected))
    counts = collections.Counter(row[0] for row in expected)
    for key, count in counts.items():
        if count != 1:
            raise ValueError(
                "This utility function does not support cases where the "
                f"count of (value, diff) pair is !=1, but the count of "
                f"{key} is {count}."
            )
    result.sort(key=repr)
    expected.sort(key=repr)
    expected_to_result_time: dict = {}
    for (res_val, res_time), (ex_val, ex_time) in zip(result, expected):
        assert res_val == ex_val, (res_val, ex_val)
        if ex_time not in expected_to_result_time:
            expected_to_result_time[ex_time] = res_time
        if res_time != expected_to_result_time[ex_time]:
            raise AssertionError(
                f"Expected {res_val} to have time "
                f"{expected_to_result_time[ex_time]} but it has time "
                f"{res_time}."
            )


def assert_stream_split_into_groups(t1, t2, **kwargs) -> None:
    """Streams equal up to a consistent renaming of times; expected may
    split one result time into several groups (reference:
    assert_streams_in_time_groups)."""
    s1, s2 = _capture_streams([t1, t2], **kwargs)

    def transform(k, v, t, d):
        return (k, tuple(_norm(x) for x in v), d), t

    _assert_split_into_time_groups(s1, s2, transform)


def assert_stream_split_into_groups_wo_index(t1, t2, **kwargs) -> None:
    s1, s2 = _capture_streams([t1, t2], **kwargs)

    def transform(k, v, t, d):
        return (tuple(_norm(x) for x in v), d), t

    _assert_split_into_time_groups(s1, s2, transform)


class CsvPathwayChecker:
    """Poll an output-csv directory until it folds to the expected table
    (reference: tests/utils.py:469)."""

    def __init__(self, expected: str, output_path, *, id_from=None):
        self.expected = expected
        self.output_path = output_path
        self.id_from = id_from
        self.exception: Exception | None = None

    def __call__(self) -> bool:
        import os

        import pandas as pd

        try:
            ex = pw.debug.table_from_markdown(self.expected)
            dfs = []
            for entry in sorted(os.listdir(self.output_path)):
                dfs.append(pd.read_csv(os.path.join(self.output_path, entry)))
            df = pd.concat(dfs, ignore_index=True).rename(
                columns={"time": "__time__", "diff": "__diff__"}
            )
            res = pw.debug.table_from_pandas(df, id_from=self.id_from)
            assert_table_equality_wo_index(res, ex)
        except Exception as exception:
            self.exception = exception
            return False
        return True

    def provide_information_on_failure(self):
        return self.exception


def wait_result_with_checker(checker, timeout_s: float = 15.0, step: float = 0.1):
    """Run the graph in a thread, poll `checker` until it holds, stop the
    run (reference: tests/utils.py wait_result_with_checker)."""
    import threading
    import time

    th = threading.Thread(
        target=lambda: pw.run(monitoring_level=pw.MonitoringLevel.NONE),
        daemon=True,
    )
    th.start()
    deadline = time.time() + timeout_s
    ok = False
    while time.time() < deadline:
        if checker():
            ok = True
            break
        time.sleep(step)
    rt = pw.internals.parse_graph.G.runtime
    if rt is not None:
        rt.stop()
    th.join(timeout=10)
    assert ok, f"checker never satisfied: {checker.provide_information_on_failure()}"
