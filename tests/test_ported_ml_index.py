"""Ported reference ml/index tests
(reference: python/pathway/tests/ml/test_index.py) — KNNIndex (LSH) and the
DataIndex family (LshKnn, USearchKnn-equivalent TPU dense index, BM25,
hybrid): batch and streaming update-old vs as-of-now semantics, variable k,
metadata filters (JMESPath-style), distances, full-text search, index
factories, and exact cosine distances."""

from __future__ import annotations

import json
from typing import Any, Iterable

import numpy as np
import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.stdlib.indexing import (
    BruteForceKnnFactory,
    DataIndex,
    HybridIndexFactory,
    LshKnnFactory,
    TantivyBM25,
    TantivyBM25Factory,
    USearchMetricKind,
    UsearchKnnFactory,
    default_lsh_knn_document_index,
)
from pathway_tpu.stdlib.indexing.data_index import _SCORE
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnnMetricKind,
    LshKnn,
    USearchKnn,
)
from pathway_tpu.stdlib.ml.index import KNNIndex

from tests.ref_utils import (
    assert_table_equality_wo_index,
    assert_table_equality_wo_index_types,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


class PointSchema(pw.Schema):
    coords: Any
    is_query: bool


def sort_arrays(arrays) -> list[tuple[float, float]]:
    if arrays is None:
        return []
    return sorted([tuple(array) for array in arrays])


def get_points() -> list[tuple[tuple[float, ...], bool]]:
    points = [
        (2, 2, 0),
        (3, -2, 0),
        (0, 0, 1),
        (-1, 0, 0),
        (2, -2, 1),
        (1, 2, 0),
        (-1, 1, 1),
        (-3, 1, 0),
        (-2, -3, 1),
        (1, -4, 0),
    ]
    return [(point[:-1], point[-1] == 1) for point in points]


def to_tuple_of_floats(input: Iterable[Any]) -> tuple[float, ...]:
    return tuple(float(x) for x in input)


def nn_as_table(to_table) -> pw.Table:
    return pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "coords": [
                    to_tuple_of_floats(point[0]) for point in to_table
                ],
                "nn": [
                    tuple(to_tuple_of_floats(x) for x in point[1])
                    for point in to_table
                ],
            }
        )
    )


def nn_with_dists_as_table(to_table) -> pw.Table:
    return pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "coords": [
                    to_tuple_of_floats(point[0]) for point in to_table
                ],
                "dist": [to_tuple_of_floats(point[2]) for point in to_table],
                "nn": [
                    tuple(to_tuple_of_floats(x) for x in point[1])
                    for point in to_table
                ],
            }
        )
    )


def make_usearch_data_index(
    data_column: pw.ColumnReference,
    data_table: pw.Table,
    dimensions: int,
    *,
    embedder: Any = None,
    metadata_column: Any = None,
):
    inner_index = USearchKnn(
        data_column=data_column,
        metadata_column=metadata_column,
        dimensions=dimensions,
        reserved_space=1000,
        metric=USearchMetricKind.L2SQ,
        embedder=embedder,
    )

    return DataIndex(
        data_table=data_table,
        inner_index=inner_index,
    )


def test_all_at_once():
    data = get_points()
    df = pd.DataFrame(
        {
            "coords": [to_tuple_of_floats(point[0]) for point in data],
            "is_query": [point[1] for point in data],
        }
    )
    table = pw.debug.table_from_pandas(df)
    points = table.filter(~pw.this.is_query).without(pw.this.is_query)
    queries = table.filter(pw.this.is_query).without(pw.this.is_query)
    index = KNNIndex(points.coords, points, n_dimensions=2, n_and=5)
    result = queries + index.get_nearest_items(queries.coords, k=2).select(
        nn=pw.apply(sort_arrays, pw.this.coords)
    )

    knn_lsh_index = LshKnn(points.coords, None, dimensions=2, n_and=5)
    index2 = DataIndex(points, knn_lsh_index)

    queries = queries.with_columns(k=2)
    result2 = index2.query(
        queries.coords, number_of_matches=queries.k
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    expected = nn_as_table(
        [
            ((0, 0), ((-1, 0), (1, 2))),
            ((2, -2), ((1, -4), (3, -2))),
            ((-1, 1), ((-3, 1), (-1, 0))),
            ((-2, -3), ((-1, 0), (1, -4))),
        ]
    )

    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result2, expected)


def test_all_at_once_metadata_filter():
    data = get_points()

    class InputSchema(pw.Schema):
        coords: tuple[float, float]
        is_query: bool
        metadata: pw.Json

    df = pd.DataFrame(
        {
            "coords": [to_tuple_of_floats(point[0]) for point in data],
            "is_query": [point[1] for point in data],
            "metadata": [{"foo": i} for i, _ in enumerate(data)],
        }
    )
    table = pw.debug.table_from_pandas(df, schema=InputSchema)
    points = table.filter(~pw.this.is_query).without(pw.this.is_query)
    queries = table.filter(pw.this.is_query).without(
        pw.this.is_query, pw.this.metadata
    )
    index = KNNIndex(
        points.coords,
        points,
        n_dimensions=2,
        n_and=5,
        metadata=points.metadata,
    )
    queries += queries.select(metadata_filter="foo > `4`")
    result = queries.without(
        pw.this.metadata_filter
    ) + index.get_nearest_items(
        queries.coords, k=2, metadata_filter=queries.metadata_filter
    ).select(
        nn=pw.apply(sort_arrays, pw.this.coords),
    )

    knn_lsh_index = LshKnn(
        points.coords,
        points.metadata,
        dimensions=2,
        n_and=5,
    )
    index2 = DataIndex(points, knn_lsh_index)
    queries = queries.with_columns(k=2)
    result2 = index2.query(
        queries.coords,
        number_of_matches=queries.k,
        metadata_filter=queries.metadata_filter,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    expected = nn_as_table(
        [
            ((0, 0), ((-3, 1), (1, 2))),
            ((2, -2), ((1, -4), (1, 2))),
            ((-1, 1), ((-3, 1), (1, 2))),
            ((-2, -3), ((-3, 1), (1, -4))),
        ]
    )
    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result2, expected)


def stream_points(with_k: bool = False) -> tuple[pw.Table, pw.Table]:
    points = (
        T(
            """
         x |  y | __time__
         2 |  2 |     2
         3 | -2 |     4
        -1 |  0 |     8
         1 |  2 |    12
        -3 |  1 |    16
         1 | -4 |    20
    """
        )
        .with_columns(
            x=pw.cast(float, pw.this.x), y=pw.cast(float, pw.this.y)
        )
        .select(coords=pw.make_tuple(pw.this.x, pw.this.y))
    )
    queries = (
        T(
            """
         x |  y | k | __time__
         0 |  0 | 1 |     6
         2 | -2 | 2 |    10
        -1 |  1 | 3 |    14
        -2 | -3 | 0 |    18
    """
        )
        .with_columns(
            x=pw.cast(float, pw.this.x), y=pw.cast(float, pw.this.y)
        )
        .select(coords=pw.make_tuple(pw.this.x, pw.this.y), k=pw.this.k)
    )
    if not with_k:
        queries = queries.without(pw.this.k)
    return points, queries


def test_update_old():
    points, queries = stream_points()
    index = KNNIndex(points.coords, points, n_dimensions=2, n_and=5)
    result = queries + index.get_nearest_items(queries.coords, k=2).select(
        nn=pw.apply(sort_arrays, pw.this.coords)
    )
    expected = nn_as_table(
        [
            ((0, 0), ((-1, 0), (1, 2))),
            ((2, -2), ((1, -4), (3, -2))),
            ((-1, 1), ((-3, 1), (-1, 0))),
            ((-2, -3), ((-1, 0), (1, -4))),
        ]
    )

    knn_lsh_index = LshKnn(
        points.coords,
        metadata_column=None,
        dimensions=2,
        n_and=5,
    )
    index2 = DataIndex(points, knn_lsh_index)
    queries = queries.with_columns(k=2)
    result2 = index2.query(
        queries.coords,
        number_of_matches=queries.k,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    from pathway_tpu.stdlib.indexing import HybridIndex

    index3 = DataIndex(points, HybridIndex([knn_lsh_index, knn_lsh_index]))
    result3 = index3.query(
        queries.coords,
        number_of_matches=queries.k,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result2, expected)
    assert_table_equality_wo_index(result3, expected)


def test_asof_now():
    points, queries = stream_points()
    index = KNNIndex(points.coords, points, n_dimensions=2, n_and=5)
    result = queries + index.get_nearest_items_asof_now(
        queries.coords, k=2
    ).select(nn=pw.apply(sort_arrays, pw.this.coords))
    expected = nn_as_table(
        [
            ((0, 0), ((2, 2), (3, -2))),
            ((2, -2), ((-1, 0), (3, -2))),
            ((-1, 1), ((-1, 0), (1, 2))),
            ((-2, -3), ((-3, 1), (-1, 0))),
        ]
    )

    knn_lsh_index = LshKnn(
        points.coords,
        metadata_column=None,
        dimensions=2,
        n_and=5,
    )
    index2 = DataIndex(points, knn_lsh_index)

    index3 = make_usearch_data_index(
        points.coords, data_table=points, dimensions=2, metadata_column=None
    )

    result2 = index2.query_as_of_now(
        queries.coords,
        number_of_matches=2,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    result3 = index3.query_as_of_now(
        queries.coords,
        number_of_matches=2,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result2, expected)
    assert_table_equality_wo_index(result3, expected)


def test_update_old_with_variable_k():
    points, queries = stream_points(with_k=True)
    index = KNNIndex(points.coords, points, n_dimensions=2, n_and=5)
    result = queries.without(pw.this.k) + index.get_nearest_items(
        queries.coords, queries.k
    ).with_universe_of(queries).select(
        nn=pw.apply(sort_arrays, pw.this.coords)
    )
    expected = nn_as_table(
        [
            ((0, 0), ((-1, 0),)),
            ((2, -2), ((1, -4), (3, -2))),
            ((-1, 1), ((-3, 1), (-1, 0), (1, 2))),
            ((-2, -3), ()),
        ]
    )

    knn_lsh_index = LshKnn(
        points.coords,
        None,
        dimensions=2,
        n_and=5,
    )
    index2 = DataIndex(points, knn_lsh_index)
    result2 = index2.query(
        queries.coords,
        number_of_matches=queries.k,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result2, expected)


def test_asof_now_with_variable_k():
    points, queries = stream_points(with_k=True)
    index = KNNIndex(points.coords, points, n_dimensions=2, n_and=5)
    result = queries.without(pw.this.k) + index.get_nearest_items_asof_now(
        queries.coords, queries.k
    ).select(nn=pw.apply(sort_arrays, pw.this.coords))
    expected = nn_as_table(
        [
            ((0, 0), ((2, 2),)),
            ((2, -2), ((-1, 0), (3, -2))),
            ((-1, 1), ((-1, 0), (1, 2), (2, 2))),
            ((-2, -3), ()),
        ]
    )
    knn_lsh_index = LshKnn(
        points.coords,
        metadata_column=None,
        dimensions=2,
        n_and=5,
    )
    index2 = DataIndex(points, knn_lsh_index)
    result2 = index2.query_as_of_now(
        queries.coords,
        number_of_matches=queries.k,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    index3 = make_usearch_data_index(
        points.coords, data_table=points, dimensions=2, metadata_column=None
    )
    result3 = index3.query_as_of_now(
        queries.coords,
        number_of_matches=queries.k,
    ).select(coords=pw.left.coords, nn=pw.apply(sort_arrays, pw.right.coords))

    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result2, expected)
    assert_table_equality_wo_index(result3, expected)


def test_get_distances():
    data = get_points()
    df = pd.DataFrame(
        {
            "coords": [to_tuple_of_floats(point[0]) for point in data],
            "is_query": [point[1] for point in data],
        }
    )
    table = pw.debug.table_from_pandas(df)
    points = table.filter(~pw.this.is_query).without(pw.this.is_query)
    queries = table.filter(pw.this.is_query).without(pw.this.is_query)
    index = KNNIndex(points.coords, points, n_dimensions=2, n_and=5)
    result = queries + index.get_nearest_items(
        queries.coords, k=2, with_distances=True
    ).select(
        pw.this.dist,
        nn=pw.this.coords,
    )

    expected = nn_with_dists_as_table(
        [
            ((0, 0), ((-1, 0), (1, 2)), (1, 5)),
            ((2, -2), ((3, -2), (1, -4)), (1, 5)),
            ((-1, 1), ((-1, 0), (-3, 1)), (1, 4)),
            ((-2, -3), ((1, -4), (-1, 0)), (10, 10)),
        ]
    )
    assert_table_equality_wo_index_types(result, expected)

    knn_lsh_index = LshKnn(
        points.coords,
        metadata_column=None,
        dimensions=2,
        n_and=5,
    )

    @pw.udf
    def negate_tuple(t):
        return tuple(-x for x in t)

    index2 = DataIndex(points, knn_lsh_index)
    queries = queries.with_columns(k=2)
    result2 = index2.query(
        queries.coords,
        number_of_matches=queries.k,
    ).select(
        coords=pw.left.coords,
        dist=negate_tuple(pw.right[_SCORE]),
        nn=pw.right.coords,
    )

    assert_table_equality_wo_index_types(result2, expected)


def test_full_text_search():
    index_data = pw.debug.table_from_markdown(
        """
        index_text                                                          | extra_info| __time__
        Lorem ipsum dolor sit amet, consectetur adipiscing elit.            | 1         |     2
        Cras ex lorem, luctus nec dui eu, pellentesque vestibulum velit.    | 2         |     2
        Nunc laoreet tortor quis odio mattis vulputate.                     | 3         |     2
        Quisque vel dictum neque, at efficitur nisi.                        | 4         |     2
        Aliquam dui nibh, cursus ac porttitor nec, placerat quis nisi.      | 5         |     2
        Curabitur vehicula enim vitae rhoncus feugiat.                      | 6         |     2
        """,
        split_on_whitespace=False,
    )

    queries = pw.debug.table_from_markdown(
        """
        query_text | __time__
        nisi       | 2
        elit       | 2
        lorem      | 2
        marchewka  | 2
        """,
        split_on_whitespace=False,
    )

    index = TantivyBM25(index_data.index_text, metadata_column=None)
    data_index = DataIndex(index_data, index)
    ret = data_index.query_as_of_now(
        query_column=queries.query_text, number_of_matches=4
    ).select(qtext=pw.left.query_text, info=pw.right.extra_info)

    class ExpSchema(pw.Schema):
        qtext: str
        info: list[int]

    df = pd.DataFrame(
        {
            "qtext": ["elit", "lorem", "marchewka", "nisi"],
            "info": [(1,), (1, 2), (), (4, 5)],
        },
    )
    expected = pw.debug.table_from_pandas(df, schema=ExpSchema)
    assert_table_equality_wo_index(ret, expected)


def test_output_joined_with_other_columns():
    @pw.udf
    def embedder(x: str) -> list[float]:
        return [0.0, 1.0, 2.0]

    @pw.udf
    def sort_docs(x: list[str]) -> list[str]:
        return sorted(x)

    query = pw.debug.table_from_rows(
        pw.schema_from_types(query=str), [("a",)]
    )
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(doc=str), [("a",), ("b",), ("c",)]
    )
    index = default_lsh_knn_document_index(
        docs.doc, docs, dimensions=3, embedder=embedder
    )
    res = query + index.query(query.query, collapse_rows=True).select(
        doc=sort_docs(pw.right.doc)
    )
    expected = pw.debug.table_from_pandas(
        pd.DataFrame({"query": ["a"], "doc": [("a", "b", "c")]})
    )
    assert_table_equality_wo_index(res.update_types(doc=list[str]), expected)


def test_no_match_is_empty_list():
    @pw.udf
    def make_point(r: int) -> list[float]:
        return [float(r), float(r)]

    @pw.udf
    def load_json(s: str) -> pw.Json:
        return json.loads(s)

    data = pw.debug.table_from_markdown(
        """
        r | filter_data
        1 | {"v":2}
        5 | {"v":1}
        8 | {"v":1}
    """
    ).with_columns(
        d=make_point(pw.this.r), filter_data=load_json(pw.this.filter_data)
    )
    queries = pw.debug.table_from_markdown(
        """
        r | filter_expr
        4 | v==`1`
        6 | v==`3`
    """
    ).with_columns(d=make_point(pw.this.r))
    index = make_usearch_data_index(
        data.d, data, dimensions=2, metadata_column=data.filter_data
    )
    result = index.query_as_of_now(
        queries.d,
        number_of_matches=2,
        collapse_rows=True,
        metadata_filter=queries.filter_expr,
    ).select(l=pw.left.r, r=pw.right.r)
    expected = pw.debug.table_from_pandas(
        pd.DataFrame({"l": [4, 6], "r": [[5, 8], []]})
    )
    assert_table_equality_wo_index(result, expected)


@pw.udf
def fake_embedder(x: str) -> list[float]:
    return [0.0, 1.0, float(ord(x[0])) / 5.0]


@pytest.mark.parametrize(
    "factory",
    [
        UsearchKnnFactory(
            dimensions=3,
            reserved_space=3,
            embedder=fake_embedder,
            metric=USearchMetricKind.COS,
        ),
        LshKnnFactory(dimensions=3, embedder=fake_embedder),
        BruteForceKnnFactory(
            dimensions=3,
            reserved_space=3,
            metric=BruteForceKnnMetricKind.COS,
            embedder=fake_embedder,
        ),
        UsearchKnnFactory(  # without dimensions
            reserved_space=3,
            embedder=fake_embedder,
            metric=USearchMetricKind.COS,
        ),
        LshKnnFactory(embedder=fake_embedder),
        BruteForceKnnFactory(
            reserved_space=3,
            metric=BruteForceKnnMetricKind.COS,
            embedder=fake_embedder,
        ),
        UsearchKnnFactory(  # without optional params
            embedder=fake_embedder,
        ),
        TantivyBM25Factory(),
        HybridIndexFactory(
            [
                TantivyBM25Factory(),
                UsearchKnnFactory(
                    dimensions=3,
                    reserved_space=3,
                    embedder=fake_embedder,
                    metric=USearchMetricKind.COS,
                ),
            ]
        ),
    ],
)
def test_index_factory(factory):
    query = pw.debug.table_from_rows(
        pw.schema_from_types(query=str), [("a",)]
    )
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(doc=str), [("a",), ("b",), ("c",)]
    )

    index = factory.build_index(docs.doc, docs)
    res = query + index.query_as_of_now(
        query.query, collapse_rows=True, number_of_matches=1
    ).select(pw.right.doc)
    expected = pw.debug.table_from_pandas(
        pd.DataFrame({"query": ["a"], "doc": [("a",)]})
    )
    assert_table_equality_wo_index(res.update_types(doc=list[str]), expected)


def test_usearch_distances():
    @pw.udf
    def fake_embedder(x: str) -> list[float]:
        if x == "a":
            return [1, 1, 1]
        elif x == "b":
            return [1, 1, 2]
        elif x == "c":
            return [1, 2, 2]
        else:
            return [1, 3, 1]

    factory = UsearchKnnFactory(
        embedder=fake_embedder,
    )

    query = pw.debug.table_from_rows(
        pw.schema_from_types(query=str), [("a",)]
    )
    docs = pw.debug.table_from_rows(
        pw.schema_from_types(doc=str), [("b",), ("c",), ("d",)]
    )

    index = factory.build_index(docs.doc, docs)
    res = (
        index.query_as_of_now(
            query.query, collapse_rows=False, number_of_matches=3
        )
        .select(
            pw.right.doc,
            distance=-pw.unwrap(pw.right[_SCORE]),
        )
        .with_id_from(pw.this.doc)
        .select(pw.this.distance)
    )

    df = pw.debug.table_to_pandas(res).sort_index()
    expected_df = pw.debug.table_to_pandas(
        pw.debug.table_from_markdown(
            """
        doc | distance
         b  | 0.05719095841793642
         c  | 0.037749551350623634
         d  | 0.12961172022151068
    """
        )
        .with_id_from(pw.this.doc)
        .select(pw.this.distance)
    ).sort_index()
    assert np.isclose(
        df.to_numpy(), expected_df.to_numpy(), rtol=1e-5, atol=0.0
    ).all()


@pytest.mark.parametrize(
    "factory",
    [UsearchKnnFactory, LshKnnFactory, BruteForceKnnFactory],
)
def test_knn_index_factory_init(factory):
    index = factory(
        dimensions=3,
        embedder=None,
    )
    index = factory(dimensions=3)
    index = factory(
        embedder=fake_embedder,
    )
    assert index is not None


@pytest.mark.parametrize(
    "factory",
    [UsearchKnnFactory, LshKnnFactory, BruteForceKnnFactory],
)
def test_knn_index_factory_creation_error(factory):
    with pytest.raises(
        ValueError,
        match="Either `dimensions` or `embedder` must be provided to index factory.",
    ):
        factory(
            dimensions=None,
            embedder=None,
        )
