"""Native parser depth tests (VERDICT r4 item 8; reference strategies:
python/pathway/xpacks/llm/parsers.py:82-775 — chunking modes, table
extraction, paged parsing, per-page vision parsing)."""

from __future__ import annotations

import pytest

from pathway_tpu.xpacks.llm.parsers import (
    DoclingParser,
    Element,
    ImageParser,
    SlideParser,
    UnstructuredParser,
    chunk_by_title,
    chunk_elements_basic,
    native_partition,
)

FIXTURE = b"""INTRODUCTION

This is the opening paragraph of the document. It describes the subject
at some length.

- first bullet
- second bullet

METHODS

| name | value |
|------|-------|
| a    | 1     |
| b    | 2     |

The methods paragraph explains how the values were obtained.
\x0cRESULTS

The results paragraph appears on the second page of the document.
"""


def test_native_partition_classifies_elements():
    els = native_partition(FIXTURE)
    cats = [e.category for e in els]
    assert cats == [
        "Title",
        "NarrativeText",
        "ListItem",
        "Title",
        "Table",
        "NarrativeText",
        "Title",
        "NarrativeText",
    ]
    # table extraction produced html
    table = next(e for e in els if e.category == "Table")
    assert "<table>" in table.metadata["text_as_html"]
    assert "<td>a</td>" in table.metadata["text_as_html"]
    # form feed advanced the page
    assert els[-1].metadata["page_number"] == 2
    assert els[0].metadata["page_number"] == 1


def test_single_mode_joins_everything():
    docs = UnstructuredParser(chunking_mode="single").parse(FIXTURE)
    assert len(docs) == 1
    text, meta = docs[0]
    assert "INTRODUCTION" in text and "RESULTS" in text


def test_elements_mode_one_chunk_per_element():
    docs = UnstructuredParser(chunking_mode="elements").parse(FIXTURE)
    assert len(docs) == 8
    assert docs[0][1]["category"] == "Title"


def test_paged_mode_groups_by_page():
    docs = UnstructuredParser(chunking_mode="paged").parse(FIXTURE)
    assert len(docs) == 2
    assert "INTRODUCTION" in docs[0][0] and "RESULTS" not in docs[0][0]
    assert "RESULTS" in docs[1][0]


def test_basic_mode_respects_max_characters():
    docs = UnstructuredParser(
        chunking_mode="basic", chunking_kwargs={"max_characters": 120}
    ).parse(FIXTURE)
    assert len(docs) > 2
    assert all(len(text) <= 120 for text, _m in docs)


def test_by_title_mode_starts_sections_at_titles():
    docs = UnstructuredParser(
        chunking_mode="by_title", chunking_kwargs={"max_characters": 10_000}
    ).parse(FIXTURE)
    # three titles -> three sections
    assert len(docs) == 3
    assert docs[0][0].startswith("INTRODUCTION")
    assert docs[1][0].startswith("METHODS")
    assert docs[2][0].startswith("RESULTS")


def test_chunk_basic_splits_oversized_elements():
    els = [Element("x" * 950)]
    chunks = chunk_elements_basic(els, max_characters=400)
    assert [len(c.text) for c in chunks] == [400, 400, 150]


def test_chunk_by_title_packs_within_sections():
    els = [
        Element("Top", "Title"),
        Element("a" * 90),
        Element("b" * 90),
        Element("Next", "Title"),
        Element("c" * 90),
    ]
    chunks = chunk_by_title(els, max_characters=120)
    texts = [c.text for c in chunks]
    assert texts[0].startswith("Top")
    assert any(t.startswith("Next") for t in texts)


def test_invalid_chunking_mode_raises():
    with pytest.raises(ValueError, match="chunking_mode"):
        UnstructuredParser(chunking_mode="bogus")


def test_docling_fallback_emits_markdown_titles():
    docs = DoclingParser(chunking_mode="single").parse(FIXTURE)
    assert "# INTRODUCTION" in docs[0][0]


def test_image_parser_uses_vision_llm():
    seen = {}

    def vision(prompt: str, image: bytes) -> str:
        seen["prompt"] = prompt
        seen["n"] = len(image)
        return "a chart with three bars"

    docs = ImageParser(llm=vision).parse(b"\x89PNG fake image bytes")
    assert docs == [("a chart with three bars", {"parser": "image"})]
    assert seen["n"] > 0 and "Describe" in seen["prompt"]


def test_image_parser_without_llm_raises():
    with pytest.raises(ValueError, match="vision"):
        ImageParser().parse(b"img")


def test_slide_parser_splits_pdf_pages():
    PdfWriter = pytest.importorskip("pypdf").PdfWriter

    import io as _io

    writer = PdfWriter()
    writer.add_blank_page(width=72, height=72)
    writer.add_blank_page(width=72, height=72)
    buf = _io.BytesIO()
    writer.write(buf)

    calls = []

    def vision(prompt: str, image: bytes) -> str:
        calls.append(len(image))
        return f"slide {len(calls)}"

    docs = SlideParser(llm=vision).parse(buf.getvalue())
    assert [d[0] for d in docs] == ["slide 1", "slide 2"]
    assert [d[1]["page_number"] for d in docs] == [1, 2]
