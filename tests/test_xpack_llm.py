"""LLM xpack tests with fake embedders — no network
(modeled on reference python/pathway/xpacks/llm/tests/test_vector_store.py)."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts
from pathway_tpu.internals.json import Json


@pw.udf
def fake_embedder(text: str) -> np.ndarray:
    """Deterministic 8-dim embedding: bag-of-chars buckets."""
    v = np.zeros(8, dtype=np.float32)
    for ch in str(text).lower():
        v[ord(ch) % 8] += 1.0
    n = np.linalg.norm(v)
    return v / n if n > 0 else v


def _docs_table():
    return T(
        """
        data
        aaaa aaaa
        bbbb bbbb
        cccc dddd
        """
    )


def test_vector_store_retrieve():
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    server = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    queries = T(
        """
        query | k | metadata_filter | filepath_globpattern
        aaaa  | 2 | None            | None
        """
    )
    result = server.retrieve_query(queries)
    _keys, cols = table_to_dicts(result)
    docs = list(cols["result"].values())[0].value
    assert len(docs) == 2
    assert docs[0]["text"] == "aaaa aaaa"
    assert docs[0]["dist"] <= docs[1]["dist"]


def test_vector_store_statistics_and_inputs():
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    server = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    stats_q = T(
        """
        _dummy
        1
        """
    ).select()
    result = server.statistics_query(stats_q)
    _keys, cols = table_to_dicts(result)
    stats = list(cols["result"].values())[0].value
    assert stats["file_count"] == 3

    inputs_q = T(
        """
        metadata_filter | filepath_globpattern
        None            | None
        """
    )
    result2 = server.inputs_query(inputs_q)
    _keys2, cols2 = table_to_dicts(result2)
    assert isinstance(list(cols2["result"].values())[0].value, list)


def test_vector_store_with_splitter():
    from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    docs = T(
        """
        data
        one two three. four five six. seven eight nine.
        """
    )
    server = VectorStoreServer(
        docs,
        embedder=fake_embedder,
        splitter=TokenCountSplitter(min_tokens=2, max_tokens=3),
    )
    chunked = server._graph["chunked_docs"]
    _keys, cols = table_to_dicts(chunked)
    assert len(cols["text"]) == 3


def test_document_store_with_bm25():
    from pathway_tpu.stdlib.indexing import TantivyBM25Factory
    from pathway_tpu.xpacks.llm.document_store import DocumentStore

    store = DocumentStore(
        _docs_table(), retriever_factory=TantivyBM25Factory()
    )
    queries = T(
        """
        query | k | metadata_filter | filepath_globpattern
        bbbb  | 1 | None            | None
        """
    )
    result = store.retrieve_query(queries)
    _keys, cols = table_to_dicts(result)
    docs = list(cols["result"].values())[0].value
    assert docs[0]["text"] == "bbbb bbbb"


def test_rag_question_answerer():
    from pathway_tpu.xpacks.llm.llms import EchoChat
    from pathway_tpu.xpacks.llm.question_answering import (
        BaseRAGQuestionAnswerer,
    )
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    indexer = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    rag = BaseRAGQuestionAnswerer(
        llm=EchoChat(prefix="ANSWER: "), indexer=indexer, search_topk=2
    )
    queries = T(
        """
        prompt | filters | model | return_context_docs
        aaaa   | None    | None  | True
        """
    )
    result = rag.answer_query(queries)
    _keys, cols = table_to_dicts(result)
    out = list(cols["result"].values())[0].value
    assert out["response"].startswith("ANSWER: ")
    assert "aaaa aaaa" in out["response"]
    assert len(out["context_docs"]) == 2


def test_adaptive_rag():
    from pathway_tpu.xpacks.llm.llms import EchoChat
    from pathway_tpu.xpacks.llm.question_answering import (
        AdaptiveRAGQuestionAnswerer,
    )
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    from pathway_tpu.xpacks.llm.llms import BaseChat

    class ConstChat(BaseChat):
        def _accept(self, messages, **kwargs) -> str:
            return "42"

    indexer = VectorStoreServer(_docs_table(), embedder=fake_embedder)
    rag = AdaptiveRAGQuestionAnswerer(
        llm=ConstChat(),
        indexer=indexer,
        n_starting_documents=1,
        factor=2,
        max_iterations=2,
    )
    queries = T(
        """
        prompt | filters | model | return_context_docs
        aaaa   | None    | None  | False
        """
    )
    result = rag.answer_query(queries)
    _keys, cols = table_to_dicts(result)
    out = list(cols["result"].values())[0].value
    assert out["response"] is not None


def test_rerank_topk_filter():
    from pathway_tpu.xpacks.llm.rerankers import rerank_topk_filter

    t = T(
        """
        marker
        x
        """
    ).select(
        docs=pw.apply_with_type(
            lambda _: ("d1", "d2", "d3"), tuple, pw.this.marker
        ),
        scores=pw.apply_with_type(
            lambda _: (1.0, 3.0, 2.0), tuple, pw.this.marker
        ),
    )
    res = t.select(best=rerank_topk_filter(t.docs, t.scores, 2))
    _keys, cols = table_to_dicts(res)
    docs, scores = list(cols["best"].values())[0]
    assert docs == ("d2", "d3")


def test_splitters():
    from pathway_tpu.xpacks.llm.splitters import (
        RecursiveSplitter,
        TokenCountSplitter,
    )

    s = TokenCountSplitter(min_tokens=2, max_tokens=4)
    chunks = s.split("a b c d e f g h")
    assert all(len(c[0].split()) <= 4 for c in chunks)
    r = RecursiveSplitter(chunk_size=3)
    chunks2 = r.split("one two three\n\nfour five six seven")
    assert len(chunks2) >= 2


def test_hashing_tokenizer_deterministic():
    from pathway_tpu.xpacks.llm._tokenizer import HashingTokenizer

    tok = HashingTokenizer()
    a1, m1 = tok.encode_batch(["hello world"], 64)
    a2, m2 = tok.encode_batch(["hello world"], 64)
    assert (a1 == a2).all()
